# kubeadmiral_tpu developer targets.
#
# Tests run on a virtual 8-device CPU mesh, fully decoupled from the TPU
# tunnel: PALLAS_AXON_POOL_IPS is unset so the axon PJRT plugin is never
# registered (the plugin serializes on the single chip and two concurrent
# processes wedge each other).  Only `make bench` touches the real TPU.

PYTEST_ENV = env -u PALLAS_AXON_POOL_IPS -u PALLAS_AXON_REMOTE_COMPILE JAX_PLATFORMS=cpu

.PHONY: test test-fast bench bench-churn bench-gate bench-restart bench-soak bench-e2e bench-e2e-scale bench-store graft-check graft-dryrun native metrics-lint lint chaos chaos-e2e profile profile-smoke restart-smoke obs-smoke

native: kubeadmiral_tpu/native/libkadmhash.so

kubeadmiral_tpu/native/libkadmhash.so: kubeadmiral_tpu/native/fnvhash.cpp kubeadmiral_tpu/native/seqsched.cpp
	g++ -O3 -shared -fPIC -o $@ $^

bench-e2e:
	$(PYTEST_ENV) python bench_e2e.py

# Store/notify microbench (ISSUE 18): raw in-process store writes/s
# (direct + columnar batch verbs) and watch fan-out µs/event with a
# controller-fleet-sized watcher population, both KT_STORE_COALESCE
# modes side by side.  Save output as BENCH_STORE_rNN.json; bench-gate
# floors writes/s and ceilings notify latency vs same-platform priors
# (see docs/operations.md "Store & notify tuning").
bench-store:
	$(PYTEST_ENV) python tools/store_bench.py

# End-to-end over a kwok-lite HTTP farm at HUNDREDS of member
# apiservers (real sockets, auth, watches): the write-path coalescing +
# bulk-read + admission work measured at the member count it exists
# for.  bench-gate keys the e2e baseline by (transport, members), so
# the first scaled round trips the loud NOTHING-GATED warning and
# seeds its own baseline (see docs/operations.md "Control-plane
# write-path tuning").
bench-e2e-scale:
	$(PYTEST_ENV) BENCH_E2E_TRANSPORT=http \
		BENCH_E2E_OBJECTS=$${BENCH_E2E_OBJECTS:-500} \
		BENCH_E2E_CLUSTERS=$${BENCH_E2E_CLUSTERS:-500} \
		python bench_e2e.py

# Fault matrix (tests/test_faults.py): fault injection, circuit
# breakers, stall-proof dispatch, watch recovery, the hard-down-member
# acceptance scenario.  The fast subset also runs in tier-1
# (`-m 'not slow'`); this target runs the WHOLE matrix including the
# long flapping-member chaos scenarios.
chaos:
	$(PYTEST_ENV) python -m pytest tests/test_faults.py -q

# Degraded-fleet e2e bench: 1 hard-down member + 1 flapping during
# churn, reporting tick-stall p50/p99 and shed-write counts in
# detail.chaos (see docs/operations.md "Degraded member runbook").
chaos-e2e:
	$(PYTEST_ENV) BENCH_E2E_CHAOS=1 python bench_e2e.py

# ktlint (tools/ktlint, ISSUE 14): the repo-specific static analyzer —
# AOT/ledger routing of every jax.jit site, the pack-sort sharding
# contracts, donated-buffer read-after-dispatch, the KT_* knob catalog
# (code <-> docs, zero orphans), and lock discipline over declared-
# shared fields.  See docs/static_analysis.md; suppressions need a
# written reason.  `--json` emits the per-rule summary bench.py embeds.
lint:
	python -m tools.ktlint

# Fails on metric emissions not in runtime/metric_catalog.py — the
# exposition, the docs and the source stay one vocabulary (see
# docs/observability.md).
metrics-lint:
	python tools/metrics_lint.py

# Fails when the latest BENCH_r*.json regresses throughput/latency vs
# the best prior round of the same metric+platform (tolerance 10%; see
# tools/bench_gate.py for the intentional-regression knob).
bench-gate:
	python tools/bench_gate.py

# Crash-recovery kill matrix (tests/test_restart.py + tools/
# restart_driver.py): durable-snapshot round trips, torn-write
# quarantine, breaker/flight-recorder restore, and the subprocess
# SIGKILL sweep — a victim dies mid-{featurize, dispatch, fetch,
# snapshot-write, snapshot-rename, dispatch-flush} and the successor
# must converge bit-identically to an uninterrupted run.  Wired into
# `make test` (the main suite run skips the file to avoid a double
# run).  See docs/operations.md "Restart & failover runbook".
restart-smoke:
	$(PYTEST_ENV) python -m pytest tests/test_restart.py -q

# Fleet-observatory smoke (tools/obs_smoke.py): a subprocess kwok-farm
# round with telemetry spill on — assembles the merged cross-process
# trace and asserts the manager's member-write span has a server-side
# child from the member process under the same trace id, the fleet
# scraper merges every member's /metrics, and spill segments survive
# teardown (see docs/observability.md "Fleet observatory").
obs-smoke:
	$(PYTEST_ENV) python tools/obs_smoke.py

test: lint metrics-lint restart-smoke obs-smoke
	$(PYTEST_ENV) python -m pytest tests/ -q --ignore=tests/test_restart.py

test-fast: lint metrics-lint
	$(PYTEST_ENV) python -m pytest tests/ -q -x -m "not slow"

bench:
	python bench.py

# jax.profiler capture around live scheduling ticks (tools/
# profile_smoke.py): writes the trace directory + the dispatch
# ledger's waterfall.json under KT_PROFILE_DIR and prints the paths.
# `profile` runs a config-3-sized world; `profile-smoke` is the 1-tick
# CPU sanity check (see docs/observability.md § Device-time
# attribution).
profile:
	PROFILE_OBJECTS=$${PROFILE_OBJECTS:-10000} \
		PROFILE_CLUSTERS=$${PROFILE_CLUSTERS:-500} \
		PROFILE_TICKS=$${PROFILE_TICKS:-3} \
		python tools/profile_smoke.py

profile-smoke:
	$(PYTEST_ENV) PROFILE_OBJECTS=1024 PROFILE_CLUSTERS=64 \
		PROFILE_TICKS=1 python tools/profile_smoke.py

# Sustained-churn streaming scenario at a tier-1-budget config: object
# arrivals/updates + periodic capacity drift stream through the slab
# scheduler; reports sustained objects-revalidated/s and event ->
# placement-visible latency p50/p99, and writes BENCH_CHURN_r<n>.json
# for bench-gate (see docs/operations.md "Streaming tick").
# Restart-to-first-tick SLO scenario: a cold boot (prewarm ladder
# traced + AOT-exported, cold tick, durable snapshot) then a warm
# subprocess whose first converged tick must be parity-exact — the
# gated restart_to_first_tick_ms metric (BENCH_RESTART_r<n>.json).
bench-restart:
	$(PYTEST_ENV) BENCH_SCENARIO=restart python bench.py

# All-stressors-at-once gated soak (ISSUE 16): sustained arrival churn
# + periodic capacity drift + one flapping and one hard-down member +
# a mid-run SIGKILL/failover, all concurrently, over the full
# federate->schedule->sync pipeline.  Placements must come out
# bit-identical to an uninterrupted oracle run, and the recorded
# telemetry timeline must show the burn-rate evaluator red ONLY inside
# declared injection windows (SOAK_r<n>.json, gated by bench-gate; see
# docs/observability.md "Soak observatory").
bench-soak:
	$(PYTEST_ENV) BENCH_SCENARIO=soak python bench.py

bench-churn:
	$(PYTEST_ENV) BENCH_SCENARIO=churn_rate \
		BENCH_OBJECTS=$${BENCH_OBJECTS:-4096} \
		BENCH_CLUSTERS=$${BENCH_CLUSTERS:-256} \
		BENCH_CHURN_SECONDS=$${BENCH_CHURN_SECONDS:-8} \
		python bench.py

graft-check:
	python -c "import __graft_entry__ as g; fn, args = g.entry(); fn(*args); print('entry ok')"

graft-dryrun:
	$(PYTEST_ENV) XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
