"""Cross-cluster rollout planning for federated Deployments.

When an FTC enables rolloutPlan, the sync dispatcher coordinates member
clusters through a rolling update so that the FEDERATION-WIDE maxSurge /
maxUnavailable invariants hold even though each member's deployment
controller acts independently (reference:
pkg/controllers/util/rolloutplan.go:58-867, applied from
pkg/controllers/sync/dispatch/managed.go:204-323).

Each tick produces a per-cluster ``RolloutPlan {replicas, maxSurge,
maxUnavailable, onlyPatchReplicas}``; a cluster with NO plan keeps its
current template ("wait for your turn").  The planner reads the member
deployments' observed state: spec.replicas, status availability, the
current-revision annotation stamped by sync, and the
``latestreplicaset.kubeadmiral.io/*`` annotations describing the member's
newest ReplicaSet.

The budget accounting: each cluster's already-unavailable /
already-surged replicas count against the global budget first
(LeastUnavailable/LeastSurge); the remainder is handed out in the
reference's fixed execution order — upgrade scale-outs, shrink
scale-ins, upgrade in-placers, grow scale-outs, upgrade scale-ins —
so shrinking funds growing within one tick.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

from kubeadmiral_tpu.federation.retain import (
    CURRENT_REVISION_ANNOTATION,
    LAST_REPLICASET_NAME,
)
from kubeadmiral_tpu.utils.unstructured import get_path

REPLICAS_PATH = "/spec/replicas"
MAX_SURGE_PATH = "/spec/strategy/rollingUpdate/maxSurge"
MAX_UNAVAILABLE_PATH = "/spec/strategy/rollingUpdate/maxUnavailable"

# Member-side annotations describing the newest ReplicaSet
# (reference: util/federatedstatus.go:35-39, common/constants.go:113).
LATEST_RS_NAME = "latestreplicaset.kubeadmiral.io/name"
LATEST_RS_REPLICAS = "latestreplicaset.kubeadmiral.io/replicas"
LATEST_RS_AVAILABLE = "latestreplicaset.kubeadmiral.io/available-replicas"
LAST_RS_NAME = LAST_REPLICASET_NAME


class RolloutPlanError(Exception):
    pass


@dataclass
class RolloutPlan:
    """What one cluster may do this tick (rolloutplan.go:58-92).
    None means "don't override; use the original value"."""

    replicas: Optional[int] = None
    max_surge: Optional[int] = None
    max_unavailable: Optional[int] = None
    only_patch_replicas: bool = False

    def to_overrides(self) -> list[dict]:
        patches = []
        if self.replicas is not None:
            patches.append(
                {"op": "replace", "path": REPLICAS_PATH, "value": self.replicas}
            )
        if self.max_surge is not None:
            patches.append(
                {"op": "replace", "path": MAX_SURGE_PATH, "value": self.max_surge}
            )
        if self.max_unavailable is not None:
            patches.append(
                {
                    "op": "replace",
                    "path": MAX_UNAVAILABLE_PATH,
                    "value": self.max_unavailable,
                }
            )
        return patches


def resolve_fenceposts(
    max_surge, max_unavailable, desired: int
) -> tuple[int, int]:
    """Int-or-percent resolution (rolloutplan.go resolveFenceposts via
    k8s intstr): surge rounds up, unavailable rounds down; both-zero
    degenerates to unavailable=1."""

    def value(raw, round_up: bool) -> int:
        if raw is None:
            return 0
        if isinstance(raw, str):
            if not raw.endswith("%"):
                return int(raw)
            pct = int(raw[:-1])
            exact = pct * desired / 100.0
            return int(math.ceil(exact) if round_up else math.floor(exact))
        return int(raw)

    surge = max(0, value(max_surge, True))
    unavailable = max(0, value(max_unavailable, False))
    if surge == 0 and unavailable == 0:
        unavailable = 1
    return surge, unavailable


def retrieve_fenceposts(obj: dict, prefix: str, replicas: int) -> tuple[int, int]:
    """Read maxSurge/maxUnavailable at ``prefix`` ("" for a member
    deployment, "spec.template." for the federated object)."""
    surge = get_path(obj, prefix + "spec.strategy.rollingUpdate.maxSurge")
    unavailable = get_path(obj, prefix + "spec.strategy.rollingUpdate.maxUnavailable")
    return resolve_fenceposts(surge, unavailable, replicas)


@dataclass
class TargetStatus:
    """Observed member-deployment state (rolloutplan.go:166-177)."""

    replicas: int = 0  # member spec.replicas
    actual_replicas: int = 0  # member status.replicas
    available_replicas: int = 0  # member status.availableReplicas
    updated_replicas: int = 0  # latest-RS replicas, 0 unless template is current
    updated_available_replicas: int = 0
    current_new_replicas: int = 0  # latest-RS replicas of the member's own newest template
    current_new_available_replicas: int = 0
    updated: bool = False  # member template == desired revision
    max_surge: int = 0  # member's own current fenceposts
    max_unavailable: int = 0


@dataclass
class Target:
    """One member cluster in the planning problem
    (rolloutplan.go:179-184 + the budget arithmetic methods)."""

    cluster: str
    status: TargetStatus = field(default_factory=TargetStatus)
    desired_replicas: int = 0

    # -- remaining work ---------------------------------------------------
    def replicas_to_update(self) -> int:
        return max(0, self.status.replicas - self.status.updated_replicas)

    def replicas_to_updated_available(self) -> int:
        return max(0, self.status.replicas - self.status.updated_available_replicas)

    def replicas_to_update_currently(self) -> int:
        return max(0, self.status.replicas - self.status.current_new_replicas)

    def replicas_to_updated_available_currently(self) -> int:
        return max(
            0, self.status.replicas - self.status.current_new_available_replicas
        )

    def during_updating(self) -> bool:
        """(rolloutplan.go:514-524)"""
        if self.status.current_new_replicas < self.status.replicas:
            return True
        return self.status.updated and self.replicas_to_update() > 0

    def update_completed(self) -> bool:
        return self.replicas_to_update() == 0

    def is_surge(self) -> bool:
        return self.status.max_surge != 0 and self.status.max_unavailable == 0

    def flip(self, default_is_surge: bool) -> bool:
        """Surge-mode member under an unavailability-mode federation
        (rolloutplan.go:327-332)."""
        return (
            self.is_surge()
            and not default_is_surge
            and self.replicas_to_updated_available() > 0
        )

    # -- budget already held by this cluster ------------------------------
    def least_surge(self) -> int:
        res = max(0, self.status.actual_replicas - self.status.replicas)
        if not self.during_updating():
            return res
        return max(
            res, min(self.status.max_surge, res + self.replicas_to_update_currently())
        )

    def least_unavailable(self) -> int:
        res = max(0, self.status.replicas - self.status.available_replicas)
        if not self.during_updating():
            return res
        return max(
            res,
            min(
                self.status.max_unavailable,
                self.replicas_to_updated_available_currently(),
            ),
        )

    # -- budget grants (return (granted, spent-from-shared-pool)) ---------
    def grant_surge(self, max_surge: int, least_surge: int) -> tuple[int, int]:
        res = min(max_surge + least_surge, self.replicas_to_update())
        res = max(0, res)
        more = max(0, res - least_surge)
        if max_surge < 0 and least_surge > self.status.max_surge and res > self.status.max_surge:
            res = self.status.max_surge
        return res, more

    def grant_unavailable(
        self, max_unavailable: int, least_unavailable: int
    ) -> tuple[int, int]:
        res = min(max_unavailable + least_unavailable, self.replicas_to_updated_available())
        res = max(0, res)
        more = max(0, res - least_unavailable)
        if (
            max_unavailable < 0
            and least_unavailable > self.status.max_unavailable
            and res > self.status.max_unavailable
        ):
            res = self.status.max_unavailable
        return res, more

    def grant_scale_out(self, max_scale_out: int, least_surge: int) -> tuple[int, int]:
        res = min(max_scale_out + least_surge, self.desired_replicas - self.status.replicas)
        res = max(0, res)
        more = max(0, res - least_surge)
        return res, more

    def grant_scale_in(
        self, max_scale_in: int, least_unavailable: int
    ) -> tuple[int, int]:
        res = min(
            max_scale_in + least_unavailable,
            self.status.replicas - self.desired_replicas,
        )
        res = min(res, self.status.replicas)
        res = max(0, res)
        more = max(0, res - least_unavailable)
        return res, more

    # -- skip predicates (rolloutplan.go:334-362) -------------------------
    def skip_plan_for_update(self, max_surge: int, max_unavailable: int) -> bool:
        return (
            max_surge <= 0
            and max_unavailable <= 0
            and not self.status.updated
            and not self.during_updating()
            and self.least_surge() <= 0
            and self.least_unavailable() <= 0
        )

    def skip_plan_for_update_when_scaling_in(
        self, max_surge: int, max_unavailable: int, least_unavailable: int
    ) -> bool:
        if (
            max_surge <= 0
            and max_unavailable <= 0
            and not self.status.updated
            and not self.during_updating()
        ):
            if least_unavailable > 0:
                return False
            least_surge = self.least_surge()
            if self.desired_replicas < self.status.replicas:
                least_surge = 0
            return least_surge <= 0
        return False

    def skip_plan_for_scale_in(self, max_unavailable: int) -> bool:
        return max_unavailable <= 0 and self.least_unavailable() <= 0

    def skip_plan_for_scale_out(self, max_surge: int) -> bool:
        return max_surge <= 0 and self.least_surge() <= 0


def target_from_cluster_object(
    cluster: str,
    cluster_obj: Optional[dict],
    desired_replicas: int,
    desired_revision: str,
    replicas_spec_path: str,
    available_replicas_status_path: str,
) -> Target:
    """Member deployment -> Target (rolloutplan.go
    unstructuredObjToTargetInfo).  Raises RolloutPlanError when required
    observed state is missing — the caller falls back to a no-plan tick."""
    if cluster_obj is None:
        return Target(cluster=cluster, desired_replicas=desired_replicas)

    replicas = get_path(cluster_obj, replicas_spec_path)
    if replicas is None:
        raise RolloutPlanError(f"{cluster}: missing {replicas_spec_path}")
    try:
        replicas = int(replicas)
    except (TypeError, ValueError) as e:
        raise RolloutPlanError(f"{cluster}: malformed {replicas_spec_path}") from e
    max_surge, max_unavailable = retrieve_fenceposts(cluster_obj, "", replicas)

    ann = cluster_obj.get("metadata", {}).get("annotations", {})
    revision = ann.get(CURRENT_REVISION_ANNOTATION)
    if revision is None:
        raise RolloutPlanError(f"{cluster}: missing {CURRENT_REVISION_ANNOTATION}")
    # The template counts as updated as soon as it's dispatched; waiting
    # for the member's async annotation refresh would stall the plan
    # (rolloutplan.go:392-394).
    updated = revision == desired_revision

    if LATEST_RS_REPLICAS not in ann or LATEST_RS_AVAILABLE not in ann:
        raise RolloutPlanError(f"{cluster}: missing latest-replicaset annotations")
    if LATEST_RS_NAME not in ann:
        raise RolloutPlanError(f"{cluster}: missing {LATEST_RS_NAME}")
    try:
        current_new = int(ann[LATEST_RS_REPLICAS])
        current_new_available = int(ann[LATEST_RS_AVAILABLE])
    except ValueError as e:
        raise RolloutPlanError(
            f"{cluster}: malformed latest-replicaset annotations: {e}"
        ) from e
    # If the newest-RS annotations still describe the replicaset of the
    # PREVIOUS dispatched template, they say nothing about the new one
    # (rolloutplan.go:817-824).
    if ann.get(LAST_RS_NAME) is not None and ann.get(LAST_RS_NAME) == ann.get(LATEST_RS_NAME):
        current_new = current_new_available = 0

    updated_replicas = current_new if updated else 0
    updated_available = current_new_available if updated else 0

    available = get_path(cluster_obj, available_replicas_status_path)

    return Target(
        cluster=cluster,
        desired_replicas=desired_replicas,
        status=TargetStatus(
            replicas=replicas,
            actual_replicas=int(get_path(cluster_obj, "status.replicas", 0) or 0),
            available_replicas=int(available or 0),
            updated_replicas=updated_replicas,
            updated_available_replicas=updated_available,
            current_new_replicas=current_new,
            current_new_available_replicas=current_new_available,
            updated=updated,
            max_surge=max_surge,
            max_unavailable=max_unavailable,
        ),
    )


class RolloutPlanner:
    """(rolloutplan.go:452-568 + Plan())"""

    def __init__(self, key: str, fed_obj: dict, replicas: int):
        self.key = key
        self.replicas = replicas
        self.max_surge, self.max_unavailable = retrieve_fenceposts(
            fed_obj, "spec.template.", replicas
        )
        revision = fed_obj.get("metadata", {}).get("annotations", {}).get(
            CURRENT_REVISION_ANNOTATION
        )
        if revision is None:
            raise RolloutPlanError(
                f"{key}: federated object missing {CURRENT_REVISION_ANNOTATION}"
            )
        self.revision = revision
        self.targets: list[Target] = []

    @classmethod
    def from_params(
        cls, replicas: int, max_surge: int, max_unavailable: int
    ) -> "RolloutPlanner":
        """Direct construction from the planning parameters — the shape
        the reference's table tests build (`&RolloutPlanner{Targets,
        MaxSurge, MaxUnavailable, Replicas}`, rolloutplan_test.go);
        production goes through __init__, which derives the fenceposts
        from the federated object."""
        planner = cls.__new__(cls)
        planner.key = "golden"
        planner.replicas = replicas
        planner.max_surge = max_surge
        planner.max_unavailable = max_unavailable
        planner.revision = "golden-revision"
        planner.targets = []
        return planner

    def register(self, target: Target) -> None:
        self.targets.append(target)

    def is_surge(self) -> bool:
        return self.max_surge != 0 and self.max_unavailable == 0

    def _sorted_groups(self) -> tuple[list[Target], list[Target], list[Target]]:
        """(to_update, to_scale_out, to_scale_in), cluster-name ordered
        (rolloutplan.go sortTargets)."""
        targets = sorted(self.targets, key=lambda t: t.cluster)
        to_update, to_scale_out, to_scale_in = [], [], []
        for t in targets:
            change = t.desired_replicas - t.status.replicas
            if change < 0:
                to_scale_in.append(t)
            elif change > 0:
                to_scale_out.append(t)
            else:
                to_update.append(t)
        return to_update, to_scale_out, to_scale_in

    def is_scaling_event(self) -> bool:
        """Pure scaling (no template change anywhere): plans are empty —
        every cluster just takes its scheduled replicas
        (rolloutplan.go:507-527)."""
        _, to_scale_out, to_scale_in = self._sorted_groups()
        if to_scale_out and to_scale_in:
            return False
        if not to_scale_out and not to_scale_in:
            return False
        return all(
            t.update_completed() and not t.flip(self.is_surge())
            for t in self.targets
        )

    def remaining_max_surge(self) -> int:
        replicas = sum(t.status.replicas for t in self.targets)
        occupied = sum(t.least_surge() for t in self.targets)
        return self.max_surge - (replicas - self.replicas) - occupied

    def remaining_max_unavailable(self) -> int:
        replicas = sum(t.status.replicas for t in self.targets)
        occupied = sum(t.least_unavailable() for t in self.targets)
        return self.max_unavailable - (self.replicas - replicas) - occupied

    def _correct_fencepost(self, plan: RolloutPlan, t: Target) -> None:
        """(rolloutplan.go:94-113)"""
        if t.update_completed() and not t.flip(self.is_surge()):
            plan.max_surge = None
            plan.max_unavailable = None
        elif plan.max_surge == 0 and plan.max_unavailable == 0:
            if t.is_surge():
                plan.max_surge = 1
            else:
                plan.max_unavailable = 1

    def plan(self) -> dict[str, RolloutPlan]:
        """The five-pass budget walk (rolloutplan.go:568-692)."""
        to_update, to_scale_out, to_scale_in = self._sorted_groups()
        plans: dict[str, RolloutPlan] = {}

        if self.is_scaling_event():
            return {t.cluster: RolloutPlan() for t in self.targets}

        max_surge = self.remaining_max_surge()
        max_unavailable = self.remaining_max_unavailable()

        # 1. Upgrade targets waiting to scale out (at current size).
        for t in to_scale_out:
            if t.skip_plan_for_update(max_surge, max_unavailable):
                continue
            s, sm = t.grant_surge(max_surge, t.least_surge())
            u, um = t.grant_unavailable(max_unavailable, t.least_unavailable())
            max_surge -= sm
            max_unavailable -= um
            plan = RolloutPlan(
                replicas=t.status.replicas, max_surge=s, max_unavailable=u
            )
            self._correct_fencepost(plan, t)
            plans[t.cluster] = plan

        # 2. Shrink targets waiting to scale in (preferring the already-
        # unavailable replicas).
        for t in to_scale_in:
            if t.skip_plan_for_scale_in(max_unavailable):
                continue
            least_unavailable = 0 if t.during_updating() else t.least_unavailable()
            scale, more = t.grant_scale_in(max_unavailable, least_unavailable)
            max_unavailable -= more
            plans[t.cluster] = RolloutPlan(
                replicas=t.status.replicas - scale, only_patch_replicas=True
            )

        # 3. Upgrade in-place targets.
        for t in to_update:
            if t.skip_plan_for_update(max_surge, max_unavailable):
                continue
            s, sm = t.grant_surge(max_surge, t.least_surge())
            u, um = t.grant_unavailable(max_unavailable, t.least_unavailable())
            max_surge -= sm
            max_unavailable -= um
            plan = RolloutPlan(max_surge=s, max_unavailable=u)
            self._correct_fencepost(plan, t)
            plans[t.cluster] = plan

        # 4. Grow the scale-outs (only once their new RS exists).
        for t in to_scale_out:
            if t.skip_plan_for_scale_out(max_surge):
                continue
            if not t.status.updated and t.status.replicas != 0:
                continue
            least_surge = 0 if t.during_updating() else t.least_surge()
            scale, more = t.grant_scale_out(max_surge, least_surge)
            max_surge -= more
            plan = plans.get(t.cluster) or RolloutPlan()
            plan.replicas = t.status.replicas + scale
            plans[t.cluster] = plan

        # 5. Upgrade the scale-ins (their shrink may have freed budget).
        for t in to_scale_in:
            plan = plans.get(t.cluster) or RolloutPlan(replicas=t.status.replicas)
            least_unavailable = t.least_unavailable()
            if not t.during_updating():
                # Unavailable replicas already removed by the pass-2
                # shrink don't count against this cluster again.
                already_shrunk = t.status.replicas - (
                    plan.replicas if plan.replicas is not None else t.status.replicas
                )
                least_unavailable = max(0, least_unavailable - already_shrunk)
            if t.skip_plan_for_update_when_scaling_in(
                max_surge, max_unavailable, least_unavailable
            ):
                continue
            plan.only_patch_replicas = False
            s, sm = t.grant_surge(max_surge, t.least_surge())
            u, um = t.grant_unavailable(max_unavailable, least_unavailable)
            max_surge -= sm
            max_unavailable -= um
            plan.max_surge = s
            plan.max_unavailable = u
            self._correct_fencepost(plan, t)
            plans[t.cluster] = plan

        if not self._validate(plans):
            # An invalid plan dispatches nothing rather than something
            # that violates the federation-wide invariants.
            return {}
        return plans

    def _validate(self, plans: dict[str, RolloutPlan]) -> bool:
        """(rolloutplan.go validatePlans)"""
        planned = desired = current = 0
        for t in self.targets:
            desired += t.desired_replicas
            current += t.status.replicas
            plan = plans.get(t.cluster)
            if plan is None:
                # An unplanned cluster keeps its current size this tick.
                planned += t.status.replicas
            elif plan.replicas is not None:
                planned += plan.replicas
            else:
                planned += t.desired_replicas
        if self.replicas - desired > self.max_unavailable:
            return False
        low, high = min(desired, current), max(desired, current)
        if low - planned > self.max_unavailable or planned - high > self.max_surge:
            return False
        return True
