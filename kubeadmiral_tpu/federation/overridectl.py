"""The override-policy controller: per-cluster JSONPatch resolution.

Matches Override/ClusterOverridePolicies to federated objects via the
policy-name labels, resolves each placed cluster's ordered patch list
from the policies' overrideRules (cluster name / selector / affinity
criteria ANDed per rule), writes the result into ``spec.overrides`` under
this controller's name, and flips the pending-controllers pipeline
(reference: pkg/controllers/override/overridepolicy_controller.go:109-427,
util.go:45-230).
"""

from __future__ import annotations

from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models import policy as P
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import Conflict, FakeKube, NotFound, obj_key
from kubeadmiral_tpu.utils.labels import match_terms, matches_selector_set
from kubeadmiral_tpu.utils.unstructured import copy_json

OVERRIDE_POLICIES = "core.kubeadmiral.io/v1alpha1/overridepolicies"
CLUSTER_OVERRIDE_POLICIES = "core.kubeadmiral.io/v1alpha1/clusteroverridepolicies"

OVERRIDE_POLICY_NAME_LABEL = C.PREFIX + "override-policy-name"
CLUSTER_OVERRIDE_POLICY_NAME_LABEL = C.PREFIX + "cluster-override-policy-name"


class PolicyResolutionError(Exception):
    """Matched policy missing or malformed (terminal until it changes)."""


def is_cluster_matched(target_clusters: Optional[dict], cluster: dict) -> bool:
    """A rule's targetClusters vs one FederatedCluster; the three criteria
    are ANDed and each empty criterion matches everything
    (override/util.go:154-222)."""
    if not target_clusters:
        return True
    name = cluster["metadata"]["name"]
    labels = cluster["metadata"].get("labels", {}) or {}

    names = target_clusters.get("clusters") or []
    if names and name not in names:
        return False

    selector = target_clusters.get("clusterSelector") or {}
    if selector and not matches_selector_set(labels, selector):
        return False

    affinity = target_clusters.get("clusterAffinity") or []
    if affinity:
        terms = [P.parse_selector_term(t) for t in affinity]
        if not match_terms(labels, {"metadata.name": name}, terms):
            return False
    return True


def parse_overrides(policy_obj: dict, clusters: list[dict]) -> dict[str, list]:
    """One policy × placed clusters -> {cluster: [RFC6902 patches]}
    (override/util.go:99-141 parseOverrides)."""
    out: dict[str, list] = {}
    rules = policy_obj.get("spec", {}).get("overrideRules", []) or []
    for cluster in clusters:
        patches: list[dict] = []
        for rule in rules:
            if not is_cluster_matched(rule.get("targetClusters"), cluster):
                continue
            for overrider in rule.get("overriders", {}).get("jsonpatch", []) or []:
                patch = {
                    "op": overrider.get("operator", "replace"),
                    "path": overrider.get("path", ""),
                }
                if "value" in overrider:
                    patch["value"] = overrider["value"]
                patches.append(patch)
        if patches:
            out[cluster["metadata"]["name"]] = patches
    return out


def merge_overrides(dest: dict[str, list], src: dict[str, list]) -> dict[str, list]:
    for cluster, patches in src.items():
        dest.setdefault(cluster, []).extend(patches)
    return dest


class OverrideController:
    """Per-FTC controller resolving override policies into
    ``spec.overrides`` (overridepolicy_controller.go)."""

    name = C.OVERRIDE_CONTROLLER

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        self.host = host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._fed_resource = ftc.federated.resource
        self.worker = Worker(
            f"override-{ftc.name}", self.reconcile, metrics=self.metrics, clock=clock
        )
        # Watch-boundary trigger filter (common.metadata_change_sig):
        # status-only fed writes never re-enqueue.
        self._event_sigs: dict[str, int] = {}
        host.watch(self._fed_resource, self._on_object_event, replay=True)
        host.watch(OVERRIDE_POLICIES, self._on_policy_event, replay=False)
        host.watch(CLUSTER_OVERRIDE_POLICIES, self._on_policy_event, replay=False)
        host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)

    # -- event fan-in (controller.go:226-252) ----------------------------
    def _on_object_event(self, event: str, obj: dict) -> None:
        key = obj_key(obj)
        if event == "DELETED":
            self._event_sigs.pop(key, None)
            self.worker.enqueue(key)
            return
        # Override application reads spec (generation), labels (policy
        # binding) and policy annotations; status writes and the
        # per-sync-round syncing feedback never change the outcome.
        sig = C.metadata_change_sig(
            obj, ignore_annotations=(C.SOURCE_FEEDBACK_SYNCING,)
        )
        if self._event_sigs.get(key) == sig:
            return
        self._event_sigs[key] = sig
        if self.worker.is_own_thread():
            return  # echo of this controller's own spec.overrides write
        self.worker.enqueue(key)

    def _on_policy_event(self, event: str, obj: dict) -> None:
        pname = obj["metadata"]["name"]
        cluster_scoped = not obj["metadata"].get("namespace")
        label = (
            CLUSTER_OVERRIDE_POLICY_NAME_LABEL
            if cluster_scoped
            else OVERRIDE_POLICY_NAME_LABEL
        )
        matched: list[str] = []

        def check(fed: dict) -> None:
            if fed["metadata"].get("labels", {}).get(label) != pname:
                return
            if not cluster_scoped and fed["metadata"].get("namespace") != obj[
                "metadata"
            ].get("namespace"):
                return
            matched.append(obj_key(fed))

        self.host.scan(self._fed_resource, check)
        self.worker.enqueue_all(matched)

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        matched: list[str] = []

        def check(fed: dict) -> None:
            labels = fed["metadata"].get("labels", {}) or {}
            if labels.get(OVERRIDE_POLICY_NAME_LABEL) or labels.get(
                CLUSTER_OVERRIDE_POLICY_NAME_LABEL
            ):
                matched.append(obj_key(fed))

        self.host.scan(self._fed_resource, check)
        self.worker.enqueue_all(matched)

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- policy lookup (util.go:45-97) -----------------------------------
    def _matched_policies(self, fed_obj: dict) -> list[dict]:
        labels = fed_obj["metadata"].get("labels", {}) or {}
        policies = []
        # View reads: parse_overrides only reads the policy objects.
        getter = getattr(self.host, "try_get_view", self.host.try_get)

        cname = labels.get(CLUSTER_OVERRIDE_POLICY_NAME_LABEL)
        if cname is not None:
            if not cname:
                raise PolicyResolutionError("policy name cannot be empty")
            obj = getter(CLUSTER_OVERRIDE_POLICIES, cname)
            if obj is None:
                raise PolicyResolutionError(
                    f"ClusterOverridePolicy {cname} not found"
                )
            policies.append(obj)

        name = labels.get(OVERRIDE_POLICY_NAME_LABEL)
        if self.ftc.namespaced and name is not None:
            if not name:
                raise PolicyResolutionError("policy name cannot be empty")
            key = fed_obj["metadata"].get("namespace", "") + "/" + name
            obj = self.host.try_get(OVERRIDE_POLICIES, key)
            if obj is None:
                raise PolicyResolutionError(f"OverridePolicy {key} not found")
            policies.append(obj)
        return policies

    def _placed_clusters(self, fed_obj: dict) -> list[dict]:
        placed = C.all_placement_clusters(fed_obj)
        if getattr(self.host, "local_views", False):
            getter = self.host.try_get_view
            # Point view reads per placed cluster: O(placed), not
            # O(members).  Scanning list_view(FEDERATED_CLUSTERS) here
            # was the top profile sink at 500 members (every reconcile
            # walked the whole fleet).
            out = []
            for name in sorted(placed):
                c = getter(C.FEDERATED_CLUSTERS, name)
                if c is not None:
                    out.append(c)
            return out
        # Remote stores: one LIST round trip beats a GET per cluster.
        return [
            c
            for c in self.host.list_view(C.FEDERATED_CLUSTERS)
            if c["metadata"]["name"] in placed
        ]

    # -- reconcile (controller.go:254-377) -------------------------------
    def reconcile(self, key: str) -> Result:
        self.metrics.counter("override.throughput")
        # View read: the steady-state reconcile (overrides already
        # current, nothing pending) touches nothing and pays no copy.
        view = self._try_get_view(key)
        if view is None or view["metadata"].get("deletionTimestamp"):
            return Result.ok()

        try:
            if not pending.dependencies_fulfilled(view, self.name):
                return Result.ok()
        except KeyError:
            return Result.ok()  # not initialized by federate yet

        try:
            policies = self._matched_policies(view)
        except PolicyResolutionError:
            # A dangling policy reference: nothing to do until the policy
            # appears (its creation re-enqueues us).
            return Result.ok()

        clusters = self._placed_clusters(view)
        overrides: dict[str, list] = {}
        for policy in policies:
            merge_overrides(overrides, parse_overrides(policy, clusters))

        needs_update = C.get_overrides(view, self.name) != overrides
        if not needs_update and not pending.would_update(
            view, self.name, False, self.ftc.controller_groups
        ):
            return Result.ok()

        fed_obj = copy_json(view)
        if needs_update:
            C.set_overrides(fed_obj, self.name, overrides)
        pending.update_pending(
            fed_obj, self.name, needs_update, self.ftc.controller_groups
        )
        try:
            # Result discarded: skip the deep copy of the stored node.
            self.host.update(self._fed_resource, fed_obj, _copy_result=False)
        except Conflict:
            return Result.retry()
        except NotFound:
            return Result.ok()
        return Result.ok()

    def _try_get_view(self, key: str):
        """No-copy read when the store offers one (FakeKube); HTTP
        clients return fresh parses either way."""
        getter = getattr(self.host, "try_get_view", self.host.try_get)
        return getter(self._fed_resource, key)
