"""Consistent-hash key→shard routing — the shard-router seam.

ROADMAP item 3's second move shards the object space across N engine
replicas.  This module cuts the seam first, shipped with
``shard_count=1`` wired in at the informer/worker boundary
(``runtime/worker.py`` consults :func:`get_default` on every enqueue),
so standing up replicas later is a knob change, not a re-plumb of the
intake path.

Routing must be

* **stable across process restarts** — a replica that restarts must
  route every key exactly where its predecessor did, or two replicas
  would both (or neither) own an object mid-failover.  Python's builtin
  ``hash()`` is salted per process, so keys are digested with BLAKE2b;
* **consistent under resharding** — growing ``shard_count`` from N to
  N+1 should move ~1/(N+1) of the keys, not reshuffle the world (every
  moved key costs a relist + re-reconcile on its new owner).  The
  64-bit digest feeds Lamping–Veach jump consistent hashing, which has
  exactly that property with zero routing state.

Knobs (resolved once per :class:`ShardMap`, like the admission knobs):

* ``KT_SHARD_COUNT`` — total engine replicas (default 1: this process
  owns everything and routing is identity);
* ``KT_SHARD_INDEX`` — this replica's shard (default 0).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Optional

_MASK64 = (1 << 64) - 1


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def jump_hash(key64: int, buckets: int) -> int:
    """Lamping–Veach jump consistent hash: 64-bit key → bucket in
    [0, buckets).  Growing ``buckets`` by one moves only ~1/buckets of
    the keyspace, always onto the NEW bucket."""
    if buckets <= 1:
        return 0
    b, j = -1, 0
    while j < buckets:
        b = j
        key64 = (key64 * 2862933555777941757 + 1) & _MASK64
        j = int((b + 1) * (float(1 << 31) / float((key64 >> 33) + 1)))
    return b


def key_digest(key: str) -> int:
    """Process-stable 64-bit digest of an object key (BLAKE2b, not the
    per-process-salted builtin ``hash``)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class ShardMap:
    """key → shard routing for one replica."""

    def __init__(
        self,
        shard_count: Optional[int] = None,
        shard_index: Optional[int] = None,
    ):
        count = (
            _env_int("KT_SHARD_COUNT", 1) if shard_count is None else shard_count
        )
        index = (
            _env_int("KT_SHARD_INDEX", 0) if shard_index is None else shard_index
        )
        self.shard_count = max(1, count)
        self.shard_index = min(max(0, index), self.shard_count - 1)

    def shard_of(self, key: str) -> int:
        if self.shard_count == 1:
            return 0
        return jump_hash(key_digest(key), self.shard_count)

    def owns(self, key: str) -> bool:
        """Does THIS replica reconcile ``key``?  The single check the
        informer/worker boundary makes per enqueue; with shard_count=1
        it is one attribute compare (identity routing)."""
        if self.shard_count == 1:
            return True
        return self.shard_of(key) == self.shard_index


# -- process default -------------------------------------------------------
_default: Optional[ShardMap] = None
_default_lock = threading.Lock()


def get_default() -> ShardMap:
    global _default
    m = _default
    if m is None:
        with _default_lock:
            m = _default
            if m is None:
                m = _default = ShardMap()
    return m


def set_default(shardmap: ShardMap) -> Optional[ShardMap]:
    """Install a map as the process default (tests, embedders);
    returns the previous one."""
    global _default
    with _default_lock:
        prev = _default
        _default = shardmap
    return prev


def reset_default() -> ShardMap:
    """Fresh default map (re-reads the KT_SHARD_* environment)."""
    set_default(ShardMap())
    return get_default()
