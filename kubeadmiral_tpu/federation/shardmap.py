"""Consistent-hash key→shard routing — the shard-router seam.

ROADMAP item 3's second move shards the object space across N engine
replicas.  This module cuts the seam first, shipped with
``shard_count=1`` wired in at the informer/worker boundary
(``runtime/worker.py`` consults :func:`get_default` on every enqueue),
so standing up replicas later is a knob change, not a re-plumb of the
intake path.

Routing must be

* **stable across process restarts** — a replica that restarts must
  route every key exactly where its predecessor did, or two replicas
  would both (or neither) own an object mid-failover.  Python's builtin
  ``hash()`` is salted per process, so keys are digested with BLAKE2b;
* **consistent under resharding** — growing ``shard_count`` from N to
  N+1 should move ~1/(N+1) of the keys, not reshuffle the world (every
  moved key costs a relist + re-reconcile on its new owner).  The
  64-bit digest feeds Lamping–Veach jump consistent hashing, which has
  exactly that property with zero routing state.

Knobs (resolved once per :class:`ShardMap`, like the admission knobs):

* ``KT_SHARD_COUNT`` — total engine replicas (default 1: this process
  owns everything and routing is identity);
* ``KT_SHARD_INDEX`` — this replica's shard (default 0).
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
from typing import Iterator, Optional

_MASK64 = (1 << 64) - 1

# Control-plane broadcast keys: pseudo-keys every replica must process
# regardless of routing (cluster lifecycle is global state — a replica
# that never sees "cluster::pool-a" would keep planning against a
# member that left the fleet).  Prefix-matched, not hashed.
BROADCAST_PREFIXES = ("cluster::",)


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def jump_hash(key64: int, buckets: int) -> int:
    """Lamping–Veach jump consistent hash: 64-bit key → bucket in
    [0, buckets).  Growing ``buckets`` by one moves only ~1/buckets of
    the keyspace, always onto the NEW bucket."""
    if buckets <= 1:
        return 0
    b, j = -1, 0
    while j < buckets:
        b = j
        key64 = (key64 * 2862933555777941757 + 1) & _MASK64
        j = int((b + 1) * (float(1 << 31) / float((key64 >> 33) + 1)))
    return b


def key_digest(key: str) -> int:
    """Process-stable 64-bit digest of an object key (BLAKE2b, not the
    per-process-salted builtin ``hash``)."""
    return int.from_bytes(
        hashlib.blake2b(key.encode("utf-8"), digest_size=8).digest(), "big"
    )


class ShardMap:
    """key → shard routing for one replica.

    ``epoch`` is the resize generation: a live resize (``resize()``)
    bumps it, and per-shard snapshot artifacts carry it so a standby
    never restores placements routed under a different shard layout.
    """

    def __init__(
        self,
        shard_count: Optional[int] = None,
        shard_index: Optional[int] = None,
        epoch: int = 0,
    ):
        count = (
            _env_int("KT_SHARD_COUNT", 1) if shard_count is None else shard_count
        )
        index = (
            _env_int("KT_SHARD_INDEX", 0) if shard_index is None else shard_index
        )
        self.shard_count = max(1, count)
        self.shard_index = min(max(0, index), self.shard_count - 1)
        self.epoch = int(epoch)

    def shard_of(self, key: str) -> int:
        if self.shard_count == 1:
            return 0
        if key.startswith(BROADCAST_PREFIXES):
            return self.shard_index
        return jump_hash(key_digest(key), self.shard_count)

    def owns(self, key: str) -> bool:
        """Does THIS replica reconcile ``key``?  The single check the
        informer/worker boundary makes per enqueue; with shard_count=1
        it is one attribute compare (identity routing).  Broadcast
        control keys (``cluster::*``) are owned by every replica."""
        if self.shard_count == 1:
            return True
        if key.startswith(BROADCAST_PREFIXES):
            return True
        return self.shard_of(key) == self.shard_index

    def resize(self, shard_count: int, shard_index: Optional[int] = None) -> "ShardMap":
        """The live-resize step: a NEW map at the next epoch.  Jump
        hashing guarantees N→N+1 moves only ~1/(N+1) of the keyspace
        (always onto the new shard); callers swap the returned map in
        atomically (``set_default``) so no key is double-owned — a key
        is routed by exactly one installed map at any instant."""
        return ShardMap(
            shard_count,
            self.shard_index if shard_index is None else shard_index,
            epoch=self.epoch + 1,
        )

    def moved_keys(self, keys, new: "ShardMap") -> list[str]:
        """Keys of ``keys`` owned HERE under self but not under ``new``
        — the handoff set a resize must re-enqueue on the new owners."""
        return [
            k for k in keys
            if self.owns(k) and not new.owns(k)
            and not k.startswith(BROADCAST_PREFIXES)
        ]

    def describe(self) -> dict:
        """The /debug/shards ownership block for this replica."""
        return {
            "shard_count": self.shard_count,
            "shard_index": self.shard_index,
            "epoch": self.epoch,
            "identity": self.shard_count == 1,
        }


# -- process default -------------------------------------------------------
_default: Optional[ShardMap] = None
_default_lock = threading.Lock()


def get_default() -> ShardMap:
    global _default
    m = _default
    if m is None:
        with _default_lock:
            m = _default
            if m is None:
                m = _default = ShardMap()
    return m


def set_default(shardmap: ShardMap) -> Optional[ShardMap]:
    """Install a map as the process default (tests, embedders);
    returns the previous one."""
    global _default
    with _default_lock:
        prev = _default
        _default = shardmap
    return prev


def reset_default() -> ShardMap:
    """Fresh default map (re-reads the KT_SHARD_* environment)."""
    set_default(ShardMap())
    return get_default()


@contextlib.contextmanager
def scoped(shardmap: ShardMap) -> Iterator[ShardMap]:
    """Install ``shardmap`` as the process default for the duration of
    the block, restoring the previous default on exit.

    This is the in-process replica-set construction seam: workers
    resolve :func:`get_default` ONCE at construction, so building a
    replica's whole controller stack inside ``scoped(ShardMap(n, i))``
    shards every one of its intake boundaries without threading a map
    through each constructor.  NOT safe for concurrent construction of
    two replicas on different threads — construct sequentially (they
    can then RUN concurrently; each holds its own resolved map).
    """
    prev = set_default(shardmap)
    try:
        yield shardmap
    finally:
        set_default(prev if prev is not None else ShardMap())
