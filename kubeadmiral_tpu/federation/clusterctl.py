"""The FederatedCluster controller: join handshake + status heartbeat.

Lifecycle of a member cluster (reference:
pkg/controllers/federatedcluster/controller.go, clusterjoin.go,
clusterstatus.go, util.go):

* join — create the federation system namespace in the member (annotated
  with the FederatedCluster UID so a cluster already owned by another
  control plane is detected as unjoinable), an authorized service
  account + token secret, and save the token into the host-side cluster
  secret; then flip the Joined condition.
* heartbeat — per-cluster periodic status collection: a /healthz-style
  reachability probe drives Offline/Ready conditions; when ready, node +
  pod listings aggregate into allocatable/available resource totals and
  a discovery pass records the cluster's API resource types.
* removal — on deletion, joined clusters get their member-side system
  namespace cleaned up once every per-FTC sync finalizer has let go.
"""

from __future__ import annotations

import time
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.transport import breaker as B
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    ClusterFleet,
    Conflict,
    FakeKube,
    NotFound,
)
from kubeadmiral_tpu.utils.quantity import cpu_to_millis, to_int_value

FEDERATED_CLUSTERS = C.FEDERATED_CLUSTERS
FED_SYSTEM_NAMESPACE = "kube-admiral-system"

# Member-side namespace annotation marking ownership
# (clusterjoin.go FederatedClusterUID).
CLUSTER_UID_ANNOTATION = C.PREFIX + "federated-cluster-uid"

# Condition types (types_federatedcluster.go).
JOINED = "Joined"
READY = "Ready"
OFFLINE = "Offline"

# Condition reasons (clusterjoin.go / clusterstatus.go).
JOIN_SUCCEEDED = "JoinSucceeded"
TOKEN_NOT_OBTAINED = "TokenNotObtained"
CLUSTER_UNJOINABLE = "ClusterUnjoinable"
JOIN_TIMEOUT_EXCEEDED = "JoinTimeoutExceeded"
CLUSTER_READY = "ClusterReady"
CLUSTER_NOT_REACHABLE = "ClusterNotReachable"
CLUSTER_HEALTHZ_NOT_OK = "HealthzNotOk"
RESOURCE_COLLECTION_FAILED = "ClusterResourceCollectionFailed"
# The member answers healthz but its write/read path tripped the
# per-member circuit breaker (transport/breaker.py): the scheduler's
# filter stage must see it unhealthy the same tick the breaker opens.
MEMBER_BREAKER_OPEN = "MemberBreakerOpen"

# Annotation on the FederatedCluster recording that join steps ran and
# member-side cleanup is owed on removal (controller.go joinPerformed).
JOIN_PERFORMED = C.PREFIX + "join-performed"

NODES = "v1/nodes"
PODS = "v1/pods"
NAMESPACES = "v1/namespaces"
SERVICE_ACCOUNTS = "v1/serviceaccounts"
SECRETS = "v1/secrets"


def get_condition(cluster: dict, ctype: str) -> Optional[dict]:
    for cond in cluster.get("status", {}).get("conditions", []):
        if cond.get("type") == ctype:
            return cond
    return None


def set_condition(cluster: dict, ctype: str, status: str, reason: str = "") -> bool:
    """Idempotent condition write; returns True when it changed."""
    conds = cluster.setdefault("status", {}).setdefault("conditions", [])
    for cond in conds:
        if cond.get("type") == ctype:
            if cond.get("status") == status and cond.get("reason") == reason:
                return False
            cond["status"] = status
            cond["reason"] = reason
            return True
    conds.append({"type": ctype, "status": status, "reason": reason})
    return True


def is_node_schedulable(node: dict) -> bool:
    """(util.go:114-131 isNodeSchedulable)."""
    spec = node.get("spec", {})
    if spec.get("unschedulable"):
        return False
    for taint in spec.get("taints", []) or []:
        if taint.get("effect") in ("NoSchedule", "NoExecute"):
            return False
    for cond in node.get("status", {}).get("conditions", []) or []:
        if cond.get("type") == "Ready" and cond.get("status") != "True":
            return False
    return True


def _parse_req(raw: dict) -> dict[str, int]:
    out = {}
    for name, q in (raw or {}).items():
        out[name] = cpu_to_millis(q) if name == "cpu" else to_int_value(q)
    return out


def pod_resource_requests(pod: dict) -> dict[str, int]:
    """max(sum(containers), initContainers...) + overhead
    (util.go:155-175 getPodResourceRequests)."""
    reqs: dict[str, int] = {}
    spec = pod.get("spec", {})
    for container in spec.get("containers", []) or []:
        for name, v in _parse_req(
            container.get("resources", {}).get("requests", {})
        ).items():
            reqs[name] = reqs.get(name, 0) + v
    for container in spec.get("initContainers", []) or []:
        for name, v in _parse_req(
            container.get("resources", {}).get("requests", {})
        ).items():
            if v > reqs.get(name, 0):
                reqs[name] = v
    for name, v in _parse_req(spec.get("overhead", {})).items():
        reqs[name] = reqs.get(name, 0) + v
    return reqs


def aggregate_resources(
    nodes: list[dict], pods: list[dict]
) -> tuple[dict[str, int], dict[str, int], int]:
    """(allocatable, available, schedulable_node_count) in canonical ints
    (cpu milli-units) — util.go:177-213 aggregateResources."""
    allocatable: dict[str, int] = {}
    schedulable = 0
    for node in nodes:
        if not is_node_schedulable(node):
            continue
        schedulable += 1
        for name, v in _parse_req(node.get("status", {}).get("allocatable", {})).items():
            if name == "pods":
                continue
            allocatable[name] = allocatable.get(name, 0) + v

    available = dict(allocatable)
    for pod in pods:
        if pod.get("status", {}).get("phase") in ("Succeeded", "Failed"):
            continue
        for name, v in pod_resource_requests(pod).items():
            if name in available:
                available[name] -= v
    return allocatable, available, schedulable


def format_resources(res: dict[str, int]) -> dict[str, str]:
    """Canonical ints back to quantity strings (cpu millis -> 'Nm')."""
    out = {}
    for name, v in res.items():
        out[name] = f"{v}m" if name == "cpu" else str(v)
    return out


class FederatedClusterController:
    """Always-on controller owning FederatedCluster lifecycle."""

    name = "cluster-controller"

    def __init__(
        self,
        fleet: ClusterFleet,
        metrics: Optional[Metrics] = None,
        resync_seconds: float = 10.0,
        join_timeout: float = 600.0,
        clock=None,
        api_resource_probe: Optional[list[str]] = None,
    ):
        self.fleet = fleet
        self.host = fleet.host
        self.metrics = metrics or Metrics()
        self.resync_seconds = resync_seconds
        self.join_timeout = join_timeout
        # GVK strings advertised when the member serves the resource; in a
        # real deployment this comes from discovery documents.
        self.api_resource_probe = api_resource_probe
        self._clock = clock or time.monotonic
        # member client id -> (probe time, advertised GVKs); see
        # _discover_api_types.
        self._api_discovery_cache: dict[int, tuple[float, list[str]]] = {}
        self._discovery_ttl = max(resync_seconds * 6, 60.0)
        # First join-failure time per cluster, for the join timeout
        # (clusterjoin.go:99-115 checks the Joined condition's
        # lastTransitionTime; conditions here don't carry timestamps, so
        # the controller tracks it in memory — state is lost on restart,
        # which only extends the timeout window).
        self._join_failed_at: dict[str, float] = {}
        # Per-member circuit breakers shared across this fleet's
        # controllers: the heartbeat's healthz probe doubles as the
        # breaker's half-open probe, and breaker transitions re-enqueue
        # the cluster so its Ready condition flips the SAME tick the
        # dispatch path discovers a sick member.
        self.breakers = B.for_fleet(fleet, metrics=self.metrics)
        self.worker = Worker(
            "cluster-controller", self.reconcile, metrics=self.metrics, clock=clock
        )
        self.breakers.on_transition(self._on_breaker_transition)
        self.host.watch(FEDERATED_CLUSTERS, self._on_event, replay=True)

    def _on_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj["metadata"]["name"])

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        self.worker.enqueue(name)

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    def _member(self, name: str) -> Optional[FakeKube]:
        try:
            return self.fleet.member(name)
        except NotFound:
            return None

    # -- reconcile (controller.go:183-351) -------------------------------
    def reconcile(self, key: str) -> Result:
        self.metrics.counter("cluster-controller.throughput")
        cluster = self.host.try_get(FEDERATED_CLUSTERS, key)
        if cluster is None:
            return Result.ok()

        if cluster["metadata"].get("deletionTimestamp"):
            return self._handle_terminating(cluster)

        if C.CLUSTER_FINALIZER not in cluster["metadata"].get("finalizers", []):
            cluster["metadata"].setdefault("finalizers", []).append(
                C.CLUSTER_FINALIZER
            )
            try:
                cluster = self.host.update(FEDERATED_CLUSTERS, cluster)
            except (Conflict, NotFound):
                return Result.retry()

        joined = get_condition(cluster, JOINED)
        if joined is None or joined.get("status") != "True":
            if joined is not None and joined.get("reason") in (
                CLUSTER_UNJOINABLE,
                JOIN_TIMEOUT_EXCEEDED,
            ):
                return Result.ok()  # terminal state (controller.go:226-232)
            name = cluster["metadata"]["name"]
            started = self._join_failed_at.get(name)
            if started is not None and self._clock() - started > self.join_timeout:
                # Join timed out: terminal failure (clusterjoin.go:99-115).
                self._join_failed_at.pop(name, None)
                return self._set_joined(
                    cluster, "False", JOIN_TIMEOUT_EXCEEDED, retry=False
                )
            try:
                result = self._join(cluster)
            except Exception:
                # A member dropping mid-handshake (partition, injected
                # fault) is a retryable join failure, not a controller
                # panic.
                self.breakers.for_member(name).record_failure()
                result = self._set_joined(
                    cluster, "False", TOKEN_NOT_OBTAINED, retry=True
                )
            if not result.success:
                self._join_failed_at.setdefault(name, self._clock())
                return result
            self._join_failed_at.pop(name, None)

        return self._collect_status(cluster["metadata"]["name"])

    # -- join handshake (clusterjoin.go:83-580) --------------------------
    def _join(self, cluster: dict) -> Result:
        name = cluster["metadata"]["name"]
        uid = cluster["metadata"].get("uid", "")
        member = self._member(name)
        if member is None:
            return self._set_joined(
                cluster, "False", TOKEN_NOT_OBTAINED, retry=True
            )

        # System namespace: create or verify ownership.
        ns = member.try_get(NAMESPACES, FED_SYSTEM_NAMESPACE)
        if ns is None:
            try:
                member.create(
                    NAMESPACES,
                    {
                        "apiVersion": "v1",
                        "kind": "Namespace",
                        "metadata": {
                            "name": FED_SYSTEM_NAMESPACE,
                            "annotations": {CLUSTER_UID_ANNOTATION: uid},
                        },
                    },
                )
            except AlreadyExists:
                pass
        elif ns["metadata"].get("annotations", {}).get(CLUSTER_UID_ANNOTATION) != uid:
            # Owned by another control plane: terminal unjoinable state.
            return self._set_joined(cluster, "False", CLUSTER_UNJOINABLE, retry=False)

        # Authorized service account + token, saved into the host secret
        # (clusterjoin.go:241-580 getAndSaveClusterToken).
        sa_name = f"kubeadmiral-{name}"
        if member.try_get(SERVICE_ACCOUNTS, f"{FED_SYSTEM_NAMESPACE}/{sa_name}") is None:
            try:
                member.create(
                    SERVICE_ACCOUNTS,
                    {
                        "apiVersion": "v1",
                        "kind": "ServiceAccount",
                        "metadata": {
                            "name": sa_name,
                            "namespace": FED_SYSTEM_NAMESPACE,
                        },
                    },
                )
            except AlreadyExists:
                pass
        # Real members (kwok-lite HTTP apiservers) mint a token secret
        # for the new service account — prefer it, as the reference does
        # (clusterjoin.go:449-529 waits for the SA token secret).  Bare
        # FakeKube members have no token controller; fall back to a
        # deterministic synthetic token.
        sa_token_secret = member.try_get(
            SECRETS, f"{FED_SYSTEM_NAMESPACE}/{sa_name}-token"
        )
        token = (sa_token_secret or {}).get("data", {}).get(
            "token"
        ) or f"token-{name}-{uid}"
        secret_name = cluster.get("spec", {}).get("secretRef", {}).get(
            "name"
        ) or f"{name}-secret"
        host_key = f"{FED_SYSTEM_NAMESPACE}/{secret_name}"
        secret = self.host.try_get(SECRETS, host_key)
        if secret is None:
            try:
                self.host.create(
                    SECRETS,
                    {
                        "apiVersion": "v1",
                        "kind": "Secret",
                        "metadata": {
                            "name": secret_name,
                            "namespace": FED_SYSTEM_NAMESPACE,
                        },
                        "data": {"token": token, "service-account": sa_name},
                    },
                )
            except AlreadyExists:
                pass
        else:
            if secret.get("data", {}).get("token") != token:
                secret.setdefault("data", {})["token"] = token
                try:
                    self.host.update(SECRETS, secret)
                except (Conflict, NotFound):
                    return Result.retry()

        cluster["metadata"].setdefault("annotations", {})[JOIN_PERFORMED] = "true"
        try:
            cluster = self.host.update(FEDERATED_CLUSTERS, cluster)
        except (Conflict, NotFound):
            return Result.retry()
        return self._set_joined(cluster, "True", JOIN_SUCCEEDED, retry=False)

    def _set_joined(
        self, cluster: dict, status: str, reason: str, retry: bool
    ) -> Result:
        if set_condition(cluster, JOINED, status, reason):
            try:
                self.host.update_status(FEDERATED_CLUSTERS, cluster)
            except (Conflict, NotFound):
                return Result.retry()
        if status == "True":
            return Result.ok()
        return Result.retry() if retry else Result.ok()

    # -- status heartbeat (clusterstatus.go:64-278) ----------------------
    def _collect_status(self, name: str) -> Result:
        cluster = self.host.try_get(FEDERATED_CLUSTERS, name)
        if cluster is None:
            return Result.ok()
        member = self._member(name)

        if member is None:
            # Unreachable: Offline=True, Ready=Unknown.
            changed = set_condition(cluster, OFFLINE, "True", CLUSTER_NOT_REACHABLE)
            changed |= set_condition(cluster, READY, "Unknown", CLUSTER_NOT_REACHABLE)
        else:
            # The healthz probe is also the breaker's out-of-band probe:
            # its latency feeds member_probe_latency, its success closes
            # a cooled-down breaker (the half-open contract), its
            # failure is breaker evidence like any other round trip.
            breaker = self.breakers.for_member(name)
            if not breaker.allow(consume_probe=False):
                # Open window still cooling: a probe CANNOT close the
                # breaker yet (record_success(probe=True) honors the
                # cool-down), so don't park the heartbeat worker on a
                # dead socket for nothing — once the window elapses,
                # allow() flips half-open and the next heartbeat probes
                # for real.
                changed = set_condition(cluster, OFFLINE, "False", "")
                changed |= set_condition(
                    cluster, READY, "False", MEMBER_BREAKER_OPEN
                )
                if changed:
                    try:
                        self.host.update_status(FEDERATED_CLUSTERS, cluster)
                    except Conflict:
                        return Result.retry()
                    except NotFound:
                        return Result.ok()
                return Result.after(
                    min(self.resync_seconds, self.breakers.config.open_seconds)
                )
            start = time.perf_counter()
            try:
                healthy = bool(member.healthy)
            except Exception:
                healthy = False
            latency = time.perf_counter() - start
            self.metrics.histogram("member_probe_latency", latency, cluster=name)
            if healthy:
                breaker.record_success(latency, probe=True)
            else:
                breaker.record_failure(latency_s=latency)
            if not healthy:
                changed = set_condition(cluster, OFFLINE, "False", "")
                changed |= set_condition(
                    cluster, READY, "False", CLUSTER_HEALTHZ_NOT_OK
                )
            elif not breaker.allow(consume_probe=False):
                # healthz answers but the read/write path tripped the
                # breaker (erroring or stalling member): not schedulable
                # until the breaker closes.
                changed = set_condition(cluster, OFFLINE, "False", "")
                changed |= set_condition(
                    cluster, READY, "False", MEMBER_BREAKER_OPEN
                )
            else:
                changed = set_condition(cluster, OFFLINE, "False", "")
                try:
                    resources_changed = self._update_resources(cluster, member)
                except Exception:
                    # healthz passed but the listings failed (member
                    # dropped between probes): collection failure, not a
                    # worker panic (clusterstatus.go:204-278).
                    breaker.record_failure()
                    changed |= set_condition(
                        cluster, READY, "False", RESOURCE_COLLECTION_FAILED
                    )
                else:
                    changed |= set_condition(cluster, READY, "True", CLUSTER_READY)
                    changed |= resources_changed

        if changed:
            try:
                self.host.update_status(FEDERATED_CLUSTERS, cluster)
            except Conflict:
                return Result.retry()
            except NotFound:
                return Result.ok()
        return Result.after(self.resync_seconds)

    def _update_resources(self, cluster: dict, member: FakeKube) -> bool:
        # View reads: aggregation only sums parsed quantities.
        nodes = member.list_view(NODES)
        pods = member.list_view(PODS)
        allocatable, available, schedulable = aggregate_resources(nodes, pods)
        status = cluster.setdefault("status", {})
        desired = {
            "schedulableNodes": schedulable,
            "allocatable": format_resources(allocatable),
            "available": format_resources(available),
        }
        changed = False
        if status.get("resources") != desired:
            status["resources"] = desired
            changed = True
        api_types = self.api_resource_probe
        if api_types is None:
            # Discovery fallback (the reference reads the member's
            # discovery documents, clusterstatus.go:204-268): probe the
            # member with a LIST per FTC-registered source type; a type
            # it serves is advertised in apiResourceTypes, which gates
            # scheduling per GVK (ops/filters APIResources).
            api_types = self._discover_api_types(member)
        if api_types is not None and status.get("apiResourceTypes") != api_types:
            status["apiResourceTypes"] = list(api_types)
            changed = True
        return changed

    def _discover_api_types(self, member: FakeKube) -> Optional[list[str]]:
        from kubeadmiral_tpu.models.ftc import FEDERATED_TYPE_CONFIGS, parse_ftc
        from kubeadmiral_tpu.testing.fakekube import NotFound

        # A fresh probe round trips once per FTC type; cache per member
        # client with a TTL so steady-state heartbeats don't re-probe
        # (the reference reads cheap discovery documents; our transport
        # has no discovery endpoint, so LIST-probing stands in).
        now = self._clock()
        cached = self._api_discovery_cache.get(id(member))
        if cached is not None and now - cached[0] < self._discovery_ttl:
            return cached[1]
        try:
            ftc_objs = self.host.list_view(FEDERATED_TYPE_CONFIGS)
        except AttributeError:
            ftc_objs = self.host.list(FEDERATED_TYPE_CONFIGS)
        except Exception:
            return None
        advertised = []
        for obj in ftc_objs:
            try:
                ftc = parse_ftc(obj)
            except Exception:
                continue  # malformed FTC: not a member problem
            try:
                probe = getattr(member, "keys", None) or (
                    member.list_view
                    if hasattr(member, "list_view")
                    else member.list
                )
                probe(ftc.source.resource)
            except NotFound:
                continue  # the member genuinely doesn't serve this type
            except Exception:
                # Transient member error: do NOT shrink the advertised
                # set (a dropped GVK would filter a healthy cluster out
                # of scheduling); keep whatever was last known.
                return cached[1] if cached is not None else None
            advertised.append(ftc.source.gvk)
        result = sorted(advertised)
        self._api_discovery_cache[id(member)] = (now, result)
        return result

    # -- removal (controller.go:353-445) ---------------------------------
    def _handle_terminating(self, cluster: dict) -> Result:
        name = cluster["metadata"]["name"]
        fins = cluster["metadata"].get("finalizers", [])
        if C.CLUSTER_FINALIZER not in fins:
            return Result.ok()

        # Per-FTC sync controllers hold their own finalizers until member
        # objects are cleaned up; wait for them to let go first.
        others = [f for f in fins if f != C.CLUSTER_FINALIZER]
        if others:
            return Result.after(1.0)

        joined = get_condition(cluster, JOINED)
        performed = (
            cluster["metadata"].get("annotations", {}).get(JOIN_PERFORMED) == "true"
        )
        if joined is not None and joined.get("status") == "True" and performed:
            member = self._member(name)
            if member is not None and member.healthy:
                # Deletion order keeps our own credential alive until the
                # last call: plain secrets first, then the namespace,
                # then ServiceAccounts LAST — deleting the SA revokes the
                # token this very client authenticates with (the member's
                # token controller also GCs the "<sa>-token" secret, so
                # nothing must come after).
                prefix = FED_SYSTEM_NAMESPACE + "/"

                def member_delete(res: str, key: str) -> bool:
                    """False = our credential is gone (expected once our
                    own SA is deleted); transient member errors RAISE so
                    the Worker retries with the finalizer still held —
                    cleanup must never silently half-finish."""
                    try:
                        member.delete(res, key)
                    except NotFound:
                        pass
                    except Exception as e:
                        msg = str(e)
                        if "401" in msg or "Unauthorized" in msg:
                            return False
                        raise
                    return True

                own_sa = prefix + f"kubeadmiral-{name}"
                # Our own SA goes LAST: deleting it revokes the very
                # token this client authenticates with.
                sa_keys = sorted(
                    (k for k in member.keys(SERVICE_ACCOUNTS) if k.startswith(prefix)),
                    key=lambda k: (k == own_sa, k),
                )
                token_names = {k.split("/", 1)[1] + "-token" for k in sa_keys}
                for key in member.keys(SECRETS):
                    if key.startswith(prefix) and key.split("/", 1)[1] not in token_names:
                        member_delete(SECRETS, key)
                member_delete(NAMESPACES, FED_SYSTEM_NAMESPACE)
                revoked = False
                for key in sa_keys:
                    if not member_delete(SERVICE_ACCOUNTS, key):
                        revoked = True
                        break
                if not revoked:
                    # Bare-store members (no token controller GC) still
                    # need the token secrets gone; over HTTP our own
                    # token secret went with our SA above.
                    for tname in token_names:
                        if not member_delete(SECRETS, prefix + tname):
                            break

        cluster["metadata"]["finalizers"] = []
        try:
            self.host.update(FEDERATED_CLUSTERS, cluster)
        except Conflict:
            return Result.retry()
        except NotFound:
            pass
        return Result.ok()
