"""The follower controller: leader workloads drag their dependencies.

Leader federated workloads (Deployment/StatefulSet/DaemonSet/Job/CronJob/
Pod) reference follower resources (ConfigMap/Secret/PVC/ServiceAccount/
Service/Ingress) through their pod templates and the followers
annotation.  This controller maintains a bidirectional in-memory cache of
(leader ↔ follower) edges, writes each follower's ``spec.follows`` list,
and sets the follower's placement to the union of its leaders' placements
so dependencies land wherever the workloads do (reference:
pkg/controllers/follower/controller.go:40-552, util.go:46-150).
"""

from __future__ import annotations

import functools
import threading
from typing import Iterable, Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import Conflict, FakeKube, NotFound, obj_key
from kubeadmiral_tpu.utils.unstructured import get_path

ENABLE_FOLLOWER_SCHEDULING = C.PREFIX + "enable-follower-scheduling"
FOLLOWERS_ANNOTATION = C.PREFIX + "followers"

# Leader source kind -> dotted path of the pod template inside the
# *template* of the federated object (follower/controller.go:71-80).
LEADER_POD_TEMPLATE_PATHS = {
    "apps/Deployment": "spec.template",
    "apps/StatefulSet": "spec.template",
    "apps/DaemonSet": "spec.template",
    "batch/Job": "spec.template",
    "batch/CronJob": "spec.jobTemplate.spec.template",
    "/Pod": "",  # the template itself is the pod
}

SUPPORTED_FOLLOWER_KINDS = frozenset(
    {
        "/ConfigMap",
        "/Secret",
        "/PersistentVolumeClaim",
        "/ServiceAccount",
        "/Service",
        "networking.k8s.io/Ingress",
    }
)


def group_kind(ftc: FederatedTypeConfig) -> str:
    return f"{ftc.source.group}/{ftc.source.kind}"


# A follower/leader reference is (group_kind, namespace, name).
Ref = tuple[str, str, str]


def visit_pod_secret_names(pod_spec: dict) -> set[str]:
    """Secrets a pod references (lifted podutil.VisitPodSecretNames
    semantics: volumes, projected sources, env/envFrom, imagePullSecrets)."""
    names: set[str] = set()
    for s in pod_spec.get("imagePullSecrets", []) or []:
        if s.get("name"):
            names.add(s["name"])
    for vol in pod_spec.get("volumes", []) or []:
        secret = vol.get("secret")
        if secret and secret.get("secretName"):
            names.add(secret["secretName"])
        for src in (vol.get("projected", {}) or {}).get("sources", []) or []:
            if src.get("secret", {}).get("name"):
                names.add(src["secret"]["name"])
    for container in _all_containers(pod_spec):
        for ef in container.get("envFrom", []) or []:
            if ef.get("secretRef", {}).get("name"):
                names.add(ef["secretRef"]["name"])
        for env in container.get("env", []) or []:
            ref = (env.get("valueFrom", {}) or {}).get("secretKeyRef", {})
            if ref.get("name"):
                names.add(ref["name"])
    return names


def visit_pod_configmap_names(pod_spec: dict) -> set[str]:
    names: set[str] = set()
    for vol in pod_spec.get("volumes", []) or []:
        cm = vol.get("configMap")
        if cm and cm.get("name"):
            names.add(cm["name"])
        for src in (vol.get("projected", {}) or {}).get("sources", []) or []:
            if src.get("configMap", {}).get("name"):
                names.add(src["configMap"]["name"])
    for container in _all_containers(pod_spec):
        for ef in container.get("envFrom", []) or []:
            if ef.get("configMapRef", {}).get("name"):
                names.add(ef["configMapRef"]["name"])
        for env in container.get("env", []) or []:
            ref = (env.get("valueFrom", {}) or {}).get("configMapKeyRef", {})
            if ref.get("name"):
                names.add(ref["name"])
    return names


def _all_containers(pod_spec: dict) -> Iterable[dict]:
    for field in ("containers", "initContainers", "ephemeralContainers"):
        yield from pod_spec.get(field, []) or []


def followers_from_pod_spec(pod_spec: dict, namespace: str) -> set[Ref]:
    """(follower/util.go:98-150 getFollowersFromPod)."""
    refs: set[Ref] = set()
    for name in visit_pod_secret_names(pod_spec):
        refs.add(("/Secret", namespace, name))
    for name in visit_pod_configmap_names(pod_spec):
        refs.add(("/ConfigMap", namespace, name))
    for vol in pod_spec.get("volumes", []) or []:
        pvc = vol.get("persistentVolumeClaim")
        if pvc and pvc.get("claimName"):
            refs.add(("/PersistentVolumeClaim", namespace, pvc["claimName"]))
    sa = pod_spec.get("serviceAccountName")
    if sa:
        refs.add(("/ServiceAccount", namespace, sa))
    return refs


class _BidirectionalCache:
    """leader ↔ follower edge cache (follower/bidirectional_cache.go)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._forward: dict[Ref, set[Ref]] = {}
        self._reverse: dict[Ref, set[Ref]] = {}

    def update(self, key: Ref, values: set[Ref]) -> None:
        with self._lock:
            old = self._forward.get(key, set())
            for gone in old - values:
                peers = self._reverse.get(gone)
                if peers is not None:
                    peers.discard(key)
                    if not peers:
                        del self._reverse[gone]
            for new in values - old:
                self._reverse.setdefault(new, set()).add(key)
            if values:
                self._forward[key] = set(values)
            else:
                self._forward.pop(key, None)

    def reverse_lookup(self, value: Ref) -> set[Ref]:
        with self._lock:
            return set(self._reverse.get(value, set()))


class FollowerController:
    """Always-on controller spanning all leader + follower FTCs."""

    name = C.FOLLOWER_CONTROLLER

    def __init__(
        self,
        host: FakeKube,
        ftcs: list[FederatedTypeConfig],
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        self.host = host
        self.metrics = metrics or Metrics()
        self.leader_ftcs: dict[str, FederatedTypeConfig] = {}
        self.follower_ftcs: dict[str, FederatedTypeConfig] = {}
        for ftc in ftcs:
            gk = group_kind(ftc)
            if gk in LEADER_POD_TEMPLATE_PATHS:
                self.leader_ftcs[gk] = ftc
            if gk in SUPPORTED_FOLLOWER_KINDS:
                self.follower_ftcs[gk] = ftc

        # Edges: leaders declare followers; followers record spec.follows.
        self.observed_from_leaders = _BidirectionalCache()
        self.observed_from_followers = _BidirectionalCache()

        self.worker = Worker(
            "follower-controller", self.reconcile, metrics=self.metrics, clock=clock
        )
        # Partials of a bound method, not lambdas: owner-based unwatch
        # (dynamic FTC lifecycle) identifies handlers by their owner.
        for gk, ftc in self.leader_ftcs.items():
            host.watch(
                ftc.federated.resource,
                functools.partial(self._on_object_event, "leader", gk),
                replay=True,
            )
        for gk, ftc in self.follower_ftcs.items():
            host.watch(
                ftc.federated.resource,
                functools.partial(self._on_object_event, "follower", gk),
                replay=True,
            )

    def _on_object_event(self, role: str, gk: str, event: str, obj: dict) -> None:
        self.worker.enqueue(f"{role}|{gk}|{obj_key(obj)}")

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    def reconcile(self, key: str) -> Result:
        role, gk, okey = key.split("|", 2)
        if role == "leader":
            return self._reconcile_leader(gk, okey)
        return self._reconcile_follower(gk, okey)

    # -- leaders (controller.go:257-352) ---------------------------------
    def _reconcile_leader(self, gk: str, key: str) -> Result:
        self.metrics.counter("follower.throughput")
        ftc = self.leader_ftcs[gk]
        ns, _, name = key.rpartition("/")
        leader: Ref = (gk, ns, name)
        fed_obj = self.host.try_get(ftc.federated.resource, key)

        desired: set[Ref] = set()
        if fed_obj is not None and not fed_obj["metadata"].get("deletionTimestamp"):
            try:
                if not pending.dependencies_fulfilled(fed_obj, self.name):
                    return Result.ok()
            except KeyError:
                return Result.ok()
            desired = self._infer_followers(gk, fed_obj)

        self.observed_from_leaders.update(leader, desired)
        current = self.observed_from_followers.reverse_lookup(leader)

        for follower in desired | current:
            fgk = follower[0]
            if fgk in self.follower_ftcs:
                fkey = f"{follower[1]}/{follower[2]}" if follower[1] else follower[2]
                self.worker.enqueue(f"follower|{fgk}|{fkey}")

        if fed_obj is not None:
            if pending.update_pending(
                fed_obj, self.name, False, ftc.controller_groups
            ):
                try:
                    self.host.update(ftc.federated.resource, fed_obj)
                except Conflict:
                    return Result.retry()
                except NotFound:
                    pass
        return Result.ok()

    def _infer_followers(self, gk: str, fed_obj: dict) -> set[Ref]:
        """(controller.go:354-378 + util.go getFollowersFromAnnotation)."""
        ann = fed_obj["metadata"].get("annotations", {}) or {}
        if ann.get(ENABLE_FOLLOWER_SCHEDULING) != "true":
            return set()
        ns = fed_obj["metadata"].get("namespace", "")
        refs: set[Ref] = set()

        raw = ann.get(FOLLOWERS_ANNOTATION)
        if raw:
            import json

            try:
                for el in json.loads(raw):
                    fgk = f"{el.get('group', '')}/{el['kind']}"
                    # Followers only from the leader's own namespace.
                    refs.add((fgk, ns, el["name"]))
            except (ValueError, KeyError):
                pass

        template = C.template(fed_obj)
        path = LEADER_POD_TEMPLATE_PATHS[gk]
        pod = get_path(template, path) if path else template
        pod_spec = (pod or {}).get("spec") or {}
        refs |= followers_from_pod_spec(pod_spec, ns)
        return {r for r in refs if r[0] in SUPPORTED_FOLLOWER_KINDS}

    # -- followers (controller.go:426-502) -------------------------------
    def _reconcile_follower(self, gk: str, key: str) -> Result:
        self.metrics.counter("follower.throughput")
        ftc = self.follower_ftcs[gk]
        ns, _, name = key.rpartition("/")
        follower: Ref = (gk, ns, name)
        fed_obj = self.host.try_get(ftc.federated.resource, key)

        if fed_obj is None:
            self.observed_from_followers.update(follower, set())
            return Result.ok()

        current_leaders = {
            (f"{f.get('group', '')}/{f.get('kind', '')}", ns, f.get("name", ""))
            for f in fed_obj.get("spec", {}).get("follows", []) or []
        }
        self.observed_from_followers.update(follower, current_leaders)
        desired_leaders = self.observed_from_leaders.reverse_lookup(follower)

        changed = desired_leaders != current_leaders
        if changed:
            fed_obj["spec"]["follows"] = [
                {"group": g.split("/", 1)[0], "kind": g.split("/", 1)[1], "name": n}
                for g, _, n in sorted(desired_leaders)
            ]

        clusters = self._leader_placement_union(desired_leaders)
        placement_changed = C.set_placement(fed_obj, self.name, clusters)

        if changed or placement_changed:
            try:
                self.host.update(ftc.federated.resource, fed_obj)
            except Conflict:
                return Result.retry()
            except NotFound:
                pass
        return Result.ok()

    def _leader_placement_union(self, leaders: set[Ref]) -> set[str]:
        """(controller.go:532-552)."""
        clusters: set[str] = set()
        for gk, ns, name in leaders:
            ftc = self.leader_ftcs.get(gk)
            if ftc is None:
                continue
            key = f"{ns}/{name}" if ns else name
            leader_obj = self.host.try_get(ftc.federated.resource, key)
            if leader_obj is None:
                continue
            clusters |= C.all_placement_clusters(leader_obj)
        return clusters
