"""The federate controller: source object → federated companion object.

For every source object (e.g. a Deployment) this controller maintains the
federated wrapper (FederatedDeployment) whose ``spec.template`` is the
pruned source, classifying source labels/annotations into ones that ride
on the federated object itself versus ones that stay in the template, and
recording bookkeeping annotations (observed key sets, a JSON merge patch
reconstructing the template generator).  Source deletion is propagated by
deleting the federated object first, gated by a finalizer on the source
(reference: pkg/controllers/federate/controller.go:95-567, util.go).
"""

from __future__ import annotations

from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime import pending, slo
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import Conflict, FakeKube, NotFound, obj_key
from kubeadmiral_tpu.utils.jsonpatch import create_merge_patch
from kubeadmiral_tpu.utils.unstructured import copy_json, get_path, set_path

FEDERATE_FINALIZER = C.PREFIX + "federate-controller"
NO_FEDERATED_RESOURCE = C.PREFIX + "no-federated-resource"

# Bookkeeping annotations on the federated object
# (reference: pkg/controllers/common/constants.go).
FEDERATED_OBJECT = C.FEDERATED_OBJECT
OBSERVED_ANNOTATION_KEYS = C.PREFIX + "observed-annotation-keys"
OBSERVED_LABEL_KEYS = C.PREFIX + "observed-label-keys"
TEMPLATE_GENERATOR_MERGE_PATCH = C.PREFIX + "template-generator-merge-patch"
NO_SCHEDULING = C.PREFIX + "no-scheduling"
REVISION_HISTORY_LIMIT = C.PREFIX + "revision-history-limit"

# Annotations copied onto the federated object rather than the template
# (reference: federate/util.go federatedAnnotationSet).
FEDERATED_ANNOTATIONS = frozenset(
    {
        C.PREFIX + "scheduling-mode",
        C.PREFIX + "sticky-cluster",
        C.CONFLICT_RESOLUTION,
        C.NO_AUTO_PROPAGATION,
        C.ORPHAN_MODE,
        C.PREFIX + "tolerations",
        C.PREFIX + "placements",
        C.PREFIX + "cluster-selector",
        C.PREFIX + "affinity",
        C.PREFIX + "max-clusters",
        NO_SCHEDULING,
        C.FOLLOWS_OBJECT,
        C.PREFIX + "followers",
    }
)

# Source annotations never copied anywhere (internal / feedback keys;
# reference: federate/util.go ignoredAnnotationSet).
IGNORED_ANNOTATIONS = frozenset(
    {
        C.RETAIN_REPLICAS,
        C.LATEST_REPLICASET_DIGESTS,
        C.SOURCE_FEEDBACK_SCHEDULING,
        C.SOURCE_FEEDBACK_SYNCING,
        C.SOURCE_FEEDBACK_STATUS,
        C.CONFLICT_RESOLUTION_INTERNAL,
        C.ORPHAN_MODE_INTERNAL,
        C.PREFIX + "enable-follower-scheduling",
    }
)

# Labels that ride on the federated object (policy bindings; reference:
# federate/util.go federatedLabelSet).
FEDERATED_LABELS = frozenset(
    {
        "kubeadmiral.io/propagation-policy-name",
        "kubeadmiral.io/cluster-propagation-policy-name",
        "kubeadmiral.io/override-policy-name",
        "kubeadmiral.io/cluster-override-policy-name",
    }
)

# metadata fields pruned from the template (reference:
# federate/util.go templateForSourceObject).
_PRUNED_META = (
    "selfLink",
    "uid",
    "resourceVersion",
    "generation",
    "creationTimestamp",
    "deletionTimestamp",
    "ownerReferences",
    "finalizers",
    "managedFields",
)


def classify_annotations(src: dict) -> tuple[dict, dict]:
    """Split source annotations into (federated, template) maps."""
    federated, template = {}, {}
    for key, value in (src or {}).items():
        if key in IGNORED_ANNOTATIONS:
            continue
        (federated if key in FEDERATED_ANNOTATIONS else template)[key] = value
    federated[FEDERATED_OBJECT] = "1"
    return federated, template


def classify_labels(src: dict) -> tuple[dict, dict]:
    federated, template = {}, {}
    for key, value in (src or {}).items():
        (federated if key in FEDERATED_LABELS else template)[key] = value
    return federated, template


# Annotations this control plane writes back onto SOURCE objects.
_FEEDBACK_ANNOTATIONS = frozenset(
    {
        C.SOURCE_FEEDBACK_SCHEDULING,
        C.SOURCE_FEEDBACK_SYNCING,
        C.SOURCE_FEEDBACK_STATUS,
    }
)


def source_for_bookkeeping(source: dict) -> dict:
    """Source with the feedback annotations stripped: observed-keys and
    the template-generator merge patch must not react to keys this
    control plane writes back onto the source, or every feedback write
    would restart the whole pipeline.  Other ignored annotations (e.g.
    retain-replicas) stay — they are user-written inputs the federated
    spec derives from.  Only the metadata/annotations layers are rebuilt
    (no deep copy of large pod templates on this hot path)."""
    ann = source.get("metadata", {}).get("annotations")
    if not ann or not (_FEEDBACK_ANNOTATIONS & ann.keys()):
        return source
    pruned = {k: v for k, v in ann.items() if k not in _FEEDBACK_ANNOTATIONS}
    meta = {**source["metadata"]}
    if pruned:
        meta["annotations"] = pruned
    else:
        meta.pop("annotations", None)
    return {**source, "metadata": meta}


def observed_keys(source_map: dict, federated_map: dict) -> str:
    """``fedKeys|otherKeys`` bookkeeping so later syncs know which source
    keys were observed (federate/util.go generateObservedKeys)."""
    if not source_map:
        return ""
    fed = sorted(k for k in source_map if k in federated_map)
    non = sorted(k for k in source_map if k not in federated_map)
    return ",".join(fed) + "|" + ",".join(non)


def template_for_source(source: dict, annotations: dict, labels: dict) -> dict:
    template = copy_json(source)
    meta = template.setdefault("metadata", {})
    for field in _PRUNED_META:
        meta.pop(field, None)
    if annotations:
        meta["annotations"] = dict(annotations)
    else:
        meta.pop("annotations", None)
    if labels:
        meta["labels"] = dict(labels)
    else:
        meta.pop("labels", None)
    template.pop("status", None)
    return template


def _is_deployment(ftc: FederatedTypeConfig) -> bool:
    return ftc.source.group == "apps" and ftc.source.kind == "Deployment"


def _ensure_deployment_fields(source: dict, fed_obj: dict) -> bool:
    """spec.retainReplicas + spec.revisionHistoryLimit from source
    annotations (federate/controller.go ensureDeploymentFields)."""
    anno = source.get("metadata", {}).get("annotations", {}) or {}
    changed = False

    retain = anno.get(C.RETAIN_REPLICAS) == "true"
    if get_path(fed_obj, "spec.retainReplicas") != retain:
        set_path(fed_obj, "spec.retainReplicas", retain)
        changed = True

    limit = int(anno.get(REVISION_HISTORY_LIMIT, "1") or 1)
    if get_path(fed_obj, "spec.revisionHistoryLimit") != limit:
        set_path(fed_obj, "spec.revisionHistoryLimit", limit)
        changed = True
    return changed


def new_federated_object(ftc: FederatedTypeConfig, source: dict) -> dict:
    source = source_for_bookkeeping(source)
    src_meta = source.get("metadata", {})
    fed_labels, tmpl_labels = classify_labels(src_meta.get("labels", {}))
    fed_anno, tmpl_anno = classify_annotations(src_meta.get("annotations", {}))
    template = template_for_source(source, tmpl_anno, tmpl_labels)

    fed_anno[OBSERVED_ANNOTATION_KEYS] = observed_keys(
        src_meta.get("annotations", {}) or {}, fed_anno
    )
    fed_anno[OBSERVED_LABEL_KEYS] = observed_keys(
        src_meta.get("labels", {}) or {}, fed_labels
    )
    fed_anno[TEMPLATE_GENERATOR_MERGE_PATCH] = C.compact_json(
        create_merge_patch(source, template)
    )

    fed_obj = {
        "apiVersion": ftc.federated.api_version,
        "kind": ftc.federated.kind,
        "metadata": {
            "name": src_meta.get("name"),
            "annotations": fed_anno,
        },
        "spec": {"template": template},
    }
    if src_meta.get("namespace"):
        fed_obj["metadata"]["namespace"] = src_meta["namespace"]
    if fed_labels:
        fed_obj["metadata"]["labels"] = fed_labels
    if _is_deployment(ftc):
        _ensure_deployment_fields(source, fed_obj)
    pending.set_pending(fed_obj, ftc.controller_groups)
    return fed_obj


def update_federated_object(
    fed_obj: dict, ftc: FederatedTypeConfig, source: dict
) -> bool:
    """Reconcile an existing federated object against the source; returns
    True when it changed (federate/util.go
    updateFederatedObjectForSourceObject)."""
    changed = False
    source = source_for_bookkeeping(source)
    src_meta = source.get("metadata", {})
    fed_meta = fed_obj.setdefault("metadata", {})

    fed_labels, tmpl_labels = classify_labels(src_meta.get("labels", {}))
    fed_anno, tmpl_anno = classify_annotations(src_meta.get("annotations", {}))

    if (fed_meta.get("labels") or {}) != fed_labels:
        if fed_labels:
            fed_meta["labels"] = fed_labels
        else:
            fed_meta.pop("labels", None)
        changed = True

    template = template_for_source(source, tmpl_anno, tmpl_labels)
    if get_path(fed_obj, "spec.template") != template:
        set_path(fed_obj, "spec.template", template)
        changed = True

    # Merge federated annotations into the existing set: other
    # controllers annotate the federated object too, so only keys this
    # controller owns are overwritten/removed.
    existing = dict(fed_meta.get("annotations", {}) or {})
    merged = dict(existing)
    for key in list(merged):
        if key in FEDERATED_ANNOTATIONS and key not in fed_anno:
            del merged[key]
    merged.update(fed_anno)
    merged[OBSERVED_ANNOTATION_KEYS] = observed_keys(
        src_meta.get("annotations", {}) or {}, fed_anno
    )
    merged[OBSERVED_LABEL_KEYS] = observed_keys(
        src_meta.get("labels", {}) or {}, fed_labels
    )
    merged[TEMPLATE_GENERATOR_MERGE_PATCH] = C.compact_json(
        create_merge_patch(source, template)
    )
    if merged != existing:
        fed_meta["annotations"] = merged
        changed = True

    if _is_deployment(ftc):
        changed = _ensure_deployment_fields(source, fed_obj) or changed

    if changed:
        # A template change restarts the controller pipeline
        # (federate/util.go:208-213).
        pending.set_pending(fed_obj, ftc.controller_groups)
    return changed


class FederateController:
    """Per-FTC controller keeping FederatedX in step with X."""

    name = "federate-controller"

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        self.host = host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._source_resource = ftc.source.resource
        self._fed_resource = ftc.federated.resource
        self.worker = Worker(
            f"federate-{ftc.name}", self.reconcile, metrics=self.metrics, clock=clock
        )
        # The source resource is the pipeline's ingress: its watch
        # events mint the SLO provenance tokens the whole
        # event→placement-written decomposition hangs off
        # (runtime/slo.py).
        slo.track(host, self._source_resource)
        # Watch-boundary trigger filter for FED events: federate reads a
        # fed object's template (generation), labels and annotations
        # (the feedback annotations it mirrors to the source included),
        # never its status — sync's per-round status-subresource write
        # must not re-reconcile every object (common.metadata_change_sig).
        self._fed_event_sigs: dict[str, int] = {}
        host.watch(self._source_resource, self._on_event, replay=True)
        host.watch(self._fed_resource, self._on_fed_event, replay=True)

    def _on_event(self, event: str, obj: dict) -> None:
        if self.worker.is_own_thread():
            return  # echo of this controller's own source/fed write
        self.worker.enqueue(obj_key(obj))

    def _on_fed_event(self, event: str, obj: dict) -> None:
        key = obj_key(obj)
        if event == "DELETED":
            self._fed_event_sigs.pop(key, None)
            if not self.worker.is_own_thread():
                self.worker.enqueue(key)
            return
        sig = C.metadata_change_sig(obj)
        if self._fed_event_sigs.get(key) == sig:
            return  # status-only fed write: nothing federate consumes
        self._fed_event_sigs[key] = sig
        if self.worker.is_own_thread():
            return  # echo of this controller's own fed write
        self.worker.enqueue(key)

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- reconcile (federate/controller.go:194-341) ----------------------
    def reconcile(self, key: str) -> Result:
        self.metrics.counter("federate.throughput")
        source = self.host.try_get(self._source_resource, key)
        fed_obj = self.host.try_get(self._fed_resource, key)

        if source is None:
            return Result.ok()

        if source["metadata"].get("deletionTimestamp"):
            return self._handle_terminating_source(source, fed_obj)

        if source["metadata"].get("annotations", {}).get(NO_FEDERATED_RESOURCE):
            return Result.ok()

        source = self._ensure_finalizer(source)
        if source is None:
            return Result.retry()

        if fed_obj is None:
            return self._create(source)
        return self._update(source, fed_obj)

    def _ensure_finalizer(self, source: dict) -> Optional[dict]:
        fins = source["metadata"].setdefault("finalizers", [])
        if FEDERATE_FINALIZER in fins:
            return source
        fins.append(FEDERATE_FINALIZER)
        try:
            # rv-only consumption: skip the result deep copy.
            updated = self.host.update(
                self._source_resource, source, _copy_result=False
            )
        except (Conflict, NotFound):
            return None
        source["metadata"]["resourceVersion"] = updated["metadata"]["resourceVersion"]
        return source

    def _handle_terminating_source(
        self, source: dict, fed_obj: Optional[dict]
    ) -> Result:
        if fed_obj is None:
            # Federated object gone: release the source
            # (federate/controller.go handleTerminatingSourceObject).
            fins = source["metadata"].get("finalizers", [])
            if FEDERATE_FINALIZER in fins:
                source["metadata"]["finalizers"] = [
                    f for f in fins if f != FEDERATE_FINALIZER
                ]
                try:
                    # Result discarded: skip the deep copy.
                    self.host.update(
                        self._source_resource, source, _copy_result=False
                    )
                except (Conflict, NotFound):
                    return Result.retry()
            return Result.ok()
        if not fed_obj["metadata"].get("deletionTimestamp"):
            try:
                self.host.delete(self._fed_resource, obj_key(fed_obj))
            except NotFound:
                pass
            # A finalizer-free federated object is gone right away (its
            # DELETED event is our own echo, suppressed): release the
            # source NOW instead of waiting for a requeue that nothing
            # would trigger.
            if self.host.try_get(self._fed_resource, obj_key(fed_obj)) is None:
                return self._handle_terminating_source(source, None)
        # Requeue until the federated object finishes terminating
        # (sync's finalizer removal fires a foreign DELETED event too).
        return Result.after(1.0)

    def _create(self, source: dict) -> Result:
        fed_obj = new_federated_object(self.ftc, source)
        try:
            # _sync_feedback only reads the created object: no copy needed.
            created = self.host.create(
                self._fed_resource, fed_obj, _copy_result=False
            )
        except Conflict:
            return Result.retry()
        except Exception:
            return Result.retry()
        # The ADDED echo is suppressed (own thread): stamp the initial
        # scheduling feedback on the source now, as the echo-driven
        # second reconcile used to.
        return self._sync_feedback(source, created)

    def _update(self, source: dict, fed_obj: dict) -> Result:
        if not update_federated_object(fed_obj, self.ftc, source):
            return self._sync_feedback(source, fed_obj)
        try:
            # rv/generation-only consumption: skip the result deep copy.
            updated = self.host.update(
                self._fed_resource, fed_obj, _copy_result=False
            )
        except (Conflict, NotFound):
            return Result.retry()
        # Server-set fields (rv AND generation — the fedGeneration the
        # feedback annotation records) must come from the stored object.
        fed_obj["metadata"]["resourceVersion"] = updated["metadata"][
            "resourceVersion"
        ]
        if "generation" in updated.get("metadata", {}):
            fed_obj["metadata"]["generation"] = updated["metadata"]["generation"]
        # Continue straight to the feedback pass: the write's own echo
        # is suppressed (is_own_thread), so nothing else would requeue
        # this key to mirror feedback onto the source.
        return self._sync_feedback(source, fed_obj)

    def _sync_feedback(self, source: dict, fed_obj: dict) -> Result:
        """Write scheduling feedback (computed from the federated object's
        placements) and copy syncing feedback onto the source object
        (federate/controller.go:485-494;
        sourcefeedback/scheduling.go PopulateSchedulingAnnotation)."""
        fed_anno = fed_obj["metadata"].get("annotations", {}) or {}
        changed = False
        src_anno = source["metadata"].setdefault("annotations", {})

        scheduling: dict = {
            # Generation of the source as observed in the template (the
            # template prunes it, as the reference's does, so this stays
            # null unless another controller kept it).
            "generation": get_path(fed_obj, "spec.template.metadata.generation"),
            "fedGeneration": fed_obj["metadata"].get("generation", 1),
        }
        placement = sorted(C.all_placement_clusters(fed_obj))
        if placement:
            scheduling["placement"] = placement
        scheduling_value = C.compact_json(scheduling)
        if src_anno.get(C.SOURCE_FEEDBACK_SCHEDULING) != scheduling_value:
            src_anno[C.SOURCE_FEEDBACK_SCHEDULING] = scheduling_value
            changed = True

        syncing = fed_anno.get(C.SOURCE_FEEDBACK_SYNCING)
        if syncing is not None and src_anno.get(C.SOURCE_FEEDBACK_SYNCING) != syncing:
            src_anno[C.SOURCE_FEEDBACK_SYNCING] = syncing
            changed = True
        if not changed:
            return Result.ok()
        try:
            self.host.update(self._source_resource, source)
        except (Conflict, NotFound):
            return Result.retry()
        return Result.ok()
