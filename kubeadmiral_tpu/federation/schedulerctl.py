"""The global scheduler controller — batch edition.

Mirrors the reference scheduler's control surface (reference:
pkg/controllers/scheduler/scheduler.go): watch federated objects,
policies, clusters and profiles; dedupe with a scheduling-trigger hash;
respect the pending-controllers pipeline; persist placements + replica
overrides + auxiliary annotations; hand off downstream.

The difference is the hot path: instead of one object per worker
goroutine through sequential plugin loops, every due object in a tick is
featurized into one batch and pushed through the XLA engine
(kubeadmiral_tpu.scheduler.engine).
"""

from __future__ import annotations

import dataclasses
import json as _json

from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models import policy as P
from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.models import profile as PR
from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.models.types import parse_resources
from kubeadmiral_tpu.runtime import pending, slo, tenancy
from kubeadmiral_tpu.runtime.eventsink import DefederatingRecorderMux
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.hostbatch import HostBatch
from kubeadmiral_tpu.runtime.worker import BatchWorker, Result
from kubeadmiral_tpu.scheduler.engine import ScheduleResult, SchedulerEngine
from kubeadmiral_tpu.scheduler import webhook as W
from kubeadmiral_tpu.testing.fakekube import (
    Conflict, FakeKube, NotFound, ShardIntake, obj_key,
)
from kubeadmiral_tpu.utils.hashing import stable_json_hash
from kubeadmiral_tpu.utils.unstructured import get_path

FEDERATED_CLUSTERS = C.FEDERATED_CLUSTERS

# Annotations the scheduler owns (reference: common constants +
# scheduler.go applySchedulingResult).
ENABLE_FOLLOWER_SCHEDULING = C.PREFIX + "enable-follower-scheduling"
POD_UNSCHEDULABLE_THRESHOLD = C.PREFIX + "pod-unschedulable-threshold"

# Per-object annotation overrides of policy fields
# (schedulingunit.go getters).
A_SCHEDULING_MODE = C.PREFIX + "scheduling-mode"
A_STICKY_CLUSTER = C.PREFIX + "sticky-cluster"
A_CLUSTER_SELECTOR = C.PREFIX + "cluster-selector"
A_PLACEMENTS = C.PREFIX + "placements"
A_MAX_CLUSTERS = C.PREFIX + "max-clusters"


def cluster_state_from_object(obj: dict) -> Optional[T.ClusterState]:
    """FederatedCluster dict -> scheduler view; None unless joined."""
    status = obj.get("status", {})
    conditions = {c.get("type"): c.get("status") for c in status.get("conditions", [])}
    if conditions.get("Joined") != "True":
        return None
    resources = status.get("resources", {})
    return T.ClusterState(
        name=obj["metadata"]["name"],
        labels=dict(obj["metadata"].get("labels", {})),
        taints=tuple(
            T.Taint(
                key=t.get("key", ""),
                value=t.get("value", ""),
                effect=t.get("effect", ""),
            )
            for t in obj.get("spec", {}).get("taints", ())
        ),
        allocatable=parse_resources(resources.get("allocatable", {})),
        available=parse_resources(resources.get("available", {})),
        api_resources=frozenset(status.get("apiResourceTypes", ())),
    )


def extract_pod_resource_request(template: dict) -> dict[str, int]:
    """Sum of container requests in the workload's pod template.

    The reference stubs this out (schedulingtriggers.go:188-191 returns an
    empty Resource); implemented here so ClusterResourcesFit/score plugins
    see real requests when present."""
    pod_spec = get_path(template, "spec.template.spec", {})
    total: dict[str, int] = {}
    for container in pod_spec.get("containers", ()) if isinstance(pod_spec, dict) else ():
        requests = get_path(container, "resources.requests", {}) or {}
        for name, q in parse_resources(requests).items():
            total[name] = total.get(name, 0) + q
    return total


class SchedulerController:
    name = C.SCHEDULER

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        engine: Optional[SchedulerEngine] = None,
        metrics: Optional[Metrics] = None,
        webhook_client: Optional[W.HTTPClient] = None,
    ):
        self.host = host
        self.ftc = ftc
        self.engine = engine or SchedulerEngine()
        self.metrics = metrics or Metrics()
        # Scheduling events land on the federated object AND its
        # de-federated source, so `kubectl describe deployment` shows the
        # federation decision (scheduler.go eventRecorder; the message
        # strings share the flight recorder's reason vocabulary).
        self.recorder = DefederatingRecorderMux(host, C.SCHEDULER)
        self.worker = BatchWorker(f"scheduler-{ftc.name}", self.reconcile_batch, metrics=self.metrics)
        self._resource = ftc.federated.resource
        self._webhook_client = webhook_client
        self._webhook_pool: Optional[ThreadPoolExecutor] = None
        # name -> WebhookPlugin, maintained from config watch events
        # (scheduler.go s.webhookPlugins sync.Map).
        self.webhook_plugins: dict[str, W.WebhookPlugin] = {}
        # (namespace, name) -> parsed PolicySpec, invalidated by policy
        # watch events (see _policy_for / _on_policy_event).  The epoch
        # counter closes the read-then-cache race: an event landing
        # between a tick's try_get and its cache store bumps the epoch,
        # and the store is skipped (caching the pre-event spec would
        # pin it forever, since the trigger hash would keep matching).
        self._policy_cache: dict[tuple[str, str], P.PolicySpec] = {}
        self._policy_epoch: dict[tuple[str, str], int] = {}
        # Watch-boundary trigger filter: last metadata_change_sig per
        # key.  Status-subresource writes (sync's per-round status +
        # every member ack echo) leave the sig unchanged and never
        # re-enqueue — the trigger-hash skip in reconcile_batch would
        # no-op them anyway, but only after paying a per-key replan
        # check; at e2e scale that recheck WAS a whole extra tick.
        self._event_sigs: dict[str, int] = {}
        # Per-cluster scheduling-relevant signature (the _clusters_hash
        # fields + joined-ness): heartbeats and capacity-only status
        # bumps leave it unchanged and must NOT sweep-enqueue every
        # object — that sweep was the ~300k-enqueue storm of the PR 18
        # 10000x500 profile (one full-keyspace enqueue_all per cluster
        # event, all of them trigger-hash no-ops downstream).
        self._cluster_sweep_sigs: dict[str, str] = {}
        # The replica's shard filter, resolved once like the worker's:
        # non-owned object events are dropped pre-delivery (kt_predicate
        # runs batch-wise in the store), before they cost a handler
        # call, a metadata sig, or an enqueue.
        self._shard = self.worker._shard

        host.watch(
            self._resource,
            ShardIntake(self._on_object_event, predicate=self._owns_event),
            replay=True,
        )
        host.watch(P.PROPAGATION_POLICIES, self._on_policy_event, replay=False)
        host.watch(P.CLUSTER_PROPAGATION_POLICIES, self._on_policy_event, replay=False)
        host.watch(
            FEDERATED_CLUSTERS,
            ShardIntake(self._on_cluster_event, batch=self._on_cluster_events),
            replay=False,
        )
        host.watch(PR.SCHEDULING_PROFILES, self._on_profile_event, replay=False)
        host.watch(W.SCHEDULER_WEBHOOK_CONFIGS, self._on_webhook_config_event, replay=True)

    def _owns_event(self, event: str, obj: dict) -> bool:
        return self._shard.owns(obj_key(obj))

    # -- event handlers (fan-in to the dirty queue) ----------------------
    def _on_object_event(self, event: str, obj: dict) -> None:
        key = obj_key(obj)
        if event == "DELETED":
            self._event_sigs.pop(key, None)
            self.worker.enqueue(key)
            return
        # The syncing feedback annotation churns once per sync round and
        # never feeds a scheduling decision; everything else in
        # generation/labels/annotations does (policy binding labels,
        # pending-controllers, placements via generation).
        sig = C.metadata_change_sig(
            obj, ignore_annotations=(C.SOURCE_FEEDBACK_SYNCING,)
        )
        if self._event_sigs.get(key) == sig:
            return  # status-only write / feedback noise: no requeue
        self._event_sigs[key] = sig
        if self.worker.is_own_thread():
            # Echo of this controller's own persist (placements +
            # trigger-hash annotation): the sig is recorded so the next
            # foreign event diffs against the post-persist state, but
            # the persist itself needs no replan.
            return
        # The reconcile path's root span: the watch event that made the
        # object dirty (its tick shows up as a later worker.tick span;
        # the gap between the two is the queue wait, gauged by
        # worker_queue_wait_seconds).  Sampled — per-event spans at e2e
        # scale only evict each other from the ring (trace.hot_span).
        with trace.hot_span(
            "informer.event", resource=self._resource, event=event, key=key
        ):
            self.worker.enqueue(key)

    def _enqueue_objects_for_policies(self, policies: set[tuple[str, str]]) -> None:
        """Re-enqueue every federated object bound to one of the given
        (namespace, name) policy keys.  Scan without deep-copying: at
        100k objects a full copying LIST per event would stall the store."""
        if not policies:
            return
        matched: list[str] = []

        def check(fed: dict) -> None:
            if P.matched_policy_key(fed) in policies:
                matched.append(obj_key(fed))

        self.host.scan(self._resource, check)
        self.worker.enqueue_all(matched)

    def _on_policy_event(self, event: str, obj: dict) -> None:
        # (schedulingtriggers.go enqueueFederatedObjectsForPolicy).
        pname = obj["metadata"]["name"]
        pns = obj["metadata"].get("namespace", "")
        # Event-invalidated parse cache: the next tick re-reads + re-
        # parses this policy once instead of once per bound object.
        key = (pns, pname)
        self._policy_epoch[key] = self._policy_epoch.get(key, 0) + 1
        self._policy_cache.pop(key, None)
        self._enqueue_objects_for_policies({(pns, pname)})

    def _on_profile_event(self, event: str, obj: dict) -> None:
        # A profile change reschedules every object bound to a policy
        # naming that profile (scheduler.go enqueueFederatedObjectsForProfile
        # analogue).  The profile's generation is part of the trigger hash,
        # so hash-gated objects re-enter the engine.
        pname = obj["metadata"]["name"]
        policies: set[tuple[str, str]] = set()

        def collect(pol: dict) -> None:
            if pol.get("spec", {}).get("schedulingProfile", "") == pname:
                policies.add(
                    (pol["metadata"].get("namespace", ""), pol["metadata"]["name"])
                )

        self.host.scan(P.PROPAGATION_POLICIES, collect)
        self.host.scan(P.CLUSTER_PROPAGATION_POLICIES, collect)
        self._enqueue_objects_for_policies(policies)

    def _cluster_sweep_sig(self, obj: dict) -> str:
        """The scheduling-relevant signature of one cluster: exactly the
        fields _clusters_hash feeds the trigger hash, plus joined-ness.
        Anything that leaves it unchanged (heartbeats, capacity status
        bumps, sync's finalizer writes) cannot change a trigger hash,
        so sweeping the keyspace for it is pure enqueue-storm."""
        state = cluster_state_from_object(obj)
        if state is None:
            return "unjoined"
        return self._clusters_hash([state])

    def _on_cluster_events(self, events: list) -> None:
        """Coalesced cluster intake: flush-level dedup BEFORE the
        router — one committed flush of K cluster events triggers at
        most ONE full-keyspace sweep, and none at all when no event
        changed a scheduling-relevant field
        (schedulingtriggers.go enqueueFederatedObjectsForCluster, minus
        the per-heartbeat replay storm)."""
        sweep = False
        for event, obj in events:
            name = obj["metadata"]["name"]
            if event == "DELETED":
                self._cluster_sweep_sigs.pop(name, None)
                sweep = True
                continue
            sig = self._cluster_sweep_sig(obj)
            if self._cluster_sweep_sigs.get(name) != sig:
                self._cluster_sweep_sigs[name] = sig
                sweep = True
        if sweep:
            self.worker.enqueue_all(self.host.keys(self._resource))

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        # Per-event (non-coalesced store) path of the same dedup.
        self._on_cluster_events([(event, obj)])

    def _on_webhook_config_event(self, event: str, obj: dict) -> None:
        """Register/refresh/remove the webhook plugin and reschedule
        everything (scheduler.go cacheWebhookPlugin + event fan-out).
        A malformed config must not escape the watch handler — it would
        break delivery to every later-registered watcher."""
        name = obj["metadata"]["name"]
        if event == "DELETED":
            self.webhook_plugins.pop(name, None)
        else:
            try:
                config = W.parse_webhook_config(obj)
            except Exception:
                self.metrics.counter(
                    f"scheduler-{self.ftc.name}.webhook_config_errors"
                )
                return
            if not any(
                v in W.SUPPORTED_PAYLOAD_VERSIONS for v in config.payload_versions
            ):
                self.metrics.counter(
                    f"scheduler-{self.ftc.name}.webhook_unsupported_payload"
                )
                self.webhook_plugins.pop(name, None)
            else:
                self.webhook_plugins[name] = W.WebhookPlugin(
                    config, client=self._webhook_client
                )
        self.worker.enqueue_all(self.host.keys(self._resource))

    # -- reconcile -------------------------------------------------------
    def _clusters(self) -> list[T.ClusterState]:
        out = []
        # list_view: cluster_state_from_object copies what it keeps.
        for obj in self.host.list_view(FEDERATED_CLUSTERS):
            state = cluster_state_from_object(obj)
            if state is not None:
                out.append(state)
        out.sort(key=lambda c: c.name)
        return out

    def _policy_for(self, fed_obj: dict) -> Optional[P.PolicySpec]:
        key = P.matched_policy_key(fed_obj)
        if key is None:
            return None
        ns, name = key
        # Watch-invalidated cache (_on_policy_event): thousands of
        # objects bind the same few policies, and per-object
        # try_get+parse was a top host cost of the scheduling tick.
        hit = self._policy_cache.get((ns, name))
        if hit is not None:
            return hit
        epoch = self._policy_epoch.get((ns, name), 0)
        resource = P.PROPAGATION_POLICIES if ns else P.CLUSTER_PROPAGATION_POLICIES
        obj = self.host.try_get(resource, f"{ns}/{name}" if ns else name)
        if obj is None:
            return None
        spec = P.parse_policy(obj)
        if self._policy_epoch.get((ns, name), 0) == epoch:
            self._policy_cache[(ns, name)] = spec
        return spec

    def _profile_for(self, policy: P.PolicySpec) -> Optional[PR.ProfileSpec]:
        """Cluster-scoped SchedulingProfile named by the policy
        (scheduler.go:371-376; missing profile schedules with defaults)."""
        if not policy.scheduling_profile:
            return None
        obj = self.host.try_get(PR.SCHEDULING_PROFILES, policy.scheduling_profile)
        return PR.parse_profile(obj) if obj else None

    @staticmethod
    def _clusters_hash(clusters) -> str:
        """One hash of the scheduling-relevant cluster state, shared by
        every object in a batch: hashing the full cluster list per object
        would be O(objects x clusters) JSON work per tick."""
        return str(
            stable_json_hash(
                [
                    [c.name, sorted(c.labels.items()),
                     [[t.key, t.value, t.effect] for t in c.taints],
                     sorted(c.api_resources)]
                    for c in clusters
                ]
            )
        )

    def _trigger_hash(
        self,
        fed_obj: dict,
        policy: P.PolicySpec,
        clusters_hash: str,
        profile: Optional[PR.ProfileSpec] = None,
        request: Optional[dict[str, int]] = None,
    ) -> str:
        ann = fed_obj["metadata"].get("annotations", {})
        scheduling_annotations = {
            k: v
            for k, v in sorted(ann.items())
            if k in (A_SCHEDULING_MODE, A_STICKY_CLUSTER, A_CLUSTER_SELECTOR,
                     A_PLACEMENTS, A_MAX_CLUSTERS)
        }
        replicas = get_path(C.template(fed_obj), self.ftc.path.replicas_spec, 0)
        trigger = {
            "annotations": scheduling_annotations,
            "replicas": replicas,
            "request": request
            if request is not None
            else extract_pod_resource_request(C.template(fed_obj)),
            "policy": [policy.namespace, policy.name, policy.generation],
            # Unlike the reference (schedulingtriggers.go hashes only the
            # policy), the profile and webhook-config generations are
            # hashed too so their edits reschedule bound objects instead
            # of being swallowed by the dedupe gate.
            "profile": [profile.name, profile.generation] if profile else None,
            # dict(...) snapshots against concurrent watch-thread mutation.
            "webhooks": sorted(
                (name, p.config.generation)
                for name, p in dict(self.webhook_plugins).items()
            ),
            "autoMigration": ann.get(C.AUTO_MIGRATION_INFO)
            if policy.auto_migration_enabled
            else None,
            "clusters": clusters_hash,
        }
        return str(stable_json_hash(trigger))

    def _scheduling_unit(
        self,
        fed_obj: dict,
        policy: P.PolicySpec,
        profile: Optional[PR.ProfileSpec] = None,
        request: Optional[dict[str, int]] = None,
    ) -> T.SchedulingUnit:
        template = C.template(fed_obj)
        meta = fed_obj["metadata"]
        ann = meta.get("annotations", {})

        mode = ann.get(A_SCHEDULING_MODE, policy.scheduling_mode)
        if mode == T.MODE_DIVIDE and not self.ftc.path.replicas_spec:
            mode = T.MODE_DUPLICATE
        desired = None
        if mode == T.MODE_DIVIDE:
            desired = get_path(template, self.ftc.path.replicas_spec)
            if desired is None:
                desired = 0

        # Current placements + this controller's replicas overrides
        # (schedulingunit.go:181-221).
        current: dict[str, Optional[int]] = {}
        placement = C.get_placement(fed_obj, self.name)
        if placement:
            own_overrides = C.get_overrides(fed_obj, self.name)
            replicas_path = "/" + self.ftc.path.replicas_spec.replace(".", "/")
            for cluster in placement:
                current[cluster] = None
                for patch in own_overrides.get(cluster, ()):
                    if patch.get("path") == replicas_path and patch.get("op", "replace") == "replace":
                        current[cluster] = int(patch["value"])
                        break

        auto = None
        if policy.auto_migration_enabled:
            info_raw = ann.get(C.AUTO_MIGRATION_INFO)
            estimated = {}
            if info_raw:
                estimated = _json.loads(info_raw).get("estimatedCapacity", {}) or {}
            auto = T.AutoMigrationSpec(
                keep_unschedulable_replicas=policy.keep_unschedulable_replicas,
                estimated_capacity={k: int(v) for k, v in estimated.items()},
            )

        sticky = ann.get(A_STICKY_CLUSTER, "").lower() == "true" or (
            A_STICKY_CLUSTER not in ann and policy.sticky_cluster
        )

        # Per-object annotation overrides of the policy's cluster set and
        # preferences (schedulingunit.go getters: placements annotation is
        # a JSON Placement list, cluster-selector a JSON object).
        cluster_selector = policy.cluster_selector
        if A_CLUSTER_SELECTOR in ann:
            cluster_selector = dict(_json.loads(ann[A_CLUSTER_SELECTOR]))
        cluster_names = policy.cluster_names
        min_replicas = policy.min_replicas()
        max_replicas = policy.max_replicas()
        weights = policy.weights()
        if A_PLACEMENTS in ann:
            placements = _json.loads(ann[A_PLACEMENTS])
            cluster_names = frozenset(p["cluster"] for p in placements)
            min_replicas, max_replicas, weights = {}, {}, {}
            for p in placements:
                prefs = p.get("preferences", {})
                if "minReplicas" in prefs:
                    min_replicas[p["cluster"]] = int(prefs["minReplicas"])
                if prefs.get("maxReplicas") is not None:
                    max_replicas[p["cluster"]] = int(prefs["maxReplicas"])
                if prefs.get("weight") is not None:
                    weights[p["cluster"]] = int(prefs["weight"])
        max_clusters = policy.max_clusters
        if A_MAX_CLUSTERS in ann:
            max_clusters = int(ann[A_MAX_CLUSTERS])

        # Profile-resolved plugin enablement (profile.go createFramework).
        # Disabling MaxCluster at the select point removes the top-K cap.
        enabled_filters, enabled_scores, enabled_selects = PR.resolve_plugins(profile)
        if T.MAX_CLUSTER not in enabled_selects:
            max_clusters = None

        return T.SchedulingUnit(
            gvk=self.ftc.source.gvk,
            namespace=meta.get("namespace", ""),
            name=meta["name"],
            labels=dict(template.get("metadata", {}).get("labels", {})),
            annotations=dict(template.get("metadata", {}).get("annotations", {})),
            desired_replicas=desired,
            resource_request=request
            if request is not None
            else extract_pod_resource_request(template),
            current_clusters=current,
            auto_migration=auto,
            scheduling_mode=mode,
            sticky_cluster=sticky,
            avoid_disruption=policy.avoid_disruption,
            cluster_selector=cluster_selector,
            cluster_names=cluster_names,
            affinity=policy.affinity(),
            tolerations=policy.tolerations,
            max_clusters=max_clusters,
            min_replicas=min_replicas,
            max_replicas=max_replicas,
            weights=weights,
            enabled_filters=enabled_filters,
            enabled_scores=enabled_scores,
            enabled_selects=enabled_selects,
        )

    def reconcile_batch(self, keys: list[str]) -> dict[str, Result]:
        results: dict[str, Result] = {}
        # SLO provenance: the batch pickup closes the ingress→scheduler
        # "queued" stage for every due key carrying a token
        # (runtime/slo.py; non-pending keys are one dict probe each).
        slo.mark_many(keys, "queued")
        clusters = self._clusters()
        clusters_hash = self._clusters_hash(clusters)
        # One profile lookup per distinct name per batch, not per object.
        profile_memo: dict[str, Optional[PR.ProfileSpec]] = {}

        def profile_for(policy: P.PolicySpec) -> Optional[PR.ProfileSpec]:
            name = policy.scheduling_profile
            if name not in profile_memo:
                profile_memo[name] = self._profile_for(policy)
            return profile_memo[name]

        to_schedule: list[tuple[str, dict, P.PolicySpec, str]] = []
        units = []
        for key in keys:
            # Per-object isolation: one malformed object (bad annotation
            # JSON, bad override value) must not poison the whole batch —
            # it alone backs off, matching the reference's per-object
            # worker semantics.
            try:
                fed_obj = self.host.try_get(self._resource, key)
                if fed_obj is None or fed_obj["metadata"].get("deletionTimestamp"):
                    results[key] = Result.ok()
                    continue
                try:
                    if not pending.dependencies_fulfilled(fed_obj, self.name):
                        results[key] = Result.ok()
                        continue
                except KeyError:
                    results[key] = Result.ok()  # not yet initialized by federate
                    continue
                if P.matched_policy_key(fed_obj) is None:
                    # No policy bound: deschedule (empty own placement)
                    # but still advance the pipeline so downstream
                    # controllers — override, follower, sync — process
                    # the object (scheduler.go:454-466 + persist).
                    results[key] = self._deschedule(fed_obj)
                    continue
                policy = self._policy_for(fed_obj)
                if policy is None:
                    # Bound policy not created yet: wait for its event
                    # (scheduler.go:356-367).
                    results[key] = Result.ok()
                    continue
                profile = profile_for(policy)
                # One template walk feeds both the trigger hash and the
                # scheduling unit (it was the tick's top repeated cost).
                request = extract_pod_resource_request(C.template(fed_obj))
                trigger = self._trigger_hash(
                    fed_obj, policy, clusters_hash, profile, request=request
                )
                if fed_obj["metadata"].get("annotations", {}).get(C.SCHEDULING_TRIGGER_HASH) == trigger:
                    # Skip scheduling, but still advance the pipeline:
                    # template-only changes re-arm pending-controllers
                    # without changing the trigger hash, and downstream
                    # controllers (override, sync) must still run
                    # (scheduler.go:423-434).
                    results[key] = self._advance_pipeline(fed_obj, modified=False)
                    continue
                units.append(
                    self._scheduling_unit(fed_obj, policy, profile, request=request)
                )
            except Exception:
                self.metrics.counter(f"scheduler-{self.ftc.name}.unit_errors")
                results[key] = Result.retry()
                continue
            to_schedule.append((key, fed_obj, policy, trigger))

        if not to_schedule:
            return results
        # Unit assembly done: the "slab" stage (trigger hashing +
        # featurization prep) closes; "engine" closes when the solve
        # returns, "fetch" when the placements are persisted below.
        slo_keys = [k for k, _, _, _ in to_schedule] if slo.active() else ()
        slo.mark_many(slo_keys, "slab")
        with trace.span(
            "scheduler.engine_tick", ftc=self.ftc.name, units=len(units)
        ) as tick_span, self.metrics.timer(
            f"scheduler-{self.ftc.name}.engine_latency"
        ):
            # ONE watch-thread-safe snapshot for the whole tick: the
            # score-decode decision and the select pass must agree on
            # the plugin set, or a select plugin registered mid-tick
            # would narrow on fabricated zero scores.
            plugins = dict(self.webhook_plugins)
            webhook_eval = self._webhook_eval(plugins, units, clusters)
            # Score decoding only matters when a select webhook might
            # consume it (the decode is the engine's main host cost).
            want_scores = any(p.has_select for p in plugins.values())
            outcomes = self.engine.schedule(
                units, clusters, webhook_eval=webhook_eval, want_scores=want_scores
            )
            outcomes = self._apply_webhook_selects(
                units, clusters, outcomes, plugins, webhook_eval
            )
            tick_span.set(tick=getattr(self.engine, "last_tick_id", 0))
        slo.mark_many(slo_keys, "engine")
        self.metrics.counter(f"scheduler-{self.ftc.name}.scheduled", len(units))
        self.metrics.counter(
            "scheduler_scheduled_total", len(units), ftc=self.ftc.name
        )
        # Per-tenant demand attribution (runtime/tenancy.py; no-op
        # unless a ledger is installed): which tenants are driving the
        # scheduler — the denominator the fair-admission arbitration
        # will weigh deferrals and sheds against.
        if tenancy.active():
            by_tenant: dict[str, int] = {}
            for key, _, _, _ in to_schedule:
                t = tenancy.tenant_of_key(key)
                by_tenant[t] = by_tenant.get(t, 0) + 1
            for t, n_objs in by_tenant.items():
                tenancy.note_scheduled(t, n_objs)

        hb = HostBatch(self.host)
        # The engine tick id rides the persist span too, so the event ->
        # engine -> member-write timeline joins on one id in
        # /debug/trace (and against /debug/waterfall).
        with trace.span(
            "scheduler.persist", ftc=self.ftc.name, units=len(to_schedule),
            tick=getattr(self.engine, "last_tick_id", 0),
        ):
            try:
                for (key, fed_obj, policy, trigger), outcome in zip(
                    to_schedule, outcomes
                ):
                    # Per-key isolation: one poison object backs off
                    # alone; every already-staged placement still
                    # flushes.
                    try:
                        results[key] = self._persist(
                            key, fed_obj, policy, trigger, outcome, hb, results
                        )
                    except Exception:
                        self.metrics.counter(
                            f"scheduler-{self.ftc.name}.persist_panic"
                        )
                        results[key] = Result.retry()
            finally:
                # ONE bulk host round trip persists every placement.
                hb.flush()
        slo.mark_many(slo_keys, "fetch")
        return results

    # -- webhook (out-of-process) plugins --------------------------------
    @staticmethod
    def _sticky_skip(su: T.SchedulingUnit) -> bool:
        """Plugins never run for a stickily placed object
        (generic_scheduler.go:103-107)."""
        return su.sticky_cluster and bool(su.current_clusters)

    def _webhook_eval(
        self, plugins: dict[str, W.WebhookPlugin], units=(), clusters=()
    ):
        """Host-side evaluator handed to the engine: AND of the unit's
        enabled webhook filters, sum of its webhook scores, per cluster.
        Any failing webhook call marks the cluster infeasible for this
        tick (the batch-mode analogue of the reference failing the whole
        per-object schedule and backing off).

        Batch-capable plugins are evaluated upfront: ONE POST per plugin
        per tick ships the whole (units x clusters) grid (vs the
        reference's O(B x C) HTTP calls, webhook/v1alpha1/plugin.go:77-251).
        Per-pair servers fall back to thread-pooled calls, memoized by
        object key so the select-narrowing rerun repeats nothing.
        ``plugins`` is the tick's plugin snapshot."""
        if not plugins:
            return None
        if self._webhook_pool is None:
            self._webhook_pool = ThreadPoolExecutor(
                max_workers=16, thread_name_prefix="webhook-eval"
            )
        pool = self._webhook_pool
        cache: dict[str, Optional[tuple]] = {}
        clusters = list(clusters)

        # -- upfront batched calls ---------------------------------------
        # plugin name -> key -> bool row / int row; a None row marks a
        # batch call that failed with a protocol error (all clusters
        # infeasible for those units this tick).
        prefilter: dict[str, dict[str, Optional[np.ndarray]]] = {}
        prescore: dict[str, dict[str, Optional[np.ndarray]]] = {}
        for name, plugin in plugins.items():
            if plugin.has_filter:
                subset = [
                    su
                    for su in units
                    if not self._sticky_skip(su)
                    and name in (su.enabled_filters or ())
                ]
                if subset:
                    try:
                        rows = plugin.filter_batch(subset, clusters)
                    except Exception:
                        self.metrics.counter(
                            f"scheduler-{self.ftc.name}.webhook_errors"
                        )
                        rows = [None] * len(subset)
                    if rows is not None:
                        prefilter[name] = {
                            su.key: np.asarray(row, bool)
                            if row is not None
                            else None
                            for su, row in zip(subset, rows)
                        }
            if plugin.has_score:
                subset = [
                    su
                    for su in units
                    if not self._sticky_skip(su)
                    and name in (su.enabled_scores or ())
                ]
                if subset:
                    try:
                        rows = plugin.score_batch(subset, clusters)
                    except Exception:
                        self.metrics.counter(
                            f"scheduler-{self.ftc.name}.webhook_errors"
                        )
                        rows = [None] * len(subset)
                    if rows is not None:
                        prescore[name] = {
                            su.key: np.asarray(row, np.int64)
                            if row is not None
                            else None
                            for su, row in zip(subset, rows)
                        }

        def eval_cluster(su, cluster, filters, scorers):
            score = np.int64(0)
            try:
                for plugin in filters:
                    if not plugin.filter(su, cluster):
                        return False, score
                for plugin in scorers:
                    score += plugin.score(su, cluster)
            except Exception:
                self.metrics.counter(f"scheduler-{self.ftc.name}.webhook_errors")
                return False, np.int64(0)
            return True, score

        def evaluate(su: T.SchedulingUnit, eval_clusters):
            if su.key in cache:
                return cache[su.key]
            if self._sticky_skip(su):
                cache[su.key] = None
                return None
            filters = [
                p
                for name in (su.enabled_filters or ())
                if (p := plugins.get(name)) is not None and p.has_filter
            ]
            scorers = [
                p
                for name in (su.enabled_scores or ())
                if (p := plugins.get(name)) is not None and p.has_score
            ]
            if not filters and not scorers:
                cache[su.key] = None
                return None
            c = len(eval_clusters)
            ok = np.ones(c, bool)
            score = np.zeros(c, np.int64)
            failed = False
            pair_filters, pair_scorers = [], []
            for plugin in filters:
                pre = prefilter.get(plugin.name)
                if pre is None:
                    pair_filters.append(plugin)
                    continue
                row = pre.get(su.key)
                if row is None:  # batch protocol error: infeasible tick
                    failed = True
                    break
                ok &= row
            if not failed:
                for plugin in scorers:
                    pre = prescore.get(plugin.name)
                    if pre is None:
                        pair_scorers.append(plugin)
                        continue
                    row = pre.get(su.key)
                    if row is None:
                        failed = True
                        break
                    score = score + row
            if failed:
                result = (np.zeros(c, bool), np.zeros(c, np.int64))
                cache[su.key] = result
                return result
            if pair_filters or pair_scorers:
                rows = list(
                    pool.map(
                        lambda cluster: eval_cluster(
                            su, cluster, pair_filters, pair_scorers
                        ),
                        eval_clusters,
                    )
                )
                ok &= np.array([r[0] for r in rows], bool)
                score = score + np.array([r[1] for r in rows], np.int64)
            cache[su.key] = (ok, score)
            return ok, score

        return evaluate

    def _apply_webhook_selects(
        self,
        units,
        clusters,
        outcomes: list[ScheduleResult],
        plugins: dict[str, W.WebhookPlugin],
        webhook_eval=None,
    ) -> list[ScheduleResult]:
        """Webhook select plugins narrow the tick's selected set; affected
        Divide-mode units are re-planned over the narrowed set in one
        follow-up batch (the sequential RunSelectClustersPlugin chain,
        framework.go:183-209, with the planner re-run batched).  The
        first pass's memoizing evaluator is reused so the rerun repeats
        no webhook filter/score calls; ``plugins`` is the same snapshot
        the scores were decoded for."""
        if not plugins:
            return outcomes
        by_name = {c.name: c for c in clusters}
        rerun_units, rerun_slots = [], []
        for i, (su, outcome) in enumerate(zip(units, outcomes)):
            if su.sticky_cluster and su.current_clusters:
                # Plugins never run for a stickily placed object
                # (generic_scheduler.go:103-107).
                continue
            selects = [
                p
                for name in (su.enabled_selects or ())
                if (p := plugins.get(name)) is not None and p.has_select
            ]
            if not selects or not outcome.clusters:
                continue
            narrowed = set(outcome.clusters)
            try:
                for plugin in selects:
                    cluster_scores = [
                        (by_name[c], outcome.scores.get(c, 0))
                        for c in sorted(narrowed)
                        if c in by_name
                    ]
                    narrowed &= set(plugin.select(su, cluster_scores))
            except Exception:
                self.metrics.counter(f"scheduler-{self.ftc.name}.webhook_errors")
                continue  # keep the un-narrowed outcome this tick
            if narrowed == set(outcome.clusters):
                continue
            if not narrowed:
                # An empty cluster_names means "no explicit placement" to
                # the featurizer, so short-circuit instead of re-running.
                outcomes = list(outcomes)
                outcomes[i] = ScheduleResult(clusters={})
                continue
            rerun_units.append(
                dataclasses.replace(
                    su,
                    cluster_names=frozenset(narrowed),
                    enabled_filters=tuple(
                        dict.fromkeys(
                            (su.enabled_filters or ()) + (T.PLACEMENT_FILTER,)
                        )
                    ),
                    enabled_selects=None,
                    max_clusters=None,
                )
            )
            rerun_slots.append(i)
        if not rerun_units:
            return outcomes
        rerun_outcomes = self.engine.schedule(
            rerun_units, clusters, webhook_eval=webhook_eval
        )
        outcomes = list(outcomes)
        for slot, new_outcome in zip(rerun_slots, rerun_outcomes):
            outcomes[slot] = new_outcome
        return outcomes

    def _record_schedule_event(
        self, key: str, fed_obj: dict, outcome: ScheduleResult, modified: bool
    ) -> None:
        """Scheduled / ScheduleFailed events with the flight recorder's
        explanation strings (scheduler.go's schedulingUnit events).
        Emitted when the decision changed (or failed), so steady-state
        re-persists don't churn event objects; identical repeats bump
        the event count instead of piling up."""
        try:
            if outcome.clusters:
                if not modified:
                    return
                placements = ", ".join(
                    name if reps is None else f"{name}({int(reps)})"
                    for name, reps in sorted(outcome.clusters.items())
                )
                self.recorder.event(
                    fed_obj, "Normal", "Scheduled",
                    f"scheduled to {len(outcome.clusters)} cluster(s): "
                    f"{placements}",
                )
                return
            detail = "no cluster selected"
            rec = getattr(self.engine, "flightrec", None)
            record = rec.lookup(key) if rec is not None else None
            if record is not None:
                from kubeadmiral_tpu.runtime import flightrec as FR

                summary = FR.summarize_reasons(record)
                if summary:
                    detail = f"no cluster selected: {summary}"
            self.recorder.event(fed_obj, "Warning", "ScheduleFailed", detail)
        except Exception:
            pass  # event loss must never fail a persist

    # -- persistence -----------------------------------------------------
    def _advance_pipeline(self, fed_obj: dict, modified: bool) -> Result:
        """Remove self from pending-controllers (re-arming downstream when
        ``modified``) and persist, sharing the Conflict/NotFound policy of
        every scheduler write."""
        if not pending.update_pending(
            fed_obj, self.name, modified, self.ftc.controller_groups
        ):
            return Result.ok()
        try:
            self.host.update(self._resource, fed_obj)
        except Conflict:
            return Result.retry()
        except NotFound:
            pass
        return Result.ok()

    def _deschedule(self, fed_obj: dict) -> Result:
        """No policy bound: clear own placement/overrides and hand off
        downstream (scheduler.go schedule() with nil policy)."""
        assert self._shard.owns(obj_key(fed_obj)), (
            f"shard violation: replica {self._shard.shard_index}/"
            f"{self._shard.shard_count} descheduling non-owned key "
            f"{obj_key(fed_obj)}"
        )
        modified = C.set_placement(fed_obj, self.name, set())
        if C.get_overrides(fed_obj, self.name):
            C.set_overrides(fed_obj, self.name, {})
            modified = True
        pend = pending.update_pending(
            fed_obj, self.name, modified, self.ftc.controller_groups
        )
        if not (modified or pend):
            return Result.ok()
        try:
            self.host.update(self._resource, fed_obj)
        except Conflict:
            return Result.retry()
        except NotFound:
            pass
        return Result.ok()

    def _persist(
        self,
        key: str,
        fed_obj: dict,
        policy: P.PolicySpec,
        trigger: str,
        outcome: ScheduleResult,
        hb: HostBatch,
        results: dict,
    ) -> Result:
        # Disjoint-by-construction guard: a replica persists placements
        # ONLY for keys its shard owns.  The intake boundary already
        # filters, so tripping this means a key bypassed the router
        # (double-scheduling across replicas) — fail loudly.
        assert self._shard.owns(key), (
            f"shard violation: replica {self._shard.shard_index}/"
            f"{self._shard.shard_count} persisting non-owned key {key}"
        )
        modified = C.set_placement(fed_obj, self.name, outcome.cluster_set)

        # Replicas overrides for Divide-mode results (scheduler/util.go:71-110).
        desired = {
            cl: reps for cl, reps in outcome.clusters.items() if reps is not None
        }
        replicas_path = "/" + self.ftc.path.replicas_spec.replace(".", "/") if self.ftc.path.replicas_spec else None
        own = C.get_overrides(fed_obj, self.name)
        new_overrides: dict[str, list] = {}
        if replicas_path:
            for cl, reps in desired.items():
                new_overrides[cl] = [
                    {"op": "replace", "path": replicas_path, "value": int(reps)}
                ]
        if new_overrides != own:
            C.set_overrides(fed_obj, self.name, new_overrides)
            modified = True

        ann = fed_obj["metadata"].setdefault("annotations", {})
        follower_value = "false" if policy.disable_follower_scheduling else "true"
        if ann.get(ENABLE_FOLLOWER_SCHEDULING) != follower_value:
            ann[ENABLE_FOLLOWER_SCHEDULING] = follower_value
            modified = True
        if policy.auto_migration_enabled and policy.pod_unschedulable_seconds is not None:
            threshold = f"{policy.pod_unschedulable_seconds:g}s"
            if ann.get(POD_UNSCHEDULABLE_THRESHOLD) != threshold:
                ann[POD_UNSCHEDULABLE_THRESHOLD] = threshold
                modified = True
        elif POD_UNSCHEDULABLE_THRESHOLD in ann:
            del ann[POD_UNSCHEDULABLE_THRESHOLD]
            modified = True

        ann[C.SCHEDULING_TRIGGER_HASH] = trigger
        pending.update_pending(fed_obj, self.name, modified, self.ftc.controller_groups)
        self._record_schedule_event(key, fed_obj, outcome, modified)

        def on_persist(result: dict) -> None:
            code = result.get("code")
            if code in (200, 404):
                return  # persisted, or object gone
            # Conflict (or transport): requeue with backoff; the next
            # tick re-reads the object, recomputes the trigger hash and
            # reschedules — the batch analogue of the reference's
            # per-object retry loop.
            results[key] = Result.retry()

        def on_panic() -> None:
            results[key] = Result.retry()

        hb.stage(
            {"verb": "update", "resource": self._resource, "object": fed_obj},
            on_persist,
            on_panic,
        )
        return Result.ok()
