"""Status collection and aggregation.

Two per-FTC controllers close the feedback loop from member clusters back
to the user:

* :class:`StatusController` — collects the FTC's ``statusCollection``
  dotted fields from each placed member object into a companion status CR
  (``FederatedXStatus`` with ``clusterStatus: [{clusterName, ...fields}]``),
  owned by the federated object (reference:
  pkg/controllers/status/controller.go:126-686).
* :class:`StatusAggregator` — folds member statuses back onto the
  **source** object via per-kind plugins: Deployments get summed
  replica counts on the status subresource; other kinds get the
  sourcefeedback annotation (reference:
  pkg/controllers/statusaggregator/controller.go:110-399, plugins/).
"""

from __future__ import annotations

from typing import Callable, Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    ClusterFleet,
    Conflict,
    NotFound,
    obj_key,
)
from kubeadmiral_tpu.utils.unstructured import copy_json, get_path, set_path


def _retry_pending_attach(reattach, worker, host, fed_resource) -> None:
    """Heartbeat-path retry for transiently failed member-watch attaches
    (mirrors sync's check).  These watches attach with replay=False, so a
    late success re-delivers nothing — whenever the pending set SHRANK
    (not only when it drained: other clusters may still be unjoined),
    fan the fed objects out to pick up statuses that accrued while
    unattached."""
    before = getattr(reattach, "pending", None)
    if not before:
        return
    before = set(before)
    reattach()
    after = set(getattr(reattach, "pending", None) or ())
    if before - after:
        worker.enqueue_all(host.keys(fed_resource))


class StatusController:
    """Collects member-object fields into the status CR."""

    name = "status-controller"

    def __init__(
        self,
        fleet: ClusterFleet,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        if ftc.status is None:
            raise ValueError(f"FTC {ftc.name} has no status type")
        self.fleet = fleet
        self.host = fleet.host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._fed_resource = ftc.federated.resource
        self._target_resource = ftc.source.resource
        self._status_resource = ftc.status.resource
        self.worker = Worker(
            f"status-{ftc.name}", self.reconcile, metrics=self.metrics, clock=clock
        )
        self._cluster_sigs: dict[str, tuple] = {}
        self.host.watch(self._fed_resource, self._on_fed_event, replay=True)
        self.host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)
        self._reattach = fleet.watch_members(
            self._target_resource, self._on_member_event
        )

    def _on_fed_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_member_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        sig = C.cluster_lifecycle_sig(obj)
        name = obj["metadata"]["name"]
        if event == "DELETED":
            self._cluster_sigs.pop(name, None)  # re-creation must fan out
        elif self._cluster_sigs.get(name) == sig:
            # Heartbeat bump: nothing placement-relevant changed, but a
            # transiently failed member-watch attach still needs its
            # retry channel.
            _retry_pending_attach(
                self._reattach, self.worker, self.host, self._fed_resource
            )
            return
        else:
            self._cluster_sigs[name] = sig
        self._reattach()
        self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- reconcile (status/controller.go:291-450) ------------------------
    def reconcile(self, key: str) -> Result:
        self.metrics.counter("status.throughput")
        fed_obj = self.host.try_get(self._fed_resource, key)

        if fed_obj is None or fed_obj["metadata"].get("deletionTimestamp"):
            # Federated object gone: drop the status CR.
            try:
                self.host.delete(self._status_resource, key)
            except NotFound:
                pass
            return Result.ok()

        cluster_status = self._cluster_statuses(fed_obj, key)
        desired = {
            "apiVersion": self.ftc.status.api_version,
            "kind": self.ftc.status.kind,
            "metadata": {
                "name": fed_obj["metadata"]["name"],
                "labels": dict(fed_obj["metadata"].get("labels", {}) or {}),
            },
            "clusterStatus": cluster_status,
        }
        if fed_obj["metadata"].get("namespace"):
            desired["metadata"]["namespace"] = fed_obj["metadata"]["namespace"]

        existing = self.host.try_get(self._status_resource, key)
        if existing is None:
            try:
                self.host.create(self._status_resource, desired)
            except AlreadyExists:
                return Result.retry()
            return Result.ok()

        if (
            existing.get("clusterStatus") != cluster_status
            or (existing["metadata"].get("labels") or {})
            != desired["metadata"]["labels"]
        ):
            existing["clusterStatus"] = cluster_status
            existing["metadata"]["labels"] = desired["metadata"]["labels"]
            try:
                self.host.update(self._status_resource, existing)
            except Conflict:
                return Result.retry()
            except NotFound:
                return Result.retry()
        return Result.ok()

    def _cluster_statuses(self, fed_obj: dict, key: str) -> list[dict]:
        """Per placed cluster, the collected dotted fields
        (status/controller.go:491-560 clusterStatuses)."""
        placed = sorted(C.all_placement_clusters(fed_obj))
        out = []
        for cname in placed:
            entry: dict = {"clusterName": cname}
            try:
                member = self.fleet.member(cname)
            except NotFound:
                entry["error"] = "cluster unavailable"
                out.append(entry)
                continue
            # View read: only the collected fields are retained, deep-
            # copied below (copying whole member objects per cluster per
            # round dominated status collection at scale).
            obj = member.try_get_view(self._target_resource, key)
            if obj is None:
                continue  # not propagated yet: skip silently
            collected: dict = {}
            for field in self.ftc.status_collection_fields:
                value = get_path(obj, field)
                if value is None:
                    continue
                set_path(collected, field, copy_json(value))
            entry["collectedFields"] = collected
            out.append(entry)
        return out


# -- aggregation plugins (statusaggregator/plugins/) ---------------------

_SUMMED_DEPLOYMENT_FIELDS = (
    "replicas",
    "updatedReplicas",
    "readyReplicas",
    "availableReplicas",
    "unavailableReplicas",
)


def aggregate_workload_status(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Deployment-family aggregation: sum the replica counters across
    clusters; bump observedGeneration to the source's generation only
    when every member status reflects the latest sync
    (plugins/deployment.go:42-160)."""
    agg = {f: 0 for f in _SUMMED_DEPLOYMENT_FIELDS}
    if not cluster_objs:
        up_to_date = False
    for obj in cluster_objs.values():
        status = obj.get("status")
        if not status:
            up_to_date = False
            continue
        for f in _SUMMED_DEPLOYMENT_FIELDS:
            agg[f] += int(status.get(f, 0) or 0)
    new_status = {f: v for f, v in agg.items() if v}
    if up_to_date:
        new_status["observedGeneration"] = source["metadata"].get("generation", 1)
    else:
        old = (source.get("status") or {}).get("observedGeneration")
        if old is not None:
            new_status["observedGeneration"] = old
    return new_status


def aggregate_single_cluster(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Adopt the lone member's status verbatim; ambiguous with more than
    one placement (plugins/single_cluster_plugin.go)."""
    if len(cluster_objs) != 1:
        return None
    (obj,) = cluster_objs.values()
    return obj.get("status")


def _job_finished_failed(status: dict) -> bool:
    return any(
        c.get("type") == "Failed" and c.get("status") == "True"
        for c in status.get("conditions", []) or []
    )


def aggregate_job_status(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Jobs: sum active/succeeded/failed, min startTime; once every
    cluster's job finished, a federation-level Complete/Failed condition
    summarizes where it completed vs failed (plugins/job.go:43-140).
    Timestamps are RFC3339 strings, so lexicographic min/max is
    chronological."""
    agg: dict = {"active": 0, "succeeded": 0, "failed": 0}
    start_time: Optional[str] = None
    completion_time: Optional[str] = None
    finished = 0
    completed_in: list[str] = []
    failed_in: list[str] = []
    for cname, obj in sorted(cluster_objs.items()):
        status = obj.get("status")
        if not status:
            continue
        st = status.get("startTime")
        if st and (start_time is None or st < start_time):
            start_time = st
        ct = status.get("completionTime")
        if ct:
            finished += 1
            completed_in.append(cname)
            if completion_time is None or ct > completion_time:
                completion_time = ct
        elif _job_finished_failed(status):
            finished += 1
            failed_in.append(cname)
        for f in ("active", "succeeded", "failed"):
            agg[f] += int(status.get(f, 0) or 0)

    new_status = {f: v for f, v in agg.items() if v}
    if start_time is not None:
        new_status["startTime"] = start_time
    if finished > 0 and finished == len(cluster_objs):
        if completed_in and failed_in:
            cond = {
                "type": "Failed",
                "status": "True",
                "reason": "Mixed",
                "message": (
                    f"Job completed in clusters {completed_in} "
                    f"and failed in clusters {failed_in}"
                ),
            }
        elif completed_in:
            cond = {
                "type": "Complete",
                "status": "True",
                "reason": "Completed",
                "message": f"Job completed in clusters {completed_in}",
            }
            if completion_time is not None:
                new_status["completionTime"] = completion_time
        else:
            cond = {
                "type": "Failed",
                "status": "True",
                "reason": "Failed",
                "message": f"Job failed in clusters {failed_in}",
            }
        new_status["conditions"] = [cond]
    return new_status


# Phase precedence: any failure dominates, then pending, running, and only
# all-succeeded reads Succeeded (plugins/pod.go:101-130).
_POD_PHASE_ORDER = ("Failed", "Pending", "Running", "Succeeded")


def aggregate_pod_status(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Pods: federation-level phase by precedence, min startTime, member
    container statuses concatenated with the cluster name suffixed
    (plugins/pod.go:41-130)."""
    phases: dict[str, list[str]] = {p: [] for p in _POD_PHASE_ORDER}
    new_status: dict = {}
    start_time: Optional[str] = None
    containers: list[dict] = []
    init_containers: list[dict] = []
    for cname, obj in sorted(cluster_objs.items()):
        status = obj.get("status") or {}
        phase = status.get("phase") or "Pending"
        if phase in phases:
            phases[phase].append(cname)
        st = status.get("startTime")
        if st and (start_time is None or st < start_time):
            start_time = st
        for cs in status.get("initContainerStatuses", []) or []:
            cs = dict(cs)
            cs["name"] = f"{cs.get('name')} ({cname})"
            init_containers.append(cs)
        for cs in status.get("containerStatuses", []) or []:
            cs = dict(cs)
            cs["name"] = f"{cs.get('name')} ({cname})"
            containers.append(cs)

    messages = []
    for phase in _POD_PHASE_ORDER:
        if not phases[phase]:
            continue
        new_status.setdefault("phase", phase)
        messages.append(f"pod is {phase} in clusters {sorted(phases[phase])}")
    if messages:
        new_status["message"] = "; ".join(messages)
    if start_time is not None:
        new_status["startTime"] = start_time
    if init_containers:
        new_status["initContainerStatuses"] = init_containers
    if containers:
        new_status["containerStatuses"] = containers
    return new_status


# GVK -> plugin, mirroring the reference registry (plugins/plugin.go:42-47:
# Deployment summed, StatefulSet single-cluster, Job merged, Pod phased).
AGGREGATION_PLUGINS: dict[str, Callable] = {
    "apps/v1/Deployment": aggregate_workload_status,
    "apps/v1/StatefulSet": aggregate_single_cluster,
    "batch/v1/Job": aggregate_job_status,
    "v1/Pod": aggregate_pod_status,
}


class StatusAggregator:
    """Folds member statuses back onto the source object."""

    name = "status-aggregator"

    def __init__(
        self,
        fleet: ClusterFleet,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        self.fleet = fleet
        self.host = fleet.host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._fed_resource = ftc.federated.resource
        self._target_resource = ftc.source.resource
        self.plugin = AGGREGATION_PLUGINS.get(ftc.source.gvk)
        self.worker = Worker(
            f"statusagg-{ftc.name}", self.reconcile, metrics=self.metrics, clock=clock
        )
        self._cluster_sigs: dict[str, tuple] = {}
        self.host.watch(self._fed_resource, self._on_event, replay=True)
        self.host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)
        self._reattach = fleet.watch_members(self._target_resource, self._on_event)

    def _on_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        sig = C.cluster_lifecycle_sig(obj)
        name = obj["metadata"]["name"]
        if event == "DELETED":
            self._cluster_sigs.pop(name, None)
        elif self._cluster_sigs.get(name) == sig:
            _retry_pending_attach(
                self._reattach, self.worker, self.host, self._fed_resource
            )
            return
        else:
            self._cluster_sigs[name] = sig
        self._reattach()
        self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- reconcile (statusaggregator/controller.go:291-399) --------------
    def reconcile(self, key: str) -> Result:
        self.metrics.counter("statusagg.throughput")
        source = self.host.try_get(self._target_resource, key)
        fed_obj = self.host.try_get(self._fed_resource, key)
        if source is None or fed_obj is None:
            return Result.ok()
        if source["metadata"].get("deletionTimestamp"):
            return Result.ok()

        cluster_objs: dict[str, dict] = {}
        up_to_date = True
        synced = {
            c.get("cluster"): c.get("status")
            for c in (fed_obj.get("status", {}) or {}).get("clusters", [])
        }
        for cname in sorted(C.all_placement_clusters(fed_obj)):
            try:
                member = self.fleet.member(cname)
            except NotFound:
                up_to_date = False
                continue
            # View read: aggregation plugins only read fields; any status
            # they return is deep-copied by the store on write.
            obj = member.try_get_view(self._target_resource, key)
            if obj is None:
                up_to_date = False
                continue
            if synced.get(cname) != "OK":
                up_to_date = False
            cluster_objs[cname] = obj

        plugin = self.plugin
        if plugin is not None:
            new_status = plugin(source, cluster_objs, up_to_date)
            if new_status is not None and new_status != source.get("status"):
                source["status"] = new_status
                try:
                    self.host.update_status(self._target_resource, source)
                except (Conflict, NotFound):
                    return Result.retry()
            return Result.ok()

        # No plugin: record statuses in the sourcefeedback annotation
        # (sourcefeedback/status.go).
        feedback = C.compact_json(
            {
                "clusters": [
                    {"name": c, "status": o.get("status")}
                    for c, o in sorted(cluster_objs.items())
                    if o.get("status") is not None
                ]
            }
        )
        ann = source["metadata"].setdefault("annotations", {})
        if ann.get(C.SOURCE_FEEDBACK_STATUS) != feedback:
            ann[C.SOURCE_FEEDBACK_STATUS] = feedback
            try:
                self.host.update(self._target_resource, source)
            except (Conflict, NotFound):
                return Result.retry()
        return Result.ok()
