"""Status collection and aggregation.

Two per-FTC controllers close the feedback loop from member clusters back
to the user:

* :class:`StatusController` — collects the FTC's ``statusCollection``
  dotted fields from each placed member object into a companion status CR
  (``FederatedXStatus`` with ``clusterStatus: [{clusterName, ...fields}]``),
  owned by the federated object (reference:
  pkg/controllers/status/controller.go:126-686).
* :class:`StatusAggregator` — folds member statuses back onto the
  **source** object via per-kind plugins: Deployments get summed
  replica counts on the status subresource; other kinds get the
  sourcefeedback annotation (reference:
  pkg/controllers/statusaggregator/controller.go:110-399, plugins/).
"""

from __future__ import annotations

import os
from typing import Callable, Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.dispatch import bulk_get
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime.hostbatch import HostBatch
from kubeadmiral_tpu.runtime.informer import MemberStore
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import BatchWorker, Result
from kubeadmiral_tpu.testing.fakekube import ClusterFleet, FakeKube, obj_key
from kubeadmiral_tpu.transport import breaker as B
from kubeadmiral_tpu.utils.unstructured import copy_json, get_path, set_path


def _host_bulk_reads(host) -> bool:
    """Bulk host point reads (KT_BULK_READS): only worth a round trip
    on network hosts — an in-process store's view reads are free."""
    return not isinstance(host, FakeKube) and os.environ.get(
        "KT_BULK_READS", "1"
    ) not in ("0", "false", "no")


def _retry_pending_attach(store: MemberStore, worker, host, fed_resource) -> None:
    """Heartbeat-path retry for transiently failed member-watch attaches
    (mirrors sync's check).  On success, replay streams the cluster's
    EXISTING member objects through the store handler (enqueuing their
    keys), but fed objects with nothing propagated to the newly attached
    cluster still hold stale 'cluster unavailable' entries — whenever
    the pending set SHRANK (not only when it drained), fan everything
    out."""
    before = store.pending
    if not before:
        return
    store.reattach()
    if before - store.pending:
        worker.enqueue_all(host.keys(fed_resource))


def _view_read(client, resource: str, key: str) -> Optional[dict]:
    """No-copy read when the client offers one; consumers must not
    mutate the result."""
    view = getattr(client, "try_get_view", None)
    return view(resource, key) if view is not None else client.try_get(resource, key)


class StatusController:
    """Collects member-object fields into the status CR.

    Batch-tick shape: member objects come from a :class:`MemberStore`
    (cached informer stores — zero member round trips per reconcile,
    reference: status/controller.go:291-450 reading FederatedInformer
    caches), and one tick's status-CR writes ride a single
    ``host.batch()`` round trip through :class:`HostBatch`."""

    name = "status-controller"

    def __init__(
        self,
        fleet: ClusterFleet,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        if ftc.status is None:
            raise ValueError(f"FTC {ftc.name} has no status type")
        self.fleet = fleet
        self.host = fleet.host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._fed_resource = ftc.federated.resource
        self._target_resource = ftc.source.resource
        self._status_resource = ftc.status.resource
        self.worker = BatchWorker(
            f"status-{ftc.name}",
            self.reconcile_batch,
            metrics=self.metrics,
            clock=clock,
        )
        self._cluster_sigs: dict[str, tuple] = {}
        # Skip cache: fingerprint of the clusterStatus+labels this
        # controller last wrote (or verified) per key — an unchanged
        # world costs zero host reads (this controller is the status
        # CR's only writer).
        self._last_written: dict[str, tuple] = {}
        # resourceVersions of this controller's own status-CR writes —
        # echo suppression for the drift-repair watch below.
        self._own_status_rv: dict[str, str] = {}
        self.store = MemberStore(
            fleet, self._target_resource, on_event=self._on_member_event
        )
        # A member coming back from a breaker-open window may have
        # changed out from under its stalled watch stream: refresh every
        # status CR (and retry any pending member-watch attach) when the
        # fleet's shared breaker closes.
        B.for_fleet(fleet, metrics=self.metrics).on_transition(
            self._on_breaker_transition
        )
        self.host.watch(self._fed_resource, self._on_fed_event, replay=True)
        self.host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)
        # Drift repair: a status CR deleted or modified out-of-band must
        # invalidate the skip cache, or the fingerprint check would
        # never rewrite it while the member world stays quiescent.
        self.host.watch(self._status_resource, self._on_status_event, replay=False)

    def _on_fed_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_member_event(self, cluster: str, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        if new == B.CLOSED:
            _retry_pending_attach(
                self.store, self.worker, self.host, self._fed_resource
            )
            self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def _on_status_event(self, event: str, obj: dict) -> None:
        key = obj_key(obj)
        if event == "DELETED":
            self._own_status_rv.pop(key, None)
            if self.worker.is_own_thread():
                return  # echo of this controller's own delete
        elif self.worker.is_own_thread() or self._own_status_rv.get(key) == str(
            obj.get("metadata", {}).get("resourceVersion", "")
        ):
            return  # echo of this controller's own write
        self._last_written.pop(key, None)
        self.worker.enqueue(key)

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        sig = C.cluster_lifecycle_sig(obj)
        name = obj["metadata"]["name"]
        if event == "DELETED":
            self._cluster_sigs.pop(name, None)  # re-creation must fan out
            # Tear down the removed cluster's store: it must report
            # 'cluster unavailable', not serve frozen last-known state.
            # No reattach here — it would re-add the evicted cluster.
            self.store.evict(name)
            self.worker.enqueue_all(self.host.keys(self._fed_resource))
            return
        elif self._cluster_sigs.get(name) == sig:
            # Heartbeat bump: nothing placement-relevant changed, but a
            # transiently failed member-watch attach still needs its
            # retry channel.
            _retry_pending_attach(
                self.store, self.worker, self.host, self._fed_resource
            )
            return
        else:
            self._cluster_sigs[name] = sig
        self.store.readmit(name)  # a re-created cluster lifts its eviction
        self.store.reattach()
        self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- reconcile (status/controller.go:291-450) ------------------------
    def reconcile_batch(self, keys: list[str]) -> dict[str, Result]:
        """One tick: every due key's status CR recomputed against the
        member store, all host writes staged into ONE batch.  Network
        hosts prefetch the tick's federated objects (and the status CRs
        of keys outside the skip cache) in bulk reads instead of two
        GETs per key."""
        results: dict[str, Result] = {}
        fed_cache = status_cache = None
        if _host_bulk_reads(self.host) and keys:
            fed_cache = bulk_get(self.host, self._fed_resource, keys)
            cold = [k for k in keys if k not in self._last_written]
            if cold:
                status_cache = bulk_get(self.host, self._status_resource, cold)
        hb = HostBatch(self.host)
        for key in keys:
            try:
                self._plan_one(key, hb, results, fed_cache, status_cache)
            except Exception:
                self.metrics.counter("status.plan_panic")
                results[key] = Result.retry()
        hb.flush()
        return results

    def _plan_one(
        self,
        key: str,
        hb: HostBatch,
        results: dict,
        fed_cache: Optional[dict] = None,
        status_cache: Optional[dict] = None,
    ) -> None:
        self.metrics.counter("status.throughput")
        if fed_cache is not None and key in fed_cache:
            fed_obj = fed_cache[key]
        else:
            fed_obj = _view_read(self.host, self._fed_resource, key)

        def on_panic(_key=key) -> None:
            self._last_written.pop(_key, None)
            results[_key] = Result.retry()

        if fed_obj is None or fed_obj["metadata"].get("deletionTimestamp"):
            # Federated object gone: drop the status CR.
            self._last_written.pop(key, None)

            def on_delete(result, _key=key) -> None:
                if result.get("code") not in (200, 404):
                    results[_key] = Result.retry()

            hb.stage(
                {"verb": "delete", "resource": self._status_resource, "key": key},
                on_delete,
                on_panic,
            )
            return

        cluster_status = self._cluster_statuses(fed_obj, key)
        labels = dict(fed_obj["metadata"].get("labels", {}) or {})
        fp = (C.compact_json(cluster_status), C.compact_json(labels))
        if self._last_written.get(key) == fp:
            return  # nothing changed since our last verified write

        if status_cache is not None and key in status_cache:
            existing = status_cache[key]
        else:
            existing = _view_read(self.host, self._status_resource, key)
        if existing is None:
            desired = {
                "apiVersion": self.ftc.status.api_version,
                "kind": self.ftc.status.kind,
                "metadata": {"name": fed_obj["metadata"]["name"], "labels": labels},
                "clusterStatus": cluster_status,
            }
            if fed_obj["metadata"].get("namespace"):
                desired["metadata"]["namespace"] = fed_obj["metadata"]["namespace"]

            def on_create(result, _key=key, _fp=fp) -> None:
                if result.get("code") == 201:
                    self._last_written[_key] = _fp
                    self._record_own(_key, result.get("object"))
                else:
                    results[_key] = Result.retry()

            hb.stage(
                {
                    "verb": "create",
                    "resource": self._status_resource,
                    "object": desired,
                },
                on_create,
                on_panic,
            )
            return

        if (
            existing.get("clusterStatus") == cluster_status
            and (existing["metadata"].get("labels") or {}) == labels
        ):
            self._last_written[key] = fp
            return

        # ``existing`` is a view: rebuild the changed layers, share the
        # rest (every store write deep-copies on entry).
        updated = dict(existing)
        meta = dict(existing["metadata"])
        meta["labels"] = labels
        updated["metadata"] = meta
        updated["clusterStatus"] = cluster_status

        def on_update(result, _key=key, _fp=fp) -> None:
            if result.get("code") == 200:
                self._last_written[_key] = _fp
                self._record_own(_key, result.get("object"))
            else:  # conflict / gone / transport: re-read next pass
                self._last_written.pop(_key, None)
                results[_key] = Result.retry()

        hb.stage(
            {"verb": "update", "resource": self._status_resource, "object": updated},
            on_update,
            on_panic,
        )

    def _record_own(self, key: str, obj) -> None:
        if isinstance(obj, dict):
            rv = obj.get("metadata", {}).get("resourceVersion")
            if rv is not None:
                self._own_status_rv[key] = str(rv)

    def _cluster_statuses(self, fed_obj: dict, key: str) -> list[dict]:
        """Per placed cluster, the collected dotted fields
        (status/controller.go:491-560 clusterStatuses) — read from the
        member store, not the member apiservers."""
        placed = sorted(C.all_placement_clusters(fed_obj))
        out = []
        for cname in placed:
            entry: dict = {"clusterName": cname}
            obj = self.store.get(cname, key)
            if obj is None:
                if not self.store.attached(cname):
                    entry["error"] = "cluster unavailable"
                    out.append(entry)
                continue  # attached but not propagated yet: skip silently
            collected: dict = {}
            for field in self.ftc.status_collection_fields:
                value = get_path(obj, field)
                if value is None:
                    continue
                # Values alias the store view; every downstream write
                # path (fp serialization, host.batch) copies on entry.
                set_path(collected, field, value)
            entry["collectedFields"] = collected
            out.append(entry)
        return out


# -- aggregation plugins (statusaggregator/plugins/) ---------------------

_SUMMED_DEPLOYMENT_FIELDS = (
    "replicas",
    "updatedReplicas",
    "readyReplicas",
    "availableReplicas",
    "unavailableReplicas",
)


def aggregate_workload_status(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Deployment-family aggregation: sum the replica counters across
    clusters; bump observedGeneration to the source's generation only
    when every member status reflects the latest sync
    (plugins/deployment.go:42-160)."""
    agg = {f: 0 for f in _SUMMED_DEPLOYMENT_FIELDS}
    if not cluster_objs:
        up_to_date = False
    for obj in cluster_objs.values():
        status = obj.get("status")
        if not status:
            up_to_date = False
            continue
        for f in _SUMMED_DEPLOYMENT_FIELDS:
            agg[f] += int(status.get(f, 0) or 0)
    new_status = {f: v for f, v in agg.items() if v}
    if up_to_date:
        new_status["observedGeneration"] = source["metadata"].get("generation", 1)
    else:
        old = (source.get("status") or {}).get("observedGeneration")
        if old is not None:
            new_status["observedGeneration"] = old
    return new_status


def aggregate_single_cluster(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Adopt the lone member's status verbatim; ambiguous with more than
    one placement (plugins/single_cluster_plugin.go)."""
    if len(cluster_objs) != 1:
        return None
    (obj,) = cluster_objs.values()
    return obj.get("status")


def _job_finished_failed(status: dict) -> bool:
    return any(
        c.get("type") == "Failed" and c.get("status") == "True"
        for c in status.get("conditions", []) or []
    )


def aggregate_job_status(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Jobs: sum active/succeeded/failed, min startTime; once every
    cluster's job finished, a federation-level Complete/Failed condition
    summarizes where it completed vs failed (plugins/job.go:43-140).
    Timestamps are RFC3339 strings, so lexicographic min/max is
    chronological."""
    agg: dict = {"active": 0, "succeeded": 0, "failed": 0}
    start_time: Optional[str] = None
    completion_time: Optional[str] = None
    finished = 0
    completed_in: list[str] = []
    failed_in: list[str] = []
    for cname, obj in sorted(cluster_objs.items()):
        status = obj.get("status")
        if not status:
            continue
        st = status.get("startTime")
        if st and (start_time is None or st < start_time):
            start_time = st
        ct = status.get("completionTime")
        if ct:
            finished += 1
            completed_in.append(cname)
            if completion_time is None or ct > completion_time:
                completion_time = ct
        elif _job_finished_failed(status):
            finished += 1
            failed_in.append(cname)
        for f in ("active", "succeeded", "failed"):
            agg[f] += int(status.get(f, 0) or 0)

    new_status = {f: v for f, v in agg.items() if v}
    if start_time is not None:
        new_status["startTime"] = start_time
    if finished > 0 and finished == len(cluster_objs):
        if completed_in and failed_in:
            cond = {
                "type": "Failed",
                "status": "True",
                "reason": "Mixed",
                "message": (
                    f"Job completed in clusters {completed_in} "
                    f"and failed in clusters {failed_in}"
                ),
            }
        elif completed_in:
            cond = {
                "type": "Complete",
                "status": "True",
                "reason": "Completed",
                "message": f"Job completed in clusters {completed_in}",
            }
            if completion_time is not None:
                new_status["completionTime"] = completion_time
        else:
            cond = {
                "type": "Failed",
                "status": "True",
                "reason": "Failed",
                "message": f"Job failed in clusters {failed_in}",
            }
        new_status["conditions"] = [cond]
    return new_status


# Phase precedence: any failure dominates, then pending, running, and only
# all-succeeded reads Succeeded (plugins/pod.go:101-130).
_POD_PHASE_ORDER = ("Failed", "Pending", "Running", "Succeeded")


def aggregate_pod_status(
    source: dict, cluster_objs: dict[str, dict], up_to_date: bool
) -> Optional[dict]:
    """Pods: federation-level phase by precedence, min startTime, member
    container statuses concatenated with the cluster name suffixed
    (plugins/pod.go:41-130)."""
    phases: dict[str, list[str]] = {p: [] for p in _POD_PHASE_ORDER}
    new_status: dict = {}
    start_time: Optional[str] = None
    containers: list[dict] = []
    init_containers: list[dict] = []
    for cname, obj in sorted(cluster_objs.items()):
        status = obj.get("status") or {}
        phase = status.get("phase") or "Pending"
        if phase in phases:
            phases[phase].append(cname)
        st = status.get("startTime")
        if st and (start_time is None or st < start_time):
            start_time = st
        for cs in status.get("initContainerStatuses", []) or []:
            cs = dict(cs)
            cs["name"] = f"{cs.get('name')} ({cname})"
            init_containers.append(cs)
        for cs in status.get("containerStatuses", []) or []:
            cs = dict(cs)
            cs["name"] = f"{cs.get('name')} ({cname})"
            containers.append(cs)

    messages = []
    for phase in _POD_PHASE_ORDER:
        if not phases[phase]:
            continue
        new_status.setdefault("phase", phase)
        messages.append(f"pod is {phase} in clusters {sorted(phases[phase])}")
    if messages:
        new_status["message"] = "; ".join(messages)
    if start_time is not None:
        new_status["startTime"] = start_time
    if init_containers:
        new_status["initContainerStatuses"] = init_containers
    if containers:
        new_status["containerStatuses"] = containers
    return new_status


# GVK -> plugin, mirroring the reference registry (plugins/plugin.go:42-47:
# Deployment summed, StatefulSet single-cluster, Job merged, Pod phased).
AGGREGATION_PLUGINS: dict[str, Callable] = {
    "apps/v1/Deployment": aggregate_workload_status,
    "apps/v1/StatefulSet": aggregate_single_cluster,
    "batch/v1/Job": aggregate_job_status,
    "v1/Pod": aggregate_pod_status,
}


class StatusAggregator:
    """Folds member statuses back onto the source object.

    Batch-tick shape mirrors :class:`StatusController`: member objects
    come from the cached :class:`MemberStore` (reference: the aggregator
    reads FederatedInformer caches, statusaggregator/controller.go:291-399)
    and one tick's source writes share one ``host.batch()`` round trip."""

    name = "status-aggregator"

    def __init__(
        self,
        fleet: ClusterFleet,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
    ):
        self.fleet = fleet
        self.host = fleet.host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._fed_resource = ftc.federated.resource
        self._target_resource = ftc.source.resource
        self.plugin = AGGREGATION_PLUGINS.get(ftc.source.gvk)
        self.worker = BatchWorker(
            f"statusagg-{ftc.name}",
            self.reconcile_batch,
            metrics=self.metrics,
            clock=clock,
        )
        self._cluster_sigs: dict[str, tuple] = {}
        self.store = MemberStore(
            fleet, self._target_resource, on_event=self._on_member_event
        )
        self.host.watch(self._fed_resource, self._on_event, replay=True)
        self.host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)

    def _on_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_member_event(self, cluster: str, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        sig = C.cluster_lifecycle_sig(obj)
        name = obj["metadata"]["name"]
        if event == "DELETED":
            self._cluster_sigs.pop(name, None)
            self.store.evict(name)
            self.worker.enqueue_all(self.host.keys(self._fed_resource))
            return
        elif self._cluster_sigs.get(name) == sig:
            _retry_pending_attach(
                self.store, self.worker, self.host, self._fed_resource
            )
            return
        else:
            self._cluster_sigs[name] = sig
        self.store.readmit(name)  # a re-created cluster lifts its eviction
        self.store.reattach()
        self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- reconcile (statusaggregator/controller.go:291-399) --------------
    def reconcile_batch(self, keys: list[str]) -> dict[str, Result]:
        results: dict[str, Result] = {}
        source_cache = fed_cache = None
        if _host_bulk_reads(self.host) and keys:
            # Aggregation reads two host objects per key; batch both.
            source_cache = bulk_get(self.host, self._target_resource, keys)
            fed_cache = bulk_get(self.host, self._fed_resource, keys)
        hb = HostBatch(self.host)
        for key in keys:
            try:
                self._plan_one(key, hb, results, source_cache, fed_cache)
            except Exception:
                self.metrics.counter("statusagg.plan_panic")
                results[key] = Result.retry()
        hb.flush()
        return results

    def _plan_one(
        self,
        key: str,
        hb: HostBatch,
        results: dict,
        source_cache: Optional[dict] = None,
        fed_cache: Optional[dict] = None,
    ) -> None:
        self.metrics.counter("statusagg.throughput")
        if source_cache is not None and key in source_cache:
            source = source_cache[key]
        else:
            source = _view_read(self.host, self._target_resource, key)
        if fed_cache is not None and key in fed_cache:
            fed_obj = fed_cache[key]
        else:
            fed_obj = _view_read(self.host, self._fed_resource, key)
        if source is None or fed_obj is None:
            return
        if source["metadata"].get("deletionTimestamp"):
            return

        cluster_objs: dict[str, dict] = {}
        up_to_date = True
        synced = {
            c.get("cluster"): c.get("status")
            for c in (fed_obj.get("status", {}) or {}).get("clusters", [])
        }
        for cname in sorted(C.all_placement_clusters(fed_obj)):
            obj = self.store.get(cname, key)
            if obj is None:
                up_to_date = False
                continue
            if synced.get(cname) != "OK":
                up_to_date = False
            cluster_objs[cname] = obj

        def on_panic(_key=key) -> None:
            results[_key] = Result.retry()

        def on_write(result, _key=key) -> None:
            if result.get("code") not in (200, 404):
                results[_key] = Result.retry()

        plugin = self.plugin
        if plugin is not None:
            new_status = plugin(source, cluster_objs, up_to_date)
            if new_status is not None and new_status != source.get("status"):
                # Status subresource write: only .status is applied, so a
                # minimal object (key + optimistic resourceVersion) rides
                # the batch instead of a deep copy of the source.
                patch = {
                    "apiVersion": source.get("apiVersion"),
                    "kind": source.get("kind"),
                    "metadata": {
                        "name": source["metadata"]["name"],
                        "resourceVersion": source["metadata"].get("resourceVersion"),
                    },
                    "status": new_status,
                }
                if source["metadata"].get("namespace"):
                    patch["metadata"]["namespace"] = source["metadata"]["namespace"]
                hb.stage(
                    {
                        "verb": "update_status",
                        "resource": self._target_resource,
                        "object": patch,
                    },
                    on_write,
                    on_panic,
                )
            return

        # No plugin: record statuses in the sourcefeedback annotation
        # (sourcefeedback/status.go).
        feedback = C.compact_json(
            {
                "clusters": [
                    {"name": c, "status": o.get("status")}
                    for c, o in sorted(cluster_objs.items())
                    if o.get("status") is not None
                ]
            }
        )
        if (source["metadata"].get("annotations") or {}).get(
            C.SOURCE_FEEDBACK_STATUS
        ) == feedback:
            return
        updated = copy_json(source)
        updated["metadata"].setdefault("annotations", {})[
            C.SOURCE_FEEDBACK_STATUS
        ] = feedback
        hb.stage(
            {"verb": "update", "resource": self._target_resource, "object": updated},
            on_write,
            on_panic,
        )
