"""Field retention: merge cluster-owned fields into the desired object.

Before updating a member-cluster object, the dispatcher grafts the fields
that member-cluster controllers own (allocated IPs, generated secrets,
admission-injected volumes, ...) from the observed cluster object onto
the freshly-computed desired object, so updates don't fight in-cluster
controllers (reference: pkg/controllers/sync/dispatch/retain.go:49-636).

All objects are unstructured dicts.  Tombstone semantics for labels and
annotations: the keys last propagated from the template are recorded on
the cluster object under the ``propagated-*-keys`` annotations; a key
present in the cluster object but absent from both the template and the
tombstone list is cluster-owned and retained, while a key in the
tombstone list was deliberately removed from the template and is dropped
(retain.go:99-156).
"""

from __future__ import annotations

from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.utils.unstructured import get_path, set_path

PROPAGATED_LABEL_KEYS = C.PREFIX + "last-propagated-label-keys"
PROPAGATED_ANNOTATION_KEYS = C.PREFIX + "last-propagated-annotation-keys"

# serviceaccount admission plugin conventions (retain.go:41-45).
SA_VOLUME_PREFIX = "kube-api-access-"
SA_TOKEN_MOUNT_PATH = "/var/run/secrets/kubernetes.io/serviceaccount"

CURRENT_REVISION_ANNOTATION = C.PREFIX + "current-revision"
LAST_REPLICASET_NAME = C.PREFIX + "last-replicaset-name"
LATEST_REPLICASET_NAME = C.PREFIX + "latest-replicaset-name"


def record_propagated_keys(obj: dict) -> None:
    """Stamp the propagated label/annotation key lists so the next
    retention pass can compute template deletions (retain.go:99-111).

    The annotation-keys list is computed *after* adding the label-keys
    annotation, matching the reference's ordering."""
    ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
    labels = obj.get("metadata", {}).get("labels", {}) or {}
    ann[PROPAGATED_LABEL_KEYS] = ",".join(sorted(labels))
    ann[PROPAGATED_ANNOTATION_KEYS] = ",".join(sorted(ann))


def _merge_string_maps(
    template_map: Optional[dict],
    observed_map: Optional[dict],
    last_template_keys: set[str],
) -> dict:
    """Template wins on conflicts; cluster-only keys survive unless they
    appear in the tombstone set (retain.go:134-156)."""
    out = dict(template_map or {})
    deleted = last_template_keys - set(out)
    for k, v in (observed_map or {}).items():
        if k in deleted:
            continue
        out.setdefault(k, v)
    return out


def merge_labels_and_annotations(desired: dict, cluster_obj: dict) -> None:
    cluster_meta = cluster_obj.get("metadata", {})
    cluster_ann = cluster_meta.get("annotations", {}) or {}
    last_labels = set(
        k for k in cluster_ann.get(PROPAGATED_LABEL_KEYS, "").split(",") if k
    )
    last_ann = set(
        k for k in cluster_ann.get(PROPAGATED_ANNOTATION_KEYS, "").split(",") if k
    )
    meta = desired.setdefault("metadata", {})
    merged_ann = _merge_string_maps(meta.get("annotations"), cluster_ann, last_ann)
    if merged_ann:
        meta["annotations"] = merged_ann
    merged_labels = _merge_string_maps(
        meta.get("labels"), cluster_meta.get("labels"), last_labels
    )
    if merged_labels:
        meta["labels"] = merged_labels


# -- per-kind retention --------------------------------------------------

def _retain_service(desired: dict, cluster_obj: dict) -> None:
    """clusterIP and nodePorts are cluster-allocated (retain.go:158-209)."""
    cluster_ip = get_path(cluster_obj, "spec.clusterIP")
    if cluster_ip:
        set_path(desired, "spec.clusterIP", cluster_ip)
    cluster_ports = get_path(cluster_obj, "spec.ports")
    if not isinstance(cluster_ports, list):
        return
    desired_ports = get_path(desired, "spec.ports")
    if not isinstance(desired_ports, list):
        desired_ports = []
    for dport in desired_ports:
        for cport in cluster_ports:
            if (
                dport.get("name") == cport.get("name")
                and dport.get("protocol") == cport.get("protocol")
                and dport.get("port") == cport.get("port")
                and "nodePort" in cport
            ):
                dport["nodePort"] = cport["nodePort"]
    set_path(desired, "spec.ports", desired_ports)


def _retain_serviceaccount(desired: dict, cluster_obj: dict) -> None:
    """Keep generated token secrets to avoid regeneration churn
    (retain.go:219-241)."""
    if desired.get("secrets"):
        return
    secrets = cluster_obj.get("secrets")
    if secrets:
        desired["secrets"] = secrets


def _retain_job(desired: dict, cluster_obj: dict) -> None:
    """controller-uid selector/labels are immutable and cluster-generated
    unless manualSelector (retain.go:247-273)."""
    if get_path(desired, "spec.manualSelector") is True:
        return
    selector = get_path(cluster_obj, "spec.selector")
    if selector is not None:
        set_path(desired, "spec.selector", selector)
    labels = get_path(cluster_obj, "spec.template.metadata.labels")
    if labels is not None:
        set_path(desired, "spec.template.metadata.labels", labels)


def _retain_persistentvolume(desired: dict, cluster_obj: dict) -> None:
    claim_ref = get_path(cluster_obj, "spec.claimRef")
    if claim_ref is not None:
        set_path(desired, "spec.claimRef", claim_ref)


def _retain_persistentvolumeclaim(desired: dict, cluster_obj: dict) -> None:
    volume_name = get_path(cluster_obj, "spec.volumeName")
    if volume_name is not None:
        set_path(desired, "spec.volumeName", volume_name)


def _find_sa_volume(pod: dict) -> tuple[Optional[dict], int]:
    volumes = get_path(pod, "spec.volumes")
    if not isinstance(volumes, list):
        return None, 0
    for i, v in enumerate(volumes):
        if isinstance(v, dict) and str(v.get("name", "")).startswith(SA_VOLUME_PREFIX):
            return v, i
    return None, 0


def _find_sa_volume_mount(container: dict) -> tuple[Optional[dict], int]:
    mounts = container.get("volumeMounts")
    if not isinstance(mounts, list):
        return None, 0
    for i, m in enumerate(mounts):
        if isinstance(m, dict) and m.get("mountPath") == SA_TOKEN_MOUNT_PATH:
            return m, i
    return None, 0


def _retain_container(desired_c: dict, cluster_c: dict) -> None:
    found, _ = _find_sa_volume_mount(desired_c)
    if found is None:
        mnt, idx = _find_sa_volume_mount(cluster_c)
        if mnt is not None:
            mounts = list(desired_c.get("volumeMounts") or [])
            mounts.insert(min(idx, len(mounts)), mnt)
            desired_c["volumeMounts"] = mounts


def _retain_pod(desired: dict, cluster_obj: dict) -> None:
    """Control-plane-managed pod fields (retain.go:302-393): always copy
    ephemeralContainers; copy admission/scheduler defaults only when the
    user left them unset; re-inject the serviceaccount admission volume
    and its per-container mounts at their original indices."""
    eph = get_path(cluster_obj, "spec.ephemeralContainers")
    if eph is not None:
        set_path(desired, "spec.ephemeralContainers", eph)
    for field in ("serviceAccountName", "serviceAccount", "nodeName", "priority"):
        if get_path(desired, f"spec.{field}") is None:
            val = get_path(cluster_obj, f"spec.{field}")
            if val is not None:
                set_path(desired, f"spec.{field}", val)
    found, _ = _find_sa_volume(desired)
    if found is None:
        volume, idx = _find_sa_volume(cluster_obj)
        if volume is not None:
            volumes = list(get_path(desired, "spec.volumes") or [])
            volumes.insert(min(idx, len(volumes)), volume)
            set_path(desired, "spec.volumes", volumes)
    for field in ("containers", "initContainers"):
        desired_cs = get_path(desired, f"spec.{field}") or []
        cluster_cs = {
            c.get("name"): c
            for c in get_path(cluster_obj, f"spec.{field}") or []
            if isinstance(c, dict)
        }
        for dc in desired_cs:
            if isinstance(dc, dict) and dc.get("name") in cluster_cs:
                _retain_container(dc, cluster_cs[dc["name"]])


_KIND_RETAINERS = {
    "Service": _retain_service,
    "ServiceAccount": _retain_serviceaccount,
    "Job": _retain_job,
    "PersistentVolume": _retain_persistentvolume,
    "PersistentVolumeClaim": _retain_persistentvolumeclaim,
    "Pod": _retain_pod,
}


def _retain_whole_status(desired: dict, cluster_obj: dict) -> None:
    """Keep the member-written ``status`` in the desired object.  For
    kinds whose status is NOT a subresource an update would wipe it;
    the member (e.g. the Argo workflow-controller) owns it
    (retain.go:624-636 retainArgoWorkflow)."""
    if "status" in cluster_obj:
        desired["status"] = cluster_obj["status"]
    else:
        desired.pop("status", None)


# Per-GVK retention registry — the "extensible framework to support
# CRDs" the reference leaves as a TODO (retain.go:89-91): CRDs register
# an apiVersion/Kind-keyed retainer; the Argo Workflow rule ships as the
# built-in precedent.
_GVK_RETAINERS: dict[str, callable] = {
    "argoproj.io/v1alpha1/Workflow": _retain_whole_status,
}


def register_gvk_retainer(gvk: str, retainer) -> None:
    """Register a CRD retention rule keyed by "group/version/Kind";
    called as retainer(desired, cluster_obj) after the generic pass."""
    _GVK_RETAINERS[gvk] = retainer


def retain_cluster_fields(
    kind: str, desired: dict, cluster_obj: dict, gvk: str = ""
) -> None:
    """The dispatcher's pre-update pass (retain.go:49-97): resourceVersion
    + finalizers from the cluster object, tombstoned label/annotation
    merge, then kind-specific rules, then any registered per-GVK CRD rule
    (retain.go:88-94; Workflow built in)."""
    meta = desired.setdefault("metadata", {})
    meta["resourceVersion"] = cluster_obj.get("metadata", {}).get("resourceVersion")
    finalizers = cluster_obj.get("metadata", {}).get("finalizers")
    if finalizers:
        meta["finalizers"] = list(finalizers)
    elif "finalizers" in meta:
        del meta["finalizers"]
    merge_labels_and_annotations(desired, cluster_obj)
    retainer = _KIND_RETAINERS.get(kind)
    if retainer is not None:
        retainer(desired, cluster_obj)
    gvk_retainer = _GVK_RETAINERS.get(gvk or desired.get("apiVersion", "") + "/" + kind)
    if gvk_retainer is not None:
        gvk_retainer(desired, cluster_obj)


def retain_replicas(
    desired: dict, cluster_obj: dict, fed_obj: dict, replicas_path: str
) -> None:
    """HPA compatibility: when spec.retainReplicas is set on the federated
    object, the member cluster owns the replica count
    (retain.go:527-557)."""
    if not replicas_path:
        return
    if not fed_obj.get("spec", {}).get("retainReplicas"):
        return
    replicas = get_path(cluster_obj, replicas_path)
    if replicas is not None:
        set_path(desired, replicas_path, replicas)
