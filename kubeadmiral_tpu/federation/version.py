"""PropagatedVersion manager: skip no-op member-cluster writes.

Records, per federated object, the (template hash, override hash) it was
propagated at plus each member cluster's observed object version.  On
the next sync, an unchanged hash pair + matching member version means the
write can be skipped entirely — including across controller restarts,
since the record is a CR on the host (reference:
pkg/controllers/sync/version/manager.go:49-487,
pkg/apis/core/v1alpha1/types_propgatedversion.go).
"""

from __future__ import annotations

import threading
from typing import Optional

from kubeadmiral_tpu.testing.fakekube import AlreadyExists, Conflict, FakeKube, NotFound

PROPAGATED_VERSIONS = "core.kubeadmiral.io/v1alpha1/propagatedversions"
CLUSTER_PROPAGATED_VERSIONS = "core.kubeadmiral.io/v1alpha1/clusterpropagatedversions"


def version_name(kind: str, resource_name: str) -> str:
    """``<lower kind>-<name>`` (manager.go:481-486)."""
    return f"{kind.lower()}-{resource_name}"


class VersionManager:
    """In-memory cache over PropagatedVersion CRs (manager.go:49-98).

    The reference primes its cache from a LIST at startup; here the cache
    loads lazily per key, which has the same restart-resume property."""

    def __init__(self, host: FakeKube, kind: str, namespaced: bool):
        self.host = host
        self.kind = kind
        self.resource = PROPAGATED_VERSIONS if namespaced else CLUSTER_PROPAGATED_VERSIONS
        self._lock = threading.Lock()
        self._cache: dict[str, dict] = {}  # fed key -> version CR

    def _cr_key(self, namespace: str, name: str) -> str:
        vname = version_name(self.kind, name)
        return f"{namespace}/{vname}" if namespace else vname

    def get(
        self, namespace: str, name: str, template_version: str, override_version: str
    ) -> dict[str, str]:
        """cluster -> recorded object version, or {} when the propagated
        hashes changed (manager.go:119-150)."""
        cr = self._load(namespace, name)
        if cr is None:
            return {}
        status = cr.get("status", {})
        if (
            status.get("templateVersion") != template_version
            or status.get("overrideVersion") != override_version
        ):
            return {}
        return {
            cv["clusterName"]: cv["version"]
            for cv in status.get("clusterVersions", [])
        }

    def update(
        self,
        namespace: str,
        name: str,
        template_version: str,
        override_version: str,
        selected_clusters: list[str],
        version_map: dict[str, str],
        batch=None,
    ) -> None:
        """Merge the dispatch round's versions and persist
        (manager.go:152-215, updateClusterVersions:448-463): versions for
        unselected clusters are dropped; clusters the round produced no
        version for keep their old record only if still selected.  With
        ``batch`` (a sync-tick host batch exposing ``stage(op, cb)``),
        the persist rides the tick's bulk host round trip; conflicts
        fall back to the direct write (recording is an optimization —
        failures are tolerated either way)."""
        with self._lock:
            cr = self._load_locked(namespace, name)
            old_versions: dict[str, str] = {}
            if cr is not None:
                status = cr.get("status", {})
                if (
                    status.get("templateVersion") == template_version
                    and status.get("overrideVersion") == override_version
                ):
                    old_versions = {
                        cv["clusterName"]: cv["version"]
                        for cv in status.get("clusterVersions", [])
                    }
            merged = {
                c: version_map.get(c, old_versions.get(c, ""))
                for c in selected_clusters
            }
            merged = {c: v for c, v in merged.items() if v}
            status = {
                "templateVersion": template_version,
                "overrideVersion": override_version,
                "clusterVersions": [
                    {"clusterName": c, "version": v}
                    for c, v in sorted(merged.items())
                ],
            }
            # Unchanged record: skip the write entirely — a restarted
            # controller re-syncing a converged world must be read-only
            # (manager.go's updatedVersionMap equality short-circuit).
            if cr is not None and cr.get("status") == status:
                return
            self._write(namespace, name, status, cr, batch)

    def delete(self, namespace: str, name: str) -> None:
        key = self._cr_key(namespace, name)
        with self._lock:
            self._cache.pop(key, None)
        try:
            self.host.delete(self.resource, key)
        except NotFound:
            pass

    # -- storage ---------------------------------------------------------
    def _load(self, namespace: str, name: str) -> Optional[dict]:
        with self._lock:
            return self._load_locked(namespace, name)

    def _load_locked(self, namespace: str, name: str) -> Optional[dict]:
        key = self._cr_key(namespace, name)
        if key in self._cache:
            return self._cache[key]
        cr = self.host.try_get(self.resource, key)
        if cr is not None:
            self._cache[key] = cr
        return cr

    def _write(
        self,
        namespace: str,
        name: str,
        status: dict,
        existing: Optional[dict],
        batch=None,
    ) -> None:
        key = self._cr_key(namespace, name)
        if existing is None:
            cr = {
                "apiVersion": "core.kubeadmiral.io/v1alpha1",
                "kind": "PropagatedVersion" if namespace else "ClusterPropagatedVersion",
                "metadata": {"name": version_name(self.kind, name)},
                "status": status,
            }
            if namespace:
                cr["metadata"]["namespace"] = namespace
            if batch is not None:

                def on_create(result: dict) -> None:
                    code = result.get("code")
                    if code == 201:
                        with self._lock:
                            self._cache[key] = result["object"]
                    elif code == 409:
                        # AlreadyExists: the cache was stale; re-load and
                        # settle through the update path.
                        self._retry_direct(namespace, name, status)
                    else:
                        # Transport trouble: recording is an optimization
                        # — drop the cache like the update path does;
                        # retrying N keys synchronously under the lock
                        # against a failing host would stall the tick.
                        with self._lock:
                            self._cache.pop(key, None)

                batch.stage(
                    {"verb": "create", "resource": self.resource, "object": cr},
                    on_create,
                )
                return
            try:
                self._cache[key] = self.host.create(self.resource, cr)
            except AlreadyExists:
                # Cache was stale (e.g. evicted after an earlier error):
                # re-load and write through the update path.
                current = self.host.try_get(self.resource, key)
                if current is not None:
                    self._cache[key] = current
                    self._write(namespace, name, status, current)
            return
        cr = dict(existing)
        cr["status"] = status
        if batch is not None:

            def on_update(result: dict) -> None:
                if result.get("code") == 200:
                    with self._lock:
                        self._cache[key] = result["object"]
                else:
                    with self._lock:
                        self._cache.pop(key, None)

            batch.stage(
                {"verb": "update_status", "resource": self.resource, "object": cr},
                on_update,
            )
            return
        try:
            # Status subresource: plain updates ignore .status.
            self._cache[key] = self.host.update_status(self.resource, cr)
        except (Conflict, NotFound):
            # Version recording is an optimization (manager.go callers
            # tolerate failure); drop the cache so the next get reloads.
            self._cache.pop(key, None)

    def _retry_direct(self, namespace: str, name: str, status: dict) -> None:
        """Batched create lost a race: settle through the direct path."""
        key = self._cr_key(namespace, name)
        with self._lock:
            self._cache.pop(key, None)
            current = self.host.try_get(self.resource, key)
            if current is not None:
                self._cache[key] = current
            self._write(namespace, name, status, current)
