"""The auto-migration controller: unschedulable-pod capacity feedback.

Closes the elastic-recovery loop (reference:
pkg/controllers/automigration/controller.go:88-441, util.go:29-70): the
scheduler stamps a pod-unschedulable-threshold annotation from the
policy; this controller lists each placed cluster's workload pods, counts
the ones stuck Unschedulable beyond the threshold, derives per-cluster
``estimatedCapacity``, and writes it into the auto-migration-info
annotation — whose change re-triggers the scheduler, which caps those
clusters in the planner and shifts replicas elsewhere.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.schedulerctl import POD_UNSCHEDULABLE_THRESHOLD
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.models.policy import _parse_duration
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import (
    ClusterFleet,
    Conflict,
    NotFound,
    obj_key,
)
from kubeadmiral_tpu.utils.unstructured import get_path

PODS = "v1/pods"


def _pod_scheduled_condition(pod: dict) -> Optional[dict]:
    for cond in pod.get("status", {}).get("conditions", []) or []:
        if cond.get("type") == "PodScheduled":
            return cond
    return None


def _transition_time(value) -> Optional[float]:
    """Condition timestamps as seconds: accepts the monotonic floats the
    in-process tests use AND the RFC3339 strings real pods carry
    (metav1.Time in automigration/util.go).  Malformed timestamps yield
    None — the caller skips the condition rather than treating the pod
    as unschedulable-since-epoch (which would silently migrate on
    garbage input).  A MISSING timestamp still maps to 0.0 — Go's
    metav1.Time zero value — matching the reference's time.Since(zero)
    behavior."""
    if not value:
        return 0.0
    try:
        return float(value)
    except (TypeError, ValueError):
        pass
    import datetime

    try:
        return datetime.datetime.fromisoformat(
            str(value).replace("Z", "+00:00")
        ).timestamp()
    except ValueError:
        return None


def count_unschedulable_pods(
    pods: list[dict], now: float, threshold: float
) -> tuple[int, Optional[float]]:
    """(count past threshold, seconds until the next pod crosses)
    (automigration/util.go:29-70)."""
    count = 0
    next_cross: Optional[float] = None
    for pod in pods:
        if pod["metadata"].get("deletionTimestamp"):
            continue
        cond = _pod_scheduled_condition(pod)
        if (
            cond is None
            or cond.get("status") != "False"
            or cond.get("reason") != "Unschedulable"
        ):
            continue
        since = _transition_time(cond.get("lastTransitionTime", 0))
        if since is None:  # malformed timestamp: not yet crossed
            continue
        crossing_in = since + threshold - now
        if crossing_in <= 0:
            count += 1
        elif next_cross is None or crossing_in < next_cross:
            next_cross = crossing_in
    return count, next_cross


def pods_for_workload(member, workload: dict) -> list[dict]:
    """Pods matching the workload's selector in its namespace
    (automigration/plugins pod listing)."""
    selector = get_path(workload, "spec.selector.matchLabels") or {}
    namespace = workload["metadata"].get("namespace", "")
    out = []

    def check(pod: dict) -> None:
        if pod["metadata"].get("namespace", "") != namespace:
            return
        labels = pod["metadata"].get("labels", {}) or {}
        if all(labels.get(k) == v for k, v in selector.items()):
            out.append(pod)

    member.scan(PODS, check)
    return out


class AutoMigrationController:
    """Per-FTC controller feeding estimatedCapacity to the scheduler."""

    name = "auto-migration-controller"

    def __init__(
        self,
        fleet: ClusterFleet,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        clock=None,
        pod_informer=None,
    ):
        self.fleet = fleet
        self.host = fleet.host
        self.ftc = ftc
        # Optional shared PodInformer (runtime/podinformer.py): pruned
        # per-cluster pod caches instead of scanning full pod objects
        # (the 50k-pod memory discipline, federatedclient/podinformer.go).
        self.pod_informer = pod_informer
        self.metrics = metrics or Metrics()
        self._clock = clock or time.time
        self._fed_resource = ftc.federated.resource
        self._target_resource = ftc.source.resource
        self.worker = Worker(
            f"automigration-{ftc.name}",
            self.reconcile,
            metrics=self.metrics,
            clock=clock,
        )
        self.host.watch(self._fed_resource, self._on_event, replay=True)
        self._reattach = fleet.watch_members(PODS, self._on_member_pod_event)
        # ktlint: ignore[shard-intake-coverage] broadcast: cluster topology changes reattach member pod watches on every replica; per-key work still routes through the shard-filtered worker
        self.host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)
        if self.pod_informer is not None:
            self.pod_informer.attach()

    def _on_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_member_pod_event(self, event: str, pod: dict) -> None:
        # A pod event re-reconciles the workloads in its namespace; the
        # reference scopes this precisely via per-workload pod informers
        # (automigration pod handler); matching by namespace over the
        # object cache is the lean equivalent.
        ns = pod["metadata"].get("namespace", "")
        matched: list[str] = []

        def check(fed: dict) -> None:
            if fed["metadata"].get("namespace", "") == ns:
                matched.append(obj_key(fed))

        self.host.scan(self._fed_resource, check)
        self.worker.enqueue_all(matched)

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        self._reattach()
        if self.pod_informer is not None:
            self.pod_informer.attach()

    def run_until_idle(self) -> None:
        while self.worker.step():
            pass

    # -- reconcile (controller.go:178-290) -------------------------------
    def reconcile(self, key: str) -> Result:
        self.metrics.counter("auto-migration.throughput")
        fed_obj = self.host.try_get(self._fed_resource, key)
        if fed_obj is None or fed_obj["metadata"].get("deletionTimestamp"):
            return Result.ok()

        ann = fed_obj["metadata"].setdefault("annotations", {})
        threshold = _parse_duration(ann.get(POD_UNSCHEDULABLE_THRESHOLD))

        needs_update = False
        requeue_after: Optional[float] = None
        if threshold is None:
            # Auto migration disabled: clean up.
            if C.AUTO_MIGRATION_INFO in ann:
                del ann[C.AUTO_MIGRATION_INFO]
                needs_update = True
        else:
            estimated, requeue_after = self._estimate_capacity(
                fed_obj, key, threshold
            )
            desired_info = {"estimatedCapacity": estimated} if estimated else {}
            try:
                existing_info = json.loads(ann.get(C.AUTO_MIGRATION_INFO, "{}"))
            except ValueError:
                existing_info = {}
            if existing_info != desired_info:
                if desired_info:
                    ann[C.AUTO_MIGRATION_INFO] = C.compact_json(desired_info)
                else:
                    ann.pop(C.AUTO_MIGRATION_INFO, None)
                needs_update = True

        if needs_update:
            try:
                self.host.update(self._fed_resource, fed_obj)
            except Conflict:
                return Result.retry()
            except NotFound:
                return Result.ok()
        if requeue_after is not None:
            return Result.after(requeue_after)
        return Result.ok()

    def _estimate_capacity(
        self, fed_obj: dict, key: str, threshold: float
    ) -> tuple[dict[str, int], Optional[float]]:
        """(controller.go:292-380 estimateCapacity)."""
        now = self._clock()
        estimated: dict[str, int] = {}
        retry_after: Optional[float] = None
        replicas_path = self.ftc.path.replicas_spec or "spec.replicas"

        for cname in sorted(C.all_placement_clusters(fed_obj)):
            try:
                member = self.fleet.member(cname)
            except NotFound:
                continue
            workload = member.try_get_view(self._target_resource, key)  # read-only
            if workload is None:
                continue

            # Skip pod listing when everything is ready (the reference's
            # total==ready optimization).
            total = get_path(workload, "status.replicas")
            ready = get_path(workload, "status.readyReplicas")
            if total is not None and total == ready:
                continue

            desired = int(get_path(workload, replicas_path) or 0)
            pods = None
            if self.pod_informer is not None:
                pods = self.pod_informer.pods_for(
                    cname,
                    workload["metadata"].get("namespace", ""),
                    get_path(workload, "spec.selector.matchLabels") or {},
                )
            if pods is None:
                # Informer not (yet) watching this cluster (cold attach /
                # rejoin window): scan the member directly rather than
                # trusting an empty snapshot.
                pods = pods_for_workload(member, workload)
            unschedulable, next_cross = count_unschedulable_pods(
                pods, now, threshold
            )
            if next_cross is not None and (
                retry_after is None or next_cross < retry_after
            ):
                retry_after = next_cross

            if len(pods) >= desired:
                capacity = len(pods) - unschedulable
            else:
                # Uncreated pods count as schedulable so they aren't
                # migrated before they exist (controller.go:349-355).
                capacity = desired - unschedulable

            if capacity >= desired:
                continue  # nothing to migrate; omit to avoid rescheduling
            estimated[cname] = max(0, capacity)
        return estimated, retry_after
