"""Monitor controller: federation-health gauges per FTC.

Off by default, as in the reference (reference:
pkg/controllers/monitor/monitor_controller.go:85-258,
monitor_subcontroller.go, report.go): per federated type it meters

* ``monitor.<ftc>.total`` / ``.propagated`` / ``.unpropagated`` — how
  many federated objects exist and how many have a True Propagation
  condition with every placed cluster reporting OK,
* ``monitor.<ftc>.sync_latency`` — per object generation, the time from
  first observation to successful propagation (the BaseMeter
  sync-latency equivalent),
* ``monitor.<ftc>.out_of_sync_seconds`` — the current age of the oldest
  unpropagated generation,
* ``monitor.clusters.ready`` / ``.total`` — member-cluster health.

Gauges land in the shared :class:`Metrics` store on a periodic tick
(report.go DoReport's interval loop).

Placement drift detection (``fleet`` given): per object it diffs the
scheduler's desired placement — the persisted placement on the
federated object, cross-checked against the engine's flight-recorder
decision — against the dispatched/observed member state, and exposes

* ``placement_drift_objects{ftc,kind}`` gauges per drift kind
  (``missing`` / ``orphan`` / ``replicas`` / ``decision``), and
* a bounded listing served at ``GET /debug/drift`` (the detector
  registers itself as a flightrec drift provider).

Drift includes in-flight propagation: an object scheduled but not yet
synced shows as ``missing`` until the dispatch lands, so the gauge's
steady-state baseline is the sync-latency window, and a persistent
non-zero value is the page.
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Optional

import numpy as np

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime import flightrec as FR
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.runtime import slo as SLO
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import FakeKube
from kubeadmiral_tpu.utils.unstructured import get_path

_TICK = "tick"

DEFAULT_INTERVAL_SECONDS = 30.0

# Drift kinds (the placement_drift_objects label vocabulary).
DRIFT_MISSING = "missing"      # desired cluster lacks the member object
DRIFT_ORPHAN = "orphan"        # member object exists off the desired set
DRIFT_REPLICAS = "replicas"    # member replicas != scheduler's override
DRIFT_DECISION = "decision"    # persisted placement != flight-recorder decision
DRIFT_KINDS = (DRIFT_MISSING, DRIFT_ORPHAN, DRIFT_REPLICAS, DRIFT_DECISION)

# Bound on the /debug/drift listing (gauges stay exact).
_DRIFT_LIST_CAP = 1000


def _is_propagated(fed_obj: dict) -> bool:
    status = fed_obj.get("status", {})
    conditions = {
        c.get("type"): c.get("status") for c in status.get("conditions", [])
    }
    if conditions.get("Propagation") != "True":
        return False
    clusters = status.get("clusters", [])
    placed = C.all_placement_clusters(fed_obj)
    reported = {c.get("cluster") for c in clusters if c.get("status") == "OK"}
    return placed <= reported


class MonitorController:
    name = "monitor"

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        interval: float = DEFAULT_INTERVAL_SECONDS,
        clock=time.monotonic,
        fleet=None,
        flight_recorder="default",
    ):
        self.host = host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self.interval = interval
        self.clock = clock
        self._resource = ftc.federated.resource
        # Placement drift detection needs the member stores; a host-only
        # monitor (the reference's shape) skips it.
        self.fleet = fleet
        self.flightrec = (
            FR.get_default() if flight_recorder == "default" else flight_recorder
        )
        self._drift: list[dict] = []
        self._drift_checked = 0
        self._drift_at: Optional[float] = None
        if fleet is not None:
            FR.register_drift_provider(
                f"monitor-{ftc.name}", self.drift_snapshot
            )
        # (key, generation) -> first-seen timestamp, dropped once synced.
        self._pending_since: dict[tuple[str, int], float] = {}
        # The same clock drives latency math AND the requeue timer, so a
        # fake clock steps the whole controller deterministically.
        self.worker = Worker(
            f"monitor-{ftc.name}", self.reconcile, metrics=self.metrics, clock=clock
        )
        self.worker.enqueue(_TICK)

    def reconcile(self, key: str) -> Result:
        if key != _TICK:
            return Result.ok()
        self._report()
        return Result.after(self.interval)

    def _report(self) -> None:
        prefix = f"monitor.{self.ftc.name}"
        now = self.clock()
        total = propagated = 0
        live: set[tuple[str, int]] = set()
        # Objects per controller in the FIRST pending group: the depth of
        # each pipeline stage's backlog (runtime/pending.py semantics —
        # only first-group controllers may act, so this is the real
        # "waiting on" gauge).
        first_group: Counter = Counter()

        def visit(fed_obj: dict) -> None:
            nonlocal total, propagated
            total += 1
            meta = fed_obj.get("metadata", {})
            obj_key = f"{meta.get('namespace', '')}/{meta.get('name', '')}".lstrip("/")
            generation = meta.get("generation", 1)
            pending_key = (obj_key, generation)
            try:
                groups = pending.get_pending(fed_obj)
            except Exception:
                groups = []
            if groups:
                first_group.update(groups[0])
            if _is_propagated(fed_obj):
                propagated += 1
                started = self._pending_since.pop(pending_key, None)
                if started is not None:
                    self.metrics.duration(f"{prefix}.sync_latency", now - started)
            else:
                live.add(pending_key)
                self._pending_since.setdefault(pending_key, now)

        self.host.scan(self._resource, visit)
        for controller, depth in first_group.items():
            self.metrics.store(
                "pending_controllers_depth",
                depth,
                ftc=self.ftc.name,
                controller=controller,
            )
        # Real controller error rates for this FTC, aggregated from the
        # labeled worker series (runtime/worker.py names workers
        # "<kind>-<ftc>"): what the stub metrics silently discarded.
        suffix = f"-{self.ftc.name}"

        def family_total(family: str) -> float:
            return sum(
                value
                for labels, value in self.metrics.counter_family(family).items()
                if dict(labels).get("controller", "").endswith(suffix)
            )

        self.metrics.store(
            f"{prefix}.worker_exceptions", family_total("worker_exceptions_total")
        )
        self.metrics.store(
            f"{prefix}.worker_retries", family_total("worker_retries_total")
        )
        # Drop meters for deleted objects / superseded generations.
        for stale in [k for k in self._pending_since if k not in live]:
            del self._pending_since[stale]

        self.metrics.store(f"{prefix}.total", total)
        self.metrics.store(f"{prefix}.propagated", propagated)
        self.metrics.store(f"{prefix}.unpropagated", total - propagated)
        oldest = min(self._pending_since.values(), default=None)
        self.metrics.store(
            f"{prefix}.out_of_sync_seconds",
            (now - oldest) if oldest is not None else 0.0,
        )

        ready = total_clusters = 0
        for cluster in self.host.list(C.FEDERATED_CLUSTERS):
            total_clusters += 1
            conditions = {
                c.get("type"): c.get("status")
                for c in cluster.get("status", {}).get("conditions", [])
            }
            if conditions.get("Ready") == "True":
                ready += 1
        self.metrics.store("monitor.clusters.total", total_clusters)
        self.metrics.store("monitor.clusters.ready", ready)
        # End-to-end SLO sampling (runtime/slo.py): publish the
        # freshness gauge pair and run one burn-rate evaluation pass —
        # on THIS periodic tick precisely so a silently-wedged dispatch
        # path stays visible when no new events flow to trigger anything
        # else.
        rec = SLO.get_default()
        if rec.enabled:
            rec.evaluate(extra=self.metrics)
        # Member circuit-breaker health (transport/breaker.py): how many
        # members the fleet's shared registry currently short-circuits.
        registry = getattr(self.fleet, "_member_breakers", None)
        self.metrics.store(
            "monitor.clusters.breaker_open",
            len(registry.open_members()) if registry is not None else 0,
        )
        self._detect_drift()

    # -- placement drift --------------------------------------------------
    def _detect_drift(self) -> None:
        """Diff the scheduler's desired placements against observed
        member state; gauges per drift kind + a bounded listing for
        GET /debug/drift.

        Vectorized over (object, member) incidence matrices: one host
        scan collects desired placements, one bulk key listing per
        member builds the observed matrix (np.isin), and missing/orphan
        drift falls out of boolean plane arithmetic — no per-(object,
        member) Python loop.  Only replicas checks (bounded by the
        override count, not N x M) and the flight-recorder cross-check
        (a dict lookup per object) stay per-object."""
        if self.fleet is None:
            return
        source = self.ftc.source.resource
        replicas_path = self.ftc.path.replicas_spec
        override_path = (
            "/" + replicas_path.replace(".", "/") if replicas_path else None
        )
        members = dict(self.fleet.members)
        member_names = list(members)
        col = {name: j for j, name in enumerate(member_names)}
        counts: Counter = Counter()
        drifted: list[dict] = []

        def note(kind: str, key: str, cluster: str, detail: str) -> None:
            counts[kind] += 1
            if len(drifted) < _DRIFT_LIST_CAP:
                drifted.append(
                    {"key": key, "cluster": cluster, "kind": kind,
                     "detail": detail}
                )

        keys: list[str] = []
        desired_sets: list[set] = []
        overrides: list[tuple[int, str, int]] = []  # (row, cluster, want)

        def visit(fed: dict) -> None:
            meta = fed.get("metadata", {})
            ns = meta.get("namespace", "")
            key = f"{ns}/{meta.get('name', '')}".lstrip("/")
            desired = C.get_placement(fed, C.SCHEDULER)
            if desired is None:
                return  # never scheduled: nothing to drift against
            row = len(keys)
            keys.append(key)
            desired_sets.append(desired)
            if override_path:
                for cl, patches in C.get_overrides(fed, C.SCHEDULER).items():
                    for p in patches:
                        if (
                            p.get("path") == override_path
                            and p.get("op", "replace") == "replace"
                        ):
                            overrides.append((row, cl, int(p["value"])))
            # Cross-check against the engine's recorded decision: the
            # persisted placement should be the flight recorder's chosen
            # set (a mismatch means a decision was recorded but never
            # persisted, or overwritten outside the scheduler).
            rec = (
                self.flightrec.lookup(key)
                if self.flightrec is not None and self.flightrec.enabled
                else None
            )
            if rec is not None and set(rec.placements) != desired:
                note(
                    DRIFT_DECISION, key, "",
                    f"flight recorder chose {sorted(rec.placements)} vs "
                    f"persisted {sorted(desired)}",
                )

        self.host.scan(self._resource, visit)
        checked = len(keys)

        n, m = len(keys), len(member_names)
        if n and m:
            keys_arr = np.asarray(keys, dtype=object)
            desired_m = np.zeros((n, m), bool)
            for i, ds in enumerate(desired_sets):
                for cl in ds:
                    j = col.get(cl)
                    if j is not None:
                        desired_m[i, j] = True
            observed_m = np.zeros((n, m), bool)
            for j, name in enumerate(member_names):
                present = members[name].keys(source)
                if present:
                    observed_m[:, j] = np.isin(
                        keys_arr, np.asarray(present, dtype=object)
                    )
            missing = desired_m & ~observed_m
            orphan = observed_m & ~desired_m
            for i, j in np.argwhere(missing):
                note(DRIFT_MISSING, keys[i], member_names[j],
                     "desired placement not present in member")
            for i, j in np.argwhere(orphan):
                note(DRIFT_ORPHAN, keys[i], member_names[j],
                     "member object outside the desired placement")
            for row, cl, want in overrides:
                j = col.get(cl)
                if j is None or not observed_m[row, j]:
                    continue
                obs = members[cl].try_get_view(source, keys[row])
                got = get_path(obs, replicas_path) if obs is not None else None
                if got != want:
                    note(
                        DRIFT_REPLICAS, keys[row], cl,
                        f"member replicas {got} != desired {want}",
                    )
        for kind in DRIFT_KINDS:
            self.metrics.store(
                "placement_drift_objects", counts.get(kind, 0),
                ftc=self.ftc.name, kind=kind,
            )
        self._drift = drifted
        self._drift_checked = checked
        self._drift_at = time.time()

    def drift_snapshot(self) -> dict:
        """The /debug/drift payload (registered as a flightrec drift
        provider when the monitor has member access)."""
        return {
            "ftc": self.ftc.name,
            "checked": self._drift_checked,
            "generated_at": self._drift_at,
            "drifted": list(self._drift),
        }
