"""Monitor controller: federation-health gauges per FTC.

Off by default, as in the reference (reference:
pkg/controllers/monitor/monitor_controller.go:85-258,
monitor_subcontroller.go, report.go): per federated type it meters

* ``monitor.<ftc>.total`` / ``.propagated`` / ``.unpropagated`` — how
  many federated objects exist and how many have a True Propagation
  condition with every placed cluster reporting OK,
* ``monitor.<ftc>.sync_latency`` — per object generation, the time from
  first observation to successful propagation (the BaseMeter
  sync-latency equivalent),
* ``monitor.<ftc>.out_of_sync_seconds`` — the current age of the oldest
  unpropagated generation,
* ``monitor.clusters.ready`` / ``.total`` — member-cluster health.

Gauges land in the shared :class:`Metrics` store on a periodic tick
(report.go DoReport's interval loop).
"""

from __future__ import annotations

import time
from collections import Counter
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import FakeKube

_TICK = "tick"

DEFAULT_INTERVAL_SECONDS = 30.0


def _is_propagated(fed_obj: dict) -> bool:
    status = fed_obj.get("status", {})
    conditions = {
        c.get("type"): c.get("status") for c in status.get("conditions", [])
    }
    if conditions.get("Propagation") != "True":
        return False
    clusters = status.get("clusters", [])
    placed = C.all_placement_clusters(fed_obj)
    reported = {c.get("cluster") for c in clusters if c.get("status") == "OK"}
    return placed <= reported


class MonitorController:
    name = "monitor"

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        interval: float = DEFAULT_INTERVAL_SECONDS,
        clock=time.monotonic,
    ):
        self.host = host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self.interval = interval
        self.clock = clock
        self._resource = ftc.federated.resource
        # (key, generation) -> first-seen timestamp, dropped once synced.
        self._pending_since: dict[tuple[str, int], float] = {}
        # The same clock drives latency math AND the requeue timer, so a
        # fake clock steps the whole controller deterministically.
        self.worker = Worker(
            f"monitor-{ftc.name}", self.reconcile, metrics=self.metrics, clock=clock
        )
        self.worker.enqueue(_TICK)

    def reconcile(self, key: str) -> Result:
        if key != _TICK:
            return Result.ok()
        self._report()
        return Result.after(self.interval)

    def _report(self) -> None:
        prefix = f"monitor.{self.ftc.name}"
        now = self.clock()
        total = propagated = 0
        live: set[tuple[str, int]] = set()
        # Objects per controller in the FIRST pending group: the depth of
        # each pipeline stage's backlog (runtime/pending.py semantics —
        # only first-group controllers may act, so this is the real
        # "waiting on" gauge).
        first_group: Counter = Counter()

        def visit(fed_obj: dict) -> None:
            nonlocal total, propagated
            total += 1
            meta = fed_obj.get("metadata", {})
            obj_key = f"{meta.get('namespace', '')}/{meta.get('name', '')}".lstrip("/")
            generation = meta.get("generation", 1)
            pending_key = (obj_key, generation)
            try:
                groups = pending.get_pending(fed_obj)
            except Exception:
                groups = []
            if groups:
                first_group.update(groups[0])
            if _is_propagated(fed_obj):
                propagated += 1
                started = self._pending_since.pop(pending_key, None)
                if started is not None:
                    self.metrics.duration(f"{prefix}.sync_latency", now - started)
            else:
                live.add(pending_key)
                self._pending_since.setdefault(pending_key, now)

        self.host.scan(self._resource, visit)
        for controller, depth in first_group.items():
            self.metrics.store(
                "pending_controllers_depth",
                depth,
                ftc=self.ftc.name,
                controller=controller,
            )
        # Real controller error rates for this FTC, aggregated from the
        # labeled worker series (runtime/worker.py names workers
        # "<kind>-<ftc>"): what the stub metrics silently discarded.
        suffix = f"-{self.ftc.name}"

        def family_total(family: str) -> float:
            return sum(
                value
                for labels, value in self.metrics.counter_family(family).items()
                if dict(labels).get("controller", "").endswith(suffix)
            )

        self.metrics.store(
            f"{prefix}.worker_exceptions", family_total("worker_exceptions_total")
        )
        self.metrics.store(
            f"{prefix}.worker_retries", family_total("worker_retries_total")
        )
        # Drop meters for deleted objects / superseded generations.
        for stale in [k for k in self._pending_since if k not in live]:
            del self._pending_since[stale]

        self.metrics.store(f"{prefix}.total", total)
        self.metrics.store(f"{prefix}.propagated", propagated)
        self.metrics.store(f"{prefix}.unpropagated", total - propagated)
        oldest = min(self._pending_since.values(), default=None)
        self.metrics.store(
            f"{prefix}.out_of_sync_seconds",
            (now - oldest) if oldest is not None else 0.0,
        )

        ready = total_clusters = 0
        for cluster in self.host.list(C.FEDERATED_CLUSTERS):
            total_clusters += 1
            conditions = {
                c.get("type"): c.get("status")
                for c in cluster.get("status", {}).get("conditions", [])
            }
            if conditions.get("Ready") == "True":
                ready += 1
        self.metrics.store("monitor.clusters.total", total_clusters)
        self.metrics.store("monitor.clusters.ready", ready)
