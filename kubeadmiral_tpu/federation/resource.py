"""FederatedResource: the sync controller's view of one federated object.

Wraps the unstructured federated object + its FTC into the operations
propagation needs: compute placement, derive the per-cluster desired
object from the template, apply overrides, and produce the template/
override hashes that key the version map (reference:
pkg/controllers/sync/resource.go:55-473, accessor.go:40-236).
"""

from __future__ import annotations

import json
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.utils.hashing import stable_json_hash
from kubeadmiral_tpu.utils.jsonpatch import apply_patch
from kubeadmiral_tpu.utils.unstructured import copy_json, delete_path, get_path

# Finalizer protecting terminating Jobs/Pods from premature GC
# (reference: dispatch/retain_terminating.go RetainTerminatingObjectFinalizer).
RETAIN_TERMINATING_FINALIZER = C.PREFIX + "retain-terminating-object"


class FederatedResource:
    """One federated object + type config (resource.go:55-90)."""

    def __init__(self, fed_obj: dict, ftc: FederatedTypeConfig):
        self.obj = fed_obj
        self.ftc = ftc
        self._overrides_by_cluster: Optional[dict[str, list]] = None
        # Version-hash memos: one reconcile computes each hash at plan
        # time AND at finish time, and spec.template/spec.overrides are
        # immutable for this wrapper's lifetime (reconcile mutates only
        # metadata/status).
        self._template_version: Optional[str] = None
        self._override_version: Optional[str] = None

    # -- identity --------------------------------------------------------
    @property
    def name(self) -> str:
        return self.obj["metadata"]["name"]

    @property
    def namespace(self) -> str:
        return self.obj["metadata"].get("namespace", "")

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}" if self.namespace else self.name

    @property
    def target_kind(self) -> str:
        return self.ftc.source.kind

    # -- placement -------------------------------------------------------
    def compute_placement(self, joined_clusters) -> set[str]:
        """Union of placements across controllers ∩ joined clusters
        (resource.go ComputePlacement + placement.go union).  Accepts a
        prebuilt set to keep per-object work off the O(fleet) path."""
        placed = C.all_placement_clusters(self.obj)
        if not isinstance(joined_clusters, (set, frozenset)):
            joined_clusters = set(joined_clusters)
        return placed & joined_clusters

    # -- per-cluster desired object --------------------------------------
    def object_for_cluster(self, cluster: str) -> dict:
        """Template -> member-cluster object (resource.go:182-262):
        name/namespace/kind stamped from the federated object, finalizers
        stripped (member controllers own them), source-generation
        annotation added, kind-specific field drops applied."""
        obj = copy_json(C.template(self.obj)) or {}
        meta = obj.setdefault("metadata", {})
        meta.pop("finalizers", None)
        meta["name"] = self.name
        if self.namespace:
            meta["namespace"] = self.namespace
        obj["kind"] = self.target_kind
        obj.setdefault("apiVersion", self.ftc.source.api_version)

        ann = meta.setdefault("annotations", {})
        ann[C.SOURCE_GENERATION] = str(
            self.obj["metadata"].get("generation", 1)
        )
        meta.pop("generation", None)
        meta.pop("resourceVersion", None)

        revision = self.obj["metadata"].get("annotations", {}).get(
            CURRENT_REVISION_ANNOTATION
        )
        if revision is not None:
            ann[CURRENT_REVISION_ANNOTATION] = revision

        kind = self.target_kind
        if kind == "Job":
            self._drop_job_fields(obj)
            self._add_retain_finalizer(obj)
        elif kind == "Service":
            self._drop_service_fields(obj)
        elif kind == "Pod":
            delete_path(obj, "spec.ephemeralContainers")
            self._add_retain_finalizer(obj)
        return obj

    @staticmethod
    def _drop_job_fields(obj: dict) -> None:
        """Drop the generated controller-uid selector unless manualSelector
        (resource.go:272-284)."""
        if get_path(obj, "spec.manualSelector") is True:
            return
        labels = get_path(obj, "spec.template.metadata.labels")
        if isinstance(labels, dict):
            labels.pop("controller-uid", None)
        match = get_path(obj, "spec.selector.matchLabels")
        if isinstance(match, dict):
            match.pop("controller-uid", None)

    @staticmethod
    def _drop_service_fields(obj: dict) -> None:
        """Drop host-allocated clusterIP unless headless (resource.go:286-296)."""
        cluster_ip = get_path(obj, "spec.clusterIP")
        if cluster_ip is not None and cluster_ip != "None":
            delete_path(obj, "spec.clusterIP")
            delete_path(obj, "spec.clusterIPs")

    @staticmethod
    def _add_retain_finalizer(obj: dict) -> None:
        meta = obj.setdefault("metadata", {})
        fins = meta.setdefault("finalizers", [])
        if RETAIN_TERMINATING_FINALIZER not in fins:
            fins.append(RETAIN_TERMINATING_FINALIZER)

    # -- overrides -------------------------------------------------------
    def _ordered_overrides(self) -> dict[str, list]:
        """cluster -> concatenated patches ordered by the FTC's controller
        pipeline, unknown controllers last in spec order
        (resource.go:336-390 overridesForCluster)."""
        if self._overrides_by_cluster is not None:
            return self._overrides_by_cluster
        order: dict[str, int] = {}
        for group in self.ftc.controllers:
            for controller in group:
                order[controller] = len(order)
        entries = list(self.obj.get("spec", {}).get("overrides", []))
        entries.sort(
            key=lambda e: (
                order.get(e.get("controller"), len(order)),
                e.get("controller", ""),
            )
        )
        out: dict[str, list] = {}
        for entry in entries:
            for clause in entry.get("clusters", []):
                out.setdefault(clause.get("cluster"), []).extend(
                    clause.get("patches", [])
                )
        self._overrides_by_cluster = out
        return out

    def apply_overrides(
        self, obj: dict, cluster: str, extra_patches: Optional[list] = None
    ) -> dict:
        """JSONPatch overrides + managed label (resource.go:305-334); the
        managed label lands even when no override matched."""
        patches = self._ordered_overrides().get(cluster)
        if patches:
            obj = apply_patch(obj, patches)
        if extra_patches:
            obj = apply_patch(obj, extra_patches)
        obj.setdefault("metadata", {}).setdefault("labels", {})[
            C.MANAGED_LABEL
        ] = C.MANAGED_TRUE
        return obj

    def replicas_override_for_cluster(self, cluster: str) -> int:
        """The replicas this cluster is scheduled for: the last
        /spec/replicas override patch, else the template's replicas
        (resource.go:392-416 ReplicasOverrideForCluster)."""
        replicas_path = "/" + self.ftc.path.replicas_spec.replace(".", "/") if (
            self.ftc.path.replicas_spec
        ) else "/spec/replicas"
        value = None
        for patch in self._ordered_overrides().get(cluster, ()):
            if patch.get("path") == replicas_path and patch.get("value") is not None:
                value = patch["value"]
        if value is not None:
            return int(value)
        template = self.obj.get("spec", {}).get("template", {})
        return int(get_path(template, self.ftc.path.replicas_spec, 0) or 0)

    def total_replicas(self, clusters) -> int:
        """(resource.go:417-427 TotalReplicas)"""
        return sum(self.replicas_override_for_cluster(c) for c in clusters)

    # -- version hashes --------------------------------------------------
    def template_version(self) -> str:
        """Hash of the template (resource.go TemplateVersion via
        GetTemplateHash)."""
        if self._template_version is None:
            self._template_version = f"{stable_json_hash(C.template(self.obj)):08x}"
        return self._template_version

    def override_version(self) -> str:
        if self._override_version is None:
            self._override_version = (
                f"{stable_json_hash(self.obj.get('spec', {}).get('overrides', [])):08x}"
            )
        return self._override_version


def should_adopt_preexisting(fed_obj: dict) -> bool:
    """conflict-resolution annotation == adopt, internal variant winning
    (util.ShouldAdoptPreexistingResources)."""
    ann = fed_obj.get("metadata", {}).get("annotations", {})
    value = ann.get(C.CONFLICT_RESOLUTION_INTERNAL, ann.get(C.CONFLICT_RESOLUTION, ""))
    return value == "adopt"


def orphaning_behavior(fed_obj: dict) -> str:
    """'' | 'all' | 'adopted', internal variant winning
    (util.GetOrphaningBehavior)."""
    ann = fed_obj.get("metadata", {}).get("annotations", {})
    value = ann.get(C.ORPHAN_MODE_INTERNAL, ann.get(C.ORPHAN_MODE, ""))
    return value if value in ("all", "adopted") else ""


def object_version(cluster_obj: dict) -> str:
    """Generation-preferring version stamp of a member object
    (reference: util/propagatedversion.go:43-49)."""
    gen = cluster_obj.get("metadata", {}).get("generation", 0)
    if gen:
        return f"gen:{gen}"
    return f"rv:{cluster_obj.get('metadata', {}).get('resourceVersion', '')}"


def object_needs_update(
    desired: dict, cluster_obj: dict, recorded_version: str, replicas_path: str
) -> bool:
    """Skip-update check (util/propagatedversion.go:54-110): the recorded
    version must match the observed object AND the fields this controller
    rewrites out-of-band (replicas, rollout maxSurge/maxUnavailable) must
    already agree."""
    if recorded_version != object_version(cluster_obj):
        return True
    if replicas_path:
        if get_path(desired, replicas_path) != get_path(cluster_obj, replicas_path):
            return True
    for p in (
        "spec.strategy.rollingUpdate.maxSurge",
        "spec.strategy.rollingUpdate.maxUnavailable",
    ):
        if get_path(desired, p) != get_path(cluster_obj, p):
            return True
    # Generation-sourced versions don't change on metadata-only edits, so
    # label/annotation drift (e.g. a new current-revision annotation during
    # a rollout) needs an explicit equivalence check
    # (propagatedversion.go:115-119 + meta.go ObjectMetaObjEquivalent).
    if recorded_version.startswith("gen:"):
        for field_ in ("labels", "annotations"):
            a = desired.get("metadata", {}).get(field_) or {}
            b = cluster_obj.get("metadata", {}).get(field_) or {}
            if a != b and (a or b):
                return True
    return False


def is_explicitly_unmanaged(cluster_obj: dict) -> bool:
    """managed=false opts a member object out (managedlabel.IsExplicitlyUnmanaged)."""
    return (
        cluster_obj.get("metadata", {}).get("labels", {}).get(C.MANAGED_LABEL)
        == "false"
    )


def has_managed_label(cluster_obj: dict) -> bool:
    return (
        cluster_obj.get("metadata", {}).get("labels", {}).get(C.MANAGED_LABEL)
        == C.MANAGED_TRUE
    )
