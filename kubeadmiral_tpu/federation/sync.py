"""The sync controller: propagate federated objects to member clusters.

The propagation engine (reference: pkg/controllers/sync/controller.go):
for each federated object, compute placement ∩ joined clusters, dispatch
parallel create/update/delete against member apiservers, record per-
cluster propagation status and object versions, and handle deletion with
finalizers, orphaning annotations and cluster cascading-delete.

Batching: where the reference runs one goroutine per federated object
(worker.go:37-174) and one goroutine per member write
(dispatch/operation.go:102-123), this controller is tick-native — a
BatchWorker drains every due object, the whole tick shares one
cluster-list scan and one cross-object :class:`dispatch.BatchSink`, and
the flush issues ONE bulk write per member cluster.  Echoes of the
controller's own writes (member events, fed status events) are
suppressed at the watch boundary so a converged tick stays converged
instead of re-reconciling itself forever.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional, Union

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation import dispatch as D
from kubeadmiral_tpu.federation import rollout as R
from kubeadmiral_tpu.federation.resource import (
    FederatedResource,
    orphaning_behavior,
    should_adopt_preexisting,
)
from kubeadmiral_tpu.federation.history import (
    LAST_REVISION_ANNOTATION,
    RevisionManager,
    RevisionSyncError,
)
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.federation.version import VersionManager
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime.eventsink import DefederatingRecorderMux
from kubeadmiral_tpu.runtime import pending, slo
from kubeadmiral_tpu.runtime.hostbatch import HostBatch
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import BatchWorker, Result
from kubeadmiral_tpu.transport import breaker as B
from kubeadmiral_tpu.testing.fakekube import (
    DELETED,
    ClusterFleet,
    Conflict,
    FakeKube,
    NotFound,
    ShardIntake,
    obj_key,
)

FEDERATED_CLUSTERS = C.FEDERATED_CLUSTERS

# Cascading-delete opt-in annotation on FederatedCluster
# (reference: util/cascadingdeleteannotation.go:24-37).
CASCADING_DELETE = C.PREFIX + "cascading-delete"

ORPHAN_ALL = "all"
ORPHAN_ADOPTED = "adopted"

# Worker-queue namespace for FederatedCluster reconciles (the reference
# runs a second ReconcileWorker, clusterWorker; one queue with a key
# prefix keeps ordering here).
_CLUSTER_KEY_PREFIX = "cluster::"

# AggregateReason values surfaced in the Propagation condition
# (reference: pkg/apis/types/v1alpha1/types_status.go AggregateReason).
AGGREGATE_SUCCESS = "AggregateSuccess"
CHECK_CLUSTERS = "CheckClusters"


def is_cluster_joined(cluster_obj: dict) -> bool:
    conds = {
        c.get("type"): c.get("status")
        for c in cluster_obj.get("status", {}).get("conditions", [])
    }
    return conds.get("Joined") == "True"


def is_cluster_ready(cluster_obj: dict) -> bool:
    conds = {
        c.get("type"): c.get("status")
        for c in cluster_obj.get("status", {}).get("conditions", [])
    }
    return conds.get("Ready") == "True"


def is_cascading_delete_enabled(cluster_obj: dict) -> bool:
    return CASCADING_DELETE in cluster_obj.get("metadata", {}).get("annotations", {})


def _apply_desired_status(
    obj: dict,
    reason: str,
    status_map: dict[str, str],
    collision_count: Optional[int],
) -> bool:
    """Write the desired propagation status shape into ``obj`` in place;
    True when anything changed (controller.go:637-721's diff) — ONE
    definition shared by the optimistic batched write and the
    synchronous conflict-retry fallback."""
    desired_clusters = [
        {"cluster": c, "status": s} for c, s in sorted(status_map.items())
    ]
    status = obj.setdefault("status", {})
    old_conditions = {c.get("type"): c for c in status.get("conditions", [])}
    prop = old_conditions.get("Propagation", {})
    new_status = "True" if reason == AGGREGATE_SUCCESS else "False"
    changed = (
        status.get("clusters") != desired_clusters
        or prop.get("reason") != reason
        or prop.get("status") != new_status
    )
    if collision_count is not None and status.get("collisionCount") != collision_count:
        status["collisionCount"] = collision_count
        changed = True
    if not changed:
        return False
    status["clusters"] = desired_clusters
    status["conditions"] = [
        c for t, c in sorted(old_conditions.items()) if t != "Propagation"
    ] + [{"type": "Propagation", "status": new_status, "reason": reason}]
    return True


def _syncing_value(status_map: dict[str, str], generation: int) -> str:
    """The sourcefeedback syncing annotation payload
    (sourcefeedback/syncing.go PopulateSyncingAnnotation)."""
    return C.compact_json(
        {
            "generation": None,
            "fedGeneration": generation,
            "clusters": [
                {"name": c, "status": s} for c, s in sorted(status_map.items())
            ],
        }
    )


def _cluster_lifecycle_sig(cluster_obj: dict) -> tuple:
    """What about a FederatedCluster makes sync re-reconcile the world:
    join/ready/terminating/cascading transitions (controller.go:244-260
    ClusterLifecycleHandlers) — NOT heartbeat timestamp bumps."""
    return (
        is_cluster_joined(cluster_obj),
        is_cluster_ready(cluster_obj),
        bool(cluster_obj["metadata"].get("deletionTimestamp")),
        is_cascading_delete_enabled(cluster_obj),
    )


class _TickClusters:
    """One tick's shared view of the member fleet: the cluster list is
    scanned ONCE per BatchWorker tick instead of once per object, and
    per-object work is O(candidate clusters), not O(all clusters)."""

    __slots__ = ("flags", "joined_set")

    def __init__(self, joined: list[dict]):
        # name -> (ready, terminating, cascading) per joined cluster.
        self.flags = {
            c["metadata"]["name"]: (
                is_cluster_ready(c),
                bool(c["metadata"].get("deletionTimestamp")),
                bool(c["metadata"].get("deletionTimestamp"))
                and is_cascading_delete_enabled(c),
            )
            for c in joined
        }
        self.joined_set = frozenset(self.flags)


class SyncController:
    """Per-FTC propagation controller (sync/controller.go:90-135)."""

    name = "sync-controller"

    def __init__(
        self,
        fleet: ClusterFleet,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
        max_dispatch_workers: int = 16,
        clock=None,
    ):
        self.fleet = fleet
        self.host = fleet.host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._fed_resource = ftc.federated.resource
        self._target_resource = ftc.source.resource
        self.versions = VersionManager(self.host, ftc.source.kind, ftc.namespaced)
        self.revisions = RevisionManager(self.host) if ftc.revision_history else None
        # Events recorded on the federated object are re-targeted to the
        # source object too (util/eventsink DefederatingRecorderMux).
        self.recorder = DefederatingRecorderMux(self.host, f"sync-{ftc.name}")
        # Local (in-process store) fleets dispatch member writes inline:
        # the per-op thread fan-out costs more than the in-memory ops it
        # parallelizes.  Network fleets keep the per-cluster parallel
        # dispatch (operation.go:102-123).
        self._inline = isinstance(fleet.host, FakeKube)
        self.pool = (
            None
            if self._inline
            else ThreadPoolExecutor(max_workers=max_dispatch_workers)
        )
        # Bulk point reads (KT_BULK_READS, network fleets only): one
        # tick's federated objects and candidate member objects are
        # prefetched through the /batch protocol — KT_MEMBER_BATCH keys
        # per round trip — instead of one GET per (object, member) pair.
        # In-process stores serve free view reads, so the in-memory
        # fleet keeps the direct path.
        self._bulk_reads = not self._inline and os.environ.get(
            "KT_BULK_READS", "1"
        ) not in ("0", "false", "no")
        # (cluster, key) -> ("ok", obj|None) | ("err", message), valid
        # for the duration of one reconcile_batch tick.
        self._tick_reads: dict[tuple[str, str], tuple[str, object]] = {}
        # Per-member circuit breakers, SHARED across this fleet's
        # controllers (transport/breaker.py): a member that stalled one
        # flush short-circuits the next tick's reads and writes to
        # ClusterNotReady immediately instead of re-parking threads.
        self.breakers = B.for_fleet(fleet, metrics=self.metrics)
        self.breakers.on_transition(self._on_breaker_transition)
        self.worker = BatchWorker(
            f"sync-{ftc.name}", self.reconcile_batch, metrics=self.metrics, clock=clock
        )
        # Echo suppression: the thread currently inside reconcile_batch
        # (in-process stores deliver watch events synchronously on the
        # writer's thread — any event arriving on it mid-tick was caused
        # by this controller's own write), plus resourceVersion maps of
        # this controller's last writes for async transports.
        self._flush_threads: set[int] = set()
        self._own_member_rv: dict[tuple[str, str], str] = {}
        self._own_fed_rv: dict[str, str] = {}
        # Live index of which member clusters hold each object (fed by
        # the member watches + this controller's own writes) — the
        # informer-cache analogue that lets a reconcile visit only
        # candidate clusters instead of scanning the whole fleet.  It is
        # an accelerator, not the source of truth: restart-safe deletion
        # candidates come from the fed object's persisted status.clusters.
        self._member_index: dict[str, set[str]] = {}
        self._index_lock = threading.Lock()
        # Last seen lifecycle signature per cluster, so heartbeat-only
        # cluster updates don't re-enqueue every federated object.
        self._cluster_sigs: dict[str, tuple] = {}
        # Per-FTC cascading-delete finalizer held on FederatedCluster
        # objects (controller.go:216 cascadingDeleteFinalizer).
        self.cluster_finalizer = C.PREFIX + "cascading-delete-" + ftc.name
        # Member-object events re-enqueue the owning federated object
        # (the FederatedInformer path, SURVEY §3.3) — rollout planning in
        # particular must observe member progress between dispatches.
        # Attached before the cluster watch: its replay fires
        # _on_cluster_event, which re-attaches members, synchronously.
        # replay=True: existing member objects stream through the handler
        # at attach, populating the member index — the informer's initial
        # LIST, without which pre-existing managed objects in clusters
        # outside the current placement would never be visited for
        # cleanup (federatedinformer.go:151-250).
        # The replica's shard filter (resolved once, like the worker's):
        # non-owned member/fed events are dropped batch-wise BEFORE
        # delivery — at 500 members a flush fans out to every replica,
        # and the filter keeps each replica's share of the handler work
        # at ~1/N instead of N copies of everything.
        self._shard = self.worker._shard
        self._reattach_members = fleet.watch_members(
            self._target_resource, self._on_member_event, named=True, replay=True,
            batch=self._on_member_events, predicate=self._owns_event,
        )
        self.host.watch(
            self._fed_resource,
            ShardIntake(self._on_fed_event, predicate=self._owns_event),
            replay=True,
        )
        self.host.watch(FEDERATED_CLUSTERS, self._on_cluster_event, replay=True)

    def _owns_event(self, event: str, obj: dict) -> bool:
        return self._shard.owns(obj_key(obj))

    def watch_owners(self) -> list[object]:
        """Everything holding watch registrations on this controller's
        behalf (consumed by the manager's dynamic teardown)."""
        owners: list[object] = [self]
        if self.revisions is not None:
            owners.append(self.revisions)
        return owners

    # -- event fan-in ----------------------------------------------------
    def _is_own_echo(self) -> bool:
        # Worker-tracked reconcile threads + the BatchSink's pool-flush
        # threads: in-process stores deliver watch events synchronously
        # on the writing thread, so an event on any of these is an echo
        # of this controller's own write.
        return (
            self.worker.is_own_thread()
            or threading.get_ident() in self._flush_threads
        )

    def _on_fed_event(self, event: str, obj: dict) -> None:
        key = obj_key(obj)
        if event == DELETED:
            # Cleanup before the echo check: inline deletions deliver
            # their DELETED event on the tick thread, and the rv entry
            # must not outlive the object.
            self._own_fed_rv.pop(key, None)
            if self._is_own_echo():
                return
        elif self._is_own_echo() or self._own_fed_rv.get(key) == str(
            obj.get("metadata", {}).get("resourceVersion", "")
        ):
            return  # our own status/annotation write coming back around
        self.worker.enqueue(key)

    def _on_member_event(self, cluster: str, event: str, obj: dict) -> None:
        key = obj_key(obj)
        # Index maintenance runs for EVERY event, echoes included.
        if event == DELETED:
            with self._index_lock:
                held = self._member_index.get(key)
                if held is not None:
                    held.discard(cluster)
                    if not held:
                        self._member_index.pop(key, None)
            self._own_member_rv.pop((cluster, key), None)
            if self._is_own_echo():
                return
        else:
            with self._index_lock:
                self._member_index.setdefault(key, set()).add(cluster)
            if self._is_own_echo() or self._own_member_rv.get((cluster, key)) == str(
                obj.get("metadata", {}).get("resourceVersion", "")
            ):
                return  # echo of our own member write
        self.worker.enqueue(key)

    def _on_member_events(self, cluster: str, events: list) -> None:
        """Coalesced member-watch intake: one committed store flush
        ``[(event, obj), ...]`` in commit order.  Same decisions as
        :meth:`_on_member_event` per event, batched where per-event cost
        was pure overhead: the thread-identity echo check runs once
        (delivery is synchronous on the writing thread, so it cannot
        change mid-flush), index maintenance runs under ONE lock hold,
        and enqueues dedupe into one :meth:`~runtime.worker._WorkerBase.
        enqueue_many` call."""
        self.metrics.counter("member_watch_flushes_total", controller=self.worker.name)
        self.metrics.counter(
            "member_watch_flush_events_total", len(events), controller=self.worker.name
        )
        own_echo = self._is_own_echo()
        enqueue: dict[str, None] = {}
        with self._index_lock:
            for event, obj in events:
                key = obj_key(obj)
                if event == DELETED:
                    held = self._member_index.get(key)
                    if held is not None:
                        held.discard(cluster)
                        if not held:
                            self._member_index.pop(key, None)
                    self._own_member_rv.pop((cluster, key), None)
                    if own_echo:
                        continue
                else:
                    self._member_index.setdefault(key, set()).add(cluster)
                    if own_echo or self._own_member_rv.get((cluster, key)) == str(
                        obj.get("metadata", {}).get("resourceVersion", "")
                    ):
                        continue
                enqueue[key] = None
        if enqueue:
            self.worker.enqueue_many(enqueue)

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        # Cluster lifecycle re-enqueues everything (controller.go:244-260)
        # and reconciles the per-cluster cascading-delete finalizer —
        # but only on join/ready/terminating transitions, not heartbeats,
        # and never for this controller's own finalizer writes.
        if self._is_own_echo():
            return
        name = obj["metadata"]["name"]
        if event == DELETED:
            self._cluster_sigs.pop(name, None)
            self.worker.enqueue_all(self.host.keys(self._fed_resource))
            return
        sig = _cluster_lifecycle_sig(obj)
        if self._cluster_sigs.get(name) == sig:
            # Heartbeat / unrelated metadata bump: no object re-enqueue,
            # but give the member-watch attach loop its retry channel —
            # a network fleet may have failed a cluster's attach (join
            # secret not yet readable) after the signature stabilized.
            if getattr(self._reattach_members, "pending", None):
                self._reattach_members()
            return
        self._cluster_sigs[name] = sig
        self._reattach_members()
        self.worker.enqueue(_CLUSTER_KEY_PREFIX + name)
        self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def _on_breaker_transition(self, name: str, old: str, new: str) -> None:
        # A member's breaker closing means its shed writes can land now:
        # re-drive every federated object instead of waiting out each
        # key's exponential backoff (the recovery-latency half of the
        # "shed to a background requeue" contract).
        if new == B.CLOSED:
            self.worker.enqueue_all(self.host.keys(self._fed_resource))

    def _member_client(self, cluster: str) -> FakeKube:
        return self.fleet.member(cluster)

    def _guarded_member_read(
        self, dispatcher: D.ManagedDispatcher, cname: str, key: str
    ):
        """Member read feeding the breaker: transport failures (a hung
        or erroring member) record breaker evidence and settle the
        cluster at ClusterNotReady — they must not escape and poison the
        whole object's plan.  Returns (ok, cluster_obj)."""
        cached = self._tick_reads.get((cname, key))
        if cached is not None:
            kind, value = cached
            if kind == "err":
                # Breaker evidence was recorded once at prefetch time.
                dispatcher.record_error(
                    cname, D.CLUSTER_NOT_READY, f"member read failed: {value}"
                )
                return False, None
            return True, value
        breaker = self.breakers.for_member(cname)
        start = time.monotonic()
        try:
            obj = self._member_read(
                self._member_client(cname), self._target_resource, key
            )
        except NotFound:
            dispatcher.record_error(
                cname, D.CACHED_RETRIEVAL_FAILED, "cluster unavailable"
            )
            return False, None
        except Exception as e:  # transport-level: the member is sick
            breaker.record_failure(latency_s=time.monotonic() - start)
            dispatcher.record_error(
                cname, D.CLUSTER_NOT_READY, f"member read failed: {e}"
            )
            return False, None
        breaker.note_ok(time.monotonic() - start)
        return True, obj

    @staticmethod
    def _member_read(client, resource: str, key: str):
        """Read-only member lookup: the no-copy view when the client
        offers one (FakeKube) — the sync hot path reads one member
        object per (object, cluster) pair, and per-read deep copies
        dominated its profile.  Consumers must NOT mutate the result
        (the dispatcher's mutating paths copy first)."""
        view = getattr(client, "try_get_view", None)
        return view(resource, key) if view is not None else client.try_get(resource, key)

    # -- reconcile -------------------------------------------------------
    def reconcile(self, key: str) -> Result:
        """Single-key compatibility entry: one tick over one key."""
        return self.reconcile_batch([key])[key]

    def reconcile_batch(self, keys: list[str]) -> dict[str, Result]:
        """One tick: every due key planned against ONE cluster-list scan,
        member writes staged into ONE BatchSink, flushed as one bulk
        write per member, then per-object status finalized."""
        results: dict[str, Result] = {}
        # Mark this thread for echo suppression even when called
        # directly (tests, the reconcile() compat wrapper) rather than
        # through BatchWorker.step.
        ident = self.worker._enter()
        try:
            fed_keys: list[str] = []
            for key in keys:
                if key.startswith(_CLUSTER_KEY_PREFIX):
                    results[key] = self._reconcile_cluster(
                        key[len(_CLUSTER_KEY_PREFIX) :]
                    )
                else:
                    fed_keys.append(key)
            if not fed_keys:
                return results
            # Disjoint-by-construction guard: a replica syncs (and
            # persists placements/status for) only keys its shard owns;
            # a non-owned key here bypassed the router.
            if self._shard.shard_count > 1:
                for key in fed_keys:
                    assert self._shard.owns(key), (
                        f"shard violation: replica "
                        f"{self._shard.shard_index}/{self._shard.shard_count}"
                        f" syncing non-owned key {key}"
                    )
            ctx = _TickClusters(
                [
                    c
                    for c in self.host.list_view(FEDERATED_CLUSTERS)
                    if is_cluster_joined(c)
                ]
            )
            # Bulk prefetch (network fleets): the tick's fed objects in
            # batched host reads, then every candidate (object, member)
            # pair in batched member reads — the per-object GET fan-out
            # becomes ceil(n / KT_MEMBER_BATCH) round trips per member.
            fed_cache: Optional[dict] = None
            if self._bulk_reads and fed_keys:
                fed_cache = D.bulk_get(self.host, self._fed_resource, fed_keys)
                if fed_cache is not None:
                    self._prefetch_member_reads(fed_keys, fed_cache, ctx)
            sink = D.BatchSink(
                self._member_client,
                pool=self.pool,
                thread_registry=self._flush_threads,
                breakers=self.breakers,
            )
            # Tick-level write-ack buffer: on_written callbacks append
            # (fed_key, cluster) here (list.append from the flush pool's
            # threads is atomic) and the SLO token closes + member-index
            # updates settle in ONE batch after the flush, instead of a
            # lock hold and an SLO round per acked op.
            acks: list[tuple[str, str]] = []
            sink.kt_acks = acks
            finishers: list[tuple[str, Callable[..., Result]]] = []
            for key in fed_keys:
                # Per-key isolation: one poison object backs off alone
                # (worker.go:119-131 semantics), the rest of the tick
                # proceeds and still flushes.
                try:
                    out = self._plan_one(key, ctx, sink, fed_cache)
                except Exception:
                    self.metrics.counter(f"sync-{self.ftc.name}.plan_panic")
                    results[key] = Result.retry()
                    continue
                if isinstance(out, Result):
                    results[key] = out
                else:
                    finishers.append((key, out))
            sink.flush()
            # Settle the flush's acks before finishers run: finish()
            # calls slo.settle, which must observe every placement ack
            # of its own tick or tokens would finalize short.
            if acks:
                with self._index_lock:
                    for fed_key, cluster in acks:
                        self._member_index.setdefault(fed_key, set()).add(cluster)
                slo.written_many(acks)
            hb = HostBatch(self.host)
            for key, finish in finishers:
                try:
                    results[key] = finish(hb, results, key)
                except Exception:
                    self.metrics.counter(f"sync-{self.ftc.name}.finish_panic")
                    results[key] = Result.retry()
            # One bulk host round trip (plus follow-ups) finalizes every
            # object's status + syncing annotation.
            hb.flush()
        finally:
            self._tick_reads.clear()
            self.worker._exit(ident)
        return results

    def _prefetch_member_reads(
        self, fed_keys: list[str], fed_cache: dict, ctx: _TickClusters
    ) -> None:
        """Populate ``self._tick_reads`` with every member object this
        tick's planning will read: the candidate computation mirrors
        :meth:`_sync_to_clusters` (over-fetching a skipped candidate is
        harmless; a miss falls back to the direct read)."""
        wanted: dict[str, list[str]] = {}
        for key in fed_keys:
            fed_obj = fed_cache.get(key)
            if fed_obj is None or fed_obj["metadata"].get("deletionTimestamp"):
                continue
            try:
                if pending.get_pending(fed_obj):
                    continue
            except KeyError:
                continue
            candidates = set(C.all_placement_clusters(fed_obj))
            for entry in fed_obj.get("status", {}).get("clusters", ()):
                cname = entry.get("cluster")
                if cname:
                    candidates.add(cname)
            with self._index_lock:
                candidates.update(self._member_index.get(key, ()))
            for cname in candidates:
                flags = ctx.flags.get(cname)
                if flags is None or not flags[0]:
                    continue  # not joined / not ready: never read
                if not self.breakers.allow(cname, consume_probe=False):
                    continue  # breaker-open: the plan short-circuits too
                wanted.setdefault(cname, []).append(key)
        for cname, keys in wanted.items():
            try:
                client = self._member_client(cname)
            except Exception:
                continue  # resolution failures take the direct path
            got = D.bulk_get(
                client, self._target_resource, keys,
                cluster=cname, breakers=self.breakers,
            )
            if got is None:
                # Transport-level failure: every planned read of this
                # member settles ClusterNotReady without another socket
                # (breaker evidence was recorded once by bulk_get).
                for key in keys:
                    self._tick_reads[(cname, key)] = (
                        "err", "member bulk read failed"
                    )
                continue
            for key in keys:
                if key in got:
                    self._tick_reads[(cname, key)] = ("ok", got[key])

    def _plan_one(
        self,
        key: str,
        ctx: _TickClusters,
        sink: D.BatchSink,
        fed_cache: Optional[dict] = None,
    ) -> Union[Result, Callable[..., Result]]:
        """Everything up to (and including) staging one object's member
        writes; returns a finisher ``finish(hb, results, key)`` to run
        after the sink flushes, or a settled Result for the early-exit
        paths."""
        if fed_cache is not None and key in fed_cache:
            fed_obj = fed_cache[key]
        else:
            fed_obj = self.host.try_get(self._fed_resource, key)
        if fed_obj is None:
            return Result.ok()
        fed = FederatedResource(fed_obj, self.ftc)

        if fed_obj["metadata"].get("deletionTimestamp"):
            return self._ensure_deletion(fed)

        # Wait until upstream pipeline controllers have run
        # (controller.go:380-388: any pending controller defers sync).
        try:
            if pending.get_pending(fed_obj):
                return Result.ok()
        except KeyError:
            return Result.ok()  # not initialized by federate yet

        # Pre-dispatch metadata: the sync finalizer (MUST be persisted
        # before any member write — controller.go:389-397) and the
        # revision annotations land in ONE host update instead of two.
        fins = fed_obj["metadata"].setdefault("finalizers", [])
        dirty = C.SYNC_FINALIZER not in fins
        if dirty:
            fins.append(C.SYNC_FINALIZER)

        collision_count = None
        if self.revisions is not None:
            # Record the template revision + annotate the fed object
            # (controller.go:399-418 syncRevisions/ensureAnnotations).
            try:
                collision_count, last_rev, current_rev = (
                    self.revisions.sync_revisions(fed_obj)
                )
            except RevisionSyncError:
                return Result.retry()
            ann = fed_obj["metadata"].setdefault("annotations", {})
            for key_, value in (
                (LAST_REVISION_ANNOTATION, last_rev),
                (CURRENT_REVISION_ANNOTATION, current_rev),
            ):
                if value and ann.get(key_) != value:
                    ann[key_] = value
                    dirty = True
        if dirty:
            try:
                # rv-only consumption: skip the result deep copy (the
                # in-process store hands back the immutable node).
                updated = self.host.update(
                    self._fed_resource, fed_obj, _copy_result=False
                )
            except Conflict:
                return Result.retry()
            except NotFound:
                return Result.ok()
            fed_obj["metadata"]["resourceVersion"] = updated["metadata"][
                "resourceVersion"
            ]
            self._record_own_fed(updated)

        return self._sync_to_clusters(fed, collision_count, ctx, sink)

    def _record_own_fed(self, obj: dict) -> None:
        self._own_fed_rv[obj_key(obj)] = str(
            obj.get("metadata", {}).get("resourceVersion", "")
        )

    # -- cluster cascading-delete finalizer (controller.go:1050-1196) ----
    def _reconcile_cluster(self, name: str) -> Result:
        cluster = self.host.try_get(FEDERATED_CLUSTERS, name)
        if cluster is None:
            return Result.ok()

        if not cluster["metadata"].get("deletionTimestamp"):
            fins = cluster["metadata"].setdefault("finalizers", [])
            if self.cluster_finalizer in fins:
                return Result.ok()
            fins.append(self.cluster_finalizer)
            try:
                self.host.update(FEDERATED_CLUSTERS, cluster)
            except Conflict:
                return Result.retry()
            except NotFound:
                pass
            return Result.ok()

        if is_cluster_joined(cluster) and is_cascading_delete_enabled(cluster):
            # Wait until no managed target objects remain in the member.
            try:
                member = self._member_client(name)
            except NotFound:
                member = None
            if member is not None:
                held = []

                def check(obj: dict) -> None:
                    if C.MANAGED_LABEL in obj.get("metadata", {}).get("labels", {}):
                        held.append(obj_key(obj))

                member.scan(self._target_resource, check)
                if held:
                    return Result.after(2.0)

        return self._remove_cluster_finalizer(cluster)

    def _remove_cluster_finalizer(self, cluster: dict) -> Result:
        fins = cluster["metadata"].get("finalizers", [])
        if self.cluster_finalizer not in fins:
            return Result.ok()
        cluster["metadata"]["finalizers"] = [
            f for f in fins if f != self.cluster_finalizer
        ]
        try:
            self.host.update(FEDERATED_CLUSTERS, cluster)
        except Conflict:
            return Result.retry()
        except NotFound:
            pass
        return Result.ok()

    # -- the propagation round (controller.go:425-596) -------------------
    def _sync_to_clusters(
        self,
        fed: FederatedResource,
        collision_count: Optional[int],
        ctx: _TickClusters,
        sink: D.BatchSink,
    ) -> Callable[[], Result]:
        selected = fed.compute_placement(ctx.joined_set)

        recorded = self.versions.get(
            fed.namespace, fed.name, fed.template_version(), fed.override_version()
        )
        # Rollout planning is Deployment-only, incompatible with
        # member-owned replicas (managed.go:204-213), and depends on the
        # current-revision annotation that only revision history stamps —
        # without it every plan would fail and nothing would ever be
        # created.
        rollout_enabled = (
            self.ftc.rollout_plan
            and self.revisions is not None
            and self.ftc.source.kind == "Deployment"
            and not fed.obj.get("spec", {}).get("retainReplicas")
        )
        plans_holder: dict[str, R.RolloutPlan] = {}
        fed_key = fed.key

        acks = getattr(sink, "kt_acks", None)

        def on_written(cluster: str, obj: dict) -> None:
            self._own_member_rv[(cluster, fed_key)] = str(
                obj.get("metadata", {}).get("resourceVersion", "")
            )
            if acks is not None:
                # Per-op bookkeeping diet: defer the member-index update
                # and SLO ack to one post-flush batch (reconcile_batch
                # drains kt_acks right after sink.flush()).
                acks.append((fed_key, cluster))
                return
            with self._index_lock:
                self._member_index.setdefault(fed_key, set()).add(cluster)
            # SLO provenance: a member apiserver acked this placement —
            # the token closes (and the e2e latency histogram samples)
            # once every expected placement has acked.
            slo.written(fed_key, cluster)

        dispatcher = D.ManagedDispatcher(
            self._member_client,
            fed,
            self._target_resource,
            replicas_path=self.ftc.path.replicas_spec,
            skip_adopting=not should_adopt_preexisting(fed.obj),
            sink=sink,
            on_written=on_written,
            rollout_overrides=(
                (
                    lambda c: plans_holder[c].to_overrides()
                    if c in plans_holder
                    else []
                )
                if rollout_enabled
                else None
            ),
        )
        # (cluster, cluster_obj, should_be_deleted, cascading) actions
        # deferred until after rollout planning.
        rollout_ops: list[tuple[str, Optional[dict], bool, bool]] = []

        # Candidate clusters — O(selected + previously-placed), not
        # O(fleet): selected placements, clusters named in the object's
        # persisted propagation status (the durable record of where it
        # was last dispatched, surviving restarts and template-version
        # bumps that invalidate the version record), and the live member
        # index (foreign-created managed objects seen by the watches).
        candidates = set(selected)
        for entry in fed.obj.get("status", {}).get("clusters", ()):
            cname = entry.get("cluster")
            if cname:
                candidates.add(cname)
        with self._index_lock:
            candidates.update(self._member_index.get(fed_key, ()))

        for cname in sorted(candidates):
            flags = ctx.flags.get(cname)
            if flags is None:
                continue  # not a joined cluster (or left the federation)
            ready, terminating, cascading = flags
            should_be_deleted = cname not in selected or cascading

            if not ready:
                if not should_be_deleted:
                    dispatcher.record_error(
                        cname, D.CLUSTER_NOT_READY, "cluster not ready"
                    )
                continue
            if not self.breakers.allow(cname, consume_probe=False):
                # Breaker hard-open: the member already stalled or
                # errored past threshold this window — short-circuit to
                # ClusterNotReady without a read, write or thread.
                if not should_be_deleted:
                    self.breakers.count_shed(cname)
                    dispatcher.record_error(
                        cname, D.CLUSTER_NOT_READY, "member circuit breaker open"
                    )
                continue
            ok, cluster_obj = self._guarded_member_read(dispatcher, cname, fed.key)
            if not ok:
                continue
            if cluster_obj is not None and C.MANAGED_LABEL not in cluster_obj[
                "metadata"
            ].get("labels", {}):
                # Unmanaged member objects are invisible to the sync view
                # (federatedinformer.go:678-680): a pre-existing object is
                # "absent", so Create runs and the AlreadyExists fallback
                # decides adoption.
                cluster_obj = None

            if should_be_deleted:
                if cluster_obj is None:
                    continue
                if cluster_obj["metadata"].get("deletionTimestamp"):
                    dispatcher.record_status(cname, D.WAITING_FOR_REMOVAL)
                    continue
                if terminating and not cascading:
                    # Preserve member objects of a non-cascading
                    # terminating cluster (controller.go:498-506).
                    continue
                if rollout_enabled:
                    # Deletions drain through the rollout plan so removing
                    # a cluster counts against maxUnavailable.
                    rollout_ops.append((cname, cluster_obj, True, cascading))
                    continue
                # Orphaning is only respected during cascading deletion,
                # not when migrating between clusters (controller.go:508).
                self._delete_one(dispatcher, cname, fed, cluster_obj, cascading)
                continue

            if terminating:
                dispatcher.record_error(
                    cname, D.CLUSTER_TERMINATING, "cluster terminating"
                )
                continue
            if rollout_enabled:
                rollout_ops.append((cname, cluster_obj, False, False))
            elif cluster_obj is None:
                dispatcher.create(cname)
            else:
                dispatcher.update(cname, cluster_obj, recorded.get(cname, ""))

        if rollout_enabled:
            plans = self._plan_rollout(fed, rollout_ops, selected)
            if plans:
                plans_holder.update(plans)
            # The dispatch decisions of managed.go:214-250: unplanned
            # clusters keep their template (and rollout knobs); planned
            # ones create/update/shrink/delete as the plan dictates.
            for cname, cluster_obj, to_delete, cascading in rollout_ops:
                plan = plans.get(cname) if plans else None
                version = recorded.get(cname, "")
                if plan is None:
                    if cluster_obj is not None:
                        dispatcher.patch_and_keep_template(
                            cname, cluster_obj, True, version
                        )
                    continue
                if to_delete and (plan.replicas is None or plan.replicas == 0):
                    self._delete_one(dispatcher, cname, fed, cluster_obj, cascading)
                    continue
                if cluster_obj is None:
                    dispatcher.create(cname)
                    continue
                if plan.only_patch_replicas and plan.replicas is not None:
                    dispatcher.patch_and_keep_template(
                        cname, cluster_obj, False, version
                    )
                    continue
                dispatcher.update(cname, cluster_obj, version)

        # SLO provenance: member writes are staged — the "dispatch"
        # stage closes here, and the declared placements become the
        # token's ack set (the freshness gauges count what has not
        # landed: a breaker-open or hard-down member keeps its
        # placements pending, which is exactly the staleness signal).
        slo.expect(fed_key, selected)
        slo.mark(fed_key, "dispatch")

        def finish(hb: HostBatch, results: dict, key: str) -> Result:
            """Runs after the tick's sink flushes: status/version
            bookkeeping over the completed dispatch round.  Host writes
            are staged into ``hb``; callbacks downgrade ``results[key]``
            on persistent failure."""
            ok = dispatcher.wait()

            # Record versions (an optimization; failures tolerated —
            # controller.go:568-576).
            self.versions.update(
                fed.namespace,
                fed.name,
                fed.template_version(),
                fed.override_version(),
                sorted(selected),
                dispatcher.version_map,
                batch=hb,
            )

            status_map = dispatcher.status_map
            reason = AGGREGATE_SUCCESS if ok else CHECK_CLUSTERS
            if not ok:
                failed = sorted(
                    c for c, s in status_map.items()
                    if s not in (D.OK, D.WAITING, D.WAITING_FOR_REMOVAL)
                )
                self.recorder.event(
                    fed.obj,
                    "Warning",
                    "PropagationFailed",
                    f"failed clusters: {', '.join(failed)}",
                )
            self._stage_status_writes(
                hb, fed, reason, status_map, collision_count, results, key
            )
            if not ok:
                return Result.retry()
            # Fully-OK round: any still-pending token is a no-op
            # (version-skips) or partially-acked event — settle it so
            # the freshness gauges only count genuinely unwritten work.
            slo.settle(key)
            if D.WAITING_FOR_REMOVAL in status_map.values():
                # A member object is finalizer-gated mid-removal; no host
                # event will fire when it finishes, so revisit on a timer
                # (controller.go recheckAfterDispatchDelay).
                return Result.after(10.0)
            return Result.ok()

        return finish

    def _plan_rollout(
        self,
        fed: FederatedResource,
        ops: list,
        selected: set[str],
    ) -> Optional[dict[str, R.RolloutPlan]]:
        """Build the cross-cluster rollout plan for this tick
        (managed.go:272-323 planRolloutProcess).  None = planning failed;
        existing members then keep their template this round."""
        try:
            replicas = fed.total_replicas(selected)
            planner = R.RolloutPlanner(fed.key, fed.obj, replicas)
            for cname, cluster_obj, to_delete, _ in ops:
                desired = 0 if to_delete else fed.replicas_override_for_cluster(cname)
                planner.register(
                    R.target_from_cluster_object(
                        cname,
                        cluster_obj,
                        desired,
                        planner.revision,
                        self.ftc.path.replicas_spec,
                        self.ftc.path.available_replicas_status,
                    )
                )
            plans = planner.plan()
        except (R.RolloutPlanError, TypeError, ValueError):
            # Malformed member-written state degrades to a no-plan tick
            # (existing members keep their template) rather than wedging
            # the whole reconcile.
            self.metrics.counter(f"sync-{self.ftc.name}.plan_rollout_failed")
            return None
        return plans or None

    def _delete_one(
        self,
        dispatcher: D.ManagedDispatcher,
        cluster: str,
        fed: FederatedResource,
        cluster_obj: dict,
        respect_orphaning: bool,
    ) -> None:
        """(controller.go:821-845 deleteFromCluster)."""
        if respect_orphaning:
            behavior = orphaning_behavior(fed.obj)
            adopted = cluster_obj.get("metadata", {}).get("annotations", {}).get(
                D.ADOPTED_ANNOTATION
            )
            if behavior == ORPHAN_ALL or (behavior == ORPHAN_ADOPTED and adopted):
                dispatcher.remove_managed_label(cluster, cluster_obj)
                return
        dispatcher.delete(cluster)

    # -- status ----------------------------------------------------------
    def _stage_status_writes(
        self,
        hb: HostBatch,
        fed: FederatedResource,
        reason: str,
        status_map: dict[str, str],
        collision_count: Optional[int],
        results: dict,
        key: str,
    ) -> None:
        """Stage the status-subresource write (and, chained on its new
        resourceVersion, the syncing annotation) into the tick's host
        batch.  The in-hand object is the optimistic base; a conflict
        falls back to the synchronous read-retry loops."""
        obj = fed.obj
        if not _apply_desired_status(obj, reason, status_map, collision_count):
            self._stage_annotation(hb, fed, obj, status_map, results, key)
            return

        def on_panic() -> None:
            self.metrics.counter(f"sync-{self.ftc.name}.host_write_panic")
            results[key] = Result.retry()

        def on_status(result: dict) -> None:
            code = result.get("code")
            if code == 200:
                updated = result["object"]
                self._record_own_fed(updated)
                obj["metadata"]["resourceVersion"] = updated["metadata"][
                    "resourceVersion"
                ]
                self._stage_annotation(hb, fed, obj, status_map, results, key)
            elif code == 404:
                pass  # object gone: nothing to finalize
            else:
                # Conflict (or transport trouble): the synchronous
                # read-retry loops own this object's finalization.
                r = self._set_federated_status(
                    fed, reason, status_map, collision_count
                )
                if not r.success:
                    results[key] = Result.retry()
                else:
                    self._set_syncing_annotation(fed, status_map)

        hb.stage(
            {"verb": "update_status", "resource": self._fed_resource, "object": obj},
            on_status,
            on_panic,
        )

    def _stage_annotation(
        self,
        hb: HostBatch,
        fed: FederatedResource,
        obj: dict,
        status_map: dict[str, str],
        results: dict,
        key: str,
    ) -> None:
        """The syncing feedback annotation is a separate (non-status)
        write: UpdateStatus ignores annotations (controller.go:686-718)."""
        syncing = _syncing_value(status_map, obj["metadata"].get("generation", 1))
        ann = obj["metadata"].setdefault("annotations", {})
        prior = ann.get(C.SOURCE_FEEDBACK_SYNCING)
        if prior == syncing:
            return
        ann[C.SOURCE_FEEDBACK_SYNCING] = syncing

        def on_panic() -> None:
            self.metrics.counter(f"sync-{self.ftc.name}.host_write_panic")
            results[key] = Result.retry()

        def on_ann(result: dict) -> None:
            code = result.get("code")
            if code == 200:
                self._record_own_fed(result["object"])
            elif code != 404:
                # Undo the optimistic in-hand mutation FIRST: the
                # fallback's cheap steady-state exit consults this very
                # dict and would otherwise see the desired value as
                # already present and skip the conflict-retry loop.
                if prior is None:
                    ann.pop(C.SOURCE_FEEDBACK_SYNCING, None)
                else:
                    ann[C.SOURCE_FEEDBACK_SYNCING] = prior
                self._set_syncing_annotation(fed, status_map)

        hb.stage(
            {"verb": "update", "resource": self._fed_resource, "object": obj},
            on_ann,
            on_panic,
        )

    def _set_federated_status(
        self,
        fed: FederatedResource,
        reason: str,
        status_map: dict[str, str],
        collision_count: Optional[int] = None,
    ) -> Result:
        """Write status.clusters + the Propagated condition (and the
        revision collisionCount, when history is on) via the status
        subresource, with conflict-retry (controller.go:637-721)."""
        for _ in range(5):
            obj = self.host.try_get(self._fed_resource, fed.key)
            if obj is None:
                return Result.ok()
            if not _apply_desired_status(obj, reason, status_map, collision_count):
                return Result.ok()
            try:
                updated = self.host.update_status(self._fed_resource, obj)
                if isinstance(updated, dict):
                    self._record_own_fed(updated)
                return Result.ok()
            except NotFound:
                return Result.ok()
            except Conflict:
                continue
        return Result.retry()

    def _set_syncing_annotation(
        self, fed: FederatedResource, status_map: dict[str, str]
    ) -> None:
        """Record per-cluster sync progress on the federated object for
        the federate controller to mirror onto the source
        (sourcefeedback/syncing.go PopulateSyncingAnnotation); best-effort
        with conflict-refresh."""

        # Cheap steady-state exit using the in-hand object: no refetch
        # (a full deep copy per tick) when the annotation is current.
        in_hand = fed.obj.get("metadata", {})
        if in_hand.get("annotations", {}).get(
            C.SOURCE_FEEDBACK_SYNCING
        ) == _syncing_value(status_map, in_hand.get("generation", 1)):
            return
        for _ in range(5):
            obj = self.host.try_get(self._fed_resource, fed.key)
            if obj is None:
                return
            syncing = _syncing_value(
                status_map, obj["metadata"].get("generation", 1)
            )
            ann = obj["metadata"].setdefault("annotations", {})
            if ann.get(C.SOURCE_FEEDBACK_SYNCING) == syncing:
                return
            ann[C.SOURCE_FEEDBACK_SYNCING] = syncing
            try:
                updated = self.host.update(self._fed_resource, obj)
                if isinstance(updated, dict):
                    self._record_own_fed(updated)
                return
            except NotFound:
                return
            except Conflict:
                continue

    # -- deletion (controller.go:723-819) --------------------------------
    def _ensure_deletion(self, fed: FederatedResource) -> Result:
        # An object heading for deletion will never be written: its
        # provenance token (if any) must not wedge the freshness gauges.
        slo.forget(fed.key)
        self.versions.delete(fed.namespace, fed.name)
        fins = fed.obj["metadata"].get("finalizers", [])
        if C.SYNC_FINALIZER not in fins:
            return Result.ok()

        if orphaning_behavior(fed.obj) == ORPHAN_ALL:
            # Orphan everywhere: strip managed labels, drop finalizer.
            if not self._remove_managed_labels_everywhere(fed):
                return Result.retry()
            return self._remove_finalizer(fed)

        remaining = self._delete_from_clusters(fed)
        if remaining is None:
            return Result.retry()
        if remaining:
            return Result(success=True, requeue_after=2.0)
        return self._remove_finalizer(fed)

    def _joined_members(self) -> list[dict]:
        return [
            c
            for c in self.host.list_view(FEDERATED_CLUSTERS)
            if is_cluster_joined(c)
        ]

    def _delete_from_clusters(self, fed: FederatedResource) -> Optional[list[str]]:
        """Returns clusters still holding the object, or None on failure
        (controller.go:846-887)."""
        dispatcher = D.ManagedDispatcher(
            self._member_client,
            fed,
            self._target_resource,
            replicas_path=self.ftc.path.replicas_spec,
            pool=self.pool,
            inline=self._inline,
            breakers=self.breakers,
        )
        remaining: list[str] = []
        unreachable: list[str] = []
        for cluster in self._joined_members():
            cname = cluster["metadata"]["name"]
            if not is_cluster_ready(cluster) or not self.breakers.allow(
                cname, consume_probe=False
            ):
                # Cannot confirm removal from an unready (or breaker-
                # open) cluster; block finalizer removal until it is
                # reachable again (controller.go:846-887 errs when a
                # cluster store is unavailable, keeping the finalizer in
                # place).
                unreachable.append(cname)
                continue
            try:
                cluster_obj = self._member_read(
                    self._member_client(cname), self._target_resource, fed.key
                )
            except NotFound:
                continue  # cluster client gone mid-leave; nothing to delete
            except Exception:
                # Transport failure mid-read: same contract as unready —
                # removal unconfirmed, finalizer held.
                self.breakers.for_member(cname).record_failure()
                unreachable.append(cname)
                continue
            if cluster_obj is None:
                continue
            if C.MANAGED_LABEL not in cluster_obj["metadata"].get("labels", {}):
                # Never delete objects this control plane doesn't manage
                # (pre-existing, non-adopted — federatedinformer.go:678).
                continue
            remaining.append(cname)
            if cluster_obj["metadata"].get("deletionTimestamp"):
                dispatcher.record_status(cname, D.WAITING_FOR_REMOVAL)
                continue
            self._delete_one(dispatcher, cname, fed, cluster_obj, True)
        if not dispatcher.wait():
            return None
        # Re-check what actually remains after the dispatch round; an
        # orphaned (label-stripped) object no longer counts as managed.
        still = []
        for c in remaining:
            try:
                obj = self._member_read(
                    self._member_client(c), self._target_resource, fed.key
                )
            except NotFound:
                continue
            except Exception:
                still.append(c)  # unconfirmed: keep the finalizer held
                continue
            if obj is None:
                continue
            if C.MANAGED_LABEL not in obj.get("metadata", {}).get("labels", {}):
                continue
            still.append(c)
        return still + unreachable

    def _remove_managed_labels_everywhere(self, fed: FederatedResource) -> bool:
        dispatcher = D.ManagedDispatcher(
            self._member_client, fed, self._target_resource, pool=self.pool,
            inline=self._inline, breakers=self.breakers,
        )
        all_reachable = True
        for cluster in self._joined_members():
            cname = cluster["metadata"]["name"]
            if not is_cluster_ready(cluster) or not self.breakers.allow(
                cname, consume_probe=False
            ):
                all_reachable = False  # cannot strip labels there yet
                continue
            try:
                cluster_obj = self._member_read(
                    self._member_client(cname), self._target_resource, fed.key
                )
            except NotFound:
                continue
            except Exception:
                self.breakers.for_member(cname).record_failure()
                all_reachable = False
                continue
            if cluster_obj is None or cluster_obj["metadata"].get("deletionTimestamp"):
                continue
            if C.MANAGED_LABEL not in cluster_obj["metadata"].get("labels", {}):
                continue
            dispatcher.remove_managed_label(cname, cluster_obj)
        return dispatcher.wait() and all_reachable

    def _remove_finalizer(self, fed: FederatedResource) -> Result:
        obj = self.host.try_get(self._fed_resource, fed.key)
        if obj is None:
            return Result.ok()
        fins = obj["metadata"].get("finalizers", [])
        if C.SYNC_FINALIZER in fins:
            fins.remove(C.SYNC_FINALIZER)
            try:
                self.host.update(self._fed_resource, obj)
            except Conflict:
                return Result.retry()
            except NotFound:
                pass
        return Result.ok()
