"""Namespace auto-propagation: every namespace, every cluster.

FederatedNamespaces are propagated to all member clusters without
requiring a policy (reference: pkg/controllers/nsautoprop/controller.go:
126-381).  The controller

* writes an all-cluster placement under its own controller name,
* marks the federated namespace to adopt pre-existing member namespaces
  (internal conflict-resolution annotation = adopt) and to orphan the
  adopted ones on deletion (internal orphan annotation = adopted),
* skips system namespaces ("kube-" prefix + the federation system
  namespace), names matched by the exclusion regexp, and namespaces
  annotated kubeadmiral.io/no-auto-propagation=true — still advancing
  the pending-controllers pipeline so downstream controllers run.

Running both this controller and the global scheduler on namespaces
makes them fight over placements, as the reference warns
(controller.go:66-72); the namespaces FTC pipeline therefore starts with
nsautoprop instead of the scheduler.
"""

from __future__ import annotations

import re
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.runtime import pending
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import Conflict, FakeKube, NotFound, obj_key

FED_SYSTEM_NAMESPACE = "kube-admiral-system"


class NamespaceAutoPropagationController:
    name = C.PREFIX + "nsautoprop-controller"

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        exclude_regexp: Optional[str] = None,
        fed_system_namespace: str = FED_SYSTEM_NAMESPACE,
        metrics: Optional[Metrics] = None,
    ):
        self.host = host
        self.ftc = ftc
        self.exclude = re.compile(exclude_regexp) if exclude_regexp else None
        self.fed_system_namespace = fed_system_namespace
        self.metrics = metrics or Metrics()
        self.worker = Worker("nsautoprop", self.reconcile, metrics=self.metrics)
        self._resource = ftc.federated.resource

        host.watch(self._resource, self._on_object_event, replay=True)
        self._cluster_sigs: dict[str, tuple] = {}
        host.watch(C.FEDERATED_CLUSTERS, self._on_cluster_event, replay=False)

    def _on_object_event(self, event: str, obj: dict) -> None:
        self.worker.enqueue(obj_key(obj))

    def _on_cluster_event(self, event: str, obj: dict) -> None:
        # Cluster membership changes re-place every namespace
        # (controller.go reconcileAll on cluster add/delete) — gated on
        # lifecycle transitions so heartbeats don't re-place the world.
        sig = C.cluster_lifecycle_sig(obj)
        name = obj["metadata"]["name"]
        if event == "DELETED":
            self._cluster_sigs.pop(name, None)
        elif self._cluster_sigs.get(name) == sig:
            return
        else:
            self._cluster_sigs[name] = sig
        self.worker.enqueue_all(self.host.keys(self._resource))

    def _should_propagate(self, fed_ns: dict) -> bool:
        """controller.go shouldBeAutoPropagated."""
        name = fed_ns["metadata"]["name"]
        if name.startswith("kube-"):
            return False
        if name == self.fed_system_namespace:
            return False
        if self.exclude is not None and self.exclude.search(name):
            return False
        ann = fed_ns["metadata"].get("annotations", {})
        return ann.get(C.NO_AUTO_PROPAGATION) != "true"

    def reconcile(self, key: str) -> Result:
        fed_ns = self.host.try_get(self._resource, key)
        if fed_ns is None or fed_ns["metadata"].get("deletionTimestamp"):
            return Result.ok()
        try:
            if not pending.dependencies_fulfilled(fed_ns, self.name):
                return Result.ok()
        except KeyError:
            return Result.ok()  # not yet initialized by federate

        modified = False
        if self._should_propagate(fed_ns):
            # All registered clusters, joined or not (controller.go:241-249
            # lists everything) — sync itself intersects with joined.
            names = {
                obj["metadata"]["name"]
                for obj in self.host.list(C.FEDERATED_CLUSTERS)
            }
            modified |= C.set_placement(fed_ns, self.name, names)
            ann = fed_ns["metadata"].setdefault("annotations", {})
            for key_, value in (
                (C.CONFLICT_RESOLUTION_INTERNAL, "adopt"),
                (C.ORPHAN_MODE_INTERNAL, "adopted"),
            ):
                if ann.get(key_) != value:
                    ann[key_] = value
                    modified = True
        pend = pending.update_pending(
            fed_ns, self.name, modified, self.ftc.controller_groups
        )
        if not (modified or pend):
            return Result.ok()
        try:
            self.host.update(self._resource, fed_ns)
        except Conflict:
            return Result.retry()
        except NotFound:
            pass
        return Result.ok()
