"""Per-cluster write dispatch for the sync controller.

The reference fans member-cluster writes out to per-cluster goroutines
with a shared timeout and collects a per-cluster propagation status +
version map (reference: pkg/controllers/sync/dispatch/operation.go:102-123,
managed.go:108-655, unmanaged.go).  Here the write fan-out is routed
through a *sink*:

* :class:`ImmediateSink` — the goroutine analogue: each operation runs
  inline (local in-process members) or on a bounded pool (network
  members), one client round trip per operation.
* :class:`BatchSink` — the tick-native variant: a whole BatchWorker tick
  of sync reconciles stages its member writes here, and ``flush()``
  issues bulk ``client.batch()`` round trips per member cluster covering
  every staged object (transport/apiserver.py _serve_batch).  Per-op
  conflict/failure results flow back through the same continuations, so
  status/version bookkeeping is identical to the immediate path.

Both sinks flush through the **per-member coalescing window**
(:func:`run_member_batches`): a member's staged ops split into
KT_MEMBER_BATCH-sized bulk requests, up to KT_MEMBER_INFLIGHT in flight
at once (the engine's KT_PIPELINE_DEPTH trick at the HTTP layer), with
the deadline and breaker re-checked between chunks.  KT_WRITE_COALESCE=0
reverts to one request per (object, member) op — the reference's
fan-out shape, kept as the bit-identical A/B baseline.  Point reads
batch the same way (:func:`bulk_get`; KT_BULK_READS consumers in sync
and the status controllers).

The fan-out is **stall-proof** (docs/operations.md § Degraded member
runbook): every flush path enforces the per-tick deadline budget
(KT_DISPATCH_DEADLINE_S), retryable failures get a bounded jittered
backoff budget (``run_batch_with_retries``), writes to a member whose
circuit breaker (transport/breaker.py) is open short-circuit to
ClusterNotReady without touching a socket, and a member that stalls a
flush sheds its writes to the owning worker's backoff requeue — the
tick's critical path scales with the HEALTHY members only.

Statuses mirror fedtypesv1a1.PropagationStatus values.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
import weakref
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Callable, Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation import retain
from kubeadmiral_tpu.runtime import slo, tenancy, trace
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.federation.rollout import (
    LAST_RS_NAME,
    LATEST_RS_NAME,
    MAX_SURGE_PATH,
    MAX_UNAVAILABLE_PATH,
)
from kubeadmiral_tpu.utils.unstructured import copy_json, delete_path, get_path, set_path
from kubeadmiral_tpu.federation.resource import (
    FederatedResource,
    has_managed_label,
    is_explicitly_unmanaged,
    object_needs_update,
    object_version,
)
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
)

# PropagationStatus values (reference: pkg/apis/types/v1alpha1/types_status.go).
OK = "OK"
WAITING = "Waiting"
CLUSTER_NOT_READY = "ClusterNotReady"
CLUSTER_TERMINATING = "ClusterTerminating"
CACHED_RETRIEVAL_FAILED = "CachedRetrievalFailed"
COMPUTE_RESOURCE_FAILED = "ComputeResourceFailed"
APPLY_OVERRIDES_FAILED = "ApplyOverridesFailed"
FIELD_RETENTION_FAILED = "FieldRetentionFailed"
CREATION_FAILED = "CreationFailed"
UPDATE_FAILED = "UpdateFailed"
DELETION_FAILED = "DeletionFailed"
ALREADY_EXISTS = "AlreadyExists"
WAITING_FOR_REMOVAL = "WaitingForRemoval"
DELETION_TIMED_OUT = "DeletionTimedOut"
CREATION_TIMED_OUT = "CreationTimedOut"
UPDATE_TIMED_OUT = "UpdateTimedOut"
MANAGED_LABEL_FALSE = "ManagedLabelFalse"
FINALIZER_CHECK_FAILED = "FinalizerCheckFailed"

ADOPTED_ANNOTATION = C.PREFIX + "adopted"

log = logging.getLogger("kubeadmiral.dispatch")


# -- retry / deadline budget ----------------------------------------------
def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def dispatch_pool_size() -> int:
    """Bounded in-flight window of the per-op fan-out (KT_DISPATCH_POOL)."""
    return max(1, int(_env_float("KT_DISPATCH_POOL", 8)))


def dispatch_deadline() -> float:
    """The per-tick member-write deadline budget (KT_DISPATCH_DEADLINE_S):
    no flush path may block its caller past this, whatever a member
    socket does."""
    return _env_float("KT_DISPATCH_DEADLINE_S", 30.0)


def write_coalesce() -> bool:
    """KT_WRITE_COALESCE: stage-and-batch member writes (default).  0
    reverts to ONE request per (object, member) operation — the
    reference's dispatch/operation.go model, kept as the bit-identical
    A/B baseline for the coalesced path."""
    return os.environ.get("KT_WRITE_COALESCE", "1") not in ("0", "false", "no")


def member_batch() -> int:
    """KT_MEMBER_BATCH: max operations per bulk member request.  A
    member's staged writes flush as ceil(n / batch) pipelined requests,
    so one request never grows unboundedly large (bounded request
    latency, bounded retry blast radius)."""
    return max(1, int(_env_float("KT_MEMBER_BATCH", 128)))


def member_inflight() -> int:
    """KT_MEMBER_INFLIGHT: bulk requests concurrently in flight per
    member during one flush — the engine's KT_PIPELINE_DEPTH trick at
    the HTTP layer."""
    return max(1, int(_env_float("KT_MEMBER_INFLIGHT", 4)))


# Ops shed before their bulk request was ever dispatched (deadline
# expiry mid-flush, breaker opening mid-flush) carry this marker so the
# flush skips their continuations: statuses stay at the pre-recorded
# *_TIMED_OUT values and the owning worker's backoff requeue re-drives
# them — identical semantics to the whole-cluster shed path.
_SHED = {"code": 503, "status": {"reason": "Shed",
                                 "message": "write shed before dispatch"},
         "shed": True}

# Histogram buckets for coalesced batch sizes (ops per bulk request).
_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _op_tenant(op: dict) -> str:
    """The tenant a member-write op belongs to: namespace (and labels,
    for the KT_TENANT_LABEL override) of the op's object, falling back
    to the namespace half of its "ns/name" key (delete verbs carry no
    object)."""
    meta = (op.get("object") or {}).get("metadata") or {}
    ns = meta.get("namespace", "")
    if not ns:
        key = op.get("key", "")
        ns = key.partition("/")[0] if "/" in key else ""
    return tenancy.tenant_of(ns, meta.get("labels"))


def _note_shed_tenants(items) -> None:
    """Per-tenant shed attribution (no-op unless a ledger is installed).
    ``items`` may be raw op dicts or the sinks' (op, continuation)
    staging entries."""
    if not tenancy.active():
        return
    for item in items:
        op = item[0] if isinstance(item, tuple) else item
        tenancy.note_shed(_op_tenant(op))


def _note_write_tenants(ops, elapsed: float) -> None:
    """Per-tenant write attribution for one completed batch round trip:
    the batch latency lands once per tenant, weighted by its op count."""
    if not tenancy.active():
        return
    groups: dict[str, int] = {}
    for op in ops:
        t = _op_tenant(op)
        groups[t] = groups.get(t, 0) + 1
    for t, n_ops in groups.items():
        tenancy.note_write(t, elapsed, ops=n_ops)


def retry_delay(attempt: int, rng=None) -> float:
    """Bounded exponential backoff with jitter for retryable member-write
    failures: uniform in [span/2, span] of the capped exponential
    (KT_RETRY_BASE_S doubling per attempt up to KT_RETRY_CAP_S) — the
    half floor keeps retries off the member's heels, the jitter keeps a
    fleet of dispatchers from thundering in phase."""
    base = _env_float("KT_RETRY_BASE_S", 0.05)
    cap = _env_float("KT_RETRY_CAP_S", 2.0)
    span = min(cap, base * (2 ** min(attempt, 10)))
    r = (rng or random).random()
    return span * (0.5 + 0.5 * r)


def retry_max() -> int:
    return max(0, int(_env_float("KT_RETRY_MAX", 3)))


def _refreshed_conflict_op(client, op: dict) -> Optional[dict]:
    """409-after-conflict-refresh: re-read the member object's current
    resourceVersion into a COPY of the update op (the staged object may
    be the shared desired-cache assembly).  None when the refresh read
    fails — the conflict then stays with the caller as before."""
    obj = op.get("object") or {}
    meta = obj.get("metadata", {})
    ns = meta.get("namespace", "")
    name = meta.get("name")
    if not name:
        return None
    key = f"{ns}/{name}" if ns else name
    try:
        fresh = client.get(op["resource"], key)
    except Exception:
        return None
    new_obj = copy_json(obj)
    new_obj.setdefault("metadata", {})["resourceVersion"] = (
        fresh.get("metadata", {}).get("resourceVersion")
    )
    return {**op, "object": new_obj}


def run_batch_with_retries(
    client,
    ops: list[dict],
    deadline: float,
    cluster: str = "",
    breakers=None,
) -> list[dict]:
    """``client.batch`` with the bounded retry budget: transport-level
    failures and 5xx results are re-sent with exponential backoff +
    jitter while the deadline budget allows (KT_RETRY_MAX attempts
    beyond the first); 409 Conflicts on update verbs retry once with a
    refreshed resourceVersion.  Always returns one result per op
    (transport failures become code-500 entries).  Feeds the member's
    circuit breaker: a final transport-level failure records a breaker
    failure (a stall-slow one opens it immediately), a completed batch
    records success."""
    n = len(ops)
    results: list[Optional[dict]] = [None] * n
    current: dict[int, dict] = dict(enumerate(ops))
    pending = list(range(n))
    conflict_refreshed: set[int] = set()
    breaker = breakers.for_member(cluster) if breakers is not None else None
    attempt = 0
    started = time.monotonic()
    transport_failed = False
    while True:
        try:
            out = list(client.batch([current[i] for i in pending]))
            transport_failed = False
        except Exception as e:  # transport-level failure: every op failed
            out = []
            transport_failed = True
            transport_result = {
                "code": 500,
                "status": {"reason": "Transport", "message": str(e)},
            }
        if len(out) < len(pending):
            filler = (
                transport_result
                if transport_failed
                else {"code": 500, "status": {"reason": "Transport",
                                              "message": "batch result missing"}}
            )
            out = out + [filler] * (len(pending) - len(out))
        for slot, res in zip(pending, out):
            results[slot] = res
        retryable: list[int] = []
        for slot in pending:
            res = results[slot]
            code = res.get("code") or 0
            if code >= 500:
                retryable.append(slot)
            elif (
                code == 409
                and (res.get("status") or {}).get("reason") == "Conflict"
                and current[slot].get("verb") in ("update", "update_status")
                and slot not in conflict_refreshed
            ):
                refreshed = _refreshed_conflict_op(client, current[slot])
                if refreshed is not None:
                    conflict_refreshed.add(slot)
                    current[slot] = refreshed
                    retryable.append(slot)
        if not retryable:
            break
        delay = retry_delay(attempt)
        if attempt >= retry_max() or time.monotonic() + delay >= deadline:
            break
        if breakers is not None:
            breakers.count_retry(cluster, len(retryable))
        log.debug(
            "retrying %d member-write op(s): cluster=%s attempt=%d "
            "delay_ms=%.0f", len(retryable), cluster, attempt + 1, delay * 1e3,
        )
        # The backoff wait IS the retry path's latency — a span makes it
        # visible in /debug/trace next to the member_flush it delays.
        with trace.span(
            "dispatch.retry", cluster=cluster, attempt=attempt + 1,
            ops=len(retryable),
        ):
            time.sleep(delay)
        pending = retryable
        attempt += 1
    elapsed = time.monotonic() - started
    final_transport = transport_failed or any(
        (r or {}).get("code") == 500
        and ((r or {}).get("status") or {}).get("reason") == "Transport"
        for r in results
    )
    if breaker is not None:
        if final_transport:
            breaker.record_failure(latency_s=elapsed)
        else:
            breaker.note_ok(elapsed)
    # Per-member write attribution (retries included): the histogram a
    # slow member shows up in when the engine is innocent
    # (member_write_seconds{cluster}), joined with breaker state at
    # GET /debug/members via the registry's latency reservoir.
    if cluster and not final_transport:
        slo.member_write(cluster, elapsed)
        _note_write_tenants(ops, elapsed)
        if breakers is not None:
            breakers.note_write(cluster, elapsed, ops=n)
    return [r if r is not None else {"code": 500, "status": {
        "reason": "Transport", "message": "batch never ran"}} for r in results]


def _note_chunk(breakers, cluster: str, n_ops: int, results: list[dict]) -> None:
    """Per-bulk-request telemetry: batch-size histogram + outcome
    counter (member_bulk_writes_total{cluster,result}) + the registry's
    batch reservoir feeding GET /debug/members."""
    if breakers is None or not cluster:
        return
    outcome = "ok"
    for r in results:
        code = (r or {}).get("code") or 0
        reason = ((r or {}).get("status") or {}).get("reason")
        if code >= 500 and reason == "Transport":
            outcome = "transport"
            break
        if code >= 400:
            outcome = "partial"
    breakers.note_batch(cluster, n_ops, outcome)
    metrics = getattr(breakers, "metrics", None)
    if metrics is not None:
        metrics.counter(
            "member_bulk_writes_total", cluster=cluster, result=outcome
        )
        metrics.histogram("member_batch_ops", n_ops, buckets=_BATCH_BUCKETS)


def run_member_batches(
    client,
    ops: list[dict],
    deadline: float,
    cluster: str = "",
    breakers=None,
    thread_registry: Optional[set] = None,
) -> list[dict]:
    """One member's staged writes as coalesced, pipelined bulk requests.

    Ops split into KT_MEMBER_BATCH-sized chunks (KT_WRITE_COALESCE=0:
    one op per request — the per-object A/B path) and dispatch under a
    KT_MEMBER_INFLIGHT-bounded window; each chunk rides
    :func:`run_batch_with_retries`, so per-op 409/5xx retry semantics
    are identical to the un-coalesced path.  Between chunks the deadline
    budget and the member's breaker are re-checked: a deadline expiry
    mid-flush sheds the REMAINING chunks (their ops return the shed
    marker — continuations must not run, member_shed_writes_total
    counts them), and a breaker that opened mid-flush sheds without
    touching another socket.  Always returns one result per op."""
    n = len(ops)
    if n == 0:
        return []
    size = member_batch() if write_coalesce() else 1
    chunks = [ops[i:i + size] for i in range(0, n, size)]
    breaker = breakers.for_member(cluster) if breakers is not None else None

    def blocked() -> bool:
        if time.monotonic() >= deadline:
            return True
        return breaker is not None and not breaker.allow(consume_probe=False)

    # Pool threads have no view of the flushing thread's span stack:
    # capture the open dispatch span here so each chunk's span (and the
    # traceparent header its HTTP request carries) stays parented under
    # the flush — without this, pipelined chunks start orphan traces.
    flush_span = trace.get_default().current()

    def run_chunk(chunk: list[dict]) -> list[dict]:
        # In-process stores deliver watch events synchronously on the
        # writing thread: a pipelined chunk thread must count as "own
        # write" for the controller's echo suppression, or every member
        # write re-enqueues its object for a spurious re-sync.
        ident = threading.get_ident()
        added = thread_registry is not None and ident not in thread_registry
        if added:
            thread_registry.add(ident)
        try:
            if blocked():
                return [_SHED] * len(chunk)
            with trace.get_default().span_from(
                "dispatch.member_chunk", flush_span,
                cluster=cluster, ops=len(chunk),
            ):
                res = run_batch_with_retries(
                    client, chunk, deadline, cluster=cluster,
                    breakers=breakers,
                )
            _note_chunk(breakers, cluster, len(chunk), res)
            return res
        finally:
            if added:
                thread_registry.discard(ident)

    inflight = member_inflight()
    # A plain in-process store has no round trips to pipeline: chunk
    # threads would cost GIL churn and move its synchronous watch
    # delivery off the flushing thread for nothing.
    if type(client) is FakeKube:
        inflight = 1
    if len(chunks) == 1 or inflight <= 1:
        out: list[dict] = []
        for chunk in chunks:
            out.extend(run_chunk(chunk))
        shed_n = sum(1 for r in out if r.get("shed"))
        if shed_n:
            if breakers is not None:
                breakers.count_shed(cluster, shed_n)
            _note_shed_tenants(
                op for op, r in zip(ops, out) if r.get("shed"))
        return out
    # Pipelined window: up to KT_MEMBER_INFLIGHT bulk requests in
    # flight at once (each chunk re-checks deadline/breaker at start,
    # so a mid-flush expiry degrades to shed markers, never new
    # sockets).  The pool is per-flush-per-member but bounded by the
    # caller's own concurrency (the sink's cluster fan-out pool).
    pool = ThreadPoolExecutor(
        max_workers=min(inflight, len(chunks)),
        thread_name_prefix=f"member-batch-{cluster}",
    )
    try:
        futures = [pool.submit(run_chunk, chunk) for chunk in chunks]
        out = []
        for f, chunk in zip(futures, chunks):
            try:
                out.extend(f.result())
            except Exception as e:  # defensive: run_chunk shouldn't raise
                out.extend(
                    [{"code": 500, "status": {"reason": "Transport",
                                              "message": str(e)}}] * len(chunk)
                )
    finally:
        pool.shutdown(wait=False)
    shed_n = sum(1 for r in out if r.get("shed"))
    if shed_n:
        if breakers is not None:
            breakers.count_shed(cluster, shed_n)
        _note_shed_tenants(op for op, r in zip(ops, out) if r.get("shed"))
    return out


_BULK_MISS = object()


def bulk_get(
    client,
    resource: str,
    keys: list[str],
    cluster: str = "",
    breakers=None,
) -> Optional[dict[str, Optional[dict]]]:
    """Batched point reads: ``get`` verbs through the bulk protocol,
    KT_MEMBER_BATCH keys per request.  Returns {key: obj | None-for-404}
    — a key absent from the result means the read failed non-fatally and
    the caller should fall back to a direct read.  Returns None outright
    on a transport-level failure (the whole endpoint is unreachable;
    breaker evidence recorded)."""
    out: dict[str, Optional[dict]] = {}
    size = member_batch()
    breaker = breakers.for_member(cluster) if breakers is not None else None
    for i in range(0, len(keys), size):
        chunk = keys[i:i + size]
        start = time.monotonic()
        try:
            results = client.batch(
                [{"verb": "get", "resource": resource, "key": k} for k in chunk]
            )
        except Exception:
            if breaker is not None:
                breaker.record_failure(latency_s=time.monotonic() - start)
            return None
        if breaker is not None:
            breaker.note_ok(time.monotonic() - start)
        for k, res in zip(chunk, results):
            code = (res or {}).get("code")
            if code == 200:
                out[k] = res.get("object")
            elif code == 404:
                out[k] = None
            # anything else: leave the key out — direct-read fallback
    return out


# -- sinks ---------------------------------------------------------------
# Live sinks, for graceful shutdown: SIGTERM drains in-flight flushes
# under a bounded deadline and then finalizes every sink that still
# holds staged or in-flight writes — queued writes are SHED (recorded
# via member_shed_writes_total; the apiserver-durable state re-drives
# them on the next boot), never silently dropped, and no
# dispatch-flush-<cluster> helper thread survives the drain.
_LIVE_SINKS: "weakref.WeakSet" = weakref.WeakSet()


def finalize_all_sinks(deadline_s: float = 0.0) -> int:
    """Finalize every live sink (manager shutdown path); returns the
    number of writes shed."""
    shed = 0
    end = time.monotonic() + max(0.0, deadline_s)
    for sink in list(_LIVE_SINKS):
        try:
            shed += sink.finalize(max(0.0, end - time.monotonic()))
        except Exception:
            log.warning("sink finalize failed", exc_info=True)
    return shed


class ImmediateSink:
    """One client call per operation, inline or on a bounded pool
    (operation.go:102-123's per-cluster goroutine fan-out; pool size =
    the in-flight window, KT_DISPATCH_POOL).

    Under KT_WRITE_COALESCE (pooled mode only — the inline in-process
    path has no round trips to amortize), submits stage into a
    per-member buffer instead of dispatching one call per op; ``wait()``
    flushes each member's buffer through the pipelined bulk window
    (:func:`run_member_batches`), one pooled task per member."""

    def __init__(
        self,
        client_for_cluster: Callable[[str], FakeKube],
        pool: Optional[ThreadPoolExecutor] = None,
        inline: bool = False,
        breakers=None,
    ):
        self.client_for_cluster = client_for_cluster
        self._pool = pool
        self._own_pool = False
        self._inline = inline
        # (cluster, future, ops): ops is the shed weight a cancel counts.
        self._futures: list[tuple[str, Future, int]] = []
        self._finalized = False
        self.breakers = breakers
        self._coalesce = write_coalesce() and not inline
        self._staged: dict[str, list[tuple[dict, Callable[[dict], None]]]] = {}
        _LIVE_SINKS.add(self)

    def _flush_member(self, cluster: str, entries: list, deadline: float) -> None:
        """One member's coalesced buffer -> pipelined bulk batches."""
        with trace.span(
            "dispatch.member_write", cluster=cluster, ops=len(entries)
        ):
            if self.breakers is not None and not self.breakers.allow(
                cluster, consume_probe=False
            ):
                self.breakers.count_shed(cluster, len(entries))
                _note_shed_tenants(entries)
                return
            try:
                client = self.client_for_cluster(cluster)
            except Exception as e:
                results = [
                    {"code": 500, "status": {"reason": "Transport", "message": str(e)}}
                ] * len(entries)
            else:
                results = run_member_batches(
                    client,
                    [op for op, _ in entries],
                    deadline,
                    cluster=cluster,
                    breakers=self.breakers,
                )
            for (_, continuation), result in zip(entries, results):
                if result.get("shed"):
                    continue  # pre-recorded *_TIMED_OUT status stands
                try:
                    continuation(result)
                except Exception:
                    pass  # continuations record their own failures

    def submit(self, cluster: str, op: dict, continuation: Callable[[dict], None]) -> None:
        if self._finalized:
            # A stale continuation must never write into an already-
            # finalized status/version map; the sink is single-round.
            raise RuntimeError(
                "ImmediateSink already finalized by wait(); build a fresh sink"
            )
        if self._coalesce:
            self._staged.setdefault(cluster, []).append((op, continuation))
            return

        def run() -> None:
            with trace.span("dispatch.member_write", cluster=cluster):
                start = time.monotonic()
                try:
                    client = self.client_for_cluster(cluster)
                    result = client.batch([op])[0]
                except Exception as e:  # transport-level failure
                    result = {"code": 500, "status": {"reason": "Transport", "message": str(e)}}
                    if self.breakers is not None:
                        self.breakers.for_member(cluster).record_failure(
                            latency_s=time.monotonic() - start
                        )
                else:
                    elapsed = time.monotonic() - start
                    if self.breakers is not None:
                        self.breakers.for_member(cluster).note_ok(elapsed)
                        self.breakers.note_write(cluster, elapsed, ops=1)
                    slo.member_write(cluster, elapsed)
                    _note_write_tenants((op,), elapsed)
                continuation(result)

        if self._inline:
            try:
                run()
            except Exception:
                pass  # continuations record their own failures
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=dispatch_pool_size())
            self._own_pool = True
        self._futures.append((cluster, self._pool.submit(run), 1))

    def _flush_staged(self, deadline: float) -> None:
        """Coalesced mode: hand each member's buffered ops to one pooled
        flush task (the per-member pipelined bulk window runs inside)."""
        staged, self._staged = self._staged, {}
        if not staged:
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=dispatch_pool_size())
            self._own_pool = True
        for cluster, entries in staged.items():
            self._futures.append(
                (
                    cluster,
                    self._pool.submit(
                        self._flush_member, cluster, entries, deadline
                    ),
                    len(entries),
                )
            )

    def wait(self, timeout: float) -> None:
        """Drain the fan-out under the deadline.  On expiry, not-yet-
        started futures are CANCELLED (their pre-recorded *_TIMED_OUT
        statuses stand) and the sink becomes unusable — a late submit
        raises instead of mutating a finalized status map."""
        deadline = time.monotonic() + timeout
        self._flush_staged(deadline)
        try:
            for cluster, f, n_ops in self._futures:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if f.cancel() and self.breakers is not None:
                        self.breakers.count_shed(cluster, n_ops)
                    continue
                try:
                    f.result(timeout=remaining)
                except FuturesTimeout:
                    if f.cancel() and self.breakers is not None:
                        self.breakers.count_shed(cluster, n_ops)
                except Exception:  # failure statuses were pre-recorded
                    pass
        finally:
            self._futures.clear()
            self._finalized = True
            if self._own_pool and self._pool is not None:
                self._pool.shutdown(wait=False, cancel_futures=True)
                self._pool = None
                self._own_pool = False

    def finalize(self, deadline_s: float = 0.0) -> int:
        """Graceful-shutdown drain: give in-flight writes ``deadline_s``
        to land, CANCEL (and count as shed) whatever has not started,
        and finalize the sink — a late submit raises.  Returns the shed
        count."""
        if self._finalized:
            return 0
        shed = 0
        # Coalesced ops still buffered never dispatched: all shed.
        staged, self._staged = self._staged, {}
        for cluster, entries in staged.items():
            shed += len(entries)
            if self.breakers is not None:
                self.breakers.count_shed(cluster, len(entries))
            _note_shed_tenants(entries)
        end = time.monotonic() + max(0.0, deadline_s)
        pending = list(self._futures)
        for cluster, f, n_ops in pending:
            if f.cancel():
                shed += n_ops
                if self.breakers is not None:
                    self.breakers.count_shed(cluster, n_ops)
                continue
            try:
                f.result(timeout=max(0.0, end - time.monotonic()))
            except FuturesTimeout:
                shed += n_ops  # running past the drain budget: abandoned
                if self.breakers is not None:
                    self.breakers.count_shed(cluster, n_ops)
            except Exception:
                pass
        self._futures.clear()
        self._finalized = True
        if self._own_pool and self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
            self._own_pool = False
        if shed:
            log.warning("ImmediateSink finalize shed %d write(s)", shed)
        return shed


class BatchSink:
    """Stage operations across MANY federated objects, flush ONE
    ``client.batch()`` per member cluster.  Shared by every dispatcher of
    a sync BatchWorker tick; the controller flushes before it finalizes
    any object's status."""

    def __init__(
        self,
        client_for_cluster: Callable[[str], FakeKube],
        pool: Optional[ThreadPoolExecutor] = None,
        thread_registry: Optional[set] = None,
        breakers=None,
        deadline: Optional[float] = None,
    ):
        self.client_for_cluster = client_for_cluster
        self._pool = pool
        self._staged: dict[str, list[tuple[dict, Callable[[dict], None]]]] = {}
        self.flushed = True
        self.breakers = breakers
        self.deadline = dispatch_deadline() if deadline is None else deadline
        # dispatch-flush-<cluster> helper threads this sink spawned for
        # stall-capable serial flushes; joined by finalize() so a
        # graceful shutdown leaves none behind (a genuinely stalled one
        # is daemon and its writes were already shed + accounted).
        self._helper_threads: list[threading.Thread] = []
        self._finalized = False
        _LIVE_SINKS.add(self)
        # Threads currently executing this sink's writes.  In-process
        # member stores deliver watch events synchronously on the writing
        # thread, so the owning controller treats events on these threads
        # as echoes of its own writes (the pool-flush analogue of the
        # tick-thread check).
        self.thread_registry = thread_registry if thread_registry is not None else set()

    def submit(self, cluster: str, op: dict, continuation: Callable[[dict], None]) -> None:
        if self._finalized:
            # The shutdown drain already shed this sink's queue; a late
            # stage would be silently lost — fail loudly instead.
            raise RuntimeError(
                "BatchSink already finalized by shutdown; build a fresh sink"
            )
        self._staged.setdefault(cluster, []).append((op, continuation))
        self.flushed = False

    def _client_can_stall(self, cluster: str) -> bool:
        """Whether this cluster's client can park a thread (sockets, or
        a fault-injecting proxy).  A plain in-process FakeKube cannot,
        and the serial path keeps calling it directly — no thread spawn
        on the local hot path."""
        try:
            client = self.client_for_cluster(cluster)
        except Exception:
            return False  # resolution failures are fast
        return type(client) is not FakeKube

    def flush(self, timeout: Optional[float] = None) -> None:
        """One batch round trip per member, in parallel across members
        when a pool is present.  Continuations run on the flushing
        thread(s); per-op failures stay in the results.

        The deadline budget (``timeout``, default KT_DISPATCH_DEADLINE_S)
        is enforced on EVERY path: pooled flushes time out per future,
        and the serial path runs stall-capable clients on a bounded
        helper thread — a hung member sheds its writes (statuses stay at
        their pre-recorded *_TIMED_OUT values and the owning worker's
        backoff requeue re-drives them) instead of parking the tick."""
        if timeout is None:
            timeout = self.deadline
        staged, self._staged = self._staged, {}
        self.flushed = True
        if not staged:
            return
        deadline = time.monotonic() + timeout

        def flush_cluster(cluster: str, entries: list) -> None:
            # Register only our own ident and remove only what we added:
            # with BatchWorker(workers>1) two concurrent ticks flush their
            # own sinks into a SHARED registry, so a blanket clear() here
            # would wipe the other tick's in-flight registrations and its
            # member-write echoes would re-enqueue keys.
            ident = threading.get_ident()
            added = ident not in self.thread_registry
            if added:
                self.thread_registry.add(ident)
            try:
                with trace.span(
                    "dispatch.member_flush", cluster=cluster, ops=len(entries)
                ):
                    # A breaker that opened between staging and flush
                    # (a sibling batch's transport failures) sheds the
                    # WHOLE staged batch without touching a socket.
                    if self.breakers is not None and not self.breakers.allow(
                        cluster, consume_probe=False
                    ):
                        self.breakers.count_shed(cluster, len(entries))
                        _note_shed_tenants(entries)
                        return
                    try:
                        client = self.client_for_cluster(cluster)
                    except Exception as e:
                        results = [
                            {"code": 500, "status": {"reason": "Transport", "message": str(e)}}
                        ] * len(entries)
                    else:
                        results = run_member_batches(
                            client,
                            [op for op, _ in entries],
                            deadline,
                            cluster=cluster,
                            breakers=self.breakers,
                            thread_registry=self.thread_registry,
                        )
                    for (_, continuation), result in zip(entries, results):
                        if result.get("shed"):
                            # Shed before dispatch: the pre-recorded
                            # *_TIMED_OUT status stands.
                            continue
                        try:
                            continuation(result)
                        except Exception:
                            pass  # continuations record their own failures
            finally:
                if added:
                    self.thread_registry.discard(ident)

        def shed(cluster: str, entries: list, stalled: bool) -> None:
            """Deadline expired for this member's flush.  Statuses stay
            at their pre-recorded *_TIMED_OUT values; a genuinely
            stalled flush (vs one merely queued behind a sick sibling)
            also opens the member's breaker."""
            log.warning(
                "shedding %d member write(s): cluster=%s stalled=%s "
                "(deadline %.1fs expired; statuses stay *_TIMED_OUT, the "
                "owning worker's backoff requeue re-drives them)",
                len(entries), cluster, stalled, timeout,
            )
            with trace.span(
                "dispatch.shed", cluster=cluster, ops=len(entries),
                stalled=stalled,
            ):
                _note_shed_tenants(entries)
                if self.breakers is None:
                    return
                self.breakers.count_shed(cluster, len(entries))
                if stalled:
                    self.breakers.for_member(cluster).record_failure(
                        timeout=True
                    )

        if self._pool is not None:
            futures = {
                self._pool.submit(flush_cluster, cluster, entries): (cluster, entries)
                for cluster, entries in staged.items()
            }
            for f, (cluster, entries) in futures.items():
                try:
                    f.result(timeout=max(0.0, deadline - time.monotonic()))
                except FuturesTimeout:
                    # cancel() succeeds only when the flush never started
                    # (queued behind siblings): shed without blaming the
                    # member.  A running one IS stalled in its client.
                    shed(cluster, entries, stalled=not f.cancel())
                except Exception:
                    pass
        else:
            for cluster, entries in staged.items():
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    shed(cluster, entries, stalled=False)
                    continue
                if not self._client_can_stall(cluster):
                    flush_cluster(cluster, entries)
                    continue
                t = threading.Thread(
                    target=flush_cluster,
                    args=(cluster, entries),
                    name=f"dispatch-flush-{cluster}",
                    daemon=True,
                )
                self._helper_threads.append(t)
                t.start()
                t.join(remaining)
                if t.is_alive():
                    # Left to die on the client's own timeout; the tick
                    # moves on.
                    shed(cluster, entries, stalled=True)
        self._helper_threads = [t for t in self._helper_threads if t.is_alive()]

    def wait(self, timeout: float) -> None:
        # Dispatchers sharing this sink call wait() after the controller
        # has flushed the tick; anything still staged (a mid-reconcile
        # wait, e.g. the deletion path) flushes now.
        if not self.flushed:
            self.flush(timeout)

    def finalize(self, deadline_s: float = 0.0) -> int:
        """Graceful-shutdown drain (SIGTERM path): writes still STAGED
        are shed — recorded via the existing member_shed_writes_total
        counter, with their pre-recorded *_TIMED_OUT statuses standing,
        exactly like a deadline expiry — and the dispatch-flush helper
        threads are joined under the remaining budget so none survives
        the drain (a thread that outlives it belongs to a stalled
        member whose writes were already shed + breaker-opened).
        Returns the shed count; a later submit raises."""
        if self._finalized:
            return 0
        self._finalized = True
        staged, self._staged = self._staged, {}
        self.flushed = True
        shed = 0
        for cluster, entries in staged.items():
            shed += len(entries)
            log.warning(
                "shutdown: shedding %d staged member write(s): cluster=%s",
                len(entries), cluster,
            )
            if self.breakers is not None:
                self.breakers.count_shed(cluster, len(entries))
            _note_shed_tenants(entries)
        end = time.monotonic() + max(0.0, deadline_s)
        for t in self._helper_threads:
            t.join(max(0.0, end - time.monotonic()))
        self._helper_threads = [t for t in self._helper_threads if t.is_alive()]
        return shed


def _result_error(result: dict) -> str:
    status = result.get("status") or {}
    return status.get("message") or status.get("reason") or f"code {result.get('code')}"


def _set_last_replicaset_name(obj: dict, cluster_obj: dict) -> None:
    """When a new template revision is being dispatched, remember which
    ReplicaSet was newest BEFORE it, so stale latest-replicaset
    annotations are recognizable (retain.go setLastReplicasetName)."""
    if cluster_obj is None:
        return
    ann = obj.get("metadata", {}).get("annotations", {})
    revision = ann.get(CURRENT_REVISION_ANNOTATION)
    if revision is None:
        return
    cluster_ann = cluster_obj.get("metadata", {}).get("annotations", {})
    last_dispatched = cluster_ann.get(CURRENT_REVISION_ANNOTATION)
    if last_dispatched is not None and revision != last_dispatched:
        rs_name = cluster_ann.get(LATEST_RS_NAME)
        if rs_name is not None:
            obj.setdefault("metadata", {}).setdefault("annotations", {})[
                LAST_RS_NAME
            ] = rs_name


def _retain_template(
    obj: dict, cluster_obj: dict, replicas_path: str, keep_rollout_settings: bool
) -> None:
    """Keep the member's current pod template (and optionally its rollout
    knobs) in the desired object: "not your turn yet"
    (retain.go retainTemplate)."""
    tpl = get_path(cluster_obj, "spec.template")
    if tpl is not None:
        set_path(obj, "spec.template", tpl)
    else:
        delete_path(obj, "spec.template")
    ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
    cluster_revision = cluster_obj.get("metadata", {}).get("annotations", {}).get(
        CURRENT_REVISION_ANNOTATION
    )
    if cluster_revision is not None:
        ann[CURRENT_REVISION_ANNOTATION] = cluster_revision
    else:
        ann.pop(CURRENT_REVISION_ANNOTATION, None)
    if keep_rollout_settings:
        if replicas_path:
            replicas = get_path(cluster_obj, replicas_path)
            if replicas is not None:
                set_path(obj, replicas_path, replicas)
            else:
                delete_path(obj, replicas_path)
        for path in (MAX_SURGE_PATH, MAX_UNAVAILABLE_PATH):
            dotted = path[1:].replace("/", ".")
            value = get_path(cluster_obj, dotted)
            if value is not None:
                set_path(obj, dotted, value)
            else:
                delete_path(obj, dotted)


class ManagedDispatcher:
    """One sync round's write fan-out (managed.go:77-126).

    ``client_for_cluster`` returns the member apiserver handle; failures
    of individual operations are recorded per cluster, never raised.
    ``sink`` routes the writes (shared BatchSink across a tick, or a
    private ImmediateSink mirroring the reference's goroutines)."""

    def __init__(
        self,
        client_for_cluster: Callable[[str], FakeKube],
        fed_resource: FederatedResource,
        resource: str,
        replicas_path: str = "",
        skip_adopting: bool = True,
        pool: Optional[ThreadPoolExecutor] = None,
        timeout: float = 30.0,
        rollout_overrides: Optional[Callable[[str], list]] = None,
        inline: bool = False,
        sink=None,
        on_written: Optional[Callable[[str, dict], None]] = None,
        breakers=None,
    ):
        self.client_for_cluster = client_for_cluster
        self.fed = fed_resource
        self.resource = resource
        self.replicas_path = replicas_path
        self.skip_adopting = skip_adopting
        self.timeout = timeout
        self.rollout_overrides = rollout_overrides
        self.breakers = breakers if breakers is not None else getattr(
            sink, "breakers", None
        )
        self._sink = sink or ImmediateSink(
            client_for_cluster, pool=pool, inline=inline, breakers=self.breakers
        )
        self._on_written = on_written
        self._lock = threading.Lock()
        self._status: dict[str, str] = {}
        self._versions: dict[str, str] = {}
        self._errors: dict[str, str] = {}
        self._resources_updated = False
        # Desired-object assembly dedup: clusters sharing an override
        # patch list share ONE assembled object (consumers that mutate —
        # the retention paths — copy first; create paths hand the shared
        # object to clients, which serialize/copy on write).
        self._desired_cache: dict[object, dict] = {}
        # id(patches) -> serialized cache key: the patch lists live in
        # fed._ordered_overrides()'s cached dict (pinned by self.fed for
        # this dispatcher's lifetime, so ids cannot be recycled), and
        # re-serializing the same list per member cluster was a
        # measurable share of the sync hot path.
        self._patch_keys: dict[int, str] = {}

    # -- bookkeeping -----------------------------------------------------
    def _submit(self, cluster: str, op: dict, continuation: Callable[[dict], None]) -> None:
        """Stage one member write, short-circuiting through the member's
        circuit breaker: an OPEN member costs a status record, never a
        thread parked on a dead socket (the ClusterNotReady propagation
        the reference assigns unreachable members).  In HALF_OPEN the
        first write through is the probe; the rest shed until it lands."""
        if self.breakers is not None:
            breaker = self.breakers.for_member(cluster)
            if not breaker.allow():
                self.breakers.count_shed(cluster)
                _note_shed_tenants((op,))
                self.record_error(
                    cluster, CLUSTER_NOT_READY, "member circuit breaker open"
                )
                return
        self._sink.submit(cluster, op, continuation)

    def record_status(self, cluster: str, status: str) -> None:
        with self._lock:
            self._status[cluster] = status

    def record_error(self, cluster: str, status: str, err: str) -> None:
        with self._lock:
            self._status[cluster] = status
            self._errors[cluster] = err

    def _record_version(self, cluster: str, version: str) -> None:
        with self._lock:
            self._versions[cluster] = version
            self._status[cluster] = OK

    def _record_written(self, cluster: str, obj: dict) -> None:
        """A real write landed: record version AND surface the written
        object (its raw resourceVersion feeds the controller's watch-echo
        suppression).  Version-based skips must NOT come through here —
        they produce no watch event to suppress."""
        self._record_version(cluster, object_version(obj))
        if self._on_written is not None:
            self._on_written(cluster, obj)

    def wait(self) -> bool:
        """Block until every operation finishes or the shared deadline
        passes (managed.go:126-159); returns False when any cluster ended
        in a non-OK, non-waiting state."""
        self._sink.wait(self.timeout)
        with self._lock:
            return all(
                s in (OK, WAITING_FOR_REMOVAL, WAITING)
                for s in self._status.values()
            )

    @property
    def version_map(self) -> dict[str, str]:
        with self._lock:
            return dict(self._versions)

    @property
    def status_map(self) -> dict[str, str]:
        with self._lock:
            return dict(self._status)

    @property
    def resources_updated(self) -> bool:
        return self._resources_updated

    # -- desired-object assembly ----------------------------------------
    def _desired(self, cluster: str, mutable: bool = False) -> dict:
        """Assembled desired object for a cluster.  Clusters whose
        override patch lists are identical (the common case — overrides
        come from shared policies) get ONE shared assembly; pass
        ``mutable=True`` to receive a private copy (retention paths
        mutate the object in place)."""
        extra = self.rollout_overrides(cluster) if self.rollout_overrides else None
        patches = self.fed._ordered_overrides().get(cluster) or ()
        if not patches and not extra:
            key = ""  # the common no-override case skips key serialization
        elif extra is None:
            key = self._patch_keys.get(id(patches))
            if key is None:
                key = json.dumps([patches, None], sort_keys=True, default=str)
                with self._lock:
                    self._patch_keys[id(patches)] = key
        else:
            key = json.dumps([patches, extra], sort_keys=True, default=str)
        with self._lock:
            obj = self._desired_cache.get(key)
        if obj is None:
            obj = self.fed.object_for_cluster(cluster)
            obj = self.fed.apply_overrides(obj, cluster, extra)
            retain.record_propagated_keys(obj)
            with self._lock:
                self._desired_cache[key] = obj
        if mutable:
            return copy_json(obj)
        return obj

    # -- operations ------------------------------------------------------
    def create(self, cluster: str) -> None:
        """Create, falling back to adoption-aware update on AlreadyExists
        (managed.go:325-400)."""
        self.record_status(cluster, CREATION_TIMED_OUT)
        try:
            obj = self._desired(cluster)
        except Exception as e:
            return self.record_error(cluster, COMPUTE_RESOURCE_FAILED, str(e))

        def done(result: dict) -> None:
            code = result.get("code")
            if code == 201:
                self._resources_updated = True
                self._record_written(cluster, result["object"])
                return
            if not (
                code == 409
                and (result.get("status") or {}).get("reason") == "AlreadyExists"
            ):
                return self.record_error(cluster, CREATION_FAILED, _result_error(result))
            # AlreadyExists: the adoption-aware fallback (rare path, runs
            # direct client calls on the flushing thread).
            client = self.client_for_cluster(cluster)
            try:
                existing = client.get(self.resource, self.fed.key)
            except NotFound as e:
                return self.record_error(cluster, CREATION_FAILED, str(e))
            if self.skip_adopting:
                return self.record_error(
                    cluster, ALREADY_EXISTS, "resource pre-exists in cluster"
                )
            if not has_managed_label(existing):
                existing.setdefault("metadata", {}).setdefault("annotations", {})[
                    ADOPTED_ANNOTATION
                ] = "true"
            self._update_now(cluster, existing, adopting=True)

        self._submit(
            cluster, {"verb": "create", "resource": self.resource, "object": obj}, done
        )

    def update(self, cluster: str, cluster_obj: dict, recorded_version: str = "") -> None:
        self.record_status(cluster, UPDATE_TIMED_OUT)
        self._stage_update(cluster, cluster_obj, recorded_version=recorded_version)

    def _prepare_update(
        self,
        cluster: str,
        cluster_obj: dict,
        recorded_version: str = "",
        adopting: bool = False,
    ) -> Optional[dict]:
        """(managed.go:402-476): retention + version-based skip.  Returns
        the object to write, or None when bookkeeping already settled the
        cluster (skip or failure, recorded)."""
        if is_explicitly_unmanaged(cluster_obj):
            self.record_error(
                cluster,
                MANAGED_LABEL_FALSE,
                f"object has label {C.MANAGED_LABEL}=false",
            )
            return None
        try:
            obj = self._desired(cluster, mutable=True)
        except Exception as e:
            self.record_error(cluster, COMPUTE_RESOURCE_FAILED, str(e))
            return None
        if adopting:
            ann = cluster_obj.get("metadata", {}).get("annotations", {})
            if ann.get(ADOPTED_ANNOTATION):
                obj.setdefault("metadata", {}).setdefault("annotations", {})[
                    ADOPTED_ANNOTATION
                ] = "true"
        try:
            retain.retain_cluster_fields(self.fed.target_kind, obj, cluster_obj)
            retain.retain_replicas(obj, cluster_obj, self.fed.obj, self.replicas_path)
            if self.fed.target_kind == "Deployment":
                _set_last_replicaset_name(obj, cluster_obj)
        except Exception as e:
            self.record_error(cluster, FIELD_RETENTION_FAILED, str(e))
            return None

        if recorded_version and not object_needs_update(
            obj, cluster_obj, recorded_version, self.replicas_path
        ):
            # Current: still record the version so status reflects it.
            self._record_version(cluster, recorded_version)
            return None
        return obj

    def _update_done(self, cluster: str) -> Callable[[dict], None]:
        def done(result: dict) -> None:
            if result.get("code") == 200:
                self._resources_updated = True
                self._record_written(cluster, result["object"])
            else:
                self.record_error(cluster, UPDATE_FAILED, _result_error(result))

        return done

    def _stage_update(
        self,
        cluster: str,
        cluster_obj: dict,
        recorded_version: str = "",
        adopting: bool = False,
    ) -> None:
        obj = self._prepare_update(cluster, cluster_obj, recorded_version, adopting)
        if obj is None:
            return
        self._submit(
            cluster,
            {"verb": "update", "resource": self.resource, "object": obj},
            self._update_done(cluster),
        )

    def _update_now(self, cluster: str, cluster_obj: dict, adopting: bool = False) -> None:
        """Direct (non-staged) update, used by the create fallback which
        already runs on a flushing thread."""
        obj = self._prepare_update(cluster, cluster_obj, adopting=adopting)
        if obj is None:
            return
        client = self.client_for_cluster(cluster)
        try:
            updated = client.update(self.resource, obj)
        except Exception as e:
            return self.record_error(cluster, UPDATE_FAILED, str(e))
        self._resources_updated = True
        self._record_written(cluster, updated)

    def patch_and_keep_template(
        self,
        cluster: str,
        cluster_obj: dict,
        keep_rollout_settings: bool,
        recorded_version: str = "",
    ) -> None:
        """Dispatch everything EXCEPT the pod template: an unplanned
        cluster waits its rollout turn with its current template (and,
        with ``keep_rollout_settings``, its current replicas/fenceposts)
        (managed.go:483-560 PatchAndKeepTemplate)."""
        self.record_status(cluster, UPDATE_TIMED_OUT)
        if is_explicitly_unmanaged(cluster_obj):
            return self.record_error(
                cluster,
                MANAGED_LABEL_FALSE,
                f"object has label {C.MANAGED_LABEL}=false",
            )
        try:
            obj = self._desired(cluster, mutable=True)
        except Exception as e:
            return self.record_error(cluster, COMPUTE_RESOURCE_FAILED, str(e))
        try:
            retain.retain_cluster_fields(self.fed.target_kind, obj, cluster_obj)
            retain.retain_replicas(
                obj, cluster_obj, self.fed.obj, self.replicas_path
            )
            # No _set_last_replicaset_name here: _retain_template just
            # forced the revision annotations equal, so the real
            # update() path is where the last-RS marker gets written.
            _retain_template(
                obj, cluster_obj, self.replicas_path, keep_rollout_settings
            )
        except Exception as e:
            return self.record_error(cluster, FIELD_RETENTION_FAILED, str(e))

        if recorded_version and not object_needs_update(
            obj, cluster_obj, recorded_version, self.replicas_path
        ):
            self._record_version(cluster, recorded_version)
            return
        self._submit(
            cluster,
            {"verb": "update", "resource": self.resource, "object": obj},
            self._update_done(cluster),
        )

    def delete(self, cluster: str) -> None:
        """Delete from a member cluster (unmanaged.go Delete): the object
        stays WAITING_FOR_REMOVAL until the member confirms it gone."""
        self.record_status(cluster, DELETION_TIMED_OUT)

        def done(result: dict) -> None:
            code = result.get("code")
            if code == 404:
                with self._lock:
                    self._status.pop(cluster, None)
                return
            if code != 200:
                return self.record_error(cluster, DELETION_FAILED, _result_error(result))
            self._resources_updated = True
            client = self.client_for_cluster(cluster)
            if client.try_get(self.resource, self.fed.key) is None:
                with self._lock:
                    self._status.pop(cluster, None)
            else:
                self.record_status(cluster, WAITING_FOR_REMOVAL)

        self._submit(
            cluster,
            {"verb": "delete", "resource": self.resource, "key": self.fed.key},
            done,
        )

    def remove_managed_label(self, cluster: str, cluster_obj: dict) -> None:
        """Orphaning: strip the managed label + adopted annotation instead
        of deleting (unmanaged.go RemoveManagedLabel)."""
        self.record_status(cluster, UPDATE_TIMED_OUT)
        # Deep copy: cluster_obj may be a no-copy store VIEW, and a
        # shallow dict() would mutate the store's nested metadata.
        obj = copy_json(cluster_obj)
        labels = obj.get("metadata", {}).get("labels", {})
        labels.pop(C.MANAGED_LABEL, None)
        obj.get("metadata", {}).get("annotations", {}).pop(ADOPTED_ANNOTATION, None)

        def done(result: dict) -> None:
            if result.get("code") == 200:
                with self._lock:
                    self._status.pop(cluster, None)
            else:
                self.record_error(cluster, UPDATE_FAILED, _result_error(result))

        self._submit(
            cluster, {"verb": "update", "resource": self.resource, "object": obj}, done
        )
