"""Per-cluster write dispatch for the sync controller.

The reference fans member-cluster writes out to per-cluster goroutines
with a shared timeout and collects a per-cluster propagation status +
version map (reference: pkg/controllers/sync/dispatch/operation.go:102-123,
managed.go:108-655, unmanaged.go).  Here the write fan-out is routed
through a *sink*:

* :class:`ImmediateSink` — the goroutine analogue: each operation runs
  inline (local in-process members) or on a bounded pool (network
  members), one client round trip per operation.
* :class:`BatchSink` — the tick-native variant: a whole BatchWorker tick
  of sync reconciles stages its member writes here, and ``flush()``
  issues ONE ``client.batch()`` round trip per member cluster covering
  every staged object (transport/apiserver.py _serve_batch).  Per-op
  conflict/failure results flow back through the same continuations, so
  status/version bookkeeping is identical to the immediate path.

Statuses mirror fedtypesv1a1.PropagationStatus values.
"""

from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation import retain
from kubeadmiral_tpu.runtime import trace
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.federation.rollout import (
    LAST_RS_NAME,
    LATEST_RS_NAME,
    MAX_SURGE_PATH,
    MAX_UNAVAILABLE_PATH,
)
from kubeadmiral_tpu.utils.unstructured import copy_json, delete_path, get_path, set_path
from kubeadmiral_tpu.federation.resource import (
    FederatedResource,
    has_managed_label,
    is_explicitly_unmanaged,
    object_needs_update,
    object_version,
)
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
)

# PropagationStatus values (reference: pkg/apis/types/v1alpha1/types_status.go).
OK = "OK"
WAITING = "Waiting"
CLUSTER_NOT_READY = "ClusterNotReady"
CLUSTER_TERMINATING = "ClusterTerminating"
CACHED_RETRIEVAL_FAILED = "CachedRetrievalFailed"
COMPUTE_RESOURCE_FAILED = "ComputeResourceFailed"
APPLY_OVERRIDES_FAILED = "ApplyOverridesFailed"
FIELD_RETENTION_FAILED = "FieldRetentionFailed"
CREATION_FAILED = "CreationFailed"
UPDATE_FAILED = "UpdateFailed"
DELETION_FAILED = "DeletionFailed"
ALREADY_EXISTS = "AlreadyExists"
WAITING_FOR_REMOVAL = "WaitingForRemoval"
DELETION_TIMED_OUT = "DeletionTimedOut"
CREATION_TIMED_OUT = "CreationTimedOut"
UPDATE_TIMED_OUT = "UpdateTimedOut"
MANAGED_LABEL_FALSE = "ManagedLabelFalse"
FINALIZER_CHECK_FAILED = "FinalizerCheckFailed"

ADOPTED_ANNOTATION = C.PREFIX + "adopted"


# -- sinks ---------------------------------------------------------------
class ImmediateSink:
    """One client call per operation, inline or on a pool
    (operation.go:102-123's per-cluster goroutine fan-out)."""

    def __init__(
        self,
        client_for_cluster: Callable[[str], FakeKube],
        pool: Optional[ThreadPoolExecutor] = None,
        inline: bool = False,
    ):
        self.client_for_cluster = client_for_cluster
        self._pool = pool
        self._own_pool = False
        self._inline = inline
        self._futures: list[Future] = []

    def submit(self, cluster: str, op: dict, continuation: Callable[[dict], None]) -> None:
        def run() -> None:
            with trace.span("dispatch.member_write", cluster=cluster):
                client = self.client_for_cluster(cluster)
                try:
                    result = client.batch([op])[0]
                except Exception as e:  # transport-level failure
                    result = {"code": 500, "status": {"reason": "Transport", "message": str(e)}}
                continuation(result)

        if self._inline:
            try:
                run()
            except Exception:
                pass  # continuations record their own failures
            return
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=8)
            self._own_pool = True
        self._futures.append(self._pool.submit(run))

    def wait(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        for f in self._futures:
            try:
                f.result(timeout=max(0.0, deadline - time.monotonic()))
            except Exception:  # timeout statuses were pre-recorded
                pass
        self._futures.clear()
        if self._own_pool and self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
            self._own_pool = False


class BatchSink:
    """Stage operations across MANY federated objects, flush ONE
    ``client.batch()`` per member cluster.  Shared by every dispatcher of
    a sync BatchWorker tick; the controller flushes before it finalizes
    any object's status."""

    def __init__(
        self,
        client_for_cluster: Callable[[str], FakeKube],
        pool: Optional[ThreadPoolExecutor] = None,
        thread_registry: Optional[set] = None,
    ):
        self.client_for_cluster = client_for_cluster
        self._pool = pool
        self._staged: dict[str, list[tuple[dict, Callable[[dict], None]]]] = {}
        self.flushed = True
        # Threads currently executing this sink's writes.  In-process
        # member stores deliver watch events synchronously on the writing
        # thread, so the owning controller treats events on these threads
        # as echoes of its own writes (the pool-flush analogue of the
        # tick-thread check).
        self.thread_registry = thread_registry if thread_registry is not None else set()

    def submit(self, cluster: str, op: dict, continuation: Callable[[dict], None]) -> None:
        self._staged.setdefault(cluster, []).append((op, continuation))
        self.flushed = False

    def flush(self, timeout: float = 30.0) -> None:
        """One batch round trip per member, in parallel across members
        when a pool is present.  Continuations run on the flushing
        thread(s); per-op failures stay in the results."""
        staged, self._staged = self._staged, {}
        self.flushed = True
        if not staged:
            return

        def flush_cluster(cluster: str, entries: list) -> None:
            # Register only our own ident and remove only what we added:
            # with BatchWorker(workers>1) two concurrent ticks flush their
            # own sinks into a SHARED registry, so a blanket clear() here
            # would wipe the other tick's in-flight registrations and its
            # member-write echoes would re-enqueue keys.
            ident = threading.get_ident()
            added = ident not in self.thread_registry
            if added:
                self.thread_registry.add(ident)
            try:
                with trace.span(
                    "dispatch.member_flush", cluster=cluster, ops=len(entries)
                ):
                    try:
                        client = self.client_for_cluster(cluster)
                        results = client.batch([op for op, _ in entries])
                    except Exception as e:
                        results = [
                            {"code": 500, "status": {"reason": "Transport", "message": str(e)}}
                        ] * len(entries)
                    if len(results) < len(entries):
                        # A short results array must not strand the tail at its
                        # pre-recorded *_TIMED_OUT status with no cause.
                        results = list(results) + [
                            {"code": 500, "status": {"reason": "Transport",
                                                     "message": "batch result missing"}}
                        ] * (len(entries) - len(results))
                    for (_, continuation), result in zip(entries, results):
                        try:
                            continuation(result)
                        except Exception:
                            pass  # continuations record their own failures
            finally:
                if added:
                    self.thread_registry.discard(ident)

        if self._pool is not None and len(staged) > 1:
            deadline = time.monotonic() + timeout
            futures = [
                self._pool.submit(flush_cluster, cluster, entries)
                for cluster, entries in staged.items()
            ]
            for f in futures:
                try:
                    f.result(timeout=max(0.0, deadline - time.monotonic()))
                except Exception:
                    pass
        else:
            for cluster, entries in staged.items():
                flush_cluster(cluster, entries)

    def wait(self, timeout: float) -> None:
        # Dispatchers sharing this sink call wait() after the controller
        # has flushed the tick; anything still staged (a mid-reconcile
        # wait, e.g. the deletion path) flushes now.
        if not self.flushed:
            self.flush(timeout)


def _result_error(result: dict) -> str:
    status = result.get("status") or {}
    return status.get("message") or status.get("reason") or f"code {result.get('code')}"


def _set_last_replicaset_name(obj: dict, cluster_obj: dict) -> None:
    """When a new template revision is being dispatched, remember which
    ReplicaSet was newest BEFORE it, so stale latest-replicaset
    annotations are recognizable (retain.go setLastReplicasetName)."""
    if cluster_obj is None:
        return
    ann = obj.get("metadata", {}).get("annotations", {})
    revision = ann.get(CURRENT_REVISION_ANNOTATION)
    if revision is None:
        return
    cluster_ann = cluster_obj.get("metadata", {}).get("annotations", {})
    last_dispatched = cluster_ann.get(CURRENT_REVISION_ANNOTATION)
    if last_dispatched is not None and revision != last_dispatched:
        rs_name = cluster_ann.get(LATEST_RS_NAME)
        if rs_name is not None:
            obj.setdefault("metadata", {}).setdefault("annotations", {})[
                LAST_RS_NAME
            ] = rs_name


def _retain_template(
    obj: dict, cluster_obj: dict, replicas_path: str, keep_rollout_settings: bool
) -> None:
    """Keep the member's current pod template (and optionally its rollout
    knobs) in the desired object: "not your turn yet"
    (retain.go retainTemplate)."""
    tpl = get_path(cluster_obj, "spec.template")
    if tpl is not None:
        set_path(obj, "spec.template", tpl)
    else:
        delete_path(obj, "spec.template")
    ann = obj.setdefault("metadata", {}).setdefault("annotations", {})
    cluster_revision = cluster_obj.get("metadata", {}).get("annotations", {}).get(
        CURRENT_REVISION_ANNOTATION
    )
    if cluster_revision is not None:
        ann[CURRENT_REVISION_ANNOTATION] = cluster_revision
    else:
        ann.pop(CURRENT_REVISION_ANNOTATION, None)
    if keep_rollout_settings:
        if replicas_path:
            replicas = get_path(cluster_obj, replicas_path)
            if replicas is not None:
                set_path(obj, replicas_path, replicas)
            else:
                delete_path(obj, replicas_path)
        for path in (MAX_SURGE_PATH, MAX_UNAVAILABLE_PATH):
            dotted = path[1:].replace("/", ".")
            value = get_path(cluster_obj, dotted)
            if value is not None:
                set_path(obj, dotted, value)
            else:
                delete_path(obj, dotted)


class ManagedDispatcher:
    """One sync round's write fan-out (managed.go:77-126).

    ``client_for_cluster`` returns the member apiserver handle; failures
    of individual operations are recorded per cluster, never raised.
    ``sink`` routes the writes (shared BatchSink across a tick, or a
    private ImmediateSink mirroring the reference's goroutines)."""

    def __init__(
        self,
        client_for_cluster: Callable[[str], FakeKube],
        fed_resource: FederatedResource,
        resource: str,
        replicas_path: str = "",
        skip_adopting: bool = True,
        pool: Optional[ThreadPoolExecutor] = None,
        timeout: float = 30.0,
        rollout_overrides: Optional[Callable[[str], list]] = None,
        inline: bool = False,
        sink=None,
        on_written: Optional[Callable[[str, dict], None]] = None,
    ):
        self.client_for_cluster = client_for_cluster
        self.fed = fed_resource
        self.resource = resource
        self.replicas_path = replicas_path
        self.skip_adopting = skip_adopting
        self.timeout = timeout
        self.rollout_overrides = rollout_overrides
        self._sink = sink or ImmediateSink(client_for_cluster, pool=pool, inline=inline)
        self._on_written = on_written
        self._lock = threading.Lock()
        self._status: dict[str, str] = {}
        self._versions: dict[str, str] = {}
        self._errors: dict[str, str] = {}
        self._resources_updated = False
        # Desired-object assembly dedup: clusters sharing an override
        # patch list share ONE assembled object (consumers that mutate —
        # the retention paths — copy first; create paths hand the shared
        # object to clients, which serialize/copy on write).
        self._desired_cache: dict[str, dict] = {}

    # -- bookkeeping -----------------------------------------------------
    def record_status(self, cluster: str, status: str) -> None:
        with self._lock:
            self._status[cluster] = status

    def record_error(self, cluster: str, status: str, err: str) -> None:
        with self._lock:
            self._status[cluster] = status
            self._errors[cluster] = err

    def _record_version(self, cluster: str, version: str) -> None:
        with self._lock:
            self._versions[cluster] = version
            self._status[cluster] = OK

    def _record_written(self, cluster: str, obj: dict) -> None:
        """A real write landed: record version AND surface the written
        object (its raw resourceVersion feeds the controller's watch-echo
        suppression).  Version-based skips must NOT come through here —
        they produce no watch event to suppress."""
        self._record_version(cluster, object_version(obj))
        if self._on_written is not None:
            self._on_written(cluster, obj)

    def wait(self) -> bool:
        """Block until every operation finishes or the shared deadline
        passes (managed.go:126-159); returns False when any cluster ended
        in a non-OK, non-waiting state."""
        self._sink.wait(self.timeout)
        with self._lock:
            return all(
                s in (OK, WAITING_FOR_REMOVAL, WAITING)
                for s in self._status.values()
            )

    @property
    def version_map(self) -> dict[str, str]:
        with self._lock:
            return dict(self._versions)

    @property
    def status_map(self) -> dict[str, str]:
        with self._lock:
            return dict(self._status)

    @property
    def resources_updated(self) -> bool:
        return self._resources_updated

    # -- desired-object assembly ----------------------------------------
    def _desired(self, cluster: str, mutable: bool = False) -> dict:
        """Assembled desired object for a cluster.  Clusters whose
        override patch lists are identical (the common case — overrides
        come from shared policies) get ONE shared assembly; pass
        ``mutable=True`` to receive a private copy (retention paths
        mutate the object in place)."""
        extra = self.rollout_overrides(cluster) if self.rollout_overrides else None
        patches = self.fed._ordered_overrides().get(cluster) or ()
        if not patches and not extra:
            key = ""  # the common no-override case skips key serialization
        else:
            key = json.dumps([patches, extra], sort_keys=True, default=str)
        with self._lock:
            obj = self._desired_cache.get(key)
        if obj is None:
            obj = self.fed.object_for_cluster(cluster)
            obj = self.fed.apply_overrides(obj, cluster, extra)
            retain.record_propagated_keys(obj)
            with self._lock:
                self._desired_cache[key] = obj
        if mutable:
            return copy_json(obj)
        return obj

    # -- operations ------------------------------------------------------
    def create(self, cluster: str) -> None:
        """Create, falling back to adoption-aware update on AlreadyExists
        (managed.go:325-400)."""
        self.record_status(cluster, CREATION_TIMED_OUT)
        try:
            obj = self._desired(cluster)
        except Exception as e:
            return self.record_error(cluster, COMPUTE_RESOURCE_FAILED, str(e))

        def done(result: dict) -> None:
            code = result.get("code")
            if code == 201:
                self._resources_updated = True
                self._record_written(cluster, result["object"])
                return
            if not (
                code == 409
                and (result.get("status") or {}).get("reason") == "AlreadyExists"
            ):
                return self.record_error(cluster, CREATION_FAILED, _result_error(result))
            # AlreadyExists: the adoption-aware fallback (rare path, runs
            # direct client calls on the flushing thread).
            client = self.client_for_cluster(cluster)
            try:
                existing = client.get(self.resource, self.fed.key)
            except NotFound as e:
                return self.record_error(cluster, CREATION_FAILED, str(e))
            if self.skip_adopting:
                return self.record_error(
                    cluster, ALREADY_EXISTS, "resource pre-exists in cluster"
                )
            if not has_managed_label(existing):
                existing.setdefault("metadata", {}).setdefault("annotations", {})[
                    ADOPTED_ANNOTATION
                ] = "true"
            self._update_now(cluster, existing, adopting=True)

        self._sink.submit(
            cluster, {"verb": "create", "resource": self.resource, "object": obj}, done
        )

    def update(self, cluster: str, cluster_obj: dict, recorded_version: str = "") -> None:
        self.record_status(cluster, UPDATE_TIMED_OUT)
        self._stage_update(cluster, cluster_obj, recorded_version=recorded_version)

    def _prepare_update(
        self,
        cluster: str,
        cluster_obj: dict,
        recorded_version: str = "",
        adopting: bool = False,
    ) -> Optional[dict]:
        """(managed.go:402-476): retention + version-based skip.  Returns
        the object to write, or None when bookkeeping already settled the
        cluster (skip or failure, recorded)."""
        if is_explicitly_unmanaged(cluster_obj):
            self.record_error(
                cluster,
                MANAGED_LABEL_FALSE,
                f"object has label {C.MANAGED_LABEL}=false",
            )
            return None
        try:
            obj = self._desired(cluster, mutable=True)
        except Exception as e:
            self.record_error(cluster, COMPUTE_RESOURCE_FAILED, str(e))
            return None
        if adopting:
            ann = cluster_obj.get("metadata", {}).get("annotations", {})
            if ann.get(ADOPTED_ANNOTATION):
                obj.setdefault("metadata", {}).setdefault("annotations", {})[
                    ADOPTED_ANNOTATION
                ] = "true"
        try:
            retain.retain_cluster_fields(self.fed.target_kind, obj, cluster_obj)
            retain.retain_replicas(obj, cluster_obj, self.fed.obj, self.replicas_path)
            if self.fed.target_kind == "Deployment":
                _set_last_replicaset_name(obj, cluster_obj)
        except Exception as e:
            self.record_error(cluster, FIELD_RETENTION_FAILED, str(e))
            return None

        if recorded_version and not object_needs_update(
            obj, cluster_obj, recorded_version, self.replicas_path
        ):
            # Current: still record the version so status reflects it.
            self._record_version(cluster, recorded_version)
            return None
        return obj

    def _update_done(self, cluster: str) -> Callable[[dict], None]:
        def done(result: dict) -> None:
            if result.get("code") == 200:
                self._resources_updated = True
                self._record_written(cluster, result["object"])
            else:
                self.record_error(cluster, UPDATE_FAILED, _result_error(result))

        return done

    def _stage_update(
        self,
        cluster: str,
        cluster_obj: dict,
        recorded_version: str = "",
        adopting: bool = False,
    ) -> None:
        obj = self._prepare_update(cluster, cluster_obj, recorded_version, adopting)
        if obj is None:
            return
        self._sink.submit(
            cluster,
            {"verb": "update", "resource": self.resource, "object": obj},
            self._update_done(cluster),
        )

    def _update_now(self, cluster: str, cluster_obj: dict, adopting: bool = False) -> None:
        """Direct (non-staged) update, used by the create fallback which
        already runs on a flushing thread."""
        obj = self._prepare_update(cluster, cluster_obj, adopting=adopting)
        if obj is None:
            return
        client = self.client_for_cluster(cluster)
        try:
            updated = client.update(self.resource, obj)
        except Exception as e:
            return self.record_error(cluster, UPDATE_FAILED, str(e))
        self._resources_updated = True
        self._record_written(cluster, updated)

    def patch_and_keep_template(
        self,
        cluster: str,
        cluster_obj: dict,
        keep_rollout_settings: bool,
        recorded_version: str = "",
    ) -> None:
        """Dispatch everything EXCEPT the pod template: an unplanned
        cluster waits its rollout turn with its current template (and,
        with ``keep_rollout_settings``, its current replicas/fenceposts)
        (managed.go:483-560 PatchAndKeepTemplate)."""
        self.record_status(cluster, UPDATE_TIMED_OUT)
        if is_explicitly_unmanaged(cluster_obj):
            return self.record_error(
                cluster,
                MANAGED_LABEL_FALSE,
                f"object has label {C.MANAGED_LABEL}=false",
            )
        try:
            obj = self._desired(cluster, mutable=True)
        except Exception as e:
            return self.record_error(cluster, COMPUTE_RESOURCE_FAILED, str(e))
        try:
            retain.retain_cluster_fields(self.fed.target_kind, obj, cluster_obj)
            retain.retain_replicas(
                obj, cluster_obj, self.fed.obj, self.replicas_path
            )
            # No _set_last_replicaset_name here: _retain_template just
            # forced the revision annotations equal, so the real
            # update() path is where the last-RS marker gets written.
            _retain_template(
                obj, cluster_obj, self.replicas_path, keep_rollout_settings
            )
        except Exception as e:
            return self.record_error(cluster, FIELD_RETENTION_FAILED, str(e))

        if recorded_version and not object_needs_update(
            obj, cluster_obj, recorded_version, self.replicas_path
        ):
            self._record_version(cluster, recorded_version)
            return
        self._sink.submit(
            cluster,
            {"verb": "update", "resource": self.resource, "object": obj},
            self._update_done(cluster),
        )

    def delete(self, cluster: str) -> None:
        """Delete from a member cluster (unmanaged.go Delete): the object
        stays WAITING_FOR_REMOVAL until the member confirms it gone."""
        self.record_status(cluster, DELETION_TIMED_OUT)

        def done(result: dict) -> None:
            code = result.get("code")
            if code == 404:
                with self._lock:
                    self._status.pop(cluster, None)
                return
            if code != 200:
                return self.record_error(cluster, DELETION_FAILED, _result_error(result))
            self._resources_updated = True
            client = self.client_for_cluster(cluster)
            if client.try_get(self.resource, self.fed.key) is None:
                with self._lock:
                    self._status.pop(cluster, None)
            else:
                self.record_status(cluster, WAITING_FOR_REMOVAL)

        self._sink.submit(
            cluster,
            {"verb": "delete", "resource": self.resource, "key": self.fed.key},
            done,
        )

    def remove_managed_label(self, cluster: str, cluster_obj: dict) -> None:
        """Orphaning: strip the managed label + adopted annotation instead
        of deleting (unmanaged.go RemoveManagedLabel)."""
        self.record_status(cluster, UPDATE_TIMED_OUT)
        # Deep copy: cluster_obj may be a no-copy store VIEW, and a
        # shallow dict() would mutate the store's nested metadata.
        obj = copy_json(cluster_obj)
        labels = obj.get("metadata", {}).get("labels", {})
        labels.pop(C.MANAGED_LABEL, None)
        obj.get("metadata", {}).get("annotations", {}).pop(ADOPTED_ANNOTATION, None)

        def done(result: dict) -> None:
            if result.get("code") == 200:
                with self._lock:
                    self._status.pop(cluster, None)
            else:
                self.record_error(cluster, UPDATE_FAILED, _result_error(result))

        self._sink.submit(
            cluster, {"verb": "update", "resource": self.resource, "object": obj}, done
        )
