"""Revision history: ControllerRevisions for federated workloads.

When an FTC enables revisionHistory, the sync controller records each
distinct pod template of a federated workload as a ControllerRevision on
the host (reference: pkg/controllers/sync/history.go:36-304), giving
rollback targets.  Mechanics mirrored from the reference:

* the revision's data is an RFC6902 patch replacing
  ``/spec/template/spec/template`` (the pod template inside the
  federated object's embedded workload),
* revisions are deduplicated by data equality; the name is
  ``<fed-name>-<hash(data, collisionCount)>`` and a collision (same name,
  different data) bumps ``status.collisionCount`` on the federated
  object,
* a new template gets revision number ``max(old)+1``; re-observing an
  old template bumps that revision back to the newest number (rollback
  detection),
* history is truncated to ``spec.revisionHistoryLimit`` (oldest first),
* the federated object is annotated with the current revision name and
  the last (previous) revision name suffixed ``|<podTemplateHash>``,
  which the rollout planner uses to pair member objects with revisions.
"""

from __future__ import annotations

import threading
from typing import Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.federation.retain import CURRENT_REVISION_ANNOTATION
from kubeadmiral_tpu.testing.fakekube import (
    AlreadyExists,
    Conflict,
    FakeKube,
    NotFound,
)
from kubeadmiral_tpu.utils.hashing import fnv32a, stable_json_hash
from kubeadmiral_tpu.utils.unstructured import copy_json, get_path

CONTROLLER_REVISIONS = "apps/v1/controllerrevisions"
LAST_REVISION_ANNOTATION = C.PREFIX + "last-revision"

# Revisions are bound to their owner by uid label (history.go:283-287).
UID_LABEL = "uid"

DEFAULT_HISTORY_LIMIT = 10


class RevisionSyncError(Exception):
    pass


def pod_template(fed_obj: dict) -> Optional[dict]:
    """spec.template.spec.template of the federated object
    (history.go getPatch)."""
    value = get_path(fed_obj, "spec.template.spec.template")
    return value if isinstance(value, dict) else None


def _revision_data(fed_obj: dict) -> list:
    tpl = pod_template(fed_obj)
    if tpl is None:
        raise RevisionSyncError("spec.template.spec.template is not found")
    return [
        {"op": "replace", "path": "/spec/template/spec/template", "value": tpl}
    ]


def _revision_name(fed_name: str, data: list, collision_count: int) -> str:
    payload = C.compact_json(data).encode() + str(collision_count).encode()
    return f"{fed_name}-{fnv32a(payload):08x}"


def _revision_labels(fed_obj: dict) -> dict[str, str]:
    """uid binding + the owner's labels (history.go
    revisionLabelsWithOriginalLabel).  The uid binding is written last so
    an owner label literally named "uid" cannot break ownership."""
    labels = dict(fed_obj["metadata"].get("labels", {}))
    labels[UID_LABEL] = str(fed_obj["metadata"].get("uid", ""))
    return labels


class RevisionManager:
    """Host-side ControllerRevision bookkeeping for one FTC.

    Revisions are indexed by owner uid from a watch (the informer-indexer
    pattern): without it every sync reconcile would scan the whole
    ControllerRevision store — O(objects^2) work per settled batch."""

    def __init__(self, host: FakeKube):
        self.host = host
        self._lock = threading.Lock()
        self._by_uid: dict[str, set[str]] = {}
        # ktlint: ignore[shard-intake-coverage] broadcast index: the revision cache is keyed by owner uid and only read from shard-owned sync reconciles; non-owned rows cost memory, never scheduling work
        host.watch(CONTROLLER_REVISIONS, self._on_revision_event, replay=True)

    def _on_revision_event(self, event: str, obj: dict) -> None:
        uid = obj.get("metadata", {}).get("labels", {}).get(UID_LABEL)
        if uid is None:
            return
        ns = obj["metadata"].get("namespace", "")
        name = obj["metadata"]["name"]
        key = f"{ns}/{name}" if ns else name
        with self._lock:
            if event == "DELETED":
                self._by_uid.get(uid, set()).discard(key)
            else:
                self._by_uid.setdefault(uid, set()).add(key)

    def _list_owned(self, fed_obj: dict) -> list[dict]:
        uid = str(fed_obj["metadata"].get("uid", ""))
        with self._lock:
            keys = sorted(self._by_uid.get(uid, ()))
        out = []
        for key in keys:
            obj = self.host.try_get(CONTROLLER_REVISIONS, key)
            if obj is not None:
                out.append(obj)
        return out

    def sync_revisions(self, fed_obj: dict) -> tuple[int, str, str]:
        """Record the current template; returns (collisionCount,
        lastRevisionNameWithHash, currentRevisionName)
        (history.go syncRevisions)."""
        collision_count = int(
            get_path(fed_obj, "status.collisionCount", 0) or 0
        )
        data = _revision_data(fed_obj)
        # An explicit limit of 0 keeps no old revisions; only an absent
        # field falls back to the default.
        raw_limit = get_path(fed_obj, "spec.revisionHistoryLimit")
        history_limit = DEFAULT_HISTORY_LIMIT if raw_limit is None else int(raw_limit)

        revisions = self._list_owned(fed_obj)
        current = [r for r in revisions if r.get("data") == data]
        old = [r for r in revisions if r.get("data") != data]
        next_number = max((r.get("revision", 0) for r in old), default=0) + 1

        if not current:
            collision_count, name = self._create_revision(
                fed_obj, data, next_number, collision_count
            )
        else:
            keep = self._dedup_current(current)
            name = keep["metadata"]["name"]
            if keep.get("revision", 0) < next_number:
                # An old template came back (rollback): renumber to newest.
                keep["revision"] = next_number
                self._update_revision(keep)
            else:
                self._ensure_labels(keep, _revision_labels(fed_obj))

        # Truncate oldest history beyond the limit (history.go:163-183).
        old.sort(key=lambda r: r.get("revision", 0))
        to_kill = len(old) - history_limit
        killed = 0
        for rev in old:
            if killed >= to_kill:
                break
            self._delete_revision(rev)
            killed += 1
        old = old[killed:]

        last_with_hash = ""
        if old and history_limit >= 1:
            last_with_hash = old[-1]["metadata"]["name"]
            prev_tpl = None
            for patch in old[-1].get("data", []):
                if patch.get("path") == "/spec/template/spec/template":
                    prev_tpl = patch.get("value")
            last_with_hash += f"|{stable_json_hash(prev_tpl):08x}"
            for rev in old:
                self._ensure_labels(rev, _revision_labels(fed_obj))

        return collision_count, last_with_hash, name

    # -- storage helpers -------------------------------------------------
    def _create_revision(
        self, fed_obj: dict, data: list, number: int, collision_count: int
    ) -> tuple[int, str]:
        """Create with collision-count retry (k8s
        history.CreateControllerRevision semantics): an existing revision
        with the same name but different data bumps the counter."""
        ns = fed_obj["metadata"].get("namespace", "")
        fed_name = fed_obj["metadata"]["name"]
        while True:
            name = _revision_name(fed_name, data, collision_count)
            key = f"{ns}/{name}" if ns else name
            existing = self.host.try_get(CONTROLLER_REVISIONS, key)
            if existing is not None:
                if existing.get("data") == data:
                    return collision_count, name
                collision_count += 1
                continue
            revision = {
                "apiVersion": "apps/v1",
                "kind": "ControllerRevision",
                "metadata": {
                    "name": name,
                    "labels": _revision_labels(fed_obj),
                },
                "data": copy_json(data),
                "revision": number,
            }
            if ns:
                revision["metadata"]["namespace"] = ns
            try:
                self.host.create(CONTROLLER_REVISIONS, revision)
            except AlreadyExists:
                continue  # raced; re-check data on the next pass
            return collision_count, name

    def _dedup_current(self, current: list[dict]) -> dict:
        """Keep the max-revision duplicate, delete the rest
        (history.go dedupCurRevisions)."""
        keep = max(current, key=lambda r: r.get("revision", 0))
        for rev in current:
            if rev["metadata"]["name"] != keep["metadata"]["name"]:
                self._delete_revision(rev)
        return keep

    def _update_revision(self, revision: dict) -> None:
        try:
            self.host.update(CONTROLLER_REVISIONS, revision)
        except (Conflict, NotFound):
            pass  # next reconcile converges

    def _delete_revision(self, revision: dict) -> None:
        ns = revision["metadata"].get("namespace", "")
        name = revision["metadata"]["name"]
        try:
            self.host.delete(CONTROLLER_REVISIONS, f"{ns}/{name}" if ns else name)
        except NotFound:
            pass

    def _ensure_labels(self, revision: dict, labels: dict[str, str]) -> None:
        current = revision["metadata"].get("labels", {})
        if all(current.get(k) == v for k, v in labels.items()):
            return
        revision["metadata"]["labels"] = {**current, **labels}
        self._update_revision(revision)
