"""PolicyRC: reference counts of policy usage, persisted to status.

Per-FTC controller (reference: pkg/controllers/policyrc/controller.go,
counter.go) that tracks how many federated objects bind each
Propagation/ClusterPropagation/Override/ClusterOverride policy and
persists the counts into the policy's ``status.refCount`` (sum over all
resource types) and ``status.typedRefCount[]`` (one entry per target
group/resource).

Two stages, as in the reference: a count worker reconciles federated
objects into in-memory Counters (diffing each object's previous policy
set against the new one), and per-policy persist workers flush dirty
counts to the policy status subresource.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from kubeadmiral_tpu.models import policy as P
from kubeadmiral_tpu.models.ftc import FederatedTypeConfig
from kubeadmiral_tpu.federation.overridectl import (
    CLUSTER_OVERRIDE_POLICY_NAME_LABEL,
    OVERRIDE_POLICY_NAME_LABEL,
)
from kubeadmiral_tpu.runtime.metrics import Metrics
from kubeadmiral_tpu.runtime.worker import Result, Worker
from kubeadmiral_tpu.testing.fakekube import Conflict, FakeKube, NotFound, obj_key

# (namespace, name); namespace "" = cluster-scoped policy.
PolicyKey = tuple[str, str]


class Counter:
    """Reference counter with per-object policy-set diffing
    (counter.go:32-92)."""

    def __init__(self, flag_dirty: Callable[[list[PolicyKey]], None]):
        self._lock = threading.Lock()
        self._known: dict[str, tuple[PolicyKey, ...]] = {}  # object -> policies
        self._counts: dict[PolicyKey, int] = {}
        self._flag_dirty = flag_dirty

    def update(self, object_key: str, policies: tuple[PolicyKey, ...]) -> None:
        dirty: list[PolicyKey] = []
        with self._lock:
            previous = self._known.get(object_key, ())
            if policies == previous:
                return  # no count changes -> nothing to flag dirty
            if policies:
                self._known[object_key] = policies
            else:
                self._known.pop(object_key, None)
            for key in previous:
                self._counts[key] -= 1
                assert self._counts[key] >= 0, f"negative refcount for {key}"
                dirty.append(key)
            for key in policies:
                self._counts[key] = self._counts.get(key, 0) + 1
            dirty.extend(policies)
        # Flag outside the lock to reduce contention (counter.go:36-39).
        self._flag_dirty(dirty)

    def count(self, key: PolicyKey) -> int:
        with self._lock:
            return self._counts.get(key, 0)


def _persist_key(key: PolicyKey) -> str:
    ns, name = key
    return f"{ns}/{name}" if ns else name


class PolicyRCController:
    name = "policyrc-controller"

    def __init__(
        self,
        host: FakeKube,
        ftc: FederatedTypeConfig,
        metrics: Optional[Metrics] = None,
    ):
        self.host = host
        self.ftc = ftc
        self.metrics = metrics or Metrics()
        self._resource = ftc.federated.resource

        self.count_worker = Worker(
            f"policyrc-count-{ftc.name}", self._reconcile_count, metrics=self.metrics
        )
        self.pp_persist_worker = Worker(
            f"policyrc-persist-pp-{ftc.name}",
            self._reconcile_persist_pp,
            metrics=self.metrics,
        )
        self.op_persist_worker = Worker(
            f"policyrc-persist-op-{ftc.name}",
            self._reconcile_persist_op,
            metrics=self.metrics,
        )
        self.pp_counter = Counter(
            lambda keys: self.pp_persist_worker.enqueue_all(
                _persist_key(k) for k in keys
            )
        )
        self.op_counter = Counter(
            lambda keys: self.op_persist_worker.enqueue_all(
                _persist_key(k) for k in keys
            )
        )

        host.watch(self._resource, self._on_object_event, replay=True)
        # A policy created after its referrers must still get its counts
        # (controller.go: persist reconcile waits for creation, and the
        # create event triggers another reconcile).
        for resource in (
            P.PROPAGATION_POLICIES,
            P.CLUSTER_PROPAGATION_POLICIES,
        ):
            host.watch(resource, self._on_pp_event, replay=False)
        for resource in (P.OVERRIDE_POLICIES, P.CLUSTER_OVERRIDE_POLICIES):
            host.watch(resource, self._on_op_event, replay=False)

    @property
    def worker(self):
        """Primary worker handle for generic drivers (settle loops)."""
        return self.count_worker

    def step_all(self) -> bool:
        progressed = self.count_worker.step()
        progressed |= self.pp_persist_worker.step()
        progressed |= self.op_persist_worker.step()
        return progressed

    # -- events ----------------------------------------------------------
    def _on_object_event(self, event: str, obj: dict) -> None:
        self.count_worker.enqueue(obj_key(obj))

    def _on_pp_event(self, event: str, obj: dict) -> None:
        self.pp_persist_worker.enqueue(obj_key(obj))

    def _on_op_event(self, event: str, obj: dict) -> None:
        self.op_persist_worker.enqueue(obj_key(obj))

    # -- count stage (controller.go reconcileCount) ----------------------
    def _reconcile_count(self, key: str) -> Result:
        fed_obj = self.host.try_get(self._resource, key)

        pps: tuple[PolicyKey, ...] = ()
        ops: tuple[PolicyKey, ...] = ()
        if fed_obj is not None:
            matched = P.matched_policy_key(fed_obj)
            if matched is not None:
                pps = (matched,)
            labels = fed_obj["metadata"].get("labels", {})
            ns = fed_obj["metadata"].get("namespace", "")
            op_list: list[PolicyKey] = []
            # The namespaced label only binds namespaced objects (the same
            # guard overridectl and matched_policy_key apply); without it a
            # cluster-scoped object's label would masquerade as a
            # ClusterOverridePolicy reference.
            if OVERRIDE_POLICY_NAME_LABEL in labels and ns:
                op_list.append((ns, labels[OVERRIDE_POLICY_NAME_LABEL]))
            if CLUSTER_OVERRIDE_POLICY_NAME_LABEL in labels:
                op_list.append(("", labels[CLUSTER_OVERRIDE_POLICY_NAME_LABEL]))
            ops = tuple(op_list)
        # A deleted object still clears its cached counts.
        self.pp_counter.update(key, pps)
        self.op_counter.update(key, ops)
        return Result.ok()

    # -- persist stage (controller.go reconcilePersist) ------------------
    def _persist(self, resources: tuple[str, str], counter: Counter, key: str) -> Result:
        ns_resource, cluster_resource = resources
        ns, _, name = key.rpartition("/")
        resource = ns_resource if ns else cluster_resource
        policy = self.host.try_get(resource, key)
        if policy is None:
            # Wait for creation; the create event re-enqueues.
            return Result.ok()

        status = policy.setdefault("status", {})
        typed = status.setdefault("typedRefCount", [])
        group = self.ftc.source.group
        plural = self.ftc.source.plural
        entry = next(
            (t for t in typed if t.get("group", "") == group and t.get("resource") == plural),
            None,
        )
        if entry is None:
            entry = {"group": group, "resource": plural, "count": 0}
            typed.append(entry)

        changed = False
        new_count = counter.count((ns, name))
        if entry.get("count", 0) != new_count:
            entry["count"] = new_count
            changed = True
        total = sum(t.get("count", 0) for t in typed)
        if status.get("refCount", 0) != total:
            status["refCount"] = total
            changed = True
        if not changed:
            return Result.ok()
        try:
            self.host.update_status(resource, policy)
        except Conflict:
            return Result.retry()
        except NotFound:
            pass  # deleted underneath us; nothing left to persist
        return Result.ok()

    def _reconcile_persist_pp(self, key: str) -> Result:
        return self._persist(
            (P.PROPAGATION_POLICIES, P.CLUSTER_PROPAGATION_POLICIES),
            self.pp_counter,
            key,
        )

    def _reconcile_persist_op(self, key: str) -> Result:
        return self._persist(
            (P.OVERRIDE_POLICIES, P.CLUSTER_OVERRIDE_POLICIES),
            self.op_counter,
            key,
        )
