"""Shared constants and federated-object accessors.

Federated objects are unstructured dicts:

    apiVersion: types.kubeadmiral.io/v1alpha1
    kind: FederatedDeployment
    metadata: {name, namespace, labels, annotations, finalizers}
    spec:
      template: <full source object, pruned>
      placements: [{controller, placement: [{cluster}]}]
      overrides:  [{controller, clusters: [{cluster, patches: [RFC6902]}]}]
      follows:    [{group, kind, namespace, name}]
    status:
      clusters: [{cluster, status}]
      conditions: [...]

mirroring the reference's federated types (reference:
pkg/apis/types/v1alpha1/types_federateddeployment.go:28-63,
types_placements.go, types_overrides.go, types_status.go).
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

PREFIX = "kubeadmiral.io/"

MANAGED_LABEL = PREFIX + "managed"
MANAGED_TRUE = "true"

# Annotations.
FEDERATED_OBJECT = PREFIX + "federated-object"  # marks federate-created objects
SCHEDULING_TRIGGER_HASH = PREFIX + "scheduling-trigger-hash"
PROPAGATION_POLICY_NAME = PREFIX + "propagation-policy-name"
CLUSTER_PROPAGATION_POLICY_NAME = PREFIX + "cluster-propagation-policy-name"
FOLLOWS_OBJECT = PREFIX + "follows-object"
DISABLE_FOLLOWING = PREFIX + "disable-following"
AUTO_MIGRATION_INFO = PREFIX + "auto-migration-info"
UNSCHEDULABLE_THRESHOLD = PREFIX + "auto-migration-unschedulable-threshold"
SOURCE_GENERATION = PREFIX + "source-generation"
CONFLICT_RESOLUTION = PREFIX + "conflict-resolution"  # adopt | abort
ORPHAN_MODE = PREFIX + "orphan"  # all | adopted
# Internal variants set by controllers (not copied from the source object;
# they win over the user-facing annotation — reference:
# util/conflictresolutionannotation.go, util/orphaningannotation.go).
CONFLICT_RESOLUTION_INTERNAL = CONFLICT_RESOLUTION + ".internal"
ORPHAN_MODE_INTERNAL = ORPHAN_MODE + ".internal"
NO_AUTO_PROPAGATION = PREFIX + "no-auto-propagation"
RETAIN_REPLICAS = PREFIX + "retain-replicas"
TEMPLATE_HASH = PREFIX + "template-hash"
OVERRIDE_HASH = PREFIX + "override-hash"
LATEST_REPLICASET_DIGESTS = PREFIX + "latest-replicaset-digests"
SOURCE_FEEDBACK_SCHEDULING = PREFIX + "scheduling"
SOURCE_FEEDBACK_SYNCING = PREFIX + "syncing"
SOURCE_FEEDBACK_STATUS = PREFIX + "status"

# Controller names (pipeline members).
SCHEDULER = PREFIX + "global-scheduler"
OVERRIDE_CONTROLLER = PREFIX + "overridepolicy-controller"
FOLLOWER_CONTROLLER = PREFIX + "follower-controller"

# Finalizers.
SYNC_FINALIZER = PREFIX + "sync-controller"
CLUSTER_FINALIZER = PREFIX + "cluster-controller"

# Host-apiserver resource keys for the core CRDs.
FEDERATED_CLUSTERS = "core.kubeadmiral.io/v1alpha1/federatedclusters"


def compact_json(value) -> str:
    import json

    return json.dumps(value, separators=(",", ":"), sort_keys=True)


def meta(obj: dict) -> dict:
    return obj.setdefault("metadata", {})


def annotations(obj: dict) -> dict:
    return meta(obj).setdefault("annotations", {})


def labels(obj: dict) -> dict:
    return meta(obj).setdefault("labels", {})


def name_of(obj: dict) -> str:
    return obj["metadata"]["name"]


def namespace_of(obj: dict) -> str:
    return obj["metadata"].get("namespace", "")


def template(fed_obj: dict) -> dict:
    return fed_obj.get("spec", {}).get("template", {})


# -- placements (extensions_placements.go semantics) --------------------

def get_placement(fed_obj: dict, controller: str) -> Optional[set[str]]:
    for entry in fed_obj.get("spec", {}).get("placements", []):
        if entry.get("controller") == controller:
            return {p["cluster"] for p in entry.get("placement", [])}
    return None


def set_placement(fed_obj: dict, controller: str, clusters: set[str]) -> bool:
    """Idempotent write; returns True when the spec changed."""
    spec = fed_obj.setdefault("spec", {})
    placements = spec.setdefault("placements", [])
    desired = [{"cluster": c} for c in sorted(clusters)]
    for entry in placements:
        if entry.get("controller") == controller:
            if entry.get("placement") == desired:
                return False
            entry["placement"] = desired
            return True
    placements.append({"controller": controller, "placement": desired})
    return True


def all_placement_clusters(fed_obj: dict) -> set[str]:
    """Union over controllers (reference: placement.go union semantics)."""
    out: set[str] = set()
    for entry in fed_obj.get("spec", {}).get("placements", []):
        out.update(p["cluster"] for p in entry.get("placement", []))
    return out


# -- overrides (util/overrides.go semantics) ----------------------------

def get_overrides(fed_obj: dict, controller: str) -> dict[str, list]:
    """cluster -> RFC6902 patch list for one controller."""
    for entry in fed_obj.get("spec", {}).get("overrides", []):
        if entry.get("controller") == controller:
            return {
                c["cluster"]: c.get("patches", [])
                for c in entry.get("clusters", [])
            }
    return {}


def set_overrides(fed_obj: dict, controller: str, per_cluster: dict[str, list]) -> bool:
    spec = fed_obj.setdefault("spec", {})
    overrides = spec.setdefault("overrides", [])
    desired = [
        {"cluster": c, "patches": patches}
        for c, patches in sorted(per_cluster.items())
        if patches
    ]
    for i, entry in enumerate(overrides):
        if entry.get("controller") == controller:
            if not desired:
                overrides.pop(i)
                return True
            if entry.get("clusters") == desired:
                return False
            entry["clusters"] = desired
            return True
    if desired:
        overrides.append({"controller": controller, "clusters": desired})
        return True
    return False


def overrides_for_cluster(fed_obj: dict, cluster: str) -> list:
    """All controllers' patches for one cluster, in spec order."""
    patches: list = []
    for entry in fed_obj.get("spec", {}).get("overrides", []):
        for c in entry.get("clusters", []):
            if c.get("cluster") == cluster:
                patches.extend(c.get("patches", []))
    return patches


def cluster_lifecycle_sig(cluster_obj: dict) -> tuple:
    """What about a FederatedCluster justifies re-reconciling the world:
    join/ready/terminating transitions (the reference's
    ClusterLifecycleHandlers, controller.go:244-260) — NOT heartbeat
    bumps.  Controllers keep a name->sig map and fan out only on
    change."""
    conds = {
        c.get("type"): c.get("status")
        for c in cluster_obj.get("status", {}).get("conditions", [])
    }
    return (
        conds.get("Joined") == "True",
        conds.get("Ready") == "True",
        bool(cluster_obj["metadata"].get("deletionTimestamp")),
    )


# Per-delivery signature memo: the store installs a scope around its
# watch fan-out so that when several controllers compute the trigger
# signature of the SAME delivered snapshot (one shared dict per event),
# the sorted-items hash runs once per object, not once per watcher.
# Thread-local because fan-out is synchronous on the writing thread and
# id()-keyed entries are only valid while the delivery pins the object.
_sig_tls = threading.local()


@contextlib.contextmanager
def sig_memo_scope():
    """Install a fresh metadata_change_sig memo for one store delivery
    (nested deliveries — a handler writing mid-fan-out — get their own
    scope; the outer memo is restored on exit)."""
    prev = getattr(_sig_tls, "memo", None)
    _sig_tls.memo = {}
    try:
        yield
    finally:
        _sig_tls.memo = prev


def metadata_change_sig(obj: dict, ignore_annotations: tuple = ()) -> int:
    """Trigger signature of the fields a fed-object watch handler cares
    about: generation (spec changes bump it), labels (policy binding),
    annotations minus declared noise keys.  Status-subresource writes —
    the bulk of a converged control plane's event volume — leave it
    unchanged, so controllers keeping a key->sig map skip the requeue
    entirely (the reference's schedulingtriggers.go idea applied at the
    watch boundary)."""
    memo = getattr(_sig_tls, "memo", None)
    if memo is not None:
        memo_key = (id(obj), ignore_annotations)
        sig = memo.get(memo_key)
        if sig is None:
            sig = _metadata_change_sig(obj, ignore_annotations)
            memo[memo_key] = sig
        return sig
    return _metadata_change_sig(obj, ignore_annotations)


def _metadata_change_sig(obj: dict, ignore_annotations: tuple = ()) -> int:
    md = obj.get("metadata", {})
    ann = md.get("annotations") or {}
    if ignore_annotations:
        ann_items = tuple(
            sorted(kv for kv in ann.items() if kv[0] not in ignore_annotations)
        )
    else:
        ann_items = tuple(sorted(ann.items()))
    return hash((
        md.get("generation"),
        bool(md.get("deletionTimestamp")),
        tuple(sorted((md.get("labels") or {}).items())),
        ann_items,
    ))
