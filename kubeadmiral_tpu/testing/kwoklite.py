"""kwok-lite: a multi-cluster farm of real HTTP apiservers.

Plays the role kwokctl plays in the reference's e2e suite (reference:
test/e2e/framework/clusterprovider/kwokprovider.go:70-260): provisions a
host apiserver plus N member apiservers — real sockets, auth, watches —
without real kubelets.  Member servers mint service-account tokens (the
piece of the cluster-join handshake a bare store can't provide), and
each member gets a bootstrap join secret on the host carrying its admin
token, mirroring how a kubeadmiral operator seeds cluster credentials
before the join handshake upgrades them to a service-account token.
"""

from __future__ import annotations

import json
import os
import secrets as pysecrets
import subprocess
import sys

from kubeadmiral_tpu.testing.fakekube import FakeKube
from kubeadmiral_tpu.transport.apiserver import KubeApiServer
from kubeadmiral_tpu.transport.client import (
    FED_SYSTEM_NAMESPACE,
    SECRETS,
    HttpFleet,
    HttpKube,
)
from kubeadmiral_tpu.transport.faults import FaultInjector, FaultPolicy


class KwokLiteFarm:
    """Host + member apiservers on localhost ports.

    ``fleet`` exposes the ClusterFleet interface (host client + join-
    secret-derived member clients) so controllers run over it unmodified.

    ``member_subprocess=True`` (or KT_FARM_SUBPROCESS=1) runs each
    member apiserver as its OWN PROCESS (kubeadmiral_tpu.testing.kwokserver),
    the reference's kwokctl model (kwokprovider.go:70-260): member-side
    request handling stops sharing the controllers' GIL, so HTTP
    numbers measure the control plane, not single-interpreter
    serialization (VERDICT r4 #6).
    """

    def __init__(
        self,
        host_token: str | None = None,
        host_port: int = 0,
        member_subprocess: bool | None = None,
    ):
        self.host_store = FakeKube("host")
        # Fault-injection seam: per-member FaultPolicy honored by every
        # in-process member apiserver (set_fault/clear_fault below) —
        # how `make chaos` partitions, stalls and flaps members.
        self.faults = FaultInjector()
        self.host_server = KubeApiServer(
            self.host_store, admin_token=host_token, port=host_port
        )
        self.host = HttpKube(self.host_server.url, token=host_token, name="host")
        self.fleet = HttpFleet(self.host)
        self.member_servers: dict[str, KubeApiServer] = {}
        self.member_procs: dict[str, subprocess.Popen] = {}
        self._member_tokens: dict[str, str] = {}
        self._member_stderr: dict[str, object] = {}
        self._member_urls: dict[str, str] = {}
        self._extra_clients: list[HttpKube] = []
        # name -> admin client, for the fault-control endpoint.
        self._member_clients: dict[str, HttpKube] = {}
        # Explicit opt-in only: consumers that reach into member_servers
        # (tests, the __main__ demo) default-construct the farm and must
        # not be flipped by ambient env; the bench passes the flag.
        self.member_subprocess = bool(member_subprocess)

    def endpoint(self, name: str) -> str:
        return self._member_urls[name]

    # -- fault injection --------------------------------------------------
    def set_fault(self, name: str, policy: FaultPolicy) -> None:
        """Apply a FaultPolicy to one member apiserver.  In-process
        members share this farm's injector directly; subprocess members
        are driven over the wire through the member's fault-control
        endpoint (POST /faultz — exempt from the fault gate, so a
        partition can always be cleared)."""
        if name in self.member_procs:
            self._fault_request(name, policy)
            return
        self.faults.set_fault(name, policy)

    def clear_fault(self, name: str) -> None:
        if name in self.member_procs:
            self._fault_request(name, None)
            return
        self.faults.clear(name)

    def _fault_request(self, name: str, policy: FaultPolicy | None) -> None:
        import dataclasses

        client = self._member_clients[name]
        body = {
            "policy": dataclasses.asdict(policy) if policy is not None else None
        }
        status, payload, _ = client._request("POST", "/faultz", body)
        if status != 200:
            raise RuntimeError(
                f"fault control on {name} failed: HTTP {status} {payload}"
            )

    def scrape_roster(self) -> list[tuple[str, str, str | None]]:
        """(instance, url, admin token) for every provisioned member —
        the roster the manager-side fleet scraper
        (runtime/fleetscrape.py) walks for /debug/fleet.  Computed per
        call: membership changes as members join or die."""
        return [
            (name, self._member_urls[name], client._token)
            for name, client in sorted(self._member_clients.items())
        ]

    def cluster_spec(self, name: str) -> dict:
        """The FederatedCluster spec fields pointing at this member."""
        return {
            "apiEndpoint": self.endpoint(name),
            "secretRef": {"name": f"{name}-secret"},
        }

    def spawn_members(self, names) -> None:
        """Launch member subprocesses WITHOUT waiting for them: child
        startup (a full package import each) overlaps instead of
        serializing at seconds-per-member; a later add_member collects
        each child's url."""
        if not self.member_subprocess:
            return
        for name in names:
            if name not in self.member_procs:
                self._launch_member(name)

    def add_member(self, name: str) -> HttpKube:
        """Provision a member apiserver + bootstrap join secret; returns
        an admin client for test setup writes."""
        if self.member_subprocess:
            if name not in self.member_procs:
                self._launch_member(name)
            admin_token = self._member_tokens[name]
            url = self._await_member_url(name)
        else:
            from kubeadmiral_tpu.runtime.metrics import Metrics

            admin_token = f"admin-{name}-{pysecrets.token_hex(8)}"
            store = FakeKube(name)
            # Each member gets its own registry (request counts by
            # verb at GET /metrics) so the fleet scraper sees the same
            # per-instance page whether members are threads or
            # subprocesses.
            server = KubeApiServer(
                store, admin_token=admin_token, mint_sa_tokens=True,
                fault_injector=self.faults, fault_name=name,
                metrics=Metrics(),
            )
            self.member_servers[name] = server
            url = server.url
        self._member_urls[name] = url
        self.host.create(
            SECRETS,
            {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {
                    "name": f"{name}-secret",
                    "namespace": FED_SYSTEM_NAMESPACE,
                },
                "data": {"token": admin_token},
            },
        )
        client = HttpKube(url, token=admin_token, name=name)
        self._extra_clients.append(client)
        self._member_clients[name] = client
        return client

    def _launch_member(self, name: str) -> None:
        import tempfile

        admin_token = f"admin-{name}-{pysecrets.token_hex(8)}"
        env = dict(os.environ)
        env["KWOK_NAME"] = name
        env["KWOK_TOKEN"] = admin_token
        # The child imports the package (which touches jax): it must run
        # CPU-only and NEVER register the axon plugin — the tunneled
        # chip is single-tenant and a stray claim wedges the relay.
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        stderr = tempfile.TemporaryFile()
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubeadmiral_tpu.testing.kwokserver"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=stderr,
            text=True,
            env=env,
        )
        self.member_procs[name] = proc
        self._member_tokens[name] = admin_token
        self._member_stderr[name] = stderr

    def _await_member_url(self, name: str) -> str:
        proc = self.member_procs[name]
        # Tolerate stray stdout noise from imports: scan for the
        # protocol's JSON line instead of trusting line one.
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("{"):
                try:
                    return json.loads(line)["url"]
                except (ValueError, KeyError):
                    continue
        proc.kill()
        proc.wait()  # reap: a killed child must not linger as a zombie
        stderr = self._member_stderr.get(name)
        tail = b""
        if stderr is not None:
            try:
                stderr.seek(0)
                tail = stderr.read()[-2000:]
            except Exception:
                pass
        raise RuntimeError(
            f"kwokserver {name} died before reporting its url; "
            f"stderr tail: {tail.decode(errors='replace')!r}"
        )

    def close(self) -> None:
        for client in self._extra_clients:
            client.close()
        self.fleet.close()
        for server in self.member_servers.values():
            server.close()
        for proc in self.member_procs.values():
            try:
                proc.stdin.close()  # EOF: the child shuts itself down
            except Exception:
                pass
        for proc in self.member_procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
                proc.wait()  # reap the SIGKILL
        for stderr in self._member_stderr.values():
            try:
                stderr.close()
            except Exception:
                pass
        self.host_server.close()
