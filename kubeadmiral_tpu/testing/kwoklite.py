"""kwok-lite: a multi-cluster farm of real HTTP apiservers.

Plays the role kwokctl plays in the reference's e2e suite (reference:
test/e2e/framework/clusterprovider/kwokprovider.go:70-260): provisions a
host apiserver plus N member apiservers — real sockets, auth, watches —
without real kubelets.  Member servers mint service-account tokens (the
piece of the cluster-join handshake a bare store can't provide), and
each member gets a bootstrap join secret on the host carrying its admin
token, mirroring how a kubeadmiral operator seeds cluster credentials
before the join handshake upgrades them to a service-account token.
"""

from __future__ import annotations

import secrets as pysecrets

from kubeadmiral_tpu.testing.fakekube import FakeKube
from kubeadmiral_tpu.transport.apiserver import KubeApiServer
from kubeadmiral_tpu.transport.client import (
    FED_SYSTEM_NAMESPACE,
    SECRETS,
    HttpFleet,
    HttpKube,
)


class KwokLiteFarm:
    """Host + member apiservers on localhost ports.

    ``fleet`` exposes the ClusterFleet interface (host client + join-
    secret-derived member clients) so controllers run over it unmodified.
    """

    def __init__(self, host_token: str | None = None, host_port: int = 0):
        self.host_store = FakeKube("host")
        self.host_server = KubeApiServer(
            self.host_store, admin_token=host_token, port=host_port
        )
        self.host = HttpKube(self.host_server.url, token=host_token, name="host")
        self.fleet = HttpFleet(self.host)
        self.member_servers: dict[str, KubeApiServer] = {}
        self._extra_clients: list[HttpKube] = []

    def endpoint(self, name: str) -> str:
        return self.member_servers[name].url

    def cluster_spec(self, name: str) -> dict:
        """The FederatedCluster spec fields pointing at this member."""
        return {
            "apiEndpoint": self.endpoint(name),
            "secretRef": {"name": f"{name}-secret"},
        }

    def add_member(self, name: str) -> HttpKube:
        """Provision a member apiserver + bootstrap join secret; returns
        an admin client for test setup writes."""
        admin_token = f"admin-{name}-{pysecrets.token_hex(8)}"
        store = FakeKube(name)
        server = KubeApiServer(store, admin_token=admin_token, mint_sa_tokens=True)
        self.member_servers[name] = server
        self.host.create(
            SECRETS,
            {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {
                    "name": f"{name}-secret",
                    "namespace": FED_SYSTEM_NAMESPACE,
                },
                "data": {"token": admin_token},
            },
        )
        client = HttpKube(server.url, token=admin_token, name=name)
        self._extra_clients.append(client)
        return client

    def close(self) -> None:
        for client in self._extra_clients:
            client.close()
        self.fleet.close()
        for server in self.member_servers.values():
            server.close()
        self.host_server.close()
