"""Standalone sharded control-plane replica over the HTTP farm.

One process = one shard of the sharded control plane (ISSUE 20): it
builds the full per-FTC controller stack — federate, schedule,
override, sync, status — against the farm's HOST apiserver over real
HTTP, with every intake boundary filtered by the jump-hash ShardMap
(``KT_SHARD_COUNT``/``KT_SHARD_INDEX`` from the environment, exactly
how a production replica would be deployed).  The replica acquires its
``kt-shard-<i>`` lease before reporting ready, so N replicas own N
disjoint shards by construction.

Protocol (the kwokserver idiom): configuration via environment
(KT_REPLICA_HOST_URL, KT_REPLICA_HOST_TOKEN, KT_SHARD_COUNT,
KT_SHARD_INDEX, KT_REPLICA_FTC); one JSON line
``{"ok": true, "shard": i, ...}`` on stdout once the controllers are
watching and the lease is held; then a line-oriented command loop:

* ``report`` → one JSON line with ``settled`` (no controller progressed
  for a full idle window), per-stage cumulative step seconds, the
  replica's owned-key count and its flight-recorder reason-count hash
  (stable_json_hash over {key: reason_counts} for owned keys — the
  parent compares it against the matching SUBSET of the unsharded
  oracle's map, so reason parity never ships 100k-key payloads);
* stdin EOF → graceful exit (parent death reaps the replica without
  pid bookkeeping).

Placements need no protocol: replicas persist them into the shared
host apiserver, where the parent reads the union directly.
"""

from __future__ import annotations

import dataclasses
import json
import os
import select
import sys
import time


def _build_controllers(fleet, ftc):
    from kubeadmiral_tpu.federation.federate import FederateController
    from kubeadmiral_tpu.federation.overridectl import OverrideController
    from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
    from kubeadmiral_tpu.federation.statusctl import StatusController
    from kubeadmiral_tpu.federation.sync import SyncController

    return [
        ("federate", FederateController(fleet.host, ftc)),
        ("schedule", SchedulerController(fleet.host, ftc)),
        ("override", OverrideController(fleet.host, ftc)),
        ("sync", SyncController(fleet, ftc)),
        ("status", StatusController(fleet, ftc)),
    ]


def _reasons_hash(engine, host, resource, shard) -> tuple[str, int]:
    """stable_json_hash over {owned key: reason_counts list} from the
    replica's flight recorder (None-safe: disabled recorder → empty)."""
    from kubeadmiral_tpu.utils.hashing import stable_json_hash

    rec = getattr(engine, "flightrec", None)
    out = {}
    if rec is not None and rec.enabled:
        for key in host.keys(resource):
            if not shard.owns(key):
                continue
            r = rec.lookup(key)
            if r is not None:
                out[key] = [int(n) for n in r.reason_counts]
    return stable_json_hash(out), len(out)


def main() -> None:
    from kubeadmiral_tpu.federation import shardmap
    from kubeadmiral_tpu.models.ftc import default_ftcs
    from kubeadmiral_tpu.runtime.leaderelection import shard_elector
    from kubeadmiral_tpu.transport.client import HttpFleet, HttpKube

    shard = shardmap.reset_default()  # KT_SHARD_COUNT / KT_SHARD_INDEX
    host_url = os.environ["KT_REPLICA_HOST_URL"]
    token = os.environ.get("KT_REPLICA_HOST_TOKEN") or None
    ftc_name = os.environ.get("KT_REPLICA_FTC", "deployments.apps")

    host = HttpKube(host_url, token=token, name=f"shard-{shard.shard_index}")
    fleet = HttpFleet(host)
    ftc = next(f for f in default_ftcs() if f.name == ftc_name)
    ftc = dataclasses.replace(
        ftc,
        controllers=(
            ("kubeadmiral.io/global-scheduler",),
            ("kubeadmiral.io/overridepolicy-controller",),
        ),
    )

    # The shard lease first: a replica that reconciles before owning its
    # lease would race a not-yet-dead predecessor for the same keys.
    elector = shard_elector(
        host,
        identity=f"replica-{shard.shard_index}-{os.getpid()}",
        shard_index=shard.shard_index,
    )
    deadline = time.monotonic() + 60.0
    while not elector.try_acquire_or_renew():
        if time.monotonic() > deadline:
            print(json.dumps({"ok": False, "error": "lease acquisition timed out"}),
                  flush=True)
            return
        time.sleep(0.25)
    last_renew = time.monotonic()

    named = _build_controllers(fleet, ftc)
    stages = {name: 0.0 for name, _ in named}
    print(
        json.dumps(
            {
                "ok": True,
                "shard": shard.shard_index,
                "shard_count": shard.shard_count,
                "pid": os.getpid(),
                "leader": elector.is_leader,
            }
        ),
        flush=True,
    )

    idle = 0
    engine = dict(named)["schedule"].engine
    try:
        while True:
            progressed = False
            for name, ctl in named:
                t0 = time.perf_counter()
                stepped = True
                while stepped:
                    stepped = ctl.worker.step()
                    progressed |= stepped
                stages[name] += time.perf_counter() - t0
            idle = 0 if progressed else idle + 1
            now = time.monotonic()
            if now - last_renew > elector.lease_seconds / 3:
                elector.try_acquire_or_renew()
                last_renew = now
            # Command poll; also the idle sleep (watch events arrive on
            # reflector threads, so blocking here costs nothing).
            ready, _, _ = select.select([sys.stdin], [], [], 0.05 if not progressed else 0.0)
            if not ready:
                continue
            line = sys.stdin.readline()
            if not line:  # EOF: parent is gone or tearing down
                return
            if line.strip() != "report":
                continue
            rhash, rkeys = _reasons_hash(
                engine, host, ftc.federated.resource, shard
            )
            owned = sum(
                1 for k in host.keys(ftc.federated.resource) if shard.owns(k)
            )
            print(
                json.dumps(
                    {
                        "type": "report",
                        "shard": shard.shard_index,
                        "settled": idle >= 12,
                        "leader": elector.is_leader,
                        "stages_s": {k: round(v, 3) for k, v in stages.items()},
                        "owned_keys": owned,
                        "reasons_hash": rhash,
                        "reasons_keys": rkeys,
                    }
                ),
                flush=True,
            )
    finally:
        elector.release()
        fleet.close()


if __name__ == "__main__":
    main()
