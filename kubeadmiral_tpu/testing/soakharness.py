"""Deterministic all-stressors-at-once soak harness.

Drives the full federate → batch-schedule → sync pipeline over an
in-process fleet through a DETERMINISTIC round schedule — seeded object
arrivals and churn across tenant namespaces, periodic capacity drift,
one flapping member, one hard-down member — so two runs of the same
:class:`SoakSchedule` produce bit-identical placements regardless of
faults or a mid-run kill/failover:

* placements depend only on host-side state (federated objects, the
  FederatedCluster capacity the drift writes) and the scheduler is
  deterministic over it;
* member faults touch ONLY the write path (sheds, breaker opens, SLO
  burn) — all of which the telemetry timeline records, none of which
  feeds back into scheduling (cluster_state_from_object gates on the
  Joined condition alone; heartbeats are frozen after the initial join
  settle so drift writes are never overwritten).

Every round's world is a PURE function of (schedule, round): a restarted
control plane (bench.py --scenario soak's successor) resumes from a
fleet dump at round k and replays rounds k+1.. without any carried
generator state.

Fault-injection windows are recorded in the harness clock (the same
clock the Timeline samples with), and a window is only CLOSED after the
post-clearance recovery settle confirms the shed writes landed and the
burn-rate evaluator is green again — so "evaluator red outside a
declared window" is a genuine finding, not a recovery-lag artifact.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

from kubeadmiral_tpu.utils.hashing import stable_json_hash

GVK = "apps/v1/Deployment"


def _mix(*parts) -> int:
    """FNV-1a over the stringified parts — the deterministic seed every
    per-round decision derives from (stable across platforms/versions,
    unlike hash())."""
    h = 2166136261
    for part in parts:
        for b in str(part).encode():
            h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h


@dataclasses.dataclass(frozen=True)
class SoakSchedule:
    """The soak's deterministic script.  Window bounds are round
    numbers [start, end); kill_round is consumed by the bench scenario
    (the harness itself never kills anything)."""

    rounds: int = 10
    arrivals_per_round: int = 6
    churn_per_round: int = 4
    tenants: tuple = ("team-a", "team-b", "team-c")
    members: int = 4
    drift_every: int = 3
    flap_member_idx: int = 1
    flap_window: tuple = (2, 8)
    down_member_idx: int = 2
    down_window: tuple = (3, 7)
    kill_round: int = 5
    seed: int = 20260806

    def member_names(self) -> list[str]:
        return [f"soak-m{j}" for j in range(self.members)]

    # -- pure per-round world generation ---------------------------------
    def arrivals(self, r: int) -> list[dict]:
        """The deployments created in round r."""
        out = []
        for i in range(self.arrivals_per_round):
            tenant = self.tenants[(r + i) % len(self.tenants)]
            rnd = _mix(self.seed, "arrival", r, i)
            out.append(_make_deployment(
                tenant, f"soak-{r:03d}-{i:03d}",
                replicas=1 + rnd % 16,
                cpu_m=(rnd // 16 % 8) * 100,
            ))
        return out

    def keys_before(self, r: int) -> list[str]:
        """Every arrival key from rounds < r, in creation order."""
        keys = []
        for rr in range(r):
            for i in range(self.arrivals_per_round):
                tenant = self.tenants[(rr + i) % len(self.tenants)]
                keys.append(f"{tenant}/soak-{rr:03d}-{i:03d}")
        return keys

    def churn(self, r: int) -> list[tuple[str, int]]:
        """(key, new_replicas) updates applied in round r."""
        keys = self.keys_before(r)
        if not keys:
            return []
        out = []
        for i in range(self.churn_per_round):
            rnd = _mix(self.seed, "churn", r, i)
            out.append((keys[rnd % len(keys)], 1 + (rnd // 7) % 20))
        return out

    def drift(self, r: int) -> Optional[dict[str, float]]:
        """member name -> available-capacity fraction for round r, or
        None on non-drift rounds."""
        if self.drift_every <= 0 or r == 0 or r % self.drift_every:
            return None
        return {
            name: 0.3 + 0.6 * ((_mix(self.seed, "drift", r, name) % 100) / 100.0)
            for name in self.member_names()
        }

    def member_cpu_m(self, j: int) -> int:
        return (32 + 16 * j) * 1000

    def member_mem_gi(self, j: int) -> int:
        return 128

    def fault_state(self, r: int) -> dict[str, bool]:
        names = self.member_names()
        return {
            "flap": self.flap_window[0] <= r < self.flap_window[1],
            "down": self.down_window[0] <= r < self.down_window[1],
            "flap_member": names[self.flap_member_idx],
            "down_member": names[self.down_member_idx],
        }


def _make_deployment(namespace: str, name: str, replicas: int, cpu_m: int) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {
            "name": name,
            "namespace": namespace,
            "labels": {"kubeadmiral.io/propagation-policy-name": "pp"},
        },
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": {"app": name}},
                "spec": {
                    "containers": [
                        {
                            "name": "c",
                            "image": "nginx",
                            "resources": {"requests": {"cpu": f"{cpu_m}m"}},
                        }
                    ]
                },
            },
        },
    }


class SoakHarness:
    """One control plane running a :class:`SoakSchedule` (see module
    docstring).  Pass a restored ``fleet`` (ClusterFleet.restore of a
    prior dump) to resume as a failover successor — members, policies,
    and Joined conditions ride the dump, so the successor skips world
    construction and the join settle entirely."""

    def __init__(self, schedule: SoakSchedule, metrics=None, fleet=None,
                 clock=time.monotonic):
        from kubeadmiral_tpu.federation.federate import FederateController
        from kubeadmiral_tpu.federation.schedulerctl import SchedulerController
        from kubeadmiral_tpu.federation.sync import SyncController
        from kubeadmiral_tpu.models.ftc import default_ftcs
        from kubeadmiral_tpu.runtime.metrics import Metrics
        from kubeadmiral_tpu.testing.fakekube import ClusterFleet

        self.schedule = schedule
        self.metrics = metrics if metrics is not None else Metrics()
        self.clock = clock
        self.timeline = None  # installed via attach_timeline()
        ftc = next(f for f in default_ftcs() if f.name == "deployments.apps")
        self.ftc = dataclasses.replace(
            ftc, controllers=(("kubeadmiral.io/global-scheduler",),)
        )
        resumed = fleet is not None
        self.fleet = fleet if resumed else ClusterFleet()
        if not resumed:
            self._build_world()
        self.controllers = [
            ("federate", FederateController(self.fleet.host, self.ftc,
                                            metrics=self.metrics)),
            ("schedule", SchedulerController(self.fleet.host, self.ftc,
                                             metrics=self.metrics)),
            ("sync", SyncController(self.fleet, self.ftc,
                                    metrics=self.metrics)),
        ]
        self.scheduler = self.controllers[1][1]
        self._injector = None
        self._wrapped: dict[str, object] = {}
        # Injection windows: [{"member", "kind", "round0", "t0", "t1"}]
        # in the harness clock; t1 None = still open (or killed mid-
        # window) — the red-outside-window gate treats open as +inf.
        self.windows: list[dict] = []
        if not resumed:
            self._join_members()
        # A resumed fleet is NOT settled here: the successor wires the
        # engine snapshot restore + timeline first, and the next
        # run_round's settle drains the watch-replay resync backlog.

    # -- world construction ------------------------------------------------
    def _build_world(self) -> None:
        from kubeadmiral_tpu.federation.clusterctl import (
            FEDERATED_CLUSTERS,
            NODES,
        )
        from kubeadmiral_tpu.models.policy import PROPAGATION_POLICIES

        sched = self.schedule
        for j, name in enumerate(sched.member_names()):
            member = self.fleet.add_member(name)
            member.create(
                NODES,
                {
                    "apiVersion": "v1",
                    "kind": "Node",
                    "metadata": {"name": "n1"},
                    "spec": {},
                    "status": {
                        "allocatable": {
                            "cpu": f"{sched.member_cpu_m(j)}m",
                            "memory": f"{sched.member_mem_gi(j)}Gi",
                        },
                        "conditions": [{"type": "Ready", "status": "True"}],
                    },
                },
            )
            self.fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": {},
                },
            )
        for tenant in sched.tenants:
            self.fleet.host.create(
                PROPAGATION_POLICIES,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "PropagationPolicy",
                    "metadata": {"name": "pp", "namespace": tenant},
                    "spec": {"schedulingMode": "Divide"},
                },
            )

    def _join_members(self) -> None:
        """Join clusters via the cluster controller, then FREEZE it: its
        heartbeat would overwrite the drift-written status.resources
        with re-aggregated member state at nondeterministic times.  The
        Joined condition and the initial capacity aggregation persist on
        the host objects."""
        from kubeadmiral_tpu.federation import shardmap
        from kubeadmiral_tpu.federation.clusterctl import (
            FederatedClusterController,
        )

        # The join controller is control-plane-GLOBAL even when the
        # harness itself is built under a shard scope (the sharded
        # soak): its worker keys are raw cluster names, and a scoped
        # replica would silently join only the clusters hashing to its
        # own shard — every replica must see every cluster Joined.
        with shardmap.scoped(shardmap.ShardMap(1, 0)):
            clusterctl = FederatedClusterController(
                self.fleet, api_resource_probe=[GVK], metrics=self.metrics
            )
        for _ in range(200):
            progressed = False
            while clusterctl.worker.step():
                progressed = True
            for _, ctl in self.controllers:
                while ctl.worker.step():
                    progressed = True
            if not progressed:
                break

    # -- observatory wiring ------------------------------------------------
    def attach_timeline(self, timeline) -> None:
        """Wire the timeline's runtime providers to THIS control plane's
        SLO recorder / breaker registry and remember it for per-round
        samples."""
        from kubeadmiral_tpu.runtime import slo as slo_mod

        self.timeline = timeline
        timeline.attach_runtime(
            slo=slo_mod.get_default(),
            breakers=getattr(self.fleet, "_member_breakers", None),
        )

    # -- stepping ----------------------------------------------------------
    def settle(self, max_rounds: int = 2000) -> None:
        """Drain every controller to quiescence (the bench_e2e settle
        shape): each controller drains fully per pass; short-fuse
        requeues (admission delays) are waited out, long-fuse backoff
        requeues (a down member's retries) read as idle."""
        for _ in range(max_rounds):
            progressed = False
            for _, ctl in self.controllers:
                while ctl.worker.step():
                    progressed = True
            if not progressed:
                dues = [
                    d
                    for _, ctl in self.controllers
                    for d in (ctl.worker.queue.next_due_in(),)
                    if d is not None and d <= 0.25
                ]
                if not dues:
                    return
                time.sleep(min(dues) + 0.002)

    # -- fault transitions -------------------------------------------------
    def _apply_faults(self, r: int, faults: bool) -> None:
        from kubeadmiral_tpu.transport.faults import (
            FaultInjector,
            FaultPolicy,
            FaultyKube,
        )

        state = self.schedule.fault_state(r)
        want = {
            state["down_member"]: (
                "down", faults and state["down"], FaultPolicy(partition=True)
            ),
            state["flap_member"]: (
                "flap",
                faults and state["flap"],
                FaultPolicy(partition=True, flap_period_s=0.4, flap_duty=0.5),
            ),
        }
        for name, (kind, active, policy) in want.items():
            wrapped = name in self._wrapped
            if active and not wrapped:
                if self._injector is None:
                    self._injector = FaultInjector()
                proxy = FaultyKube(
                    self.fleet.members[name], name, self._injector,
                    timeout=0.2,
                )
                self._wrapped[name] = self.fleet.members[name]
                self.fleet.members[name] = proxy
                self._injector.set_fault(name, policy)
                self.windows.append({
                    "member": name, "kind": kind, "round0": r,
                    "t0": self.clock(), "t1": None,
                })
            elif not active and wrapped:
                self._clear_fault(name)

    def _clear_fault(self, name: str) -> None:
        self._injector.clear(name)
        proxy = self.fleet.members[name]
        self.fleet.members[name] = self._wrapped.pop(name)
        proxy.drain_stalled()
        self._recover()
        for w in self.windows:
            if w["member"] == name and w["t1"] is None:
                w["t1"] = self.clock()

    def _recover(self, deadline_s: float = 30.0) -> None:
        """Settle until shed writes landed and the evaluator is green —
        the recovery tail belongs INSIDE the injection window (the fault
        caused it), so the window stays open until here."""
        from kubeadmiral_tpu.runtime import slo as slo_mod

        rec = slo_mod.get_default()
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            self.settle()
            if rec is None or not rec.enabled:
                return
            unwritten = rec.unwritten_placements()
            status = rec.evaluate()
            if unwritten == 0 and not any(
                e.get("red") for e in status.values()
            ):
                return
            time.sleep(0.2)

    # -- the round loop ----------------------------------------------------
    def run_round(self, r: int, faults: bool = True) -> dict:
        from kubeadmiral_tpu.federation.clusterctl import FEDERATED_CLUSTERS

        sched = self.schedule
        self._apply_faults(r, faults)
        for dep in sched.arrivals(r):
            self.fleet.host.create(self.ftc.source.resource, dep)
        for key, replicas in sched.churn(r):
            obj = self.fleet.host.try_get(self.ftc.source.resource, key)
            if obj is not None:
                obj["spec"]["replicas"] = replicas
                self.fleet.host.update(self.ftc.source.resource, obj)
        drift = sched.drift(r)
        if drift:
            for j, name in enumerate(sched.member_names()):
                frac = drift[name]
                obj = self.fleet.host.get(FEDERATED_CLUSTERS, name)
                res = obj.setdefault("status", {}).setdefault("resources", {})
                res["available"] = {
                    "cpu": f"{int(sched.member_cpu_m(j) * frac)}m",
                    "memory": f"{int(sched.member_mem_gi(j) * frac)}Gi",
                }
                self.fleet.host.update_status(FEDERATED_CLUSTERS, obj)
        self.settle()
        if self.timeline is not None:
            self.timeline.sample_now()
        return {
            "round": r,
            "drift": bool(drift),
            "faults": {
                k: v for k, v in sched.fault_state(r).items()
                if isinstance(v, bool)
            } if faults else {},
        }

    def finish(self) -> None:
        """Clear any still-active fault (closing its window through the
        recovery settle) and converge the world."""
        for name in list(self._wrapped):
            self._clear_fault(name)
        self.settle()
        if self.timeline is not None:
            self.timeline.sample_now()

    # -- read side ---------------------------------------------------------
    def fingerprint(self) -> dict:
        """Bit-comparable placement state: per federated object, the
        scheduler-written placements + overrides (deterministic by
        construction; annotations/status are excluded — they may carry
        timestamps)."""
        placements = {}
        for key in sorted(self.fleet.host.keys(self.ftc.federated.resource)):
            fed = self.fleet.host.get(self.ftc.federated.resource, key)
            spec = fed.get("spec", {})
            placements[key] = {
                "placements": spec.get("placements", []),
                "overrides": spec.get("overrides", []),
            }
        return {
            "objects": len(placements),
            "hash": stable_json_hash(placements),
            "placements": placements,
        }

    def member_object_counts(self) -> dict[str, int]:
        return {
            name: len(kube.keys(self.ftc.source.resource))
            for name, kube in sorted(self.fleet.members.items())
        }
