"""Member-cluster deployment-controller simulator for rollout tests.

Plays the role a real kube-controller-manager + kubelet play in the
reference's e2e environment (and KWOK plays in its scale tests): for each
member Deployment it advances ReplicaSets step by step under the
member-local maxSurge/maxUnavailable constraints, and maintains the
observed state the rollout planner consumes —

* ``status.replicas`` / ``status.availableReplicas``
* the ``latestreplicaset.kubeadmiral.io/{name,replicas,available-replicas}``
  annotations describing the ReplicaSet of the CURRENT pod template
  (reference: pkg/controllers/util/rolloutplan.go retrieveNewReplicaSetInfo).

Pods created in one step become available in the next, so a rollout takes
multiple ticks and the federation-wide invariants are observable.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeadmiral_tpu.federation.rollout import (
    LATEST_RS_AVAILABLE,
    LATEST_RS_NAME,
    LATEST_RS_REPLICAS,
    resolve_fenceposts,
)
from kubeadmiral_tpu.testing.fakekube import ClusterFleet, Conflict, obj_key
from kubeadmiral_tpu.utils.hashing import stable_json_hash
from kubeadmiral_tpu.utils.unstructured import get_path

DEPLOYMENTS = "apps/v1/deployments"


@dataclass
class _ReplicaSet:
    replicas: int = 0
    available: int = 0


@dataclass
class _DeploymentState:
    replica_sets: dict[str, _ReplicaSet] = field(default_factory=dict)


class MemberDeploymentSimulator:
    def __init__(self, fleet: ClusterFleet, resource: str = DEPLOYMENTS):
        self.fleet = fleet
        self.resource = resource
        self._state: dict[tuple[str, str], _DeploymentState] = {}

    def _rs_name(self, dep: dict) -> str:
        tpl = get_path(dep, "spec.template", {})
        return f"{dep['metadata']['name']}-{stable_json_hash(tpl):08x}"

    def step(self) -> bool:
        """One controller round in every member; returns True when any
        deployment's observed state changed."""
        progressed = False
        for member_name, member in self.fleet.members.items():
            for key in member.keys(self.resource):
                dep = member.try_get(self.resource, key)
                if dep is None:
                    continue
                if self._step_one(member_name, dep):
                    try:
                        # Like the real deployment controller: replica-set
                        # bookkeeping annotations go through a main update
                        # (which ignores .status), observed counts through
                        # the status subresource.
                        updated = member.update(self.resource, dep)
                        dep["metadata"]["resourceVersion"] = updated[
                            "metadata"
                        ]["resourceVersion"]
                        member.update_status(self.resource, dep)
                    except Conflict:
                        pass  # raced with sync; next step retries
                    progressed = True
        return progressed

    def settle(self, max_steps: int = 100) -> None:
        for _ in range(max_steps):
            if not self.step():
                return

    # -- the simulated deployment controller ------------------------------
    def _step_one(self, member_name: str, dep: dict) -> bool:
        """Advance one deployment one round; mutates ``dep`` in place and
        returns True when its observed state changed."""
        state = self._state.setdefault(
            (member_name, obj_key(dep)), _DeploymentState()
        )
        spec_replicas = int(get_path(dep, "spec.replicas", 0) or 0)
        max_surge, max_unavailable = resolve_fenceposts(
            get_path(dep, "spec.strategy.rollingUpdate.maxSurge"),
            get_path(dep, "spec.strategy.rollingUpdate.maxUnavailable"),
            spec_replicas,
        )
        new_rs_name = self._rs_name(dep)
        sets = state.replica_sets
        new_rs = sets.setdefault(new_rs_name, _ReplicaSet())
        before = {n: (rs.replicas, rs.available) for n, rs in sets.items()}

        # 1. Pods created in earlier rounds become available.
        for rs in sets.values():
            rs.available = rs.replicas

        # 2. Scale down: old ReplicaSets drain to zero and a shrunk spec
        # reduces the new one, never dropping federation-visible
        # availability below spec - maxUnavailable.
        total_available = sum(rs.available for rs in sets.values())
        removable = max(0, total_available - (spec_replicas - max_unavailable))
        for name in [n for n in sets if n != new_rs_name]:
            rs = sets[name]
            take = min(rs.replicas, removable)
            rs.replicas -= take
            rs.available = rs.replicas
            removable -= take
        if new_rs.replicas > spec_replicas:
            take = min(new_rs.replicas - spec_replicas, removable)
            new_rs.replicas -= take
            new_rs.available = min(new_rs.available, new_rs.replicas)

        # 3. Scale up the new ReplicaSet within the surge budget; new pods
        # stay unavailable until the next round.
        total = sum(rs.replicas for rs in sets.values())
        room = spec_replicas + max_surge - total
        grow = max(0, min(room, spec_replicas - new_rs.replicas))
        new_rs.replicas += grow

        for name in list(sets):
            if name != new_rs_name and sets[name].replicas == 0:
                del sets[name]

        # 4. Publish observed state onto the deployment object.
        status = dep.setdefault("status", {})
        ann = dep["metadata"].setdefault("annotations", {})
        observed_before = (
            dict(status),
            {k: ann.get(k) for k in (LATEST_RS_NAME, LATEST_RS_REPLICAS, LATEST_RS_AVAILABLE)},
        )
        status["replicas"] = sum(rs.replicas for rs in sets.values())
        status["availableReplicas"] = sum(rs.available for rs in sets.values())
        status["updatedReplicas"] = new_rs.replicas
        ann[LATEST_RS_NAME] = new_rs_name
        ann[LATEST_RS_REPLICAS] = str(new_rs.replicas)
        ann[LATEST_RS_AVAILABLE] = str(new_rs.available)
        observed_after = (
            dict(status),
            {k: ann.get(k) for k in (LATEST_RS_NAME, LATEST_RS_REPLICAS, LATEST_RS_AVAILABLE)},
        )

        after = {n: (rs.replicas, rs.available) for n, rs in sets.items()}
        return before != after or observed_before != observed_after

    # -- observability for assertions -------------------------------------
    def total_unavailable(self, desired_total: int) -> int:
        """Federation-wide unavailability: desired total minus what is
        actually available across members."""
        avail = 0
        for member in self.fleet.members.values():
            for key in member.keys(self.resource):
                dep = member.try_get(self.resource, key)
                if dep is not None:
                    avail += int(get_path(dep, "status.availableReplicas", 0) or 0)
        return max(0, desired_total - avail)

    def total_surge(self, desired_total: int) -> int:
        total = 0
        for member in self.fleet.members.values():
            for key in member.keys(self.resource):
                dep = member.try_get(self.resource, key)
                if dep is not None:
                    total += int(get_path(dep, "status.replicas", 0) or 0)
        return max(0, total - desired_total)
