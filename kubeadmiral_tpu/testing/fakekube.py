"""In-memory apiserver with watch semantics — the test/bench cluster farm.

Plays the role KWOK clusters play in the reference's e2e suite
(reference: test/e2e/framework/clusterprovider/kwokprovider.go): a cheap
stand-in for a real apiserver that preserves the semantics the control
plane depends on — optimistic concurrency via resourceVersion, finalizer-
gated deletion with deletionTimestamp, generation bumps on spec changes,
label-selector lists, and synchronous ADDED/MODIFIED/DELETED watch events.

Objects are unstructured dicts ({apiVersion, kind, metadata, spec, ...});
resources are addressed by a plural-ish resource key like
"apps/v1/deployments" (helpers in models.ftc derive these from type
configs).

Storage is **copy-on-write**: every write replaces the stored dict with
a fresh immutable *version node* (structural sharing with the previous
node — an update that only touches metadata shares the old node's spec
and status subtrees by reference), and the store NEVER mutates a node
after it is published.  That makes three things free that used to cost
a deep copy each:

* watch fan-out hands watchers the node itself instead of a per-event
  snapshot copy (handlers must not mutate delivered objects — now
  enforced by discipline AND by the fact that later writes never touch
  the dict they were handed);
* view reads (``try_get_view``/``list_view``/``scan``) are true
  immutable snapshots — retaining one is safe, mutating one is not;
* the bulk ``batch`` verb commits a whole chunk under ONE lock pass
  (columnar commit) and delivers watchers ONE coalesced notification
  per flush, with per-op results derived from the columnar outcome.

``KT_STORE_COALESCE=0`` reverts ``batch`` to the per-op
lock/apply/notify loop — the A/B baseline whose event stream the
coalesced path must reproduce bit-identically
(tests/test_store_rewrite.py).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterable, Optional

from kubeadmiral_tpu.federation import common as C
from kubeadmiral_tpu.runtime import lockcheck, slo as _slo
from kubeadmiral_tpu.utils.unstructured import copy_json

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

Handler = Callable[[str, dict], None]


class Conflict(Exception):
    """resourceVersion mismatch on update (optimistic concurrency)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


def obj_key(obj: dict) -> str:
    meta = obj.get("metadata", {})
    ns = meta.get("namespace", "")
    return f"{ns}/{meta['name']}" if ns else meta["name"]


def split_key(key: str) -> tuple[str, str]:
    if "/" in key:
        ns, name = key.split("/", 1)
        return ns, name
    return "", key


def handler_owner(handler: Handler) -> Optional[object]:
    """The instance a handler is bound to (directly, or through a
    functools.partial of a bound method) — shared by every transport's
    unwatch_owner."""
    owner = getattr(handler, "__self__", None)
    if owner is not None:
        return owner
    return getattr(getattr(handler, "func", None), "__self__", None)


def store_coalesce() -> bool:
    """KT_STORE_COALESCE: columnar batch commits + coalesced watch
    fan-out on the in-process store (default on).  ``0`` reverts the
    bulk verb to one lock/apply/notify cycle per operation — the A/B
    baseline whose event stream coalescing must reproduce
    bit-identically."""
    return os.environ.get("KT_STORE_COALESCE", "1") not in ("0", "false", "no")


class _Watch:
    """One watch registration, with the handler's delivery capabilities
    resolved ONCE at registration instead of per event:

    * ``kt_predicate`` attribute — ``(event, obj) -> bool`` filter the
      store applies batch-wise before delivery;
    * ``kt_batch`` attribute — ``(events) -> None`` taking the ordered
      ``[(event, obj), ...]`` list of one committed flush, replacing N
      per-event calls with one coalesced notification."""

    __slots__ = ("handler", "predicate", "batch")

    def __init__(self, handler: Handler):
        self.handler = handler
        self.predicate = getattr(handler, "kt_predicate", None)
        self.batch = getattr(handler, "kt_batch", None)


class _NamedHandler:
    """functools.partial(handler, cluster) equivalent that can also
    advertise the batch-delivery protocol — ``handler_owner`` keeps
    working through ``func.__self__``."""

    __slots__ = ("func", "cluster", "kt_batch", "kt_predicate")

    def __init__(
        self,
        func: Callable,
        cluster: str,
        batch: Optional[Callable],
        predicate: Optional[Callable] = None,
    ):
        self.func = func
        self.cluster = cluster
        self.kt_predicate = predicate
        if batch is not None:
            self.kt_batch = lambda events: batch(cluster, events)
        else:
            self.kt_batch = None

    def __call__(self, event: str, obj: dict) -> None:
        self.func(self.cluster, event, obj)


class ShardIntake:
    """Watch-handler wrapper advertising the pre-delivery protocols a
    sharded (or flush-coalescing) controller intake needs — bound
    methods cannot carry ``kt_predicate``/``kt_batch`` attributes, so
    the wrapper does:

    * ``predicate`` — ``(event, obj) -> bool``, applied by the store
      batch-wise BEFORE delivery; a replica's shard filter here drops a
      non-owned event before it costs a handler call, a signature
      computation or an enqueue;
    * ``batch`` — ``(events) -> None`` coalesced-flush delivery (one
      call per committed flush instead of N per-event calls).

    ``handler_owner`` (and thus ``unwatch_owner``) keeps working
    through ``func.__self__``."""

    __slots__ = ("func", "kt_predicate", "kt_batch")

    def __init__(
        self,
        func: Callable,
        predicate: Optional[Callable] = None,
        batch: Optional[Callable] = None,
    ):
        self.func = func
        self.kt_predicate = predicate
        self.kt_batch = batch

    def __call__(self, event: str, obj: dict) -> None:
        self.func(event, obj)


_SCALARS = (str, int, float, bool, type(None))


@lockcheck.shared_field_guard
class FakeKube:
    """One apiserver (host or member cluster)."""

    # Tests flip this to simulate a failing /healthz probe.
    healthy: bool = True

    # In-process store: try_get_view point reads are lock-scoped dict
    # lookups, so O(placed) point reads beat one list scan.  Remote
    # clients (HttpKube) flip this off — there a LIST round trip beats
    # a GET per key.
    local_views = True

    # This store's watch fan-out mints SLO provenance tokens itself
    # (runtime/slo.py): informers layered on top must not double-mint.
    _slo_ingress = True

    # Producer threads (controllers, flush pools, HTTP handler threads)
    # all commit and fan out under the one store lock; _rv and the
    # container fields are only ever touched inside it (ktlint
    # lock-discipline is the static half, runtime/lockcheck.py the
    # dynamic half of the guard).
    _shared_fields_ = {
        "_objects": "_lock",
        "_watchers": "_lock",
        "_all_watchers": "_lock",
        "_rv": "_lock",
    }

    def __init__(self, name: str = "host"):
        self.name = name
        self._lock = lockcheck.make_rlock("fakekube")
        self._objects: dict[str, dict[str, dict]] = {}  # resource -> key -> node
        self._watchers: dict[str, list[_Watch]] = {}
        self._all_watchers: list[tuple[Callable, Optional[Callable]]] = []
        self._rv = 0
        self._coalesce = store_coalesce()

    # -- helpers ---------------------------------------------------------
    def _bump_locked(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _store_locked(self, resource: str) -> dict[str, dict]:
        return self._objects.setdefault(resource, {})

    # -- notify ----------------------------------------------------------
    def _notify_locked(self, resource: str, event: str, node: dict) -> None:
        """Per-event fan-out (direct verbs + the KT_STORE_COALESCE=0
        batch path): the delivered object is the immutable stored node —
        content-identical to the old per-event snapshot copy, minus the
        copy.  Handlers must not mutate delivered objects."""
        watches = list(self._watchers.get(resource, ())) + list(
            self._watchers.get("*", ())
        )
        if not watches and not self._all_watchers:
            return
        # SLO provenance: this is the single per-event point where a
        # watch event enters the in-process control plane — the birth
        # timestamp of the event→placement-written clock (runtime/slo.py;
        # untracked stores/resources early-out on one dict probe).
        _slo.ingest(self, resource, event, node)
        # One sig-memo scope per delivery: N watchers computing the
        # metadata-change trigger signature of this node hash it once.
        with C.sig_memo_scope():
            for w in watches:
                if w.predicate is not None and not w.predicate(event, node):
                    continue
                w.handler(event, node)
        for observer, _ in list(self._all_watchers):
            observer(resource, event, node, self._rv)

    def _deliver_flush_locked(self, flush: list) -> None:
        """Coalesced fan-out of one committed columnar flush
        (``[(resource, event, node, seq), ...]`` in commit order).

        Observers (the apiserver event-log feed) run FIRST over the
        whole flush in seq order: a nested write triggered mid-fan-out
        (SA token minting, a handler that writes) then appends strictly
        after this flush's lines, keeping per-resource log seqs sorted
        for watch-resume bisect.  Handlers then receive one coalesced
        notification each — the ordered per-resource event list, with
        predicates applied batch-wise and the metadata-change sig
        memoized once per object across every watcher."""
        if not flush:
            return
        observers = list(self._all_watchers)
        res_watch: dict[str, list[_Watch]] = {}
        consumers = bool(observers)
        for resource, _, _, _ in flush:
            if resource not in res_watch:
                ws = list(self._watchers.get(resource, ())) + list(
                    self._watchers.get("*", ())
                )
                res_watch[resource] = ws
                consumers = consumers or bool(ws)
        if not consumers:
            return
        # SLO token mint per event in stream order — the watch-ingress
        # stage decomposition is byte-for-byte the per-op path's.
        for resource, event, node, _ in flush:
            if res_watch[resource] or observers:
                _slo.ingest(self, resource, event, node)
        for observer, batch_all in observers:
            if batch_all is not None:
                batch_all(flush)
            else:
                for resource, event, node, seq in flush:
                    observer(resource, event, node, seq)
        with C.sig_memo_scope():
            per_res: dict[str, list] = {}
            for resource, event, node, _ in flush:
                per_res.setdefault(resource, []).append((event, node))
            for resource, events in per_res.items():
                for w in res_watch[resource]:
                    evs = events
                    if w.predicate is not None:
                        evs = [p for p in evs if w.predicate(p[0], p[1])]
                        if not evs:
                            continue
                    if w.batch is not None:
                        w.batch(evs)
                    else:
                        for event, node in evs:
                            w.handler(event, node)

    # -- copy-on-write appliers (all run under self._lock) ---------------
    def _create_locked(self, resource: str, obj: dict, adopt: bool) -> dict:
        meta_in = obj.get("metadata") or {}
        meta = copy_json(meta_in) if meta_in else {}
        name = meta["name"]
        ns = meta.get("namespace", "")
        key = f"{ns}/{name}" if ns else name
        store = self._store_locked(resource)
        if key in store:
            raise AlreadyExists(f"{resource} {key}")
        # Version-node construction: metadata is always a fresh copy
        # (the store stamps rv/uid/generation into it); other subtrees
        # are adopted by reference on the trusted bulk path (op objects
        # are fresh JSON parses over HTTP, staged-and-never-mutated
        # assemblies in process) and deep-copied for direct callers.
        node: dict = {}
        for k, v in obj.items():
            if k == "metadata":
                node[k] = meta
            else:
                node[k] = v if adopt or type(v) in _SCALARS else copy_json(v)
        if "metadata" not in node:
            node["metadata"] = meta
        meta["resourceVersion"] = self._bump_locked()
        # Like the real apiserver, only spec-bearing kinds carry a
        # generation; data-only kinds (ConfigMap, Secret) must fall
        # back to resourceVersion-based drift detection.
        if "spec" in node:
            meta.setdefault("generation", 1)
        meta.setdefault("uid", f"{self.name}-{resource}-{key}-{self._rv}")
        store[key] = node
        return node

    def _update_locked(
        self, resource: str, obj: dict, adopt: bool
    ) -> tuple[str, dict]:
        key = obj_key(obj)
        store = self._store_locked(resource)
        if key not in store:
            raise NotFound(f"{resource} {key} in {self.name}")
        old = store[key]
        old_meta = old["metadata"]
        meta_in = obj.get("metadata") or {}
        sent_rv = meta_in.get("resourceVersion")
        if sent_rv is not None and sent_rv != old_meta["resourceVersion"]:
            raise Conflict(
                f"{resource} {key}: {sent_rv} != {old_meta['resourceVersion']}"
            )
        meta = copy_json(meta_in) if meta_in else {}
        meta["uid"] = old_meta.get("uid")
        meta["resourceVersion"] = self._bump_locked()
        old_spec = old.get("spec")
        new_spec = obj.get("spec")
        spec_changed = new_spec != old_spec
        node: dict = {}
        for k, v in obj.items():
            if k == "metadata":
                node[k] = meta
            elif k == "status":
                # Status is a subresource: like a real apiserver, a
                # main-resource update ignores the request's .status and
                # keeps the stored one (only update_status writes it).
                # This is what lets sync push template updates without
                # clobbering member-owned status.
                if "status" in old:
                    node[k] = old["status"]
            elif k == "spec":
                # Structural sharing: an unchanged spec re-uses the old
                # node's subtree (the equality compare is needed for the
                # generation decision anyway), so metadata-only updates
                # cost one small metadata copy, not a whole-object one.
                if not spec_changed and "spec" in old:
                    node[k] = old_spec
                else:
                    node[k] = v if adopt or type(v) in _SCALARS else copy_json(v)
            else:
                node[k] = v if adopt or type(v) in _SCALARS else copy_json(v)
        if "metadata" not in node:
            node["metadata"] = meta
        if "status" in old and "status" not in node:
            node["status"] = old["status"]
        if "spec" in old or "spec" in obj:
            old_gen = old_meta.get("generation", 1)
            meta["generation"] = old_gen + 1 if spec_changed else old_gen
        else:
            meta.pop("generation", None)
        if old_meta.get("deletionTimestamp"):
            meta.setdefault("deletionTimestamp", old_meta["deletionTimestamp"])
            if not meta.get("finalizers"):
                del store[key]
                return DELETED, node
        store[key] = node
        return MODIFIED, node

    def _update_status_locked(
        self, resource: str, obj: dict, adopt: bool
    ) -> dict:
        key = obj_key(obj)
        store = self._store_locked(resource)
        if key not in store:
            raise NotFound(f"{resource} {key} in {self.name}")
        old = store[key]
        sent_rv = obj.get("metadata", {}).get("resourceVersion")
        if sent_rv is not None and sent_rv != old["metadata"]["resourceVersion"]:
            raise Conflict(
                f"{resource} {key}: {sent_rv} != {old['metadata']['resourceVersion']}"
            )
        # Only .status is applied: the node shares EVERY other subtree
        # with the old node (shallow copies re-point at immutable
        # children), so the hottest converged-control-plane write —
        # status feedback — costs two small dict copies.
        node = dict(old)
        node["metadata"] = dict(old["metadata"])
        node["metadata"]["resourceVersion"] = self._bump_locked()
        status_in = obj.get("status")
        node["status"] = (
            status_in
            if adopt or type(status_in) in _SCALARS
            else copy_json(status_in)
        )
        store[key] = node
        return node

    def _delete_locked(
        self, resource: str, key: str
    ) -> tuple[Optional[str], Optional[dict]]:
        store = self._store_locked(resource)
        if key not in store:
            raise NotFound(f"{resource} {key} in {self.name}")
        old = store[key]
        if old["metadata"].get("finalizers"):
            if not old["metadata"].get("deletionTimestamp"):
                # Replace, don't mutate in place: published nodes are
                # immutable (view readers and watchers hold them).
                node = dict(old)
                node["metadata"] = dict(old["metadata"])
                node["metadata"]["deletionTimestamp"] = "now"
                node["metadata"]["resourceVersion"] = self._bump_locked()
                store[key] = node
                return MODIFIED, node
            return None, None
        del store[key]
        # Like etcd, deletion advances the revision: the DELETED
        # event must carry a resourceVersion newer than any previous
        # event or watch-resume cursors would skip it.
        node = dict(old)
        node["metadata"] = dict(old["metadata"])
        node["metadata"]["resourceVersion"] = self._bump_locked()
        return DELETED, node

    def _get_locked(self, resource: str, key: str) -> dict:
        store = self._store_locked(resource)
        if key not in store:
            raise NotFound(f"{resource} {key} in {self.name}")
        return copy_json(store[key])

    # -- CRUD ------------------------------------------------------------
    def create(self, resource: str, obj: dict, _copy_result: bool = True) -> dict:
        with self._lock:
            node = self._create_locked(resource, obj, adopt=False)
            self._notify_locked(resource, ADDED, node)
            return copy_json(node) if _copy_result else node

    def get(self, resource: str, key: str) -> dict:
        with self._lock:
            return self._get_locked(resource, key)

    def try_get(self, resource: str, key: str) -> Optional[dict]:
        try:
            return self.get(resource, key)
        except NotFound:
            return None

    def try_get_view(self, resource: str, key: str) -> Optional[dict]:
        """Read WITHOUT deep-copying.  The returned dict is an immutable
        version node: retaining it is safe (later writes REPLACE the
        node, never mutate it), mutating it is not."""
        with self._lock:
            return self._store_locked(resource).get(key)

    def update(self, resource: str, obj: dict, _copy_result: bool = True) -> dict:
        """Full-object update with optimistic concurrency; removing the
        last finalizer of a deleting object completes the deletion."""
        with self._lock:
            event, node = self._update_locked(resource, obj, adopt=False)
            self._notify_locked(resource, event, node)
            return copy_json(node) if _copy_result else node

    def update_status(
        self, resource: str, obj: dict, _copy_result: bool = True
    ) -> dict:
        """Status-subresource style update: only .status is applied.
        Optimistic concurrency applies as on the main resource — without
        it, two controllers read-modify-writing different parts of the
        same status would silently lose each other's updates."""
        with self._lock:
            node = self._update_status_locked(resource, obj, adopt=False)
            self._notify_locked(resource, MODIFIED, node)
            return copy_json(node) if _copy_result else node

    def batch(self, operations: list) -> list[dict]:
        """Interface parity with HttpKube.batch: apply many operations,
        return one {"code", "object"|"status"} entry per operation, order
        preserved, each operation succeeding or failing independently.

        With coalescing on (KT_STORE_COALESCE, the default) the chunk
        commits COLUMNAR: one lock pass applies every operation, then
        watchers get one coalesced notification for the whole flush.
        Write-verb result objects are store version nodes, not copies,
        and op objects are adopted into the store by reference where
        safe — callers must not mutate op objects after submission nor
        the results they retain (over HTTP both sides are fresh JSON
        parses; in process the staged-op contract already forbids it).
        ``get`` results remain copies (they flow to general read
        consumers)."""
        if not self._coalesce:
            return self._batch_per_op(operations)
        results: list[dict] = []
        flush: list = []
        with self._lock:
            for op in operations:
                verb = op.get("verb")
                resource = op.get("resource", "")
                try:
                    if verb == "create":
                        node = self._create_locked(resource, op["object"], adopt=True)
                        flush.append((resource, ADDED, node, self._rv))
                        results.append({"code": 201, "object": node})
                    elif verb == "update":
                        event, node = self._update_locked(
                            resource, op["object"], adopt=True
                        )
                        flush.append((resource, event, node, self._rv))
                        results.append({"code": 200, "object": node})
                    elif verb == "update_status":
                        node = self._update_status_locked(
                            resource, op["object"], adopt=True
                        )
                        flush.append((resource, MODIFIED, node, self._rv))
                        results.append({"code": 200, "object": node})
                    elif verb == "delete":
                        event, node = self._delete_locked(resource, op["key"])
                        if event is not None:
                            flush.append((resource, event, node, self._rv))
                        results.append({"code": 200, "status": {"status": "Success"}})
                    elif verb == "get":
                        results.append(
                            {"code": 200, "object": self._get_locked(resource, op["key"])}
                        )
                    else:
                        results.append({"code": 400, "status": {"reason": "BadRequest", "message": f"unknown verb {verb!r}"}})
                except AlreadyExists as e:
                    results.append({"code": 409, "status": {"reason": "AlreadyExists", "message": str(e)}})
                except Conflict as e:
                    results.append({"code": 409, "status": {"reason": "Conflict", "message": str(e)}})
                except NotFound as e:
                    results.append({"code": 404, "status": {"reason": "NotFound", "message": str(e)}})
                except Exception as e:
                    results.append({"code": 400, "status": {"reason": "BadRequest", "message": str(e)}})
            self._deliver_flush_locked(flush)
        return results

    def _batch_per_op(self, operations: list) -> list[dict]:
        """KT_STORE_COALESCE=0: the per-op lock/apply/notify loop — the
        A/B baseline the columnar path must match event-for-event."""
        results = []
        for op in operations:
            verb = op.get("verb")
            resource = op.get("resource", "")
            try:
                if verb == "create":
                    results.append({"code": 201, "object": self.create(resource, op["object"], _copy_result=False)})
                elif verb == "update":
                    results.append({"code": 200, "object": self.update(resource, op["object"], _copy_result=False)})
                elif verb == "update_status":
                    results.append({"code": 200, "object": self.update_status(resource, op["object"], _copy_result=False)})
                elif verb == "delete":
                    self.delete(resource, op["key"])
                    results.append({"code": 200, "status": {"status": "Success"}})
                elif verb == "get":
                    results.append({"code": 200, "object": self.get(resource, op["key"])})
                else:
                    results.append({"code": 400, "status": {"reason": "BadRequest", "message": f"unknown verb {verb!r}"}})
            except AlreadyExists as e:
                results.append({"code": 409, "status": {"reason": "AlreadyExists", "message": str(e)}})
            except Conflict as e:
                results.append({"code": 409, "status": {"reason": "Conflict", "message": str(e)}})
            except NotFound as e:
                results.append({"code": 404, "status": {"reason": "NotFound", "message": str(e)}})
            except Exception as e:
                results.append({"code": 400, "status": {"reason": "BadRequest", "message": str(e)}})
        return results

    def delete(self, resource: str, key: str) -> None:
        with self._lock:
            event, node = self._delete_locked(resource, key)
            if event is not None:
                self._notify_locked(resource, event, node)

    def list(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        with self._lock:
            return [
                copy_json(obj)
                for obj in self.list_view(resource, namespace, label_selector)
            ]

    def list_view(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        """Like :meth:`list` but WITHOUT deep-copying — the cheap path
        for hot read-only fan-outs (cluster sets, policy matching).
        The returned dicts are immutable version nodes: retain freely,
        never mutate (the same contract as :meth:`scan`)."""
        with self._lock:
            out = []
            for obj in self._store_locked(resource).values():
                if namespace is not None:
                    if obj["metadata"].get("namespace", "") != namespace:
                        continue
                if label_selector:
                    labels = obj["metadata"].get("labels", {})
                    if any(labels.get(k) != v for k, v in label_selector.items()):
                        continue
                out.append(obj)
            return out

    def list_with_rv(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> tuple[list[dict], int]:
        """Atomic (items, resourceVersion) snapshot — what a LIST
        response needs so a subsequent watch can resume without a gap."""
        with self._lock:
            return self.list(resource, namespace, label_selector), self._rv

    def keys(self, resource: str) -> list[str]:
        with self._lock:
            return list(self._store_locked(resource))

    def scan(self, resource: str, fn: Callable[[dict], None]) -> None:
        """Read-only visit of every object WITHOUT deep-copying — the
        cheap path for large fan-out scans (e.g. policy -> bound objects).
        ``fn`` must not mutate the dicts it is handed."""
        with self._lock:
            for obj in self._store_locked(resource).values():
                fn(obj)

    # -- persistence ------------------------------------------------------
    def dump(self) -> dict:
        """JSON-serializable snapshot of the whole store (etcd's role in
        the reference: all control-plane state lives in the apiserver, so
        a controller restart resumes from LIST+WATCH alone)."""
        with self._lock:
            return {
                "name": self.name,
                "rv": self._rv,
                "objects": copy_json(self._objects),
            }

    @classmethod
    def restore(cls, snapshot: dict) -> "FakeKube":
        kube = cls(snapshot.get("name", "host"))
        with kube._lock:
            kube._rv = int(snapshot["rv"])
            kube._objects = copy_json(snapshot["objects"])
        return kube

    # -- watch -----------------------------------------------------------
    def watch(self, resource: str, handler: Handler, replay: bool = True) -> None:
        """Register a handler; with replay, existing objects are delivered
        as ADDED first (LIST+WATCH).  Handlers may advertise the batch
        protocol via a ``kt_batch`` attribute (one call per committed
        flush with the ordered event list) and a pre-delivery filter via
        ``kt_predicate`` — both resolved here, once."""
        w = _Watch(handler)
        with self._lock:
            self._watchers.setdefault(resource, []).append(w)
            if replay:
                nodes = list(self._store_locked(resource).values())
                if w.predicate is not None:
                    nodes = [n for n in nodes if w.predicate(ADDED, n)]
                if w.batch is not None:
                    if nodes:
                        w.batch([(ADDED, n) for n in nodes])
                else:
                    for node in nodes:
                        handler(ADDED, node)

    def watch_all(
        self,
        observer: Callable[[str, str, dict, int], None],
        batch: Optional[Callable[[list], None]] = None,
    ) -> None:
        """Register a cross-resource observer, called under the store
        lock as ``observer(resource, event, obj, seq)`` where ``seq`` is
        the event's resourceVersion.  ``batch``, when given, replaces
        the per-event calls for a coalesced flush with ONE
        ``batch([(resource, event, obj, seq), ...])`` call.  This is the
        apiserver's event-log feed; observers must be fast and must not
        mutate ``obj``."""
        with self._lock:
            self._all_watchers.append((observer, batch))

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def unwatch(self, resource: str, handler: Handler) -> None:
        with self._lock:
            watches = self._watchers.get(resource, [])
            for i, w in enumerate(watches):
                if w.handler == handler:
                    del watches[i]
                    break

    def unwatch_owner(self, owner: object) -> None:
        """Remove every handler owned by ``owner`` — how a dynamically
        stopped controller detaches all its watches without having
        tracked each registration."""
        with self._lock:
            for watches in self._watchers.values():
                watches[:] = [
                    w for w in watches if handler_owner(w.handler) is not owner
                ]


class ClusterFleet:
    """Host + member apiservers — the FederatedClientFactory analogue
    (reference: pkg/controllers/util/federatedclient/client.go)."""

    def __init__(self):
        self.host = FakeKube("host")
        self.members: dict[str, FakeKube] = {}

    def add_member(self, name: str) -> FakeKube:
        kube = FakeKube(name)
        self.members[name] = kube
        return kube

    def member(self, name: str) -> FakeKube:
        if name not in self.members:
            raise NotFound(f"cluster {name}")
        return self.members[name]

    def unwatch_owner(self, owner: object) -> None:
        """Detach a controller's handlers from the host and every member."""
        self.host.unwatch_owner(owner)
        for member in self.members.values():
            member.unwatch_owner(owner)

    def dump(self) -> dict:
        return {
            "host": self.host.dump(),
            "members": {n: m.dump() for n, m in self.members.items()},
        }

    @classmethod
    def restore(cls, snapshot: dict) -> "ClusterFleet":
        fleet = cls()
        fleet.host = FakeKube.restore(snapshot["host"])
        for name, member_snap in snapshot["members"].items():
            fleet.members[name] = FakeKube.restore(member_snap)
        return fleet

    def watch_members(
        self, resource: str, handler: Handler, named: bool = False,
        replay: bool = False, batch: Optional[Callable] = None,
        predicate: Optional[Callable] = None,
    ) -> Callable[[], None]:
        """Watch ``resource`` in every current member and return a
        re-attach callable for members added later — the
        FederatedInformer lifecycle (federatedinformer.go:151-250).
        With ``named``, the handler receives ``(cluster, event, obj)``;
        with ``replay``, existing objects stream through as ADDED (the
        informer's initial LIST); ``batch`` (named fleets only) is the
        coalesced-delivery variant ``(cluster, events)`` a store flushes
        one committed chunk through instead of per-event calls;
        ``predicate`` (named fleets only) is a pre-delivery
        ``(event, obj) -> bool`` filter the member store applies before
        either delivery path — a shard replica drops non-owned member
        events here, before they cost a handler call."""
        attached: set[str] = set()
        detached: set[str] = set()
        wrapped: dict[str, Handler] = {}

        def attach() -> None:
            for name, kube in list(self.members.items()):
                if name not in attached and name not in detached:
                    attached.add(name)
                    h = (
                        _NamedHandler(handler, name, batch, predicate)
                        if named
                        else handler
                    )
                    wrapped[name] = h
                    kube.watch(resource, h, replay=replay)

        def detach(name: str) -> None:
            """Tear down one cluster's watch (the FederatedInformer
            remove-cluster lifecycle, federatedinformer.go:151-250).
            Sticky: attach() skips the cluster until readmit(name) —
            the fleet keeps removed members' kube handles, so a plain
            re-attach would silently resurrect the watch."""
            attached.discard(name)
            detached.add(name)
            h = wrapped.pop(name, None)
            kube = self.members.get(name)
            if h is not None and kube is not None:
                kube.unwatch(resource, h)

        def readmit(name: str) -> None:
            """Lift a detach (the cluster's object re-appeared)."""
            detached.discard(name)

        attach.attached = attached
        attach.detach = detach
        attach.readmit = readmit
        attach()
        return attach
