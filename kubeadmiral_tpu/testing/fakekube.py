"""In-memory apiserver with watch semantics — the test/bench cluster farm.

Plays the role KWOK clusters play in the reference's e2e suite
(reference: test/e2e/framework/clusterprovider/kwokprovider.go): a cheap
stand-in for a real apiserver that preserves the semantics the control
plane depends on — optimistic concurrency via resourceVersion, finalizer-
gated deletion with deletionTimestamp, generation bumps on spec changes,
label-selector lists, and synchronous ADDED/MODIFIED/DELETED watch events.

Objects are unstructured dicts ({apiVersion, kind, metadata, spec, ...});
resources are addressed by a plural-ish resource key like
"apps/v1/deployments" (helpers in models.ftc derive these from type
configs).
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, Iterable, Optional

from kubeadmiral_tpu.runtime import slo as _slo
from kubeadmiral_tpu.utils.unstructured import copy_json

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"

Handler = Callable[[str, dict], None]


class Conflict(Exception):
    """resourceVersion mismatch on update (optimistic concurrency)."""


class NotFound(Exception):
    pass


class AlreadyExists(Exception):
    pass


def obj_key(obj: dict) -> str:
    meta = obj.get("metadata", {})
    ns = meta.get("namespace", "")
    return f"{ns}/{meta['name']}" if ns else meta["name"]


def split_key(key: str) -> tuple[str, str]:
    if "/" in key:
        ns, name = key.split("/", 1)
        return ns, name
    return "", key


def handler_owner(handler: Handler) -> Optional[object]:
    """The instance a handler is bound to (directly, or through a
    functools.partial of a bound method) — shared by every transport's
    unwatch_owner."""
    owner = getattr(handler, "__self__", None)
    if owner is not None:
        return owner
    return getattr(getattr(handler, "func", None), "__self__", None)


class FakeKube:
    """One apiserver (host or member cluster)."""

    # Tests flip this to simulate a failing /healthz probe.
    healthy: bool = True

    # This store's watch fan-out mints SLO provenance tokens itself
    # (runtime/slo.py): informers layered on top must not double-mint.
    _slo_ingress = True

    def __init__(self, name: str = "host"):
        self.name = name
        self._lock = threading.RLock()
        self._objects: dict[str, dict[str, dict]] = {}  # resource -> key -> obj
        self._watchers: dict[str, list[Handler]] = {}
        self._all_watchers: list[Callable[[str, str, dict, int], None]] = []
        self._rv = 0

    # -- helpers ---------------------------------------------------------
    def _bump(self) -> str:
        self._rv += 1
        return str(self._rv)

    def _store(self, resource: str) -> dict[str, dict]:
        return self._objects.setdefault(resource, {})

    def _notify(self, resource: str, event: str, obj: dict) -> None:
        handlers = list(self._watchers.get(resource, ())) + list(
            self._watchers.get("*", ())
        )
        if not handlers and not self._all_watchers:
            return
        # ONE snapshot shared by every handler: with a dozen controllers
        # watching, per-handler deep copies dominate the control plane's
        # host time at scale.  Handlers must not mutate delivered objects.
        snapshot = copy_json(obj)
        # SLO provenance: this is the single per-event point where a
        # watch event enters the in-process control plane — the birth
        # timestamp of the event→placement-written clock (runtime/slo.py;
        # untracked stores/resources early-out on one dict probe).
        _slo.ingest(self, resource, event, snapshot)
        for handler in handlers:
            handler(event, snapshot)
        for observer in self._all_watchers:
            observer(resource, event, snapshot, self._rv)

    # -- CRUD ------------------------------------------------------------
    def create(self, resource: str, obj: dict, _copy_result: bool = True) -> dict:
        with self._lock:
            obj = copy_json(obj)
            meta = obj.setdefault("metadata", {})
            key = obj_key(obj)
            store = self._store(resource)
            if key in store:
                raise AlreadyExists(f"{resource} {key}")
            meta["resourceVersion"] = self._bump()
            # Like the real apiserver, only spec-bearing kinds carry a
            # generation; data-only kinds (ConfigMap, Secret) must fall
            # back to resourceVersion-based drift detection.
            if "spec" in obj:
                meta.setdefault("generation", 1)
            meta.setdefault("uid", f"{self.name}-{resource}-{key}-{self._rv}")
            store[key] = obj
            self._notify(resource, ADDED, obj)
            return copy_json(obj) if _copy_result else obj

    def get(self, resource: str, key: str) -> dict:
        with self._lock:
            store = self._store(resource)
            if key not in store:
                raise NotFound(f"{resource} {key} in {self.name}")
            return copy_json(store[key])

    def try_get(self, resource: str, key: str) -> Optional[dict]:
        try:
            return self.get(resource, key)
        except NotFound:
            return None

    def try_get_view(self, resource: str, key: str) -> Optional[dict]:
        """Read WITHOUT deep-copying — for hot read-only paths.  Callers
        must not mutate the dict and must copy anything they retain
        (every store write deep-copies on entry, so short-lived aliasing
        is safe)."""
        with self._lock:
            return self._store(resource).get(key)

    def update(self, resource: str, obj: dict, _copy_result: bool = True) -> dict:
        """Full-object update with optimistic concurrency; removing the
        last finalizer of a deleting object completes the deletion."""
        with self._lock:
            obj = copy_json(obj)
            key = obj_key(obj)
            store = self._store(resource)
            if key not in store:
                raise NotFound(f"{resource} {key} in {self.name}")
            old = store[key]
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != old["metadata"]["resourceVersion"]:
                raise Conflict(f"{resource} {key}: {sent_rv} != {old['metadata']['resourceVersion']}")
            meta = obj.setdefault("metadata", {})
            meta["uid"] = old["metadata"].get("uid")
            meta["resourceVersion"] = self._bump()
            # Status is a subresource: like a real apiserver, a main-
            # resource update ignores the request's .status and keeps the
            # stored one (only update_status writes it).  This is what
            # lets sync push template updates without clobbering
            # member-owned status.
            if "status" in old:
                obj["status"] = copy_json(old["status"])
            else:
                obj.pop("status", None)
            if "spec" in old or "spec" in obj:
                old_gen = old["metadata"].get("generation", 1)
                spec_changed = obj.get("spec") != old.get("spec")
                meta["generation"] = old_gen + 1 if spec_changed else old_gen
            else:
                meta.pop("generation", None)
            if old["metadata"].get("deletionTimestamp"):
                meta.setdefault("deletionTimestamp", old["metadata"]["deletionTimestamp"])
                if not meta.get("finalizers"):
                    del store[key]
                    self._notify(resource, DELETED, obj)
                    return copy_json(obj) if _copy_result else obj
            store[key] = obj
            self._notify(resource, MODIFIED, obj)
            return copy_json(obj) if _copy_result else obj

    def update_status(
        self, resource: str, obj: dict, _copy_result: bool = True
    ) -> dict:
        """Status-subresource style update: only .status is applied.
        Optimistic concurrency applies as on the main resource — without
        it, two controllers read-modify-writing different parts of the
        same status would silently lose each other's updates."""
        with self._lock:
            key = obj_key(obj)
            store = self._store(resource)
            if key not in store:
                raise NotFound(f"{resource} {key} in {self.name}")
            old = store[key]
            sent_rv = obj.get("metadata", {}).get("resourceVersion")
            if sent_rv is not None and sent_rv != old["metadata"]["resourceVersion"]:
                raise Conflict(
                    f"{resource} {key}: {sent_rv} != {old['metadata']['resourceVersion']}"
                )
            cur = copy_json(old)
            cur["status"] = copy_json(obj.get("status"))
            cur["metadata"]["resourceVersion"] = self._bump()
            store[key] = cur
            self._notify(resource, MODIFIED, cur)
            return copy_json(cur) if _copy_result else cur

    def batch(self, operations: list) -> list[dict]:
        """Interface parity with HttpKube.batch: apply many operations,
        return one {"code", "object"|"status"} entry per operation (the
        in-process transport has no round trips to amortize, but callers
        written against the bulk protocol run unmodified).

        Write-verb result objects are store VIEWS, not copies — the bulk
        path's contract is read-only results (over HTTP they are fresh
        JSON parses; here aliasing saves a deep copy per operation on
        the control plane's hottest write path).  Callers must copy
        anything they retain and mutate.  ``get`` results remain copies
        (they flow to general read consumers)."""
        results = []
        for op in operations:
            verb = op.get("verb")
            resource = op.get("resource", "")
            try:
                if verb == "create":
                    results.append({"code": 201, "object": self.create(resource, op["object"], _copy_result=False)})
                elif verb == "update":
                    results.append({"code": 200, "object": self.update(resource, op["object"], _copy_result=False)})
                elif verb == "update_status":
                    results.append({"code": 200, "object": self.update_status(resource, op["object"], _copy_result=False)})
                elif verb == "delete":
                    self.delete(resource, op["key"])
                    results.append({"code": 200, "status": {"status": "Success"}})
                elif verb == "get":
                    results.append({"code": 200, "object": self.get(resource, op["key"])})
                else:
                    results.append({"code": 400, "status": {"reason": "BadRequest", "message": f"unknown verb {verb!r}"}})
            except AlreadyExists as e:
                results.append({"code": 409, "status": {"reason": "AlreadyExists", "message": str(e)}})
            except Conflict as e:
                results.append({"code": 409, "status": {"reason": "Conflict", "message": str(e)}})
            except NotFound as e:
                results.append({"code": 404, "status": {"reason": "NotFound", "message": str(e)}})
            except Exception as e:
                results.append({"code": 400, "status": {"reason": "BadRequest", "message": str(e)}})
        return results

    def delete(self, resource: str, key: str) -> None:
        with self._lock:
            store = self._store(resource)
            if key not in store:
                raise NotFound(f"{resource} {key} in {self.name}")
            obj = store[key]
            if obj["metadata"].get("finalizers"):
                if not obj["metadata"].get("deletionTimestamp"):
                    # Replace, don't mutate in place: view readers
                    # (try_get_view/list_view) may hold the old dict.
                    obj = copy_json(obj)
                    obj["metadata"]["deletionTimestamp"] = "now"
                    obj["metadata"]["resourceVersion"] = self._bump()
                    store[key] = obj
                    self._notify(resource, MODIFIED, obj)
                return
            del store[key]
            # Like etcd, deletion advances the revision: the DELETED
            # event must carry a resourceVersion newer than any previous
            # event or watch-resume cursors would skip it.
            obj = copy_json(obj)
            obj["metadata"]["resourceVersion"] = self._bump()
            self._notify(resource, DELETED, obj)

    def list(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        with self._lock:
            return [
                copy_json(obj)
                for obj in self.list_view(resource, namespace, label_selector)
            ]

    def list_view(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> list[dict]:
        """Like :meth:`list` but WITHOUT deep-copying — the cheap path
        for hot read-only fan-outs (cluster sets, policy matching).
        Callers must not mutate or retain the returned dicts, the same
        contract as :meth:`scan`."""
        with self._lock:
            out = []
            for obj in self._store(resource).values():
                if namespace is not None:
                    if obj["metadata"].get("namespace", "") != namespace:
                        continue
                if label_selector:
                    labels = obj["metadata"].get("labels", {})
                    if any(labels.get(k) != v for k, v in label_selector.items()):
                        continue
                out.append(obj)
            return out

    def list_with_rv(
        self,
        resource: str,
        namespace: Optional[str] = None,
        label_selector: Optional[dict[str, str]] = None,
    ) -> tuple[list[dict], int]:
        """Atomic (items, resourceVersion) snapshot — what a LIST
        response needs so a subsequent watch can resume without a gap."""
        with self._lock:
            return self.list(resource, namespace, label_selector), self._rv

    def keys(self, resource: str) -> list[str]:
        with self._lock:
            return list(self._store(resource))

    def scan(self, resource: str, fn: Callable[[dict], None]) -> None:
        """Read-only visit of every object WITHOUT deep-copying — the
        cheap path for large fan-out scans (e.g. policy -> bound objects).
        ``fn`` must not mutate or retain the dicts it is handed."""
        with self._lock:
            for obj in self._store(resource).values():
                fn(obj)

    # -- persistence ------------------------------------------------------
    def dump(self) -> dict:
        """JSON-serializable snapshot of the whole store (etcd's role in
        the reference: all control-plane state lives in the apiserver, so
        a controller restart resumes from LIST+WATCH alone)."""
        with self._lock:
            return {
                "name": self.name,
                "rv": self._rv,
                "objects": copy_json(self._objects),
            }

    @classmethod
    def restore(cls, snapshot: dict) -> "FakeKube":
        kube = cls(snapshot.get("name", "host"))
        kube._rv = int(snapshot["rv"])
        kube._objects = copy_json(snapshot["objects"])
        return kube

    # -- watch -----------------------------------------------------------
    def watch(self, resource: str, handler: Handler, replay: bool = True) -> None:
        """Register a handler; with replay, existing objects are delivered
        as ADDED first (LIST+WATCH)."""
        with self._lock:
            self._watchers.setdefault(resource, []).append(handler)
            if replay:
                for obj in self._store(resource).values():
                    handler(ADDED, copy_json(obj))

    def watch_all(
        self, observer: Callable[[str, str, dict, int], None]
    ) -> None:
        """Register a cross-resource observer, called under the store
        lock as ``observer(resource, event, obj, seq)`` where ``seq`` is
        the store's monotonic resourceVersion counter at notify time.
        This is the apiserver's event-log feed; observers must be fast
        and must not mutate ``obj``."""
        with self._lock:
            self._all_watchers.append(observer)

    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def unwatch(self, resource: str, handler: Handler) -> None:
        with self._lock:
            handlers = self._watchers.get(resource, [])
            if handler in handlers:
                handlers.remove(handler)

    def unwatch_owner(self, owner: object) -> None:
        """Remove every handler owned by ``owner`` — how a dynamically
        stopped controller detaches all its watches without having
        tracked each registration."""
        with self._lock:
            for handlers in self._watchers.values():
                handlers[:] = [
                    h for h in handlers if handler_owner(h) is not owner
                ]


class ClusterFleet:
    """Host + member apiservers — the FederatedClientFactory analogue
    (reference: pkg/controllers/util/federatedclient/client.go)."""

    def __init__(self):
        self.host = FakeKube("host")
        self.members: dict[str, FakeKube] = {}

    def add_member(self, name: str) -> FakeKube:
        kube = FakeKube(name)
        self.members[name] = kube
        return kube

    def member(self, name: str) -> FakeKube:
        if name not in self.members:
            raise NotFound(f"cluster {name}")
        return self.members[name]

    def unwatch_owner(self, owner: object) -> None:
        """Detach a controller's handlers from the host and every member."""
        self.host.unwatch_owner(owner)
        for member in self.members.values():
            member.unwatch_owner(owner)

    def dump(self) -> dict:
        return {
            "host": self.host.dump(),
            "members": {n: m.dump() for n, m in self.members.items()},
        }

    @classmethod
    def restore(cls, snapshot: dict) -> "ClusterFleet":
        fleet = cls()
        fleet.host = FakeKube.restore(snapshot["host"])
        for name, member_snap in snapshot["members"].items():
            fleet.members[name] = FakeKube.restore(member_snap)
        return fleet

    def watch_members(
        self, resource: str, handler: Handler, named: bool = False,
        replay: bool = False,
    ) -> Callable[[], None]:
        """Watch ``resource`` in every current member and return a
        re-attach callable for members added later — the
        FederatedInformer lifecycle (federatedinformer.go:151-250).
        With ``named``, the handler receives ``(cluster, event, obj)``;
        with ``replay``, existing objects stream through as ADDED (the
        informer's initial LIST)."""
        attached: set[str] = set()
        detached: set[str] = set()
        wrapped: dict[str, Handler] = {}

        def attach() -> None:
            for name, kube in list(self.members.items()):
                if name not in attached and name not in detached:
                    attached.add(name)
                    h = functools.partial(handler, name) if named else handler
                    wrapped[name] = h
                    kube.watch(resource, h, replay=replay)

        def detach(name: str) -> None:
            """Tear down one cluster's watch (the FederatedInformer
            remove-cluster lifecycle, federatedinformer.go:151-250).
            Sticky: attach() skips the cluster until readmit(name) —
            the fleet keeps removed members' kube handles, so a plain
            re-attach would silently resurrect the watch."""
            attached.discard(name)
            detached.add(name)
            h = wrapped.pop(name, None)
            kube = self.members.get(name)
            if h is not None and kube is not None:
                kube.unwatch(resource, h)

        def readmit(name: str) -> None:
            """Lift a detach (the cluster's object re-appeared)."""
            detached.discard(name)

        attach.attached = attached
        attach.detach = detach
        attach.readmit = readmit
        attach()
        return attach
