"""Standalone member-apiserver process for the kwok-lite farm.

The reference's KWOK provider spawns each fake cluster as separate
processes (reference: test/e2e/framework/clusterprovider/kwokprovider.go:70-260
via kwokctl — one apiserver + etcd per cluster).  The single-process
farm serializes every member apiserver and every controller on one GIL,
which BASELINE.md identified as the remaining HTTP-e2e ceiling; running
members here, as real subprocesses, removes that artifact from the
measurement.

Protocol: configuration arrives via environment (KWOK_NAME, KWOK_TOKEN,
KWOK_PORT); once the server is listening, one JSON line {"url": ...} is
printed to stdout; the process exits when stdin reaches EOF (the parent
holds the pipe, so farm teardown — or a parent crash — reaps the child
without pid bookkeeping).

Observability: each member carries its own Metrics registry (request
counts by verb, served at GET /metrics with the rest of the /debug
surface) — the per-instance page the manager's fleet scraper merges
into /debug/fleet — and, when KT_TELEMETRY_DIR is set, a telemetry
spiller (runtime/telespill.py) persisting the member's span ring (the
server-side halves of propagated traces) so tools/trace_assemble.py
can rebuild cross-process traces even after the member dies.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    from kubeadmiral_tpu.runtime import telespill
    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.testing.fakekube import FakeKube
    from kubeadmiral_tpu.transport.apiserver import KubeApiServer
    from kubeadmiral_tpu.transport.faults import FaultInjector

    name = os.environ.get("KWOK_NAME", "member")
    token = os.environ.get("KWOK_TOKEN") or None
    port = int(os.environ.get("KWOK_PORT", "0"))
    store = FakeKube(name)
    metrics = Metrics()
    # The child's own injector, driven over the wire by the parent's
    # farm.set_fault/clear_fault via POST /faultz — subprocess members
    # are chaos-injectable exactly like in-process ones.
    server = KubeApiServer(
        store, admin_token=token, port=port, mint_sa_tokens=True,
        fault_injector=FaultInjector(), fault_name=name,
        metrics=metrics,
    )
    spiller = telespill.TelemetrySpiller(instance=name, metrics=metrics)
    spiller.start()
    print(json.dumps({"url": server.url}), flush=True)
    try:
        sys.stdin.read()  # block until the parent closes the pipe
    finally:
        spiller.stop()  # final spill: the ring's tail outlives teardown
        server.close()


if __name__ == "__main__":
    main()
