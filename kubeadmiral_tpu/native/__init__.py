"""Native (C++) hot-path library loader.

``load()`` returns the ctypes handle to libkadmhash.so, building it with
g++ on first use when only the source is present (the toolchain path; CI
and the Makefile prebuild it with ``make native``).  Returns None when
neither a prebuilt library nor a working compiler is available — callers
fall back to the pure-Python implementations.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
SOURCES = [
    os.path.join(_DIR, "fnvhash.cpp"),
    os.path.join(_DIR, "seqsched.cpp"),
]
SOURCE = SOURCES[0]  # kept for callers that reference the hash source
LIBRARY = os.path.join(_DIR, "libkadmhash.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.kadm_fnv32.restype = ctypes.c_uint32
    lib.kadm_fnv32.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.kadm_fnv32a.restype = ctypes.c_uint32
    lib.kadm_fnv32a.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.kadm_fnv32_batch.restype = None
    lib.kadm_fnv32_batch.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_uint32),
    ]
    lib.kadm_fnv32_extend_batch.restype = None
    lib.kadm_fnv32_extend_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    u8 = ctypes.POINTER(ctypes.c_uint8)
    i32 = ctypes.POINTER(ctypes.c_int32)
    i64 = ctypes.POINTER(ctypes.c_int64)
    lib.kadm_seq_schedule_batch.restype = None
    lib.kadm_seq_schedule_batch.argtypes = (
        [ctypes.c_int32] * 3
        + [u8, u8, u8, u8, u8, u8, u8]      # filter flags + masks
        + [i64, i64, i64]                   # request, alloc, used
        + [u8, i64, i64]                    # score flags, taints, affinity
        + [i32, u8, u8, u8, i64, i32]       # maxc, mode, sticky, cur, total
        + [u8, i32, i32, i32, i32]          # weights_given..capacity
        + [u8, u8, i32, i64, i64]           # keep, avoid, tiebreak, cpu
        + [u8, i64, u8]                     # outputs
    )
    return lib


def _compile(
    sources: list[str], library: str, extra_flags: list[str], force: bool = False
) -> bool:
    """Compile ``sources`` into ``library`` when the sources are newer;
    True when a usable library is in place afterwards.  The output lands
    in a temp file first and is renamed into place, so concurrent
    builders (parallel test workers, several controller processes) never
    dlopen a half-written library.  A prebuilt library with no sources
    on disk (a packaged install) is accepted as-is."""
    present = [src for src in sources if os.path.exists(src)]
    if not force and os.path.exists(library) and (
        not present
        or all(os.path.getmtime(library) >= os.path.getmtime(src) for src in present)
    ):
        return True
    if len(present) != len(sources):
        return False  # stale/no library and sources incomplete
    tmp = f"{library}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", *extra_flags, "-o", tmp, *sources],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, library)
        return True
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def build(force: bool = False) -> bool:
    """Compile the ctypes hot-path library; True on success.  A forced
    rebuild still goes through the tmp+rename path, so a failed compile
    leaves the previous working library in place."""
    return _compile(SOURCES, LIBRARY, [], force=force)


FASTCOPY_SOURCE = os.path.join(_DIR, "fastcopy.cpp")
FASTCOPY_LIBRARY = os.path.join(_DIR, "_kadmfastcopy.so")

_fastcopy_mod = None
_fastcopy_failed = False


def load_fastcopy():
    """Build (if needed) and import the _kadmfastcopy CPython extension;
    returns its ``copy`` callable, or None when no toolchain/headers are
    available — callers fall back to the pure-Python copier."""
    global _fastcopy_mod, _fastcopy_failed
    if _fastcopy_mod is not None or _fastcopy_failed:
        return getattr(_fastcopy_mod, "copy", None)
    with _lock:
        if _fastcopy_mod is not None or _fastcopy_failed:
            return getattr(_fastcopy_mod, "copy", None)
        try:
            import sysconfig

            include = sysconfig.get_paths()["include"]
            if not _compile(
                [FASTCOPY_SOURCE], FASTCOPY_LIBRARY, [f"-I{include}"]
            ):
                _fastcopy_failed = True
                return None
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "_kadmfastcopy", FASTCOPY_LIBRARY
            )
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _fastcopy_mod = mod
        except Exception:
            _fastcopy_failed = True
            return None
    return _fastcopy_mod.copy


def load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not build():  # no-op when the library is newer than the source
            _load_failed = True
            return None
        try:
            _lib = _configure(ctypes.CDLL(LIBRARY))
        except (OSError, AttributeError):
            # AttributeError: a stale prebuilt library lacking newly
            # added symbols; degrade to the pure-Python fallbacks.
            _load_failed = True
            _lib = None
            return None
    return _lib
