// Sequential in-process scheduler: the compiled baseline for bench.py.
//
// A faithful native re-statement of the reference's per-object scheduling
// control flow (reference: pkg/controllers/scheduler/core/
// generic_scheduler.go:92-150 via framework/runtime/framework.go plugin
// loops, and pkg/controllers/util/planner/planner.go:83-366), matching
// kubeadmiral_tpu.ops.pipeline_oracle.schedule_one bit for bit — it is
// differentially tested against that oracle.  The Go toolchain is not
// available in this environment, so this C++ build (g++ -O3) stands in
// for the in-process Go scheduler when computing vs_baseline: same
// algorithm, same performance class of language.
//
// Operates on the featurized arrays a tick carries (TickInputs layout);
// per-cluster sort order uses the precomputed fnv32 tie-break values so
// no string hashing happens in the hot loop (the Go planner hashes
// cluster+key per comparison; precomputing favors the baseline).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int32_t kInf = INT32_MAX;
constexpr int64_t kNil = -1;
constexpr int64_t kMaxScore = 100;

struct Pref {
  int64_t weight = 0;
  int64_t min_replicas = 0;
  int64_t max_replicas = -1;  // -1 = unbounded
  int32_t tiebreak = 0;
};

// planner.go:62-66 order: weight desc, fnv32 tie-break asc; cluster
// index asc as the FINAL canonical key — fnv32 collisions between
// equal-weight clusters must order identically in the device kernel
// (ops/planner.py num_keys=3 sort), the Python oracle (stable sort =
// insertion/index order), and here (total order makes std::sort
// deterministic).
void sort_order(std::vector<int>& order, const std::vector<Pref>& prefs) {
  // Clamped weight in the sort key too (non-positive = no share);
  // negative-weight clusters tie with zero-weight ones everywhere.
  std::sort(order.begin(), order.end(), [&](int a, int b) {
    int64_t wa = std::max<int64_t>(prefs[a].weight, 0);
    int64_t wb = std::max<int64_t>(prefs[b].weight, 0);
    if (wa != wb) return wa > wb;
    if (prefs[a].tiebreak != prefs[b].tiebreak)
      return prefs[a].tiebreak < prefs[b].tiebreak;
    return a < b;
  });
}

// planner.go getDesiredPlan: min pass + weighted ceil rounds.
// capacity: -1 = no estimate.  Returns (plan, overflow) in `out`/`over`.
void distribute(const std::vector<int>& order, const std::vector<Pref>& prefs,
                const std::vector<int64_t>& capacity, int64_t total,
                bool keep_unschedulable, std::vector<int64_t>& out,
                std::vector<int64_t>& over) {
  int64_t remaining = total;
  for (int idx : order) {
    int64_t take = std::min(prefs[idx].min_replicas, remaining);
    int64_t cap = capacity.empty() ? -1 : capacity[idx];
    if (cap >= 0 && cap < take) {
      over[idx] = take - cap;
      take = cap;
    }
    remaining -= take;
    out[idx] = take;
  }

  // Non-positive weight = no share (the defined rule shared with the
  // device kernel and the Python oracle; a negative weight — dynamic-
  // weight residual at thousands of clusters, or a bad policy value —
  // would corrupt the ceil quotas).
  std::vector<int> active = order;
  bool moved = true;
  while (moved && remaining > 0) {
    moved = false;
    int64_t weight_sum = 0;
    for (int idx : active) weight_sum += std::max<int64_t>(prefs[idx].weight, 0);
    if (weight_sum <= 0) break;
    int64_t snapshot = remaining;
    std::vector<int> survivors;
    for (int idx : active) {
      int64_t start = out[idx];
      int64_t extra =
          (snapshot * std::max<int64_t>(prefs[idx].weight, 0) + weight_sum - 1) /
          weight_sum;
      extra = std::min(extra, remaining);
      int64_t total_n = start + extra;

      bool full = false;
      if (prefs[idx].max_replicas >= 0 && total_n > prefs[idx].max_replicas) {
        total_n = prefs[idx].max_replicas;
        full = true;
      }
      int64_t cap = capacity.empty() ? -1 : capacity[idx];
      if (cap >= 0 && total_n > cap) {
        over[idx] += total_n - cap;
        total_n = cap;
        full = true;
      }
      if (!full) survivors.push_back(idx);
      remaining -= total_n - start;
      out[idx] = total_n;
      if (total_n > start) moved = true;
    }
    active = std::move(survivors);
  }

  if (!keep_unschedulable) {
    for (size_t i = 0; i < over.size(); ++i) {
      over[i] = std::min(over[i], remaining);
      if (over[i] < 0) over[i] = 0;
    }
  }
}

struct Object {
  // Views into the batch arrays for one object (row i).
  const uint8_t *filter_enabled, *score_enabled;
  const uint8_t *api_ok, *taint_ok_new, *taint_ok_cur, *selector_ok,
      *placement_ok, *current_mask;
  uint8_t placement_has, mode_divide, sticky, weights_given,
      keep_unschedulable, avoid_disruption;
  const int64_t *request, *taint_counts, *affinity_scores, *current_replicas;
  const int32_t *weights, *min_replicas, *max_replicas, *capacity, *tiebreak;
  int32_t max_clusters, total;
};

struct World {
  int c, r;
  const int64_t *alloc, *used, *cpu_alloc, *cpu_avail;
};

bool fits(const Object& o, const World& w, int j) {
  bool any = false;
  for (int k = 0; k < w.r; ++k) any |= o.request[k] > 0;
  if (!any) return true;
  for (int k = 0; k < w.r; ++k) {
    if (k >= 2 && o.request[k] <= 0) continue;
    if (w.alloc[j * w.r + k] < o.request[k] + w.used[j * w.r + k]) return false;
  }
  return true;
}

// Smallest multiple-of-8 shift with (cap >> s) < 2^26 — the shared
// range reduction of the exact integer balanced score (ops/scores.py).
static int balanced_shift(int64_t cap) {
  int s = 0;
  for (int k = 0; k < 5; ++k)
    if (cap >= ((int64_t)1 << (26 + 8 * k))) s += 8;
  return s;
}

// Exact integer balanced-allocation score, bit-identical to the device
// kernel and the Python oracle on every backend (float forms diverge:
// axon TPUs demote f64 to f32, flipping scores at integer boundaries).
int64_t balanced_score(const Object& o, const World& w, int j) {
  int64_t ac = w.alloc[j * w.r + 0], am = w.alloc[j * w.r + 1];
  int64_t rc = w.used[j * w.r + 0] + o.request[0];
  int64_t rm = w.used[j * w.r + 1] + o.request[1];
  if (ac == 0 || am == 0 || rc >= ac || rm >= am) return 0;
  int s_cpu = balanced_shift(ac), s_mem = balanced_shift(am);
  ac >>= s_cpu; rc >>= s_cpu;
  am >>= s_mem; rm >>= s_mem;
  int64_t total = std::max<int64_t>(ac * am, 1);
  int64_t diff_num = std::llabs(rc * am - rm * ac);
  return kMaxScore * (total - diff_num) / total;
}

int64_t ratio_score(const Object& o, const World& w, int j, bool least) {
  int64_t total = 0;
  for (int k = 0; k < 2; ++k) {
    int64_t cap = w.alloc[j * w.r + k];
    int64_t req = w.used[j * w.r + k] + o.request[k];
    int64_t s;
    if (cap == 0 || req > cap)
      s = 0;
    else if (least)
      s = (cap - req) * kMaxScore / cap;
    else
      s = req * kMaxScore / cap;
    total += s;
  }
  return total / 2;
}

// framework normalize: scale to [0,100] by max, optionally reversed.
void normalize_add(std::vector<int64_t>& totals,
                   const std::vector<int>& feasible,
                   const std::vector<int64_t>& raw, bool reverse) {
  int64_t max_count = 0;
  for (int j : feasible) max_count = std::max(max_count, raw[j]);
  if (max_count == 0) {
    if (reverse)
      for (int j : feasible) totals[j] += kMaxScore;
    else
      for (int j : feasible) totals[j] += raw[j];
    return;
  }
  for (int j : feasible) {
    int64_t s = kMaxScore * raw[j] / max_count;
    totals[j] += reverse ? kMaxScore - s : s;
  }
}

// Round-half-away-from-zero of num/den for non-negative integers — the
// exact shared rule of the device kernel (ops/weights.py) and the
// Python oracle (float forms diverge on axon TPUs: f64 -> f32).
static int64_t round_half_div(int64_t num, int64_t den) {
  return (2 * num + den) / (2 * den);
}

// rsp.go CalcWeightLimit + AvailableToPercentage over the selection, in
// exact integer arithmetic (x1.4 supply limit as 1400/1000).
void dynamic_weights(const World& w, const std::vector<int>& selected,
                     std::vector<int64_t>& weights_out) {
  int n = (int)selected.size();
  int64_t alloc_sum = 0;
  for (int j : selected) alloc_sum += w.cpu_alloc[j];
  std::vector<int64_t> limit(w.c, 0);
  if (alloc_sum == 0) {
    for (int j : selected) limit[j] = round_half_div(1000, n);
  } else {
    for (int j : selected)
      limit[j] = round_half_div(w.cpu_alloc[j] * 1400, alloc_sum);
  }
  int64_t avail_sum = 0;
  for (int j : selected)
    if (w.cpu_avail[j] > 0) avail_sum += w.cpu_avail[j];
  std::vector<int64_t> tmp(w.c, 0);
  if (avail_sum == 0) {
    for (int j : selected) tmp[j] = round_half_div(1000, n);
  } else {
    for (int j : selected) {
      int64_t avail = std::max(w.cpu_avail[j], (int64_t)0);
      tmp[j] = std::min(round_half_div(avail * 1000, avail_sum), limit[j]);
    }
  }
  int64_t tmp_sum = 0;
  for (int j : selected) tmp_sum += tmp[j];
  if (tmp_sum <= 0) {
    for (int j : selected) weights_out[j] = 0;
    return;
  }
  int64_t other = 0;
  for (int j : selected) {
    int64_t wgt = round_half_div(tmp[j] * 1000, tmp_sum);
    weights_out[j] = wgt;
    other += wgt;
  }
  // Rounding residual to the max-weight cluster, first by CLUSTER INDEX
  // on ties — the canonical rule shared with ops/weights.py and the
  // python oracle (the reference's own pick is Go-map-order dependent,
  // rsp.go:248-272).  `selected` arrives score-ranked, so scan a sorted
  // copy; picking the first max in ranked order diverges from the
  // batched kernel whenever scores reorder tied-weight clusters.
  std::vector<int> by_index(selected);
  std::sort(by_index.begin(), by_index.end());
  int64_t max_w = 0;
  int max_j = -1;
  for (int j : by_index) {
    if (weights_out[j] > max_w) {
      max_w = weights_out[j];
      max_j = j;
    }
  }
  if (max_j >= 0)
    // Clamped at zero — see ops/weights.py (the round-up bias across
    // thousands of clusters can exceed the max weight).
    weights_out[max_j] = std::max<int64_t>(weights_out[max_j] + 1000 - other, 0);
}

// planner.go scaleUp: grow clusters under their desired share.
void scale_up(const std::vector<Pref>& rsp_prefs,
              const std::vector<int>& selected,
              const std::vector<int64_t>& current,
              const std::vector<int64_t>& desired, int64_t count, int c,
              std::vector<int64_t>& result) {
  std::vector<Pref> prefs(c);
  std::vector<int> order;
  for (int j : selected) {
    int64_t have = current[j], want = desired[j];
    if (want > have) {
      Pref p;
      p.weight = want - have;
      p.tiebreak = rsp_prefs[j].tiebreak;
      if (rsp_prefs[j].max_replicas >= 0)
        p.max_replicas = rsp_prefs[j].max_replicas - have;
      prefs[j] = p;
      order.push_back(j);
    }
  }
  sort_order(order, prefs);
  std::vector<int64_t> grow(c, 0), over(c, 0);
  distribute(order, prefs, {}, count, false, grow, over);
  result = current;
  for (int j : order) result[j] += grow[j];
}

// planner.go scaleDown: shrink clusters over their desired share.
void scale_down(const std::vector<Pref>& rsp_prefs,
                const std::vector<int>& selected,
                const std::vector<int64_t>& current,
                const std::vector<int64_t>& desired, int64_t count, int c,
                std::vector<int64_t>& result) {
  std::vector<Pref> prefs(c);
  std::vector<int> order;
  for (int j : selected) {
    int64_t have = current[j], want = desired[j];
    if (want < have) {
      Pref p;
      p.weight = have - want;
      p.max_replicas = have;
      p.tiebreak = rsp_prefs[j].tiebreak;
      prefs[j] = p;
      order.push_back(j);
    }
  }
  sort_order(order, prefs);
  std::vector<int64_t> shrink(c, 0), over(c, 0);
  distribute(order, prefs, {}, count, false, shrink, over);
  result = current;
  for (int j : order) result[j] -= shrink[j];
}

void schedule_one(const Object& o, const World& w, uint8_t* out_selected,
                  int64_t* out_replicas, uint8_t* out_counted) {
  const int c = w.c;
  std::memset(out_selected, 0, c);
  std::memset(out_counted, 0, c);
  for (int j = 0; j < c; ++j) out_replicas[j] = 0;

  // Sticky short-circuit (generic_scheduler.go:103-107).
  bool has_current = false;
  for (int j = 0; j < c; ++j) has_current |= o.current_mask[j] != 0;
  if (o.sticky && has_current) {
    for (int j = 0; j < c; ++j) {
      if (!o.current_mask[j]) continue;
      out_selected[j] = 1;
      out_replicas[j] = o.current_replicas[j];
      out_counted[j] = o.current_replicas[j] != kNil;
    }
    return;
  }

  // Filter.
  std::vector<int> feasible;
  feasible.reserve(c);
  for (int j = 0; j < c; ++j) {
    bool ok = true;
    if (o.filter_enabled[0]) ok &= o.api_ok[j] != 0;
    if (o.filter_enabled[1])
      ok &= (o.current_mask[j] ? o.taint_ok_cur[j] : o.taint_ok_new[j]) != 0;
    if (ok && o.filter_enabled[2]) ok &= fits(o, w, j);
    if (o.filter_enabled[3] && o.placement_has) ok &= o.placement_ok[j] != 0;
    if (o.filter_enabled[4]) ok &= o.selector_ok[j] != 0;
    if (ok) feasible.push_back(j);
  }
  if (feasible.empty()) return;

  // Score + normalize + sum.
  std::vector<int64_t> totals(c, 0), raw(c, 0);
  if (o.score_enabled[0]) {
    for (int j : feasible) raw[j] = o.taint_counts[j];
    normalize_add(totals, feasible, raw, true);
  }
  if (o.score_enabled[1])
    for (int j : feasible) totals[j] += balanced_score(o, w, j);
  if (o.score_enabled[2])
    for (int j : feasible) totals[j] += ratio_score(o, w, j, true);
  if (o.score_enabled[3]) {
    for (int j : feasible) raw[j] = o.affinity_scores[j];
    normalize_add(totals, feasible, raw, false);
  }
  if (o.score_enabled[4])
    for (int j : feasible) totals[j] += ratio_score(o, w, j, false);

  // Select: top-K by (score desc, index asc).
  if (o.max_clusters < 0 && o.max_clusters != kInf) return;
  std::vector<int> ranked = feasible;
  std::stable_sort(ranked.begin(), ranked.end(), [&](int a, int b) {
    if (totals[a] != totals[b]) return totals[a] > totals[b];
    return a < b;
  });
  size_t k = ranked.size();
  if (o.max_clusters != kInf) k = std::min(k, (size_t)o.max_clusters);
  std::vector<int> selected(ranked.begin(), ranked.begin() + k);

  if (!o.mode_divide) {
    for (int j : selected) {
      out_selected[j] = 1;
      out_replicas[j] = kNil;
    }
    return;
  }

  // Replicas: the planner (planner.go:83-177).
  std::vector<int64_t> weights(c, 0);
  if (o.weights_given) {
    for (int j : selected) weights[j] = o.weights[j];
  } else {
    dynamic_weights(w, selected, weights);
  }
  std::vector<Pref> prefs(c);
  for (int j : selected) {
    prefs[j].weight = weights[j];
    prefs[j].min_replicas = o.min_replicas[j];
    prefs[j].max_replicas = o.max_replicas[j] == kInf ? -1 : o.max_replicas[j];
    prefs[j].tiebreak = o.tiebreak[j];
  }
  std::vector<int64_t> capacity(c, -1);
  for (int j = 0; j < c; ++j)
    if (o.capacity[j] != kInf) capacity[j] = o.capacity[j];

  std::vector<int> order = selected;
  sort_order(order, prefs);

  bool keep = o.keep_unschedulable || !o.avoid_disruption;
  std::vector<int64_t> desired(c, 0), overflow(c, 0);
  distribute(order, prefs, capacity, o.total, keep, desired, overflow);

  std::vector<int64_t> plan_out;
  if (!o.avoid_disruption) {
    plan_out = desired;
  } else {
    std::vector<int64_t> current(c, 0);
    int64_t cur_total = 0, want_total = 0;
    for (int j : order) {
      int64_t reps =
          o.current_mask[j]
              ? (o.current_replicas[j] == kNil ? o.total : o.current_replicas[j])
              : 0;
      if (capacity[j] >= 0) reps = std::min(reps, capacity[j]);
      current[j] = reps;
      cur_total += reps;
      want_total += desired[j];
    }
    if (cur_total == want_total) {
      plan_out = current;
    } else if (cur_total > want_total) {
      scale_down(prefs, order, current, desired, cur_total - want_total, c,
                 plan_out);
    } else {
      scale_up(prefs, order, current, desired, want_total - cur_total, c,
               plan_out);
    }
  }

  // Merge plan + overflow, drop zero entries (rsp.go:158-177).
  for (int j : selected) {
    int64_t reps = plan_out[j] + overflow[j];
    if (reps != 0) {
      out_selected[j] = 1;
      out_replicas[j] = reps;
      out_counted[j] = 1;
    }
  }
}

}  // namespace

extern "C" {

void kadm_seq_schedule_batch(
    int32_t b, int32_t c, int32_t r, const uint8_t* filter_enabled,
    const uint8_t* api_ok, const uint8_t* taint_ok_new,
    const uint8_t* taint_ok_cur, const uint8_t* selector_ok,
    const uint8_t* placement_has, const uint8_t* placement_ok,
    const int64_t* request, const int64_t* alloc, const int64_t* used,
    const uint8_t* score_enabled, const int64_t* taint_counts,
    const int64_t* affinity_scores, const int32_t* max_clusters,
    const uint8_t* mode_divide, const uint8_t* sticky,
    const uint8_t* current_mask, const int64_t* current_replicas,
    const int32_t* total, const uint8_t* weights_given, const int32_t* weights,
    const int32_t* min_replicas, const int32_t* max_replicas,
    const int32_t* capacity, const uint8_t* keep_unschedulable,
    const uint8_t* avoid_disruption, const int32_t* tiebreak,
    const int64_t* cpu_alloc, const int64_t* cpu_avail, uint8_t* out_selected,
    int64_t* out_replicas, uint8_t* out_counted) {
  World w{c, r, alloc, used, cpu_alloc, cpu_avail};
  for (int32_t i = 0; i < b; ++i) {
    Object o;
    o.filter_enabled = filter_enabled + i * 5;
    o.score_enabled = score_enabled + i * 5;
    o.api_ok = api_ok + (size_t)i * c;
    o.taint_ok_new = taint_ok_new + (size_t)i * c;
    o.taint_ok_cur = taint_ok_cur + (size_t)i * c;
    o.selector_ok = selector_ok + (size_t)i * c;
    o.placement_ok = placement_ok + (size_t)i * c;
    o.current_mask = current_mask + (size_t)i * c;
    o.placement_has = placement_has[i];
    o.mode_divide = mode_divide[i];
    o.sticky = sticky[i];
    o.weights_given = weights_given[i];
    o.keep_unschedulable = keep_unschedulable[i];
    o.avoid_disruption = avoid_disruption[i];
    o.request = request + (size_t)i * r;
    o.taint_counts = taint_counts + (size_t)i * c;
    o.affinity_scores = affinity_scores + (size_t)i * c;
    o.current_replicas = current_replicas + (size_t)i * c;
    o.weights = weights + (size_t)i * c;
    o.min_replicas = min_replicas + (size_t)i * c;
    o.max_replicas = max_replicas + (size_t)i * c;
    o.capacity = capacity + (size_t)i * c;
    o.tiebreak = tiebreak + (size_t)i * c;
    o.max_clusters = max_clusters[i];
    o.total = total[i];
    schedule_one(o, w, out_selected + (size_t)i * c,
                 out_replicas + (size_t)i * c, out_counted + (size_t)i * c);
  }
}

}  // extern "C"
