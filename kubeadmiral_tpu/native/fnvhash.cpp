// Native hot-path hashing for the host-side control plane.
//
// The schedulers' dedupe gate hashes a canonical JSON trigger per
// federated object every reconcile tick (reference:
// pkg/controllers/scheduler/schedulingtriggers.go:106-148 uses Go's
// hash/fnv), and the replica planner tie-breaks clusters with FNV-1 over
// cluster+key pairs (reference: pkg/controllers/util/planner/
// planner.go:184-198).  At the 100k-object scale those byte loops are
// the control plane's hottest host-side code; this library provides the
// exact Go-compatible bit patterns at native speed, loaded via ctypes
// with a pure-Python fallback (kubeadmiral_tpu/utils/hashing.py).
//
// Build: make native (g++ -O3 -shared -fPIC).

#include <cstddef>
#include <cstdint>

namespace {
constexpr uint32_t kOffset = 2166136261u;
constexpr uint32_t kPrime = 16777619u;
}  // namespace

extern "C" {

// FNV-1 32-bit (multiply, then xor) — Go fnv.New32().
uint32_t kadm_fnv32(const uint8_t* data, size_t len) {
  uint32_t h = kOffset;
  for (size_t i = 0; i < len; ++i) {
    h = (h * kPrime) ^ data[i];
  }
  return h;
}

// FNV-1a 32-bit (xor, then multiply) — Go fnv.New32a().
uint32_t kadm_fnv32a(const uint8_t* data, size_t len) {
  uint32_t h = kOffset;
  for (size_t i = 0; i < len; ++i) {
    h = (h ^ data[i]) * kPrime;
  }
  return h;
}

// FNV-1 of prefixes[i] + suffix for n prefixes packed back to back in
// buf; offsets has n+1 entries delimiting each prefix.
void kadm_fnv32_batch(const uint8_t* buf, const uint64_t* offsets, size_t n,
                      const uint8_t* suffix, size_t suffix_len,
                      uint32_t* out) {
  for (size_t i = 0; i < n; ++i) {
    uint32_t h = kOffset;
    for (uint64_t j = offsets[i]; j < offsets[i + 1]; ++j) {
      h = (h * kPrime) ^ buf[j];
    }
    for (size_t j = 0; j < suffix_len; ++j) {
      h = (h * kPrime) ^ suffix[j];
    }
    out[i] = h;
  }
}

// Continue n FNV-1 states over the same extra bytes (streaming property:
// fnv32(a+b) == extend(fnv32(a), b)); states are updated in place.
void kadm_fnv32_extend_batch(uint32_t* states, size_t n, const uint8_t* data,
                             size_t len) {
  for (size_t i = 0; i < n; ++i) {
    uint32_t h = states[i];
    for (size_t j = 0; j < len; ++j) {
      h = (h * kPrime) ^ data[j];
    }
    states[i] = h;
  }
}

}  // extern "C"
