// C-accelerated deep copy for JSON-shaped control-plane objects.
//
// The in-process store (testing/fakekube.py) and the transport layer
// copy objects on every create/update/get/watch-notify — the analogue
// of a real apiserver's serialization boundary.  At e2e-bench scale the
// pure-Python recursion in utils/unstructured.copy_json is the single
// hottest function in the whole control plane (half the profile), so
// the same recursion is provided here as a CPython extension module.
//
// Semantics match _copy_json_fast exactly: dict/list/tuple copied
// element-wise, str/int/float/bool/None shared (immutable), dict keys
// shared, any other node raises TypeError and the Python wrapper falls
// back to copy.deepcopy for the whole call.
#define PY_SSIZE_T_CLEAN
#include <Python.h>

static PyObject *copy_obj(PyObject *obj) {
    if (obj == Py_None || PyBool_Check(obj) || PyUnicode_CheckExact(obj) ||
        PyLong_CheckExact(obj) || PyFloat_CheckExact(obj)) {
        Py_INCREF(obj);
        return obj;
    }
    if (PyDict_CheckExact(obj)) {
        PyObject *out = PyDict_New();
        if (!out) return NULL;
        if (Py_EnterRecursiveCall(" in kadm fastcopy")) {
            Py_DECREF(out);
            return NULL;
        }
        Py_ssize_t pos = 0;
        PyObject *k, *v;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            PyObject *cv = copy_obj(v);
            if (!cv || PyDict_SetItem(out, k, cv) < 0) {
                Py_XDECREF(cv);
                Py_DECREF(out);
                Py_LeaveRecursiveCall();
                return NULL;
            }
            Py_DECREF(cv);
        }
        Py_LeaveRecursiveCall();
        return out;
    }
    if (PyList_CheckExact(obj)) {
        Py_ssize_t n = PyList_GET_SIZE(obj);
        PyObject *out = PyList_New(n);
        if (!out) return NULL;
        if (Py_EnterRecursiveCall(" in kadm fastcopy")) {
            Py_DECREF(out);
            return NULL;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *cv = copy_obj(PyList_GET_ITEM(obj, i));
            if (!cv) {
                Py_DECREF(out);
                Py_LeaveRecursiveCall();
                return NULL;
            }
            PyList_SET_ITEM(out, i, cv);
        }
        Py_LeaveRecursiveCall();
        return out;
    }
    if (PyTuple_CheckExact(obj)) {
        Py_ssize_t n = PyTuple_GET_SIZE(obj);
        PyObject *out = PyTuple_New(n);
        if (!out) return NULL;
        if (Py_EnterRecursiveCall(" in kadm fastcopy")) {
            Py_DECREF(out);
            return NULL;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *cv = copy_obj(PyTuple_GET_ITEM(obj, i));
            if (!cv) {
                Py_DECREF(out);
                Py_LeaveRecursiveCall();
                return NULL;
            }
            PyTuple_SET_ITEM(out, i, cv);
        }
        Py_LeaveRecursiveCall();
        return out;
    }
    PyErr_Format(PyExc_TypeError, "non-JSON node of type %s",
                 Py_TYPE(obj)->tp_name);
    return NULL;
}

static PyObject *fastcopy(PyObject *self, PyObject *arg) {
    (void)self;
    return copy_obj(arg);
}

static PyMethodDef methods[] = {
    {"copy", fastcopy, METH_O,
     "Deep copy a JSON-shaped object (dict/list/tuple/str/int/float/bool/None)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_kadmfastcopy",
    "C deep copy for JSON-shaped objects", -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__kadmfastcopy(void) { return PyModule_Create(&moduledef); }
