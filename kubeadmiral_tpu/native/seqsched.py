"""ctypes wrapper for the native sequential scheduler baseline.

``seq_schedule_batch`` runs the C++ per-object scheduling loop
(native/seqsched.cpp — the compiled stand-in for the reference's
in-process Go scheduler) over a featurized batch, returning
(selected, replicas, counted) arrays shaped like TickOutputs.  Returns
None when no native toolchain/library is available.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from kubeadmiral_tpu import native


def _ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def prepare(inp) -> tuple:
    """Dtype/layout conversion of a TickInputs-like namedtuple into the
    C ABI's arrays — separated from :func:`run` so benchmarks can keep
    marshalling out of the timed region (the Go scheduler operates on
    its own in-memory structs; charging the baseline for numpy
    conversions would inflate vs_baseline)."""

    def u8(x):
        return np.ascontiguousarray(np.asarray(x).astype(np.uint8))

    def i32(x):
        return np.ascontiguousarray(np.asarray(x), dtype=np.int32)

    def i64(x):
        return np.ascontiguousarray(np.asarray(x), dtype=np.int64)

    api_ok = u8(inp.api_ok)
    b, c = api_ok.shape
    request = i64(inp.request)
    r = request.shape[1]

    args = [
        u8(inp.filter_enabled),
        api_ok,
        u8(inp.taint_ok_new),
        u8(inp.taint_ok_cur),
        u8(inp.selector_ok),
        u8(inp.placement_has),
        u8(inp.placement_ok),
        request,
        i64(inp.alloc),
        i64(inp.used),
        u8(inp.score_enabled),
        i64(inp.taint_counts),
        i64(inp.affinity_scores),
        i32(inp.max_clusters),
        u8(inp.mode_divide),
        u8(inp.sticky),
        u8(inp.current_mask),
        i64(inp.current_replicas),
        i32(inp.total),
        u8(inp.weights_given),
        i32(inp.weights),
        i32(inp.min_replicas),
        i32(inp.max_replicas),
        i32(inp.capacity),
        u8(inp.keep_unschedulable),
        u8(inp.avoid_disruption),
        i32(inp.tiebreak),
        i64(inp.cpu_alloc),
        i64(inp.cpu_avail),
    ]
    return b, c, r, args


def run(prepared) -> Optional[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Run the C++ scheduling loop on :func:`prepare`'s output."""
    lib = native.load()
    if lib is None:
        return None
    b, c, r, args = prepared
    out_selected = np.zeros((b, c), np.uint8)
    out_replicas = np.zeros((b, c), np.int64)
    out_counted = np.zeros((b, c), np.uint8)

    ctype_for = {np.uint8: ctypes.c_uint8, np.int32: ctypes.c_int32,
                 np.int64: ctypes.c_int64}
    lib.kadm_seq_schedule_batch(
        b,
        c,
        r,
        *[_ptr(a, ctype_for[a.dtype.type]) for a in args],
        _ptr(out_selected, ctypes.c_uint8),
        _ptr(out_replicas, ctypes.c_int64),
        _ptr(out_counted, ctypes.c_uint8),
    )
    return out_selected, out_replicas, out_counted


def seq_schedule_batch(
    inp,
) -> Optional[tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """inp: a TickInputs-like namedtuple of (numpy-convertible) arrays."""
    if native.load() is None:
        return None
    return run(prepare(inp))
