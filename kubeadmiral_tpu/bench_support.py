"""Sequential one-object-at-a-time scheduling, used as the bench baseline.

This walks the same logical pipeline as the reference's in-process
scheduler (reference: pkg/controllers/scheduler/core/generic_scheduler.go
via framework/runtime/framework.go plugin loops): for each object, match
every cluster through the filter plugins, score, select and plan — no
batching, no dedup, no device.  bench.py measures it on a sample to set
``vs_baseline``.
"""

from __future__ import annotations

from typing import Sequence

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops.pipeline_oracle import OracleProblem, schedule_one
from kubeadmiral_tpu.utils import labels as L


def _canonical_row(res: dict, scalars: Sequence[str]) -> list[int]:
    return [res.get("cpu", 0), res.get("memory", 0)] + [
        res.get(s, 0) for s in scalars
    ]


def sequential_schedule(
    units: Sequence[T.SchedulingUnit], clusters: Sequence[T.ClusterState]
) -> list[dict[int, "int | None"]]:
    scalars = sorted(
        {
            r
            for su in units
            for r in su.resource_request
            if r not in ("cpu", "memory", "ephemeral-storage")
        }
    )
    names = [c.name for c in clusters]
    index = {n: j for j, n in enumerate(names)}
    alloc = [_canonical_row(c.allocatable, scalars) for c in clusters]
    avail = [_canonical_row(c.available, scalars) for c in clusters]
    used = [[a - v for a, v in zip(ar, vr)] for ar, vr in zip(alloc, avail)]
    cpu_alloc = [-(-c.allocatable.get("cpu", 0) // 1000) for c in clusters]
    cpu_avail = [-(-c.available.get("cpu", 0) // 1000) for c in clusters]

    results = []
    for su in units:
        filters = su.enabled_filters if su.enabled_filters is not None else T.DEFAULT_FILTERS
        scores = su.enabled_scores if su.enabled_scores is not None else T.DEFAULT_SCORES
        filter_enabled = [
            T.APIRESOURCES in filters,
            T.TAINT_TOLERATION in filters,
            T.CLUSTER_RESOURCES_FIT in filters,
            T.PLACEMENT_FILTER in filters,
            T.CLUSTER_AFFINITY in filters,
        ]
        score_enabled = [
            T.TAINT_TOLERATION in scores,
            T.CLUSTER_RESOURCES_BALANCED in scores,
            T.CLUSTER_RESOURCES_LEAST in scores,
            T.CLUSTER_AFFINITY in scores,
            T.CLUSTER_RESOURCES_MOST in scores,
        ]

        def tolerated(cl: T.ClusterState, effects) -> bool:
            for taint in cl.taints:
                if taint.effect in effects and not any(
                    t.tolerates(taint) for t in su.tolerations
                ):
                    return False
            return True

        prefer_tols = [
            t
            for t in su.tolerations
            if not t.effect or t.effect == T.PREFER_NO_SCHEDULE
        ]
        capacity = {}
        keep = False
        if su.auto_migration is not None:
            keep = su.auto_migration.keep_unschedulable_replicas
            for cname, cap in su.auto_migration.estimated_capacity.items():
                if cname in index and cap >= 0:
                    capacity[index[cname]] = cap

        problem = OracleProblem(
            n_clusters=len(clusters),
            filter_enabled=filter_enabled,
            score_enabled=score_enabled,
            api_ok=[su.gvk in c.api_resources for c in clusters],
            taint_ok_new=[
                tolerated(c, (T.NO_SCHEDULE, T.NO_EXECUTE)) for c in clusters
            ],
            taint_ok_cur=[tolerated(c, (T.NO_EXECUTE,)) for c in clusters],
            selector_ok=[
                L.cluster_feasible(c.labels, c.name, su.cluster_selector, su.affinity)
                for c in clusters
            ],
            placement_ok=[c.name in su.cluster_names for c in clusters],
            placement_has=len(su.cluster_names) > 0,
            request=_canonical_row(su.resource_request, scalars),
            alloc=alloc,
            used=used,
            taint_counts=[
                sum(
                    1
                    for taint in c.taints
                    if taint.effect == T.PREFER_NO_SCHEDULE
                    and not any(t.tolerates(taint) for t in prefer_tols)
                )
                for c in clusters
            ],
            affinity_scores=[
                L.preferred_score(c.labels, c.name, su.affinity) for c in clusters
            ],
            max_clusters=su.max_clusters,
            mode_divide=su.scheduling_mode == T.MODE_DIVIDE,
            sticky=su.sticky_cluster,
            current={
                index[n]: reps
                for n, reps in su.current_clusters.items()
                if n in index
            },
            total=su.desired_replicas or 0,
            weights={index[n]: w for n, w in su.weights.items() if n in index}
            if su.weights
            else None,
            min_replicas={
                index[n]: v for n, v in su.min_replicas.items() if n in index
            },
            max_replicas={
                index[n]: v for n, v in su.max_replicas.items() if n in index
            },
            capacity=capacity,
            keep_unschedulable=keep,
            avoid_disruption=su.avoid_disruption,
            cluster_names=names,
            key=su.key,
            cpu_alloc=cpu_alloc,
            cpu_avail=cpu_avail,
        )
        results.append(schedule_one(problem))
    return results
