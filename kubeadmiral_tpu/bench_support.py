"""Sequential one-object-at-a-time scheduling, used as the bench baseline.

This walks the same logical pipeline as the reference's in-process
scheduler (reference: pkg/controllers/scheduler/core/generic_scheduler.go
via framework/runtime/framework.go plugin loops): for each object, match
every cluster through the filter plugins, score, select and plan — no
batching, no dedup, no device.  bench.py measures it on a sample to set
``vs_baseline``.
"""

from __future__ import annotations

from typing import Sequence

from kubeadmiral_tpu.models import types as T
from kubeadmiral_tpu.ops.pipeline_oracle import OracleProblem, schedule_one
from kubeadmiral_tpu.utils import labels as L


def _canonical_row(res: dict, scalars: Sequence[str]) -> list[int]:
    return [res.get("cpu", 0), res.get("memory", 0)] + [
        res.get(s, 0) for s in scalars
    ]


def sequential_schedule(
    units: Sequence[T.SchedulingUnit], clusters: Sequence[T.ClusterState]
) -> list[dict[int, "int | None"]]:
    scalars = sorted(
        {
            r
            for su in units
            for r in su.resource_request
            if r not in ("cpu", "memory", "ephemeral-storage")
        }
    )
    names = [c.name for c in clusters]
    index = {n: j for j, n in enumerate(names)}
    alloc = [_canonical_row(c.allocatable, scalars) for c in clusters]
    avail = [_canonical_row(c.available, scalars) for c in clusters]
    used = [[a - v for a, v in zip(ar, vr)] for ar, vr in zip(alloc, avail)]
    cpu_alloc = [-(-c.allocatable.get("cpu", 0) // 1000) for c in clusters]
    cpu_avail = [-(-c.available.get("cpu", 0) // 1000) for c in clusters]

    results = []
    for su in units:
        filters = su.enabled_filters if su.enabled_filters is not None else T.DEFAULT_FILTERS
        scores = su.enabled_scores if su.enabled_scores is not None else T.DEFAULT_SCORES
        filter_enabled = [
            T.APIRESOURCES in filters,
            T.TAINT_TOLERATION in filters,
            T.CLUSTER_RESOURCES_FIT in filters,
            T.PLACEMENT_FILTER in filters,
            T.CLUSTER_AFFINITY in filters,
        ]
        score_enabled = [
            T.TAINT_TOLERATION in scores,
            T.CLUSTER_RESOURCES_BALANCED in scores,
            T.CLUSTER_RESOURCES_LEAST in scores,
            T.CLUSTER_AFFINITY in scores,
            T.CLUSTER_RESOURCES_MOST in scores,
        ]

        def tolerated(cl: T.ClusterState, effects) -> bool:
            for taint in cl.taints:
                if taint.effect in effects and not any(
                    t.tolerates(taint) for t in su.tolerations
                ):
                    return False
            return True

        prefer_tols = [
            t
            for t in su.tolerations
            if not t.effect or t.effect == T.PREFER_NO_SCHEDULE
        ]
        capacity = {}
        keep = False
        if su.auto_migration is not None:
            keep = su.auto_migration.keep_unschedulable_replicas
            for cname, cap in su.auto_migration.estimated_capacity.items():
                if cname in index and cap >= 0:
                    capacity[index[cname]] = cap

        problem = OracleProblem(
            n_clusters=len(clusters),
            filter_enabled=filter_enabled,
            score_enabled=score_enabled,
            api_ok=[su.gvk in c.api_resources for c in clusters],
            taint_ok_new=[
                tolerated(c, (T.NO_SCHEDULE, T.NO_EXECUTE)) for c in clusters
            ],
            taint_ok_cur=[tolerated(c, (T.NO_EXECUTE,)) for c in clusters],
            selector_ok=[
                L.cluster_feasible(c.labels, c.name, su.cluster_selector, su.affinity)
                for c in clusters
            ],
            placement_ok=[c.name in su.cluster_names for c in clusters],
            placement_has=len(su.cluster_names) > 0,
            request=_canonical_row(su.resource_request, scalars),
            alloc=alloc,
            used=used,
            taint_counts=[
                sum(
                    1
                    for taint in c.taints
                    if taint.effect == T.PREFER_NO_SCHEDULE
                    and not any(t.tolerates(taint) for t in prefer_tols)
                )
                for c in clusters
            ],
            affinity_scores=[
                L.preferred_score(c.labels, c.name, su.affinity) for c in clusters
            ],
            max_clusters=su.max_clusters,
            mode_divide=su.scheduling_mode == T.MODE_DIVIDE,
            sticky=su.sticky_cluster,
            current={
                index[n]: reps
                for n, reps in su.current_clusters.items()
                if n in index
            },
            total=su.desired_replicas or 0,
            weights={index[n]: w for n, w in su.weights.items() if n in index}
            if su.weights
            else None,
            min_replicas={
                index[n]: v for n, v in su.min_replicas.items() if n in index
            },
            max_replicas={
                index[n]: v for n, v in su.max_replicas.items() if n in index
            },
            capacity=capacity,
            keep_unschedulable=keep,
            avoid_disruption=su.avoid_disruption,
            cluster_names=names,
            key=su.key,
            cpu_alloc=cpu_alloc,
            cpu_avail=cpu_avail,
        )
        results.append(schedule_one(problem))
    return results


# -- bench platform resilience (shared by bench.py / bench_e2e.py) ------
# The round-3 lesson: a wedged TPU relay zeroed the round's evidence.
# Probe the chip from a sacrificial subprocess with retries+backoff; on
# persistent unavailability re-exec the bench on CPU with a structured
# "cpu-fallback" label instead of crashing.

_PROBE_SNIPPET = (
    "import jax; d = jax.devices(); "
    "assert d[0].platform == 'tpu', f'resolved platform {d[0].platform}'; "
    "print(float(jax.numpy.ones((128, 128)).sum()), d[0].platform)"
)


def probe_tpu(attempts: int, probe_timeout: float) -> str:
    """Try to claim the chip from a throwaway subprocess; returns '' on
    success or the last failure description.  If the relay is wedged the
    subprocess (not the bench) hangs and is killed at the timeout."""
    import subprocess
    import sys
    import time

    err = "no attempts made"
    for attempt in range(attempts):
        if attempt:
            backoff = min(60.0, 15.0 * (2 ** (attempt - 1)))
            print(
                f"# tpu probe retry {attempt + 1}/{attempts} in {backoff:.0f}s: {err}",
                file=sys.stderr,
                flush=True,
            )
            time.sleep(backoff)
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SNIPPET],
                capture_output=True,
                timeout=probe_timeout,
                text=True,
            )
        except subprocess.TimeoutExpired:
            err = f"chip claim hung > {probe_timeout:.0f}s (relay wedged?)"
            continue
        if proc.returncode == 0:
            return ""
        tail = (proc.stderr or proc.stdout).strip().splitlines()[-1:] or ["unknown"]
        err = f"probe rc={proc.returncode}: {tail[0][:300]}"
    return err


def exec_cpu_fallback(script_path: str, reason: str) -> None:
    """Replace this process with a CPU-platform run of ``script_path``;
    the child emits the structured artifact (platform: cpu-fallback)."""
    import os
    import sys

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # axon plugin must not register
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_PLATFORM"] = "cpu-fallback"
    env["BENCH_PLATFORM_ERROR"] = reason[:500]
    print(f"# falling back to CPU: {reason}", file=sys.stderr, flush=True)
    os.execve(sys.executable, [sys.executable, os.path.abspath(script_path)], env)


def run_resilient(main, script_path: str) -> None:
    """The bench entrypoint wrapper: probe-gate the TPU, fall back to
    CPU on unavailability (including mid-run chip loss), never rc=1 for
    platform problems."""
    import os

    if os.environ.get("BENCH_PLATFORM") or not os.environ.get("PALLAS_AXON_POOL_IPS"):
        main()
        return
    reason = probe_tpu(
        attempts=int(os.environ.get("BENCH_TPU_ATTEMPTS", 3)),
        probe_timeout=float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", 240)),
    )
    if reason:
        exec_cpu_fallback(script_path, reason)
    try:
        main()
    except Exception as e:  # chip lost mid-run: degrade, don't crash
        import re

        msg = f"{type(e).__name__}: {e}"
        # Whole-token match, unambiguous platform markers ONLY: generic
        # words ("backend", "deadline", bare-substring "tpu" inside
        # "output") would relabel genuine code bugs as platform failures
        # and hide them behind a green cpu-fallback artifact.
        if re.search(
            r"\b(unavailable|deadline[_ ]exceeded|axon|tpu|pjrt)\b",
            msg,
            re.IGNORECASE,
        ):
            exec_cpu_fallback(script_path, msg)
        raise


def bench_platform_detail() -> dict:
    """The platform fields every bench artifact carries — one place owns
    the BENCH_PLATFORM / BENCH_PLATFORM_ERROR env contract."""
    import os

    label = os.environ.get("BENCH_PLATFORM")
    import jax

    if not label:
        label = jax.default_backend()
    try:
        device_count = int(jax.device_count())
    except Exception:
        device_count = 1
    return {
        "platform": label,
        # Visible device count (ISSUE 12): folded into the bench-gate
        # baseline key so a multi-device round never gates against a
        # single-device baseline (and vice versa).
        "device_count": device_count,
        "platform_error": os.environ.get("BENCH_PLATFORM_ERROR"),
    }
