"""kubeadmiral_tpu — a TPU-native multi-cluster federation framework.

A from-scratch re-design of the capabilities of KubeAdmiral (the reference
control plane surveyed in SURVEY.md): CRD-driven type federation, member
cluster lifecycle, propagation/override policies, a pluggable
Filter/Score/Select/Replicas scheduling pipeline, sync with field retention,
status collection/aggregation, follower scheduling and auto-migration.

The defining difference from the reference's in-process sequential Go
scheduler (reference: pkg/controllers/scheduler): the replica-scheduling hot
path is a batched tensor program — all pending FederatedObjects x member
clusters are packed into dense arrays and pushed through a single jit/XLA
pass per reconcile tick (see kubeadmiral_tpu.ops.pipeline).

Layout:
  models/      CRD-equivalent data model (FederatedTypeConfig, clusters,
               policies, federated objects)
  ops/         device kernels: planner, filters, scores, select, fused tick
  parallel/    mesh construction + shardings for scaling B x C over chips
  scheduler/   featurization (string world -> tensors), engine, controller
  runtime/     reconcile workers, delaying deliverer, informers, pipeline
               annotations, metrics
  federation/  control-plane controllers (cluster, federate, sync, status,
               override, follower, automigration, ...)
  utils/       hashing, quantity parsing, label selectors, unstructured paths
  testing/     in-memory apiserver (KWOK-analogue) + object builders
"""

import jax as _jax

# The scheduling engine does byte-exact resource arithmetic (memory in
# bytes, cluster-aggregate allocatable can exceed 2**53 nowhere but 2**31
# easily), so int64 must be real on device. The planner's hot loops stay
# explicitly int32. This framework owns its process (it is a control
# plane, not an embeddable ML library), so setting the global flag here
# is deliberate.
_jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the fused scheduling tick's compile
# time grows steeply with the cluster axis (26s at C=512, ~2min at
# C=1024 on the tunneled backend) while the compiled program is
# millisecond-fast; the on-disk cache makes that a one-time cost per
# shape per machine.  Precedence: KT_COMPILE_CACHE_DIR (this control
# plane's knob; empty/"0" disables), then JAX's native
# JAX_COMPILATION_CACHE_DIR / app setting, then the profile-dir default.
# The engine reports per-trace hit/miss as
# engine_persistent_cache_total{result} (docs/observability.md).
try:
    import os as _os

    _kt_dir = _os.environ.get("KT_COMPILE_CACHE_DIR")
    if _kt_dir is not None:
        if _kt_dir not in ("", "0"):
            _jax.config.update("jax_compilation_cache_dir", _kt_dir)
    elif _jax.config.jax_compilation_cache_dir is None:
        _jax.config.update(
            "jax_compilation_cache_dir",
            _os.path.expanduser("~/.cache/kubeadmiral_tpu/xla-cache"),
        )
    # Persist EVERY compile, not just the >1s ones (jax's default
    # threshold): the warm-restart path (scheduler/aot.py preload)
    # recompiles the exported ladder from StableHLO, and its per-program
    # compiles are individually sub-second — under the default threshold
    # none of them would ever land on disk, so every failover would
    # re-pay the whole ladder's XLA time.  Disk cost is small (the
    # ladder is ~100 entries) and this control plane owns its process.
    _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    try:
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:
        pass
except Exception:  # older jax without the option
    pass

__version__ = "0.1.0"

