"""Sequential reference implementation of the replica planner.

This is the semantic oracle for the batched TPU planner in
``kubeadmiral_tpu.ops.planner``: a direct, readable statement of the
reference algorithm (reference: pkg/controllers/util/planner/planner.go:83-366)
used (a) in differential tests against the device kernel and (b) as the
"in-process sequential scheduler" baseline that bench.py compares against.

Semantics recap (all order-sensitive integer math):

* clusters are processed in (weight desc, fnv32(cluster+key) asc) order;
* a first pass hands every cluster ``min(minReplicas, remaining)``, capped
  by estimated capacity (the clipped amount is recorded as overflow);
* remaining replicas are distributed in rounds: each round snapshots the
  remaining count D and hands cluster i ``ceil(D * w_i / sum_w)`` capped by
  the *running* remainder, then by maxReplicas and capacity; clusters that
  hit a cap drop out of later rounds; rounds repeat until nothing moves;
* with ``avoid_disruption`` the result is re-derived from current replica
  counts: only the delta between current and desired is moved, via a
  recursive scale-up/scale-down distribution.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from kubeadmiral_tpu.utils.hashing import fnv32

UNBOUNDED = None


@dataclass
class ClusterPref:
    """Per-cluster scheduling preference (planner.go:30-41)."""

    weight: int = 0
    min_replicas: int = 0
    max_replicas: int | None = UNBOUNDED


@dataclass
class PlanInput:
    prefs: dict[str, ClusterPref]  # "*" entry = default for all clusters
    total: int
    clusters: list[str]
    current: dict[str, int] = field(default_factory=dict)
    capacity: dict[str, int] = field(default_factory=dict)
    key: str = ""
    avoid_disruption: bool = False
    keep_unschedulable: bool = False


def plan(inp: PlanInput) -> tuple[dict[str, int], dict[str, int]]:
    """Returns (plan, overflow) maps, both keyed by cluster name."""
    prefs: dict[str, ClusterPref] = {}
    for name in inp.clusters:
        if name in inp.prefs:
            prefs[name] = inp.prefs[name]
        elif "*" in inp.prefs:
            prefs[name] = inp.prefs["*"]

    order = _sorted_names(prefs, inp.key)

    # Without avoid_disruption a reschedule would keep bouncing replicas
    # that overflowed capacity, so unschedulable replicas are always kept.
    keep = inp.keep_unschedulable or not inp.avoid_disruption

    desired, overflow = _distribute(order, prefs, inp.capacity, inp.total, keep)
    if not inp.avoid_disruption:
        return desired, overflow

    current = {}
    for name in order:
        replicas = inp.current.get(name, 0)
        cap = inp.capacity.get(name)
        current[name] = min(replicas, cap) if cap is not None else replicas

    cur_total = sum(current.values())
    want_total = sum(desired.values())
    if cur_total == want_total:
        return current, overflow
    if cur_total > want_total:
        return _scale_down(current, desired, cur_total - want_total, inp.key), overflow
    return (
        _scale_up(inp.prefs, current, desired, want_total - cur_total, inp.key),
        overflow,
    )


def _sorted_names(prefs: dict[str, ClusterPref], key: str) -> list[str]:
    # Ties between equal weights break on a per-object hash so that
    # single-replica workloads don't all pile onto one lexicographically
    # small cluster (planner.go:62-66).  On fnv32 collisions, Python's
    # stable sort preserves insertion order — callers build ``prefs``
    # in cluster-index order, which is the canonical final key shared
    # with the device kernel (ops/planner.py num_keys=3 sort) and the
    # C++ baseline (seqsched.cpp sort_order index tie).
    # The sort key clamps at zero like the share math (non-positive
    # weight = no share): all implementations order negative-weight
    # clusters together with zero-weight ones, tie-broken by hash/index.
    return sorted(
        prefs,
        key=lambda name: (
            -max(prefs[name].weight, 0),
            fnv32(name.encode() + key.encode()),
        ),
    )


def _distribute(
    order: list[str],
    prefs: dict[str, ClusterPref],
    capacity: dict[str, int],
    total: int,
    keep_unschedulable: bool,
) -> tuple[dict[str, int], dict[str, int]]:
    remaining = total
    out: dict[str, int] = {}
    overflow: dict[str, int] = {}

    # Pass 1: minimum replicas, oblivious to maxReplicas but capped by
    # capacity; the clipped portion is remembered as overflow.
    for name in order:
        take = min(prefs[name].min_replicas, remaining)
        cap = capacity.get(name)
        if cap is not None and cap < take:
            overflow[name] = take - cap
            take = cap
        remaining -= take
        out[name] = take

    # Pass 2: weighted rounds until a fixed point.  Non-positive weight
    # = no share (the defined rule shared with the device kernel and the
    # C++ baseline; negative weights would corrupt the ceil quotas).
    active = list(order)
    moved = True
    while moved and remaining > 0:
        moved = False
        weight_sum = sum(max(prefs[n].weight, 0) for n in active)
        if weight_sum <= 0:
            break
        snapshot = remaining
        survivors = []
        for name in active:
            start = out[name]
            extra = (
                snapshot * max(prefs[name].weight, 0) + weight_sum - 1
            ) // weight_sum
            extra = min(extra, remaining)
            total_n = start + extra

            full = False
            max_r = prefs[name].max_replicas
            if max_r is not None and total_n > max_r:
                total_n = max_r
                full = True
            cap = capacity.get(name)
            if cap is not None and total_n > cap:
                overflow[name] = overflow.get(name, 0) + total_n - cap
                total_n = cap
                full = True
            if not full:
                survivors.append(name)

            remaining -= total_n - start
            out[name] = total_n
            if total_n > start:
                moved = True
        active = survivors

    if keep_unschedulable:
        return out, overflow

    # Otherwise overflow only up to what could not be placed anywhere.
    trimmed = {}
    for name, value in overflow.items():
        value = min(value, remaining)
        if value > 0:
            trimmed[name] = value
    return out, trimmed


def _scale_up(
    rsp_prefs: dict[str, ClusterPref],
    current: dict[str, int],
    desired: dict[str, int],
    count: int,
    key: str,
) -> dict[str, int]:
    # Grow only clusters sitting below their desired share, weighted by the
    # shortfall, so no replica has to move between clusters.
    prefs: dict[str, ClusterPref] = {}
    for name, want in desired.items():
        have = current.get(name, 0)
        if want > have:
            pref = ClusterPref(weight=want - have)
            orig = rsp_prefs.get(name)
            if orig is not None and orig.max_replicas is not None:
                pref.max_replicas = orig.max_replicas - have
            prefs[name] = pref
    order = _sorted_names(prefs, key)
    grow, _ = _distribute(order, prefs, {}, count, keep_unschedulable=False)
    result = dict(current)
    for name, extra in grow.items():
        result[name] = result.get(name, 0) + extra
    return result


def _scale_down(
    current: dict[str, int],
    desired: dict[str, int],
    count: int,
    key: str,
) -> dict[str, int]:
    # Shrink only clusters sitting above their desired share, weighted by
    # the excess and never below zero.
    prefs: dict[str, ClusterPref] = {}
    for name, want in desired.items():
        have = current.get(name, 0)
        if want < have:
            prefs[name] = ClusterPref(weight=have - want, max_replicas=have)
    order = _sorted_names(prefs, key)
    shrink, _ = _distribute(order, prefs, {}, count, keep_unschedulable=False)
    result = dict(current)
    for name, less in shrink.items():
        result[name] = result.get(name, 0) - less
    return result
