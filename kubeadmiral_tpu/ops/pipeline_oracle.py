"""Sequential per-object oracle for the fused scheduling tick.

Mirrors the reference generic scheduler's control flow one object at a
time in plain Python (reference: pkg/controllers/scheduler/core/
generic_scheduler.go, framework/plugins/*), over the same featurized
inputs that TickInputs carries.  Used as the differential-test oracle for
ops.pipeline.schedule_tick and as bench.py's "in-process sequential
scheduler" baseline.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field

from kubeadmiral_tpu.ops.planner_oracle import ClusterPref, PlanInput, plan as planner

NIL = -1
MAX_SCORE = 100


@dataclass
class OracleProblem:
    """Featurized single-object scheduling problem over C clusters."""

    n_clusters: int
    filter_enabled: list[bool]  # 5 entries, ops.filters order
    score_enabled: list[bool]   # 5 entries, ops.scores order
    api_ok: list[bool]
    taint_ok_new: list[bool]
    taint_ok_cur: list[bool]
    selector_ok: list[bool]
    placement_ok: list[bool]
    placement_has: bool
    request: list[int]          # [R]
    alloc: list[list[int]]      # [C][R]
    used: list[list[int]]       # [C][R]
    taint_counts: list[int]
    affinity_scores: list[int]
    max_clusters: int | None
    mode_divide: bool
    sticky: bool
    current: dict[int, int | None]  # cluster idx -> replicas (None = nil)
    total: int
    weights: dict[int, int] | None  # static policy weights; None = dynamic
    min_replicas: dict[int, int] = field(default_factory=dict)
    max_replicas: dict[int, int] = field(default_factory=dict)
    capacity: dict[int, int] = field(default_factory=dict)
    keep_unschedulable: bool = False
    avoid_disruption: bool = False
    cluster_names: list[str] = field(default_factory=list)
    key: str = ""
    cpu_alloc: list[int] = field(default_factory=list)
    cpu_avail: list[int] = field(default_factory=list)


def _fits(p: OracleProblem, c: int) -> bool:
    if all(r <= 0 for r in p.request):
        return True
    for r in range(len(p.request)):
        if r >= 2 and p.request[r] <= 0:
            continue
        if p.alloc[c][r] < p.request[r] + p.used[c][r]:
            return False
    return True


def _balanced_shift(cap: int) -> int:
    """Smallest multiple-of-8 shift with (cap >> s) < 2^26 — the shared
    range reduction of the exact balanced score (ops/scores.py)."""
    s = 0
    for k in range(5):
        if cap >= 1 << (26 + 8 * k):
            s += 8
    return s


def _balanced(p: OracleProblem, c: int) -> int:
    """Exact integer balanced-allocation score — bit-identical to the
    device kernel (ops/scores.py balanced_allocation_score) and the C++
    baseline on every backend; see the kernel docstring for why float
    forms diverge (axon f64->f32 demotion)."""
    ac, am = p.alloc[c][0], p.alloc[c][1]
    rc = p.used[c][0] + p.request[0]
    rm = p.used[c][1] + p.request[1]
    if ac == 0 or am == 0 or rc >= ac or rm >= am:
        return 0
    s_cpu, s_mem = _balanced_shift(ac), _balanced_shift(am)
    ac, rc = ac >> s_cpu, rc >> s_cpu
    am, rm = am >> s_mem, rm >> s_mem
    total = max(ac * am, 1)
    diff_num = abs(rc * am - rm * ac)
    return MAX_SCORE * (total - diff_num) // total


def _ratio(p: OracleProblem, c: int, least: bool) -> int:
    total = 0
    for r in (0, 1):
        cap = p.alloc[c][r]
        req = p.used[c][r] + p.request[r]
        if cap == 0 or req > cap:
            s = 0
        elif least:
            s = (cap - req) * MAX_SCORE // cap
        else:
            s = req * MAX_SCORE // cap
        total += s
    return total // 2


def _normalize(scores: dict[int, int], reverse: bool) -> dict[int, int]:
    max_count = max(scores.values(), default=0)
    if max_count == 0:
        if reverse:
            return {c: MAX_SCORE for c in scores}
        return dict(scores)
    out = {}
    for c, s in scores.items():
        s = MAX_SCORE * s // max_count
        out[c] = MAX_SCORE - s if reverse else s
    return out


def round_half_div(num: int, den: int) -> int:
    """Round-half-away-from-zero of num/den for non-negative integers —
    the exact shared rule of the device kernel (ops/weights.py), this
    oracle, and the C++ baseline (float forms diverge on axon TPUs,
    which demote f64 to f32)."""
    return (2 * num + den) // (2 * den)


def _dynamic_weights(p: OracleProblem, selected: list[int]) -> dict[int, int]:
    """rsp.go CalcWeightLimit + AvailableToPercentage over the selection,
    in exact integer arithmetic (x1.4 supply limit as 1400/1000)."""
    n = len(selected)
    alloc_sum = sum(p.cpu_alloc[c] for c in selected)
    if alloc_sum == 0:
        limit = {c: round_half_div(1000, n) for c in selected}
    else:
        limit = {
            c: round_half_div(p.cpu_alloc[c] * 1400, alloc_sum) for c in selected
        }
    avail_sum = sum(p.cpu_avail[c] for c in selected if p.cpu_avail[c] > 0)
    if avail_sum == 0:
        tmp = {c: round_half_div(1000, n) for c in selected}
    else:
        tmp = {
            c: min(
                round_half_div(max(p.cpu_avail[c], 0) * 1000, avail_sum), limit[c]
            )
            for c in selected
        }
    tmp_sum = sum(tmp.values())
    if tmp_sum <= 0:
        return {c: 0 for c in selected}
    weights = {}
    other = 0
    for c in selected:
        w = round_half_div(tmp[c] * 1000, tmp_sum)
        weights[c] = w
        other += w
    # Rounding residual goes to the max-weight cluster, first by CLUSTER
    # INDEX on ties — the device kernel's canonical choice
    # (ops/weights.py).  The reference's own pick is Go-map-iteration-
    # order dependent (rsp.go:248-272), so any deterministic rule is
    # faithful; all three implementations (device, this oracle, the C++
    # baseline) must share ONE rule or large-shape parity breaks on
    # score-ordered vs index-ordered selections (found by the r5 bench
    # parity check at 10k x 500).
    max_w, max_c = 0, None
    for c in sorted(selected):
        if weights[c] > max_w:
            max_w, max_c = weights[c], c
    if max_c is not None:
        # Clamped at zero — see ops/weights.py (the round-up bias across
        # thousands of clusters can exceed the max weight).
        weights[max_c] = max(weights[max_c] + 1000 - other, 0)
    return weights


def _filter_reasons(p: OracleProblem) -> list[int]:
    """Per-cluster filter-rejection bitmask (ops.reasons vocabulary):
    bit i set iff enabled plugin i rejects the pair.  ``bits == 0`` is
    exactly the feasibility predicate schedule_one applies."""
    from kubeadmiral_tpu.ops import reasons as RSN

    out = []
    for c in range(p.n_clusters):
        bits = 0
        if p.filter_enabled[0] and not p.api_ok[c]:
            bits |= RSN.REASON_API_RESOURCES
        taint_ok = p.taint_ok_cur[c] if c in p.current else p.taint_ok_new[c]
        if p.filter_enabled[1] and not taint_ok:
            bits |= RSN.REASON_TAINT_TOLERATION
        if p.filter_enabled[2] and not _fits(p, c):
            bits |= RSN.REASON_RESOURCES_FIT
        if p.filter_enabled[3] and p.placement_has and not p.placement_ok[c]:
            bits |= RSN.REASON_PLACEMENT
        if p.filter_enabled[4] and not p.selector_ok[c]:
            bits |= RSN.REASON_CLUSTER_AFFINITY
        out.append(bits)
    return out


def _totals(p: OracleProblem, feasible: list[int]) -> dict[int, int]:
    """Score + normalize + sum over the feasible set."""
    totals = {c: 0 for c in feasible}
    if p.score_enabled[0]:
        for c, s in _normalize({c: p.taint_counts[c] for c in feasible}, True).items():
            totals[c] += s
    if p.score_enabled[1]:
        for c in feasible:
            totals[c] += _balanced(p, c)
    if p.score_enabled[2]:
        for c in feasible:
            totals[c] += _ratio(p, c, True)
    if p.score_enabled[3]:
        for c, s in _normalize(
            {c: p.affinity_scores[c] for c in feasible}, False
        ).items():
            totals[c] += s
    if p.score_enabled[4]:
        for c in feasible:
            totals[c] += _ratio(p, c, False)
    return totals


def _select(p: OracleProblem, totals: dict[int, int], feasible: list[int]) -> list[int]:
    """Top-K by (score desc, index asc); a negative maxClusters selects
    nothing (the reference returns Unschedulable)."""
    if p.max_clusters is not None and p.max_clusters < 0:
        return []
    ranked = sorted(feasible, key=lambda c: (-totals[c], c))
    k = len(ranked) if p.max_clusters is None else min(p.max_clusters, len(ranked))
    return ranked[:k]


def schedule_one(p: OracleProblem) -> dict[int, int | None]:
    """Returns {cluster_idx: replicas-or-None} like ScheduleResult."""
    if p.sticky and p.current:
        return dict(p.current)

    # Filter.
    bits = _filter_reasons(p)
    feasible = [c for c in range(p.n_clusters) if bits[c] == 0]
    if not feasible:
        return {}

    # Score + normalize + sum, then select.
    totals = _totals(p, feasible)
    selected = _select(p, totals, feasible)
    if not selected:
        return {}

    if not p.mode_divide:
        return {c: None for c in selected}

    # Replicas via the planner oracle.
    weights = p.weights if p.weights is not None else _dynamic_weights(p, selected)
    prefs = {}
    for c in selected:
        prefs[p.cluster_names[c]] = ClusterPref(
            weight=weights.get(c, 0),
            min_replicas=p.min_replicas.get(c, 0),
            max_replicas=p.max_replicas.get(c),
        )
    current = {}
    for c, reps in p.current.items():
        current[p.cluster_names[c]] = p.total if reps is None else reps
    plan_map, overflow = planner(
        PlanInput(
            prefs=prefs,
            total=p.total,
            clusters=[p.cluster_names[c] for c in selected],
            current=current,
            capacity={p.cluster_names[c]: cap for c, cap in p.capacity.items()},
            key=p.key,
            avoid_disruption=p.avoid_disruption,
            keep_unschedulable=p.keep_unschedulable,
        )
    )
    merged: dict[str, int] = dict(plan_map)
    for name, extra in overflow.items():
        merged[name] = merged.get(name, 0) + extra
    by_name = {p.cluster_names[c]: c for c in selected}
    return {
        by_name[name]: reps
        for name, reps in merged.items()
        if reps != 0 and name in by_name
    }


def explain_one(p: OracleProblem) -> list[int]:
    """Per-cluster rejection bitmask (ops.reasons vocabulary) — the
    sequential oracle for ``TickOutputs.reasons``, asserted bit-exact
    against the XLA tick by tests/test_explain.py.

    Mirrors the device's dataflow, which computes every stage
    unconditionally and folds the per-object special cases in as masks:
    filter bits and select-stage cuts are derived from the NON-sticky
    pipeline, then the sticky short-circuit overlays them (current
    clusters win with mask 0, everything else gains the sticky bit on
    top of the would-be verdicts).  ``bits[c] == 0`` holds exactly for
    the clusters ``schedule_one`` selects."""
    from kubeadmiral_tpu.ops import reasons as RSN

    bits = _filter_reasons(p)
    feasible = [c for c in range(p.n_clusters) if bits[c] == 0]
    selected: list[int] = []
    if feasible:
        totals = _totals(p, feasible)
        selected = _select(p, totals, feasible)
        chosen = set(selected)
        for c in feasible:
            if c not in chosen:
                bits[c] |= RSN.REASON_MAX_CLUSTERS
    if p.mode_divide and selected:
        q = dataclasses.replace(p, sticky=False)
        final = schedule_one(q)
        for c in selected:
            if c not in final:
                bits[c] |= RSN.REASON_ZERO_REPLICAS
    if p.sticky and p.current:
        for c in range(p.n_clusters):
            if c in p.current:
                bits[c] = 0
            else:
                bits[c] |= RSN.REASON_STICKY
    return bits


def pack_one(p: OracleProblem, k: int) -> dict:
    """Packed-export reference for one object — the sequential oracle
    for ``ops.pipeline.pack_rows``, asserted bit-exact against the XLA
    pack by tests/test_packed_export.py.

    Canonical slot order: (score desc, cluster index asc) over the
    selected clusters — the select stage's ranking, so ties at the K
    boundary resolve identically to the device sort — truncated to the
    first K; ``nsel`` is the TRUE selected count, so ``nsel > k`` is
    the overflow flag.  Scores reproduce the device's score plane (the
    non-sticky pipeline's post-normalize totals, 0 on infeasible
    clusters), replicas use the device's NIL sentinel for countless
    placements."""
    from kubeadmiral_tpu.ops import reasons as RSN

    res = schedule_one(p)
    bits = _filter_reasons(p)
    feasible = [c for c in range(p.n_clusters) if bits[c] == 0]
    totals = _totals(p, feasible) if feasible else {}
    explain = explain_one(p)

    sel_sorted = sorted(res, key=lambda c: (-totals.get(c, 0), c))
    idx = [NIL] * k
    rep = [0] * k
    cnt = [0] * k
    sco = [0] * k
    for slot, c in enumerate(sel_sorted[:k]):
        idx[slot] = c
        reps = res[c]
        rep[slot] = NIL if reps is None else int(reps)
        cnt[slot] = 0 if reps is None else 1
        sco[slot] = int(totals.get(c, 0))
    rsum = [
        sum(1 for mask in explain if mask & bit) for bit in RSN.REASON_BITS
    ]
    nfeas = sum(
        1 for mask in explain if not (mask & RSN.FILTER_REASON_MASK)
    )
    return {
        "idx": idx,
        "rep": rep,
        "cnt": cnt,
        "sco": sco,
        "nsel": len(res),
        "nfeas": nfeas,
        "rsum": rsum,
    }
