"""Batched replica planner as a single XLA program.

Re-derivation of the reference's weighted fair distribution
(reference: pkg/controllers/util/planner/planner.go:83-366) as dense tensor
math over ``[B objects x C cluster slots]``, bit-compatible with the
sequential oracle in :mod:`kubeadmiral_tpu.ops.planner_oracle`.

The reference walks clusters one at a time, carrying a running remainder
``rem`` and handing each cluster ``take_j = min(c_j, rem)`` for a per-cluster
constant ``c_j``.  That recurrence is ``rem' = max(rem - c_j, 0)`` — and
functions of the form ``r -> max(r - A, B)`` are closed under composition::

    (A1,B1) then (A2,B2)  ==  (A1+A2, max(B1-A2, B2))

so every sequential pass (the minReplicas pass and each weighted round)
becomes one ``lax.associative_scan`` over the cluster axis: O(log C) depth
on device instead of O(C) Python.  Rounds still iterate via
``lax.while_loop`` (each round either finishes or saturates at least one
cluster), which preserves the reference's exact rounding/tie-break
semantics including:

* (weight desc, fnv32(cluster+objectKey) asc) processing order,
* ceil division ``(D*w + W - 1) // W`` against the round-start snapshot D,
* capacity clipping recorded as overflow (re-counted every round),
* negative "takes" when an earlier pass already exceeded a cap,
* the avoid-disruption branch that rescales from current replica counts.

Value contract (int32 device math): ``total * max(weight) + sum(weight)``
must stay below 2**31.  The featurizer normalizes weights to sum<=1000
(as the reference's RSP plugin does), which makes this hold for any
realistic replica count; ``validate_ranges`` enforces it host-side.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from kubeadmiral_tpu.parallel import shardguard
import numpy as np

INT32_INF = np.int32(np.iinfo(np.int32).max)
UNBOUNDED = INT32_INF  # sentinel for "no max replicas" / "no capacity estimate"


class PlannerInputs(NamedTuple):
    """One scheduling problem per row; cluster slots padded to C.

    All int32.  ``UNBOUNDED`` marks absent max-replicas / capacity.
    ``tiebreak`` is fnv32(clusterName + objectKey) shifted into sortable
    int32 space (utils.hashing.uint32_to_sortable_int32).
    ``scale_max`` is the max-replicas bound used by the avoid-disruption
    scale-up pass: the reference resolves it from the *directly named*
    preference only (planner.go:320-324), so a wildcard-provided max must
    be UNBOUNDED here while still set in ``max_replicas``.
    """

    weight: jax.Array        # [B, C]
    min_replicas: jax.Array  # [B, C]
    max_replicas: jax.Array  # [B, C]
    scale_max: jax.Array     # [B, C]
    capacity: jax.Array      # [B, C]
    tiebreak: jax.Array      # [B, C]
    member: jax.Array        # [B, C] bool — cluster participates
    total: jax.Array         # [B]
    current: jax.Array       # [B, C]
    avoid_disruption: jax.Array    # [B] bool
    keep_unschedulable: jax.Array  # [B] bool


class PlannerOutputs(NamedTuple):
    plan: jax.Array      # [B, C]
    overflow: jax.Array  # [B, C]


def _running_remainder(r0: jax.Array, c: jax.Array) -> jax.Array:
    """Remainder seen by each cluster in a sequential min-take pass.

    Position j receives the value of ``rem`` after clusters 0..j-1 each took
    ``min(c_i, rem)``, i.e. after applying ``r -> max(r - c_i, 0)`` in order.
    Computed with an associative scan over (A, B) pairs representing
    ``r -> max(r - A, B)``.
    """
    a = c
    b = jnp.zeros_like(c)

    def compose(x, y):
        ax, bx = x
        ay, by = y
        return ax + ay, jnp.maximum(bx - ay, by)

    a_s, b_s = jax.lax.associative_scan(compose, (a, b))
    rem_after = jnp.maximum(r0 - a_s, b_s)
    return jnp.concatenate([jnp.full((1,), r0, dtype=c.dtype), rem_after[:-1]])


@shardguard.rows_first
def _distribute(
    weight: jax.Array,
    min_replicas: jax.Array,
    max_replicas: jax.Array,
    capacity: jax.Array,
    tiebreak: jax.Array,
    member: jax.Array,
    total: jax.Array,
    keep_unschedulable: jax.Array,
    tail_weight=None,
    return_active: bool = False,
):
    """getDesiredPlan (planner.go:211-304) for one object. Returns
    (plan, overflow, unplaced_remainder) in original cluster order.

    ``tail_weight``/``return_active`` serve the NARROW planner
    (``plan_batch_narrow``): the cluster axis then holds only the top-M
    member slots in this pass's own processing order, and
    ``tail_weight`` is the summed (clamped) weight of the member columns
    left OUT of the slots — added to every round's ``weight_sum`` so
    ceil quotas match the full-width run exactly, while the tail slots
    themselves receive nothing (the narrow certificate in
    ``_plan_one_narrow`` proves the remainder never reaches them, or the
    row falls back to the dense solve).  Both are Python-static for the
    dense path, so the compiled full-width program is unchanged."""
    c_slots = weight.shape[0]

    # Processing order: members first, weight desc, tiebreak hash asc,
    # cluster index as the FINAL comparator key — fnv32 tiebreak
    # collisions between equal-weight clusters would otherwise order
    # backend-dependently (jnp.lexsort carries the iota as a value
    # operand and trusts backend sort stability, which the axon TPU
    # ignores at wide rows; see ops/select.py).
    # Non-positive weight = no share (defined identically in the Python
    # oracle and the C++ baseline): a negative weight — the dynamic-
    # weight residual at thousands of selected clusters, or a bad
    # policy value — would turn the ceil-quota negative and blow up the
    # remaining-replica accounting (caught by the r5 full-shape parity
    # check as INT32_INF-scale replica plans at 100k x 5k).  The SORT
    # also runs on the clamped weight: negating a raw INT32_MIN would
    # wrap, ordering that cluster backend-dependently.
    w_clamped = jnp.maximum(weight, 0)
    sort_weight = jnp.where(member, -w_clamped, INT32_INF)
    iota = jax.lax.iota(jnp.int32, c_slots)
    perm = jax.lax.sort((sort_weight, tiebreak, iota), num_keys=3)[-1]
    w = w_clamped[perm]
    min_r = min_replicas[perm]
    max_r = max_replicas[perm]
    cap = capacity[perm]
    mem = member[perm]

    # --- minReplicas pass (ignores max_replicas, clips at capacity) ---
    want_min = jnp.where(mem, min_r, 0)
    take_cap = jnp.minimum(want_min, cap)
    rem_before = _running_remainder(total, take_cap)
    plan = jnp.minimum(take_cap, rem_before)
    # Overflow = the capacity-clipped part of what the pass tried to place.
    wanted = jnp.minimum(want_min, rem_before)
    overflow = jnp.where(mem, jnp.maximum(wanted - cap, 0), 0)
    remaining = rem_before[c_slots - 1] - plan[c_slots - 1]

    # --- weighted rounds until fixed point ---
    def round_cond(state):
        remaining, moved = state[3], state[4]
        return moved & (remaining > 0)

    def round_body(state):
        plan, overflow, active, remaining, _ = state[:5]
        w_active = jnp.where(active, w, 0)
        weight_sum = jnp.sum(w_active, dtype=jnp.int32)
        if tail_weight is not None:
            # Phantom tail: out-of-slot members keep contributing their
            # weight to the quota denominator every round (they never
            # saturate — the narrow certificate rejects rows whose tail
            # carries min/max/capacity structure).
            weight_sum = weight_sum + tail_weight
        d = remaining  # round-start snapshot
        safe_sum = jnp.maximum(weight_sum, 1)
        quota = (d * w_active + safe_sum - 1) // safe_sum
        quota = jnp.where(active & (weight_sum > 0), quota, 0)

        allowed = jnp.minimum(max_r, cap) - plan  # may be negative
        c_take = jnp.where(active, jnp.minimum(quota, allowed), 0)
        rem_before = _running_remainder(d, c_take)
        take = jnp.minimum(c_take, rem_before)
        extra = jnp.minimum(quota, rem_before)

        after_max = jnp.minimum(plan + extra, max_r)
        overflow = overflow + jnp.where(
            active, jnp.maximum(after_max - cap, 0), 0
        )
        full = active & ((plan + extra > max_r) | (after_max > cap))

        plan = plan + jnp.where(active, take, 0)
        remaining = d - jnp.sum(jnp.where(active, take, 0), dtype=jnp.int32)
        moved = jnp.any(jnp.where(active, take, 0) > 0) & (weight_sum > 0)
        out = (plan, overflow, active & ~full, remaining, moved)
        if tail_weight is not None:
            # A round whose remainder survives past the slots is the
            # narrow certificate's kill condition: the full-width run
            # hands that remainder to tail members WITHIN this round's
            # cascade (their ceil quota is >= 1 whenever tail_weight >
            # 0), which no later prefix-only round can reproduce.
            out = out + (state[5] | (remaining > 0),)
        return out

    init = (plan, overflow, mem, remaining, jnp.asarray(True))
    if tail_weight is not None:
        init = init + (jnp.asarray(False),)
    state = jax.lax.while_loop(round_cond, round_body, init)
    plan, overflow, active, remaining = state[:4]
    spilled = state[5] if tail_weight is not None else None

    # Without keep_unschedulable, overflow is trimmed to what could not be
    # placed anywhere at all.
    overflow = jnp.where(
        keep_unschedulable,
        overflow,
        jnp.maximum(jnp.minimum(overflow, remaining), 0),
    )

    # Back to the caller's cluster order.
    inv_plan = jnp.zeros_like(plan).at[perm].set(plan)
    inv_overflow = jnp.zeros_like(overflow).at[perm].set(overflow)
    if return_active:
        inv_active = jnp.zeros_like(active).at[perm].set(active)
        return inv_plan, inv_overflow, remaining, inv_active, spilled
    return inv_plan, inv_overflow, remaining


def _plan_one(inp: PlannerInputs) -> PlannerOutputs:
    """Full planner for a single object (vmapped over the batch)."""
    zeros = jnp.zeros_like(inp.weight)
    no_cap = jnp.full_like(inp.weight, INT32_INF)

    # A reschedule would keep bouncing capacity-overflowed replicas if they
    # were dropped while disruption is allowed (planner.go:108-118).
    keep = inp.keep_unschedulable | ~inp.avoid_disruption

    desired, overflow, _ = _distribute(
        inp.weight,
        inp.min_replicas,
        inp.max_replicas,
        inp.capacity,
        inp.tiebreak,
        inp.member,
        inp.total,
        keep,
    )

    # --- avoid-disruption: move only the delta from current replicas ---
    current_ok = jnp.where(
        inp.member, jnp.minimum(inp.current, inp.capacity), 0
    )
    current_total = jnp.sum(current_ok, dtype=jnp.int32)
    desired_total = jnp.sum(desired, dtype=jnp.int32)

    # Scale up: clusters below their desired share grow, weighted by the
    # shortfall, bounded by the directly-named max minus current.
    up_member = inp.member & (desired > current_ok)
    up_weight = jnp.where(up_member, desired - current_ok, 0)
    up_max = jnp.where(
        inp.scale_max == INT32_INF, INT32_INF, inp.scale_max - current_ok
    )
    grow, _, _ = _distribute(
        up_weight,
        zeros,
        up_max,
        no_cap,
        inp.tiebreak,
        up_member,
        jnp.maximum(desired_total - current_total, 0),
        jnp.asarray(False),
    )

    # Scale down: clusters above their desired share shrink, weighted by
    # the excess, never below zero.
    down_member = inp.member & (desired < current_ok)
    down_weight = jnp.where(down_member, current_ok - desired, 0)
    shrink, _, _ = _distribute(
        down_weight,
        zeros,
        jnp.where(down_member, current_ok, INT32_INF),
        no_cap,
        inp.tiebreak,
        down_member,
        jnp.maximum(current_total - desired_total, 0),
        jnp.asarray(False),
    )

    steady = jnp.where(
        current_total == desired_total,
        current_ok,
        jnp.where(
            current_total > desired_total,
            current_ok - shrink,
            current_ok + grow,
        ),
    )
    plan = jnp.where(inp.avoid_disruption, steady, desired)
    return PlannerOutputs(plan=plan, overflow=overflow)


# -- narrow solve ---------------------------------------------------------
# The planner's decision for one object touches only a PREFIX of its
# processing order (weight desc, tiebreak asc, index asc): clusters past
# the point where the running remainder hits zero receive nothing, and —
# when they carry no min/max/capacity/current structure — contribute
# nothing but their weight to the ceil-quota denominator.  The narrow
# solve exploits that: run the planner over the top-M member slots in
# processing-order, feed the left-out members' summed weight in as a
# phantom ``tail_weight``, and certify per row that the result equals
# the full-width run (ops/pipeline.py's narrow tick routes uncertified
# rows back through the dense program).

# Bit layout of the processing-order composite key (int64): the weight
# field clamps at 2^20-1 — far above the featurizer's sum<=1000 contract
# — and a clamp collision merely fails the strict certificate (dense
# fallback), never silently reorders.
_KEY_W_BITS = 20
_KEY_TB_BITS = 32
_KEY_SPECIAL_SHIFT = _KEY_W_BITS + _KEY_TB_BITS


def processing_key(weight, tiebreak, special):
    """int64 composite ordering members by (special desc, clamped weight
    desc, tiebreak asc): larger key = processed earlier, modulo the
    final index tie-break (left to the consumer — the narrow solve
    packs an inverted iota under this key, preferring the lower index
    on equal keys, matching the planner's iota comparator).
    ``special`` marks columns carrying planner structure (min/max/
    capacity/current) that must never land in the phantom tail."""
    w = jnp.clip(jnp.maximum(weight, 0), 0, (1 << _KEY_W_BITS) - 1).astype(
        jnp.int64
    )
    # tiebreak asc preferred -> invert into an unsigned 32-bit field.
    tbu = jnp.int64(np.iinfo(np.int32).max) - tiebreak.astype(jnp.int64)
    return (
        (special.astype(jnp.int64) << _KEY_SPECIAL_SHIFT)
        + (w << _KEY_TB_BITS)
        + tbu
    )


def _plan_one_narrow(
    inp: PlannerInputs, tail_weight, best_tail, comp
) -> tuple[PlannerOutputs, jax.Array]:
    """_plan_one over top-M member slots (processing-order prefix), plus
    the exactness certificate.  ``tail_weight`` is the summed clamped
    weight of member columns outside the slots, ``best_tail`` the
    largest processing_key among them (-1 when none), ``comp`` the slots'
    own processing keys.  Returns (outputs, cert bool): cert True iff
    the narrow result provably equals the full-width planner:

    * every slot that received replicas, accrued overflow, or saturated
      out of the active set orders strictly before the best tail member
      (so the true remainder cascade never interleaves with the tail),
      and
    * NO weighted round's remainder survived past the slots — the
      full-width cascade would have handed it to the tail within that
      round (or the tail carries zero weight, making it inert: zero
      quota, and the caller guarantees zero min/max/capacity/current
      structure outside the slots).
    """
    zeros = jnp.zeros_like(inp.weight)
    no_cap = jnp.full_like(inp.weight, INT32_INF)
    keep = inp.keep_unschedulable | ~inp.avoid_disruption

    desired, overflow, remaining, active_end, spilled = _distribute(
        inp.weight,
        inp.min_replicas,
        inp.max_replicas,
        inp.capacity,
        inp.tiebreak,
        inp.member,
        inp.total,
        keep,
        tail_weight=tail_weight,
        return_active=True,
    )
    touched = (desired > 0) | (overflow > 0) | (inp.member & ~active_end)
    cert = (tail_weight == 0) | (
        ~spilled & jnp.all(~touched | (comp > best_tail))
    )

    # --- avoid-disruption scale passes: members derive from desired and
    # current, both zero outside the slots for certified rows (desired
    # nonzero => touched; current nonzero => special => in-slot), so
    # these run full-fidelity on the narrow shapes with no phantom tail.
    current_ok = jnp.where(
        inp.member, jnp.minimum(inp.current, inp.capacity), 0
    )
    current_total = jnp.sum(current_ok, dtype=jnp.int32)
    desired_total = jnp.sum(desired, dtype=jnp.int32)

    up_member = inp.member & (desired > current_ok)
    up_weight = jnp.where(up_member, desired - current_ok, 0)
    up_max = jnp.where(
        inp.scale_max == INT32_INF, INT32_INF, inp.scale_max - current_ok
    )
    grow, _, _ = _distribute(
        up_weight,
        zeros,
        up_max,
        no_cap,
        inp.tiebreak,
        up_member,
        jnp.maximum(desired_total - current_total, 0),
        jnp.asarray(False),
    )

    down_member = inp.member & (desired < current_ok)
    down_weight = jnp.where(down_member, current_ok - desired, 0)
    shrink, _, _ = _distribute(
        down_weight,
        zeros,
        jnp.where(down_member, current_ok, INT32_INF),
        no_cap,
        inp.tiebreak,
        down_member,
        jnp.maximum(current_total - desired_total, 0),
        jnp.asarray(False),
    )

    steady = jnp.where(
        current_total == desired_total,
        current_ok,
        jnp.where(
            current_total > desired_total,
            current_ok - shrink,
            current_ok + grow,
        ),
    )
    plan = jnp.where(inp.avoid_disruption, steady, desired)
    return PlannerOutputs(plan=plan, overflow=overflow), cert


def plan_batch_narrow(
    inp: PlannerInputs, tail_weight, best_tail, comp
) -> tuple[PlannerOutputs, jax.Array]:
    """Narrow planner over [B, M] slots; see _plan_one_narrow.  Jitted
    by the caller (ops.pipeline's narrow tick) — not here, so the trace
    fuses with the surrounding gather/scatter."""
    return jax.vmap(_plan_one_narrow)(inp, tail_weight, best_tail, comp)


# ktlint: ignore[aot-ledger-coverage] host-validation entry (plan_batch) and oracle comparisons only: inside the engine this traces INLINE into the aot+ledger-wrapped tick programs, never as its own dispatch
@jax.jit
def plan_batch_jit(inp: PlannerInputs) -> PlannerOutputs:
    """Plan every object in the batch in one XLA dispatch (no host checks).

    Callers must have enforced the int32 value contract already (the fused
    scheduler pipeline validates once when packing tensors).
    """
    return jax.vmap(_plan_one)(inp)


def plan_batch(inp: PlannerInputs, *, validate: bool = True) -> PlannerOutputs:
    """Plan every object in the batch; validates the int32 contract first."""
    if validate:
        validate_ranges(np.asarray(inp.total), np.asarray(inp.weight))
    return plan_batch_jit(inp)


def validate_ranges(total: np.ndarray, weight: np.ndarray) -> None:
    """Host-side guard for the int32 value contract.  Sums the CLAMPED
    weights — the kernel zeroes negatives, so negative entries must not
    cancel positive ones in the overflow estimate."""
    clamped = np.maximum(weight, 0)
    max_w = int(clamped.max(initial=0))
    max_t = int(total.max(initial=0))
    w_sum = int(clamped.sum(axis=-1).max(initial=0))
    if max_t * max_w + w_sum >= 2**31:
        raise OverflowError(
            f"planner int32 contract violated: total={max_t} * weight={max_w} "
            f"+ weight_sum={w_sum} >= 2**31; normalize weights first"
        )


def make_inputs(
    batch: int,
    clusters: int,
    total: "np.ndarray | int",
    weight: np.ndarray,
    *,
    min_replicas: np.ndarray | None = None,
    max_replicas: np.ndarray | None = None,
    scale_max: np.ndarray | None = None,
    capacity: np.ndarray | None = None,
    tiebreak: np.ndarray | None = None,
    member: np.ndarray | None = None,
    current: np.ndarray | None = None,
    avoid_disruption: np.ndarray | bool = False,
    keep_unschedulable: np.ndarray | bool = False,
) -> PlannerInputs:
    """Convenience builder filling sentinel defaults (host-side, numpy)."""

    def arr(x, fill, dtype=np.int32, shape=(batch, clusters)):
        if x is None:
            return np.full(shape, fill, dtype=dtype)
        return np.broadcast_to(np.asarray(x, dtype=dtype), shape).copy()

    max_r = arr(max_replicas, INT32_INF)
    return PlannerInputs(
        weight=arr(weight, 0),
        min_replicas=arr(min_replicas, 0),
        max_replicas=max_r,
        scale_max=max_r.copy() if scale_max is None else arr(scale_max, INT32_INF),
        capacity=arr(capacity, INT32_INF),
        tiebreak=arr(tiebreak, 0),
        member=arr(member, True, dtype=bool),
        total=np.broadcast_to(np.asarray(total, np.int32), (batch,)).copy(),
        current=arr(current, 0),
        avoid_disruption=np.broadcast_to(
            np.asarray(avoid_disruption, bool), (batch,)
        ).copy(),
        keep_unschedulable=np.broadcast_to(
            np.asarray(keep_unschedulable, bool), (batch,)
        ).copy(),
    )
