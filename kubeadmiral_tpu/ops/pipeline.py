"""The fused scheduling tick: one XLA program per reconcile batch.

Composes the stages of the reference's generic scheduler (reference:
pkg/controllers/scheduler/core/generic_scheduler.go:92-150) over the whole
pending batch at once:

    feasible = AND(enabled filter masks)            # Filter, O(B*C)
    scores   = sum(enabled normalized score plugins)# Score + Normalize
    selected = top-K(scores)                        # Select (MaxCluster)
    replicas = planner(weights, mins, maxes, caps)  # Replicas (RSP)

with the per-object special cases folded in as masks: sticky-cluster
short-circuit, Duplicate vs Divide mode, static vs dynamic RSP weights.

The featurizer (kubeadmiral_tpu.scheduler.featurize) is responsible for
producing TickInputs from API objects; this module is pure tensor math and
is jit-compiled once per (B, C, R) shape bucket.
"""

from __future__ import annotations

import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubeadmiral_tpu.parallel import shardguard

from kubeadmiral_tpu.ops import filters as F
from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.ops import scores as S
from kubeadmiral_tpu.ops.planner import (
    INT32_INF,
    PlannerInputs,
    plan_batch_jit,
    plan_batch_narrow,
    processing_key,
)
from kubeadmiral_tpu.ops.select import select_topk
from kubeadmiral_tpu.ops.weights import dynamic_weights

NIL_REPLICAS = np.int64(-1)  # "no replica count" (Duplicate-mode placement)

# -- XLA (re)compile telemetry -------------------------------------------
# A jitted function's Python body runs exactly once per trace, i.e. per
# XLA compile of a new program shape — so a counter in the body is a
# TRUE recompile detector, not a heuristic.  The engine drains these
# events after each tick into ``engine_xla_compiles_total`` counters
# labeled by program and (B, C) shape bucket.
_trace_lock = threading.Lock()
_trace_events: list[tuple[str, int, int]] = []
_trace_seq = 0


def _note_trace(program: str, b: int, c: int) -> None:
    global _trace_seq
    with _trace_lock:
        _trace_seq += 1
        _trace_events.append((program, int(b), int(c)))


def trace_seq() -> int:
    """Monotonic count of XLA traces of this module's programs — compare
    around a dispatch to tell a compile from a cache hit."""
    with _trace_lock:
        return _trace_seq


def drain_trace_events() -> list[tuple[str, int, int]]:
    """Take (program, B, C) events recorded since the last drain."""
    global _trace_events
    with _trace_lock:
        events, _trace_events = _trace_events, []
        return events


class TickInputs(NamedTuple):
    """One scheduling problem per row. See featurize.py for construction."""

    # --- filter stage ---
    filter_enabled: jax.Array  # bool[B,5] (ops.filters.F_* order)
    api_ok: jax.Array          # bool[B,C]
    taint_ok_new: jax.Array    # bool[B,C]
    taint_ok_cur: jax.Array    # bool[B,C]
    selector_ok: jax.Array     # bool[B,C]
    placement_has: jax.Array   # bool[B]
    placement_ok: jax.Array    # bool[B,C]
    request: jax.Array         # i64[B,R]
    alloc: jax.Array           # i64[C,R]
    used: jax.Array            # i64[C,R]
    # --- score stage ---
    score_enabled: jax.Array   # bool[B,5] (ops.scores.S_* order)
    taint_counts: jax.Array    # i64[B,C]
    affinity_scores: jax.Array # i64[B,C]
    # --- out-of-process (webhook) plugins, evaluated host-side ---
    webhook_ok: jax.Array      # bool[B,C]; AND-ed into the filter result
    webhook_scores: jax.Array  # i64[B,C]; added to the score totals
    # --- select stage ---
    max_clusters: jax.Array    # i32[B]; INT32_INF = unlimited, <0 = none
    # --- replicas stage ---
    mode_divide: jax.Array     # bool[B]
    sticky: jax.Array          # bool[B]
    current_mask: jax.Array    # bool[B,C]
    current_replicas: jax.Array  # i64[B,C]; NIL_REPLICAS = nil entry
    total: jax.Array           # i32[B]
    weights_given: jax.Array   # bool[B]
    weights: jax.Array         # i32[B,C] static policy weights
    min_replicas: jax.Array    # i32[B,C]
    max_replicas: jax.Array    # i32[B,C]; INT32_INF = unbounded
    scale_max: jax.Array       # i32[B,C]; INT32_INF = unbounded
    capacity: jax.Array        # i32[B,C]; INT32_INF = no estimate
    keep_unschedulable: jax.Array  # bool[B]
    avoid_disruption: jax.Array    # bool[B]
    tiebreak: jax.Array        # i32[B,C]
    # --- dynamic weights ---
    cpu_alloc: jax.Array       # i64[C] Quantity.Value() cores
    cpu_avail: jax.Array       # i64[C]
    # --- padding ---
    cluster_valid: jax.Array   # bool[C]; False marks padded cluster slots


class TickOutputs(NamedTuple):
    """Mask outputs are int8 (0/1) and numeric outputs int32, NOT bool /
    i64: device->host transfer of bool arrays is pathologically slow on
    the tunneled TPU backend (~35x vs int8 for the same bytes), and the
    tick's outputs are the per-reconcile transfer volume."""

    selected: jax.Array   # i8[B,C] final placements (0/1)
    replicas: jax.Array   # i32[B,C]; meaningful only where counted
    counted: jax.Array    # i8[B,C]; 0 = placement carries no replica
                          # count (Duplicate mode / nil sticky entries)
    feasible: jax.Array   # i8[B,C] post-filter (introspection)
    scores: jax.Array     # i32[B,C] post-normalize totals (introspection)
    reasons: jax.Array    # i32[B,C] rejection bitmask (ops.reasons); 0
                          # exactly where selected — the decision audit
                          # plane the flight recorder serves


def fnv_tiebreak_plane(key_bytes, key_len, name_hash_state) -> jax.Array:
    """The planner tie-break plane: continue each cluster name's FNV-1
    state over the object key's bytes (h = h*prime ^ byte, uint32
    wraparound), then map to order-preserving int32 (hashing.py
    semantics).  O(B*C*L) — the single most expensive part of
    expand_compact, and the only per-(object, cluster) input that is
    STABLE across ticks for unchanged rows: the engine precomputes it
    into a device-resident per-chunk plane (patched row-wise on churn)
    so the drift survivor kernels never re-run the byte scan."""
    b = key_bytes.shape[0]
    c = name_hash_state.shape[0]
    prime = jnp.uint32(16777619)
    state0 = jnp.broadcast_to(
        jnp.asarray(name_hash_state), (b, c)
    ).astype(jnp.uint32)
    key_cols = jnp.asarray(key_bytes).T  # [L, B] — scanned xs
    key_len = jnp.asarray(key_len)
    n_bytes = key_cols.shape[0]

    def fnv_step(state, xs):
        byte, j = xs
        upd = (state * prime) ^ byte.astype(jnp.uint32)[:, None]
        keep = (j < key_len)[:, None]
        return jnp.where(keep, upd, state), None

    state, _ = jax.lax.scan(
        fnv_step, state0, (key_cols, jnp.arange(n_bytes))
    )
    return jax.lax.bitcast_convert_type(
        state ^ jnp.uint32(0x80000000), jnp.int32
    )


def expand_compact(ci, tiebreak=None) -> TickInputs:
    """Device-side expansion of CompactInputs into the dense planes the
    fused tick consumes: vocabulary-table gathers, sparse policy
    scatters, and the planner tie-break FNV-1 hash — all in HBM, where
    the [B, C] planes cost bandwidth instead of host-link transfer
    (scheduler/compact.py explains why this is the 100k x 5k enabler).

    Bit-exact with scheduler/featurize.featurize: the tables are built
    by the same host matching code, and the FNV continuation reproduces
    utils/hashing.fnv32_extend + uint32_to_sortable_int32 exactly.

    ``tiebreak`` (i32[B, C]) short-circuits the FNV byte scan with a
    precomputed plane — the engine's drift survivor kernels gather rows
    from a per-chunk device-resident plane built once per upload and
    patched incrementally, so the scan's O(B*C*L) cost stays off the
    per-drift floor."""
    b = ci.gvk_id.shape[0]
    c = ci.cluster_valid.shape[0]
    _note_trace("expand_compact", b, c)

    api_ok = ci.api_matrix[ci.gvk_id]
    taint_row = ci.taint_set_id  # i32[C]
    taint_ok_new = ci.taint_new[ci.tol_id][:, taint_row]
    taint_ok_cur = ci.taint_cur[ci.tol_id][:, taint_row]
    taint_counts = ci.taint_prefer[ci.tol_id][:, taint_row]
    selector_ok = ci.sel_matrix[ci.sel_id]
    affinity_scores = ci.pref_matrix[ci.pref_id]
    placement_ok = ci.place_matrix[ci.place_id]

    # Sparse per-(object, cluster) policy entries -> dense grids.  The
    # EMPTY_SLOT sentinel is out of range for any cluster padding, so
    # mode='drop' ignores unused entries.
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]

    def scatter(default, vals, dtype):
        base = jnp.full((b, c), default, dtype)
        return base.at[rows, ci.sparse_idx].set(vals.astype(dtype), mode="drop")

    min_replicas = scatter(0, ci.sparse_min, jnp.int32)
    max_replicas = scatter(INT32_INF, ci.sparse_max, jnp.int32)
    weights = scatter(0, ci.sparse_weight, jnp.int32)
    capacity = scatter(INT32_INF, ci.sparse_capacity, jnp.int32)
    cur_present = ci.sparse_cur != -2  # CUR_ABSENT
    current_mask = (
        jnp.zeros((b, c), bool)
        .at[rows, ci.sparse_idx]
        .set(cur_present, mode="drop")
    )
    current_replicas = scatter(
        NIL_REPLICAS, jnp.where(ci.sparse_cur >= 0, ci.sparse_cur, NIL_REPLICAS),
        jnp.int32,
    )

    if tiebreak is None:
        tiebreak = fnv_tiebreak_plane(
            ci.key_bytes, ci.key_len, ci.name_hash_state
        )

    return TickInputs(
        filter_enabled=ci.filter_enabled,
        api_ok=api_ok,
        taint_ok_new=taint_ok_new,
        taint_ok_cur=taint_ok_cur,
        selector_ok=selector_ok,
        placement_has=ci.placement_has,
        placement_ok=placement_ok,
        request=ci.request,
        alloc=ci.alloc,
        used=ci.used,
        score_enabled=ci.score_enabled,
        taint_counts=taint_counts,
        affinity_scores=affinity_scores,
        webhook_ok=jnp.ones((b, c), bool),
        webhook_scores=jnp.zeros((b, c), jnp.int32),
        max_clusters=ci.max_clusters,
        mode_divide=ci.mode_divide,
        sticky=ci.sticky,
        current_mask=current_mask,
        current_replicas=current_replicas,
        total=ci.total,
        weights_given=ci.weights_given,
        weights=weights,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        scale_max=max_replicas,
        capacity=capacity,
        keep_unschedulable=ci.keep_unschedulable,
        avoid_disruption=ci.avoid_disruption,
        tiebreak=tiebreak,
        cpu_alloc=ci.cpu_alloc,
        cpu_avail=ci.cpu_avail,
        cluster_valid=ci.cluster_valid,
    )


def _phase1(inp: TickInputs):
    """The dense-but-cheap front of the tick: filter masks, reason bits
    and per-cell score totals — elementwise work plus per-row
    reductions, NO sorts.  Shared verbatim by the dense and narrow
    solves, so the (feasible, reasons, totals) planes are bit-identical
    between them by construction."""
    # --- Filter ---
    fit_ok = F.resources_fit(inp.request, inp.alloc, inp.used)
    feasible, reasons = F.combine_filters_explain(
        inp.filter_enabled,
        inp.api_ok,
        inp.taint_ok_new,
        inp.taint_ok_cur,
        inp.current_mask,
        fit_ok,
        inp.placement_has,
        inp.placement_ok,
        inp.selector_ok,
    )
    reasons = (
        reasons
        | jnp.where(~inp.webhook_ok, jnp.int32(RSN.REASON_WEBHOOK_FILTER), 0)
        | jnp.where(
            ~inp.cluster_valid[None, :], jnp.int32(RSN.REASON_CLUSTER_INVALID), 0
        )
    )
    feasible = feasible & inp.cluster_valid[None, :] & inp.webhook_ok

    # --- Score + Normalize ---
    totals = S.total_scores(
        inp.score_enabled,
        feasible,
        inp.request,
        inp.alloc,
        inp.used,
        inp.taint_counts,
        inp.affinity_scores,
    )
    # Webhook scores arrive pre-computed (one HTTP call per object x
    # cluster happens host-side); like in-tree plugin sums they only
    # matter on feasible clusters.
    totals = totals + jnp.where(feasible, inp.webhook_scores, 0)
    return feasible, reasons, totals


def _current_plane(inp: TickInputs):
    """The planner's current-replica grid: NIL sticky entries stand in
    for the full desired total (scheduler.go treats a nil count as
    'everything here')."""
    total64 = inp.total.astype(jnp.int64)
    return jnp.where(
        inp.current_mask,
        jnp.where(
            inp.current_replicas == NIL_REPLICAS, total64[:, None], inp.current_replicas
        ),
        0,
    ).astype(jnp.int32)


def _planner_weights(inp: TickInputs, selected):
    """Static-or-dynamic per-cluster weights, zeroed outside the
    selection — dense elementwise math (dynamic_weights is reductions
    over the selection, no sorts), shared by the dense and narrow
    solves."""
    dyn_w = dynamic_weights(selected, inp.cpu_alloc, inp.cpu_avail)
    weights = jnp.where(
        inp.weights_given[:, None], inp.weights, dyn_w
    ).astype(jnp.int32)
    return jnp.where(selected, weights, 0)


# ktlint: ignore[aot-ledger-coverage] oracle/test entry point: the engine never dispatches this jit — it re-traces schedule_tick.__wrapped__ inside its own aot+ledger-wrapped tick programs (see scheduler/engine._tick_with_diff)
@jax.jit
def schedule_tick(inp: TickInputs) -> TickOutputs:
    _note_trace(
        "schedule_tick", inp.total.shape[0], inp.cluster_valid.shape[0]
    )
    feasible, reasons, totals = _phase1(inp)

    # --- Select ---
    selected = select_topk(totals, feasible, inp.max_clusters)

    # --- Replicas (Divide mode) ---
    weights = _planner_weights(inp, selected)
    plan_out = plan_batch_jit(
        PlannerInputs(
            weight=weights,
            min_replicas=jnp.where(selected, inp.min_replicas, 0),
            max_replicas=inp.max_replicas,
            scale_max=inp.scale_max,
            capacity=inp.capacity,
            tiebreak=inp.tiebreak,
            member=selected,
            total=inp.total,
            current=_current_plane(inp),
            avoid_disruption=inp.avoid_disruption,
            keep_unschedulable=inp.keep_unschedulable,
        )
    )
    # The RSP merges capacity overflow back into the result as
    # "nice to schedule" replicas (rsp.go:158-177) and drops zero entries.
    divide_replicas = (plan_out.plan + plan_out.overflow).astype(jnp.int64)
    return _finalize(inp, feasible, reasons, totals, selected, divide_replicas)


def _finalize(
    inp: TickInputs, feasible, reasons, totals, selected, divide_replicas
) -> TickOutputs:
    """Shared tail of the dense and narrow solves: select/divide reason
    bits, Duplicate-vs-Divide output shaping, the sticky-cluster
    short-circuit, and the reasons==0-iff-selected invariant.  All
    elementwise — given equal (selected, divide_replicas) planes the
    outputs are bit-identical."""
    # Feasible pairs the top-K cut: score rank >= K (including K == 0
    # for a negative maxClusters).
    reasons = reasons | jnp.where(
        feasible & ~selected, jnp.int32(RSN.REASON_MAX_CLUSTERS), 0
    )
    # Zero entries are dropped; negative entries (pathological min>max
    # policies) are preserved, as the reference's merge does.
    divide_selected = selected & (divide_replicas != 0)

    # Selected by top-K but dropped by the Divide-mode zero-entry merge.
    reasons = reasons | jnp.where(
        inp.mode_divide[:, None] & selected & ~divide_selected,
        jnp.int32(RSN.REASON_ZERO_REPLICAS),
        0,
    )

    mode_divide = inp.mode_divide[:, None]
    out_selected = jnp.where(mode_divide, divide_selected, selected)
    out_replicas = jnp.where(
        mode_divide, jnp.where(divide_selected, divide_replicas, 0), NIL_REPLICAS
    )
    out_counted = mode_divide & divide_selected

    # --- Sticky-cluster short-circuit (generic_scheduler.go:103-107) ---
    sticky_active = (inp.sticky & jnp.any(inp.current_mask, axis=-1))[:, None]
    out_selected = jnp.where(sticky_active, inp.current_mask, out_selected)
    out_replicas = jnp.where(
        sticky_active,
        jnp.where(inp.current_mask, inp.current_replicas, 0),
        out_replicas,
    )
    out_counted = jnp.where(
        sticky_active,
        inp.current_mask & (inp.current_replicas != NIL_REPLICAS),
        out_counted,
    )
    out_replicas = jnp.where(out_selected, out_replicas, 0)

    # Sticky short-circuit reasons: the current clusters win regardless
    # of plugin verdicts; everything else is cut by stickiness (the
    # filter bits are kept for context — they explain what WOULD reject
    # the pair if the object were rescheduled from scratch).
    reasons = jnp.where(
        sticky_active & ~inp.current_mask,
        reasons | jnp.int32(RSN.REASON_STICKY),
        reasons,
    )
    # Invariant the flight recorder (and test_explain) rely on:
    # reasons == 0 exactly where selected.
    reasons = jnp.where(out_selected, 0, reasons)

    return TickOutputs(
        selected=out_selected.astype(jnp.int8),
        replicas=out_replicas.astype(jnp.int32),
        counted=(out_counted & out_selected).astype(jnp.int8),
        feasible=feasible.astype(jnp.int8),
        scores=totals.astype(jnp.int32),
        reasons=reasons.astype(jnp.int32),
    )


# -- narrow solve ---------------------------------------------------------
# The tick's cost at wide cluster axes is its sorts: the select stage's
# full-width rank and the planner's per-row processing-order sorts are
# O(B*C*logC) while everything else is elementwise.  The narrow solve is
# the candidate-set reduction of large-scale cluster schedulers (Borg
# samples a feasible machine subset; Sparrow's batch sampling makes the
# same bet): keep phase 1 dense and cheap, then rank/bin-pack over M
# candidate columns per row instead of C.  Exactness is ENFORCED by a
# per-row certificate, not hoped for — uncertified rows are re-solved
# through the dense program by the engine, so placements are
# bit-identical by construction:
#
# * Rows where the top-K cut cannot engage (max_clusters unlimited, >=
#   nfeas, or negative) need no select sort at all: selection IS the
#   feasible mask, taken dense from phase 1.
# * Rows with an engaged cut select over the top-M columns by the select
#   stage's own (-total, index) comparator, packed into one int64 key
#   and SINGLE-key sorted (ties prefer the lower index, exactly like
#   lax.top_k — whose index payload would lower to a row-serial
#   variadic sort on CPU, ~6x slower).  The certificate compares the
#   worst selected composite key against the best feasible
#   NON-candidate dense-side, so a tie at the M boundary (or any
#   backend sort quirk) forces the dense fallback instead of a silent
#   mis-ranking.
# * The planner narrows to the top-M members in its OWN processing order
#   (ops.planner.processing_key), with the left-out members' summed
#   weight fed in as a phantom quota denominator; ops.planner's
#   _plan_one_narrow certifies that the remainder cascade provably never
#   reached the tail (see its docstring for the argument).  Columns
#   carrying planner structure (min/max/capacity/current) outside the
#   candidate set also fail the certificate.
# * Sticky rows short-circuit dense (elementwise) and always certify.
#
# The composite select key is (sort key, index) packed into int64 —
# collision-free, so the certificate needs no backend stability
# assumptions.

_CERT_INF = np.int64(1) << 62


def _select_comp(totals, feasible, c, iota, i32_keys):
    """The select stage's collision-free composite key ((-total, index)
    ascending) for the narrow candidate sort and its certificate.

    Returns (comp, key_ok bool[B], cert_inf): with ``i32_keys`` (and a
    cluster axis narrow enough to leave >= 12 value bits) the key packs
    into int32 — on CPU the [B, C] single-key sort is the narrow
    kernel's floor, and an i32 sort moves half the bytes of the i64
    one.  The demotion is CERT-GUARDED, not assumed: rows whose
    feasible totals overflow the narrowed value field (webhook scores
    can reach int32max/2) get ``key_ok`` False and must take the dense
    fallback — the same pattern as the quantized planner key."""
    if i32_keys:
        cbits = max(1, (c - 1).bit_length())
        if cbits <= 18:
            lim = np.int64(1) << (30 - cbits)
            t64 = totals.astype(jnp.int64)
            inrange = (t64 < lim) & (t64 > -lim)
            key_ok = ~jnp.any(feasible & ~inrange, axis=-1)
            key1 = jnp.where(
                feasible & inrange,
                -totals.astype(jnp.int32),
                jnp.int32(lim),
            )
            comp = (key1 << cbits) | iota
            return comp, key_ok, jnp.int32(np.iinfo(np.int32).max)
    key1 = jnp.where(
        feasible, -totals.astype(jnp.int32), jnp.iinfo(jnp.int32).max
    )
    comp = key1.astype(jnp.int64) * c + iota
    return comp, jnp.ones(totals.shape[0], bool), _CERT_INF


def _decode_comp(sorted_comp, c, i32_keys):
    """Low-bits decode of a sorted composite back to column indices."""
    if i32_keys:
        cbits = max(1, (c - 1).bit_length())
        if cbits <= 18:
            return (sorted_comp & ((1 << cbits) - 1)).astype(jnp.int32)
    return (sorted_comp % c).astype(jnp.int32)


@shardguard.rows_first
def _plan_topm(inp: TickInputs, selected, weights, m: int, cs):
    """Planner over the top-M member slots in ITS OWN processing order —
    the narrow planner leg, shared by the narrow tick, the score-only
    solve and the selection-known drift replan.  Returns
    (divide_replicas i64[B, C], cert bool[B]); cert True iff
    plan_batch_narrow's phantom-tail certificate held and no selected
    special column was left outside the slots."""
    b, c = selected.shape
    m = min(m, c)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    iota = lax.broadcasted_iota(jnp.int32, (b, c), 1)
    special = (
        (inp.min_replicas > 0)
        | (inp.max_replicas != INT32_INF)
        | (inp.scale_max != INT32_INF)
        | (inp.capacity != INT32_INF)
        | inp.current_mask
    )
    # Candidate PRIORITY boosts structured columns so they land in the
    # slots; the CERTIFICATE compares the planner's true processing
    # order (weight, tiebreak — no special bit), so a low-weight special
    # candidate that would genuinely order after a heavier tail member
    # fails the cert instead of silently taking its replicas.
    comp_prio = processing_key(weights, inp.tiebreak, special)
    comp_true = processing_key(
        weights, inp.tiebreak, jnp.zeros((b, c), bool)
    )
    # Same single-key-sort trick as cand_s, descending.  comp_prio fits
    # 53 bits (special bit 52 | weight 20b | inverted tiebreak 32b), so
    # packing the inverted index underneath costs a `shift`-bit
    # right-shift of the priority when 53 + cbits > 63: exact (shift=0)
    # through C=1024; at C=5120 the low 3 tiebreak-hash bits are
    # dropped, so an fnv32 near-collision (|delta| < 8) straddling the
    # M boundary may pick a different candidate than top_k would — the
    # certificate compares TRUE processing keys, so any mis-pick that
    # could matter falls back to dense instead of mis-planning.
    # Selected columns get key (prio+1 | inv_iota) > any unselected
    # (inv_iota alone), and keys stay unique per column, so spare
    # slots decode to the lowest-index unselected columns — exactly
    # top_k's tie order on the masked -1s (member_p masks them off).
    # The one key that can wrap ((prio>>shift)+1 == 2^(63-cbits),
    # attainable only with the special bit AND maxed weight AND
    # tiebreak == INT32_MIN) sorts itself out of the candidates, and an
    # excluded selected special column always trips spec_out -> dense
    # fallback, so the wrap cannot produce a silently-wrong plan.
    cbits = max(1, (c - 1).bit_length())
    shift = max(0, 53 + cbits - 63)
    inv_iota = jnp.int64((1 << cbits) - 1) - iota.astype(jnp.int64)
    key_p = jnp.where(
        selected,
        (((comp_prio >> shift) + 1) << cbits) | inv_iota,
        inv_iota,
    )
    sorted_p = -lax.sort(cs(-key_p), dimension=-1)[:, :m]
    cand_p = (
        jnp.int64((1 << cbits) - 1) - (sorted_p & ((1 << cbits) - 1))
    ).astype(jnp.int32)
    cand_p = jnp.sort(cand_p, axis=-1)

    def take_p(plane):
        return jnp.take_along_axis(cs(plane), cand_p, axis=-1)

    cand_p_mask = jnp.zeros((b, c), bool).at[rows, cand_p].set(True)
    outside = selected & ~cand_p_mask
    tail_w = jnp.sum(
        jnp.where(outside, jnp.maximum(weights, 0), 0),
        axis=-1,
        dtype=jnp.int32,
    )
    best_tail = jnp.max(
        jnp.where(outside, comp_true, jnp.int64(-1)), axis=-1
    )
    spec_out = jnp.any(outside & special, axis=-1)

    member_p = take_p(selected)
    plan_out, pcert = plan_batch_narrow(
        PlannerInputs(
            weight=take_p(weights),
            min_replicas=jnp.where(member_p, take_p(inp.min_replicas), 0),
            max_replicas=take_p(inp.max_replicas),
            scale_max=take_p(inp.scale_max),
            capacity=take_p(inp.capacity),
            tiebreak=take_p(inp.tiebreak),
            member=member_p,
            total=inp.total,
            current=take_p(_current_plane(inp)),
            avoid_disruption=inp.avoid_disruption,
            keep_unschedulable=inp.keep_unschedulable,
        ),
        tail_w,
        best_tail,
        take_p(comp_true),
    )
    divide_n = (plan_out.plan + plan_out.overflow).astype(jnp.int64)
    divide_replicas = (
        jnp.zeros((b, c), jnp.int64).at[rows, cand_p].set(divide_n)
    )
    return divide_replicas, pcert & ~spec_out


@shardguard.rows_first
def _narrow_solve(
    inp: TickInputs, feasible, reasons, totals, m: int, rows_only,
    i32_keys: bool,
) -> tuple[TickOutputs, jax.Array]:
    """The select + planner back half of the narrow solve, given a
    phase-1 triple (from ``_phase1`` for the narrow tick, or from
    ``_phase1_from_stored`` for the drift score-only path)."""
    b, c = inp.api_ok.shape[0], inp.cluster_valid.shape[0]
    m = min(m, c)

    def cs(x):
        if rows_only is None:
            return x
        return jax.lax.with_sharding_constraint(x, rows_only)

    feasible = cs(feasible)
    totals = cs(totals)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    iota = lax.broadcasted_iota(jnp.int32, (b, c), 1)

    def take(plane):
        return jnp.take_along_axis(cs(plane), cand_s, axis=-1)

    # --- select resolution ------------------------------------------------
    nfeas = jnp.sum(feasible, axis=-1, dtype=jnp.int32)
    k_eff = jnp.where(
        inp.max_clusters < 0, 0, jnp.minimum(inp.max_clusters, jnp.int32(c))
    )
    # The cut cannot engage: selection is the feasible set, no sort.
    kinf = k_eff >= nfeas

    # Candidate selection is a SINGLE-key sort of the collision-free
    # composite (key1 asc, index asc) — not lax.top_k: XLA lowers
    # top_k's index payload to a variadic sort, which on CPU is a
    # row-serial comparator loop ~6x slower than the packed single-key
    # form (36.0 -> 6.5ms at [256, 512], m=128).  The first m sorted
    # values decode to exactly top_k's indices, ties preferring the
    # lower index, same as top_k.  _select_comp narrows the key to i32
    # when the range analysis allows (cert-guarded, i64 fallback).
    comp_sel, key_ok, cert_inf = _select_comp(
        totals, feasible, c, iota, i32_keys
    )
    cand_s = _decode_comp(
        lax.sort(cs(comp_sel), dimension=-1)[:, :m], c, i32_keys
    )
    cand_s = jnp.sort(cand_s, axis=-1)  # ascending: narrow slot order
    #                                     preserves the dense index order
    fea_s = take(feasible)
    sel_n = select_topk(take(totals), fea_s, inp.max_clusters)
    sel_scatter = (
        jnp.zeros((b, c), bool).at[rows, cand_s].set(sel_n)
    )
    selected = jnp.where(kinf[:, None], feasible, sel_scatter)

    # Select certificate (comp_sel is collision-free when key_ok): every
    # feasible non-candidate must rank strictly after every selected
    # column, and the narrow cut must have had enough feasible
    # candidates to fill k (or seen every feasible column).
    cand_mask = jnp.zeros((b, c), bool).at[rows, cand_s].set(True)
    out_feas = feasible & ~cand_mask
    best_out = jnp.min(
        jnp.where(out_feas, comp_sel, cert_inf), axis=-1
    )
    worst_sel = jnp.max(
        jnp.where(sel_n, jnp.take_along_axis(comp_sel, cand_s, -1), -cert_inf),
        axis=-1,
    )
    nf_cand = jnp.sum(fea_s, axis=-1, dtype=jnp.int32)
    cert_sel = kinf | (
        key_ok
        & ((nf_cand >= k_eff) | (nfeas == nf_cand))
        & (best_out > worst_sel)
    )

    # --- planner candidates: top-M members in processing order ------------
    weights = _planner_weights(inp, selected)
    divide_replicas, plan_cert = _plan_topm(inp, selected, weights, m, cs)

    # No sticky shortcut here: sticky placements bypass the solve, but
    # their REASONS keep the would-be pipeline's zero-replica bits
    # (explain_one's "context" contract), so sticky rows certify under
    # the same select+planner conditions as everyone else.
    cert = cert_sel & (~inp.mode_divide | plan_cert)
    out = _finalize(inp, feasible, reasons, totals, selected, divide_replicas)
    return out, cert.astype(jnp.int8)


def schedule_tick_narrow(
    inp: TickInputs, m: int, rows_only=None, i32_keys: bool = False,
    phase1=None,
) -> tuple[TickOutputs, jax.Array]:
    """Two-phase narrow solve; returns (outputs, cert i8[B]).

    ``m`` is a static candidate width (engine: KT_NARROW_M-floored pow2
    of the chunk's finite maxClusters bound, capped at the cluster
    bucket).  ``cert[b] == 1`` guarantees the row's outputs are
    bit-identical to ``schedule_tick``; rows with 0 must be re-solved
    dense (the engine's fallback sub-batch).  ``rows_only`` (a mesh
    NamedSharding) constrains the per-row top-k/gather sources to
    rows-only layout — like the pack sort, GSPMD must not run them on a
    sharded cluster axis.  ``i32_keys`` (KT_PHASE1_I32) demotes the
    select candidate composite to int32 where the key range analysis
    allows — cert-guarded per row, i64 semantics otherwise.

    ``phase1`` optionally supplies a precomputed (feasible bool[B, C],
    reasons i32[B, C], totals i64[B, C]) triple — the KT_PALLAS slab
    path computes it with the fused ops/pallas_slab.py kernel instead
    of the XLA ``_phase1``; the supplied triple must be bit-identical
    to ``_phase1(inp)`` (the Pallas kernel runs the very same integer
    plugin math, enforced by interpret-mode parity tests), so the
    select/planner certificates and outputs are unchanged."""
    b, c = inp.api_ok.shape[0], inp.cluster_valid.shape[0]
    _note_trace("schedule_tick_narrow", b, c)
    feasible, reasons, totals = (
        _phase1(inp) if phase1 is None else phase1
    )
    return _narrow_solve(
        inp, feasible, reasons, totals, m, rows_only, i32_keys
    )


# -- stored-plane phase 1 (drift survivors) -------------------------------
# A capacity drift cannot move any topology-derived filter result (api/
# taint/placement/affinity/webhook/validity): of the filter stage, ONLY
# resources_fit reads the cluster resource planes.  For rows whose
# cached reason plane is trustworthy (clean cache hit, same topology,
# no stale-out marking — the engine's drift path enforces all three),
# phase 1 can therefore be reconstructed WITHOUT re-running the filter
# gathers or the expand FNV scan:
#
# * non-fit filter verdicts come from the stored reason bits (exact:
#   a selected column carries mask 0 but was feasible, so every filter
#   passed; a rejected column's topology bits cannot have drifted);
# * the ONE capacity-derived bit (resources_fit — which the skip path
#   is allowed to leave stale on infeasible columns) is recomputed
#   dense against the new cluster planes;
# * the score plane is recomputed in full over the NEW feasibility
#   (fit flips shift the normalization maxima, so stored totals are
#   unusable for these rows — this is the "score-only phase 1": the
#   score half runs, the filter half is table lookups on stored bits).
#
# Sticky-active rows are the one soundness exception (their current
# columns carry mask 0 regardless of filter verdicts) — both consumers
# fail the certificate for them, and the engine's gate never routes
# sticky rows to these kernels in the first place.

_NONFIT_BLOCK = np.int32(RSN.FILTER_REASON_MASK & ~RSN.REASON_RESOURCES_FIT)


def _stored_filters(inp: TickInputs, reasons_rows):
    """(feasible, base_reasons) for drift survivor rows, from the
    stored reason plane plus a dense resources_fit recompute — no
    filter-table gathers, no reason-bit assembly beyond the fit bit."""
    fit_ok = F.resources_fit(inp.request, inp.alloc, inp.used)
    fit_enabled = inp.filter_enabled[:, F.F_RESOURCES_FIT, None]
    topo_ok = (reasons_rows & _NONFIT_BLOCK) == 0
    feasible = (
        topo_ok
        & (~fit_enabled | fit_ok)
        & inp.cluster_valid[None, :]
        & inp.webhook_ok
    )
    fit_bit = jnp.where(
        fit_enabled & ~fit_ok, jnp.int32(RSN.REASON_RESOURCES_FIT), 0
    )
    base_reasons = (
        reasons_rows
        & ~jnp.int32(RSN.SELECT_REASON_MASK | RSN.REASON_RESOURCES_FIT)
    ) | fit_bit
    return feasible, base_reasons


def _phase1_from_stored(inp: TickInputs, reasons_rows):
    """(feasible, base_reasons, totals): _stored_filters plus the full
    score recompute (the "score-only phase 1")."""
    feasible, base_reasons = _stored_filters(inp, reasons_rows)
    totals = S.total_scores(
        inp.score_enabled,
        feasible,
        inp.request,
        inp.alloc,
        inp.used,
        inp.taint_counts,
        inp.affinity_scores,
    )
    totals = totals + jnp.where(feasible, inp.webhook_scores, 0)
    return feasible, base_reasons, totals


def drift_scoreonly(
    inp: TickInputs,   # gathered survivor rows [n, C] (expanded)
    reasons_rows,      # i32[n, C] previous reason plane rows
    m: int,
    rows_only=None,
    i32_keys: bool = False,
) -> tuple[TickOutputs, jax.Array]:
    """Score-only re-solve of fit-flip survivors whose top-K cut may
    engage: phase 1 reconstructed from stored planes (see the module
    comment above), then the UNCHANGED narrow select/planner machinery.
    Returns (outputs [n, C], cert i8[n]); cert semantics match
    ``schedule_tick_narrow`` plus a fail-closed arm for sticky-active
    rows (whose stored reasons cannot reconstruct feasibility)."""
    n, c = inp.api_ok.shape[0], inp.cluster_valid.shape[0]
    _note_trace("drift_scoreonly", n, c)
    feasible, base_reasons, totals = _phase1_from_stored(inp, reasons_rows)
    out, cert = _narrow_solve(
        inp, feasible, base_reasons, totals, m, rows_only, i32_keys
    )
    sticky_active = inp.sticky & jnp.any(inp.current_mask, axis=-1)
    return out, (cert.astype(bool) & ~sticky_active).astype(jnp.int8)


def drift_replan(
    inp: TickInputs,   # gathered survivor rows [n, C] (expanded)
    reasons_rows,      # i32[n, C] previous reason plane rows
    scores_rows,       # i32[n, C] stored score plane rows (NOT recomputed)
    m: int,
) -> tuple[TickOutputs, jax.Array]:
    """Selection-known replan of kinf fit-flip survivors: rows whose
    top-K cut provably cannot engage (maxClusters unlimited, negative,
    or >= the NEW feasible count) need NO select sort and NO scores —
    the new selection IS the new feasible set, which ``_stored_filters``
    reconstructs as prev_feas ± the fit-flipped columns.  Duplicate
    rows are then done (no planner); Divide rows run the top-M
    processing-order planner leg only.  The kernel runs ONE full-width
    sort (the planner candidate key) where the narrow slab runs three,
    plus the FNV scan and the five score plugins it also skips.

    The score INTROSPECTION plane is the one thing that goes stale:
    outputs carry ``scores_rows`` unrecomputed, so a replan row's
    /debug/explain scores and recorded top-k reflect the last solved
    score plane (the same fresh-as-of-last-solve contract the gate's
    skip path already has).  That staleness is provably decision-free:
    replan rows are host-kinf (maxClusters unlimited/negative), so the
    gate's rank refinement, the resolve path and the select cut never
    consult their stored scores.  Placements, replicas and reason
    planes are EXACT.

    Returns (outputs [n, C], cert i8[n]).  cert == 1 guarantees
    placement/replica/reason outputs bit-identical to a dense re-solve;
    rows with 0 (cut would engage, sticky, planner cert failure) take
    the slab path."""
    n, c = inp.api_ok.shape[0], inp.cluster_valid.shape[0]
    _note_trace("drift_replan", n, c)
    feasible, base_reasons = _stored_filters(inp, reasons_rows)
    totals = scores_rows

    nfeas = jnp.sum(feasible, axis=-1, dtype=jnp.int32)
    k_eff = jnp.where(
        inp.max_clusters < 0, 0, jnp.minimum(inp.max_clusters, jnp.int32(c))
    )
    kinf = (inp.max_clusters == INT32_INF) | (k_eff >= nfeas)
    # Negative maxClusters selects nothing; otherwise the cut cannot
    # engage and selection equals the new feasible set.
    selected = feasible & (inp.max_clusters >= 0)[:, None]
    sticky_active = inp.sticky & jnp.any(inp.current_mask, axis=-1)

    weights = _planner_weights(inp, selected)
    divide_replicas, plan_cert = _plan_topm(
        inp, selected, weights, m, lambda x: x
    )
    cert = (
        (kinf | (inp.max_clusters < 0))
        & ~sticky_active
        & (~inp.mode_divide | plan_cert)
    )
    out = _finalize(
        inp, feasible, base_reasons, totals, selected, divide_replicas
    )
    return out, cert.astype(jnp.int8)


def drift_survivor(
    inp: TickInputs,   # gathered survivor rows [n, C] (expanded)
    reasons_rows,      # i32[n, C] previous reason plane rows
    m: int,
    rows_only=None,
    i32_keys: bool = False,
) -> tuple[TickOutputs, jax.Array]:
    """The UNIFIED drift-survivor kernel: ONE program for every gate
    survivor, whatever its classification (the ISSUE 11 tentpole).

    PR 10 ran three separate survivor streams per gated chunk —
    ``drift_resolve`` (recompute rows without a fit flip),
    ``drift_replan`` (kinf fit-flip rows) and ``drift_scoreonly``
    (finite-K fit-flip rows) — each greedy-grouped independently, so a
    chunk with 90+50+40 survivors padded three {256,128,64} ladders
    (~1.6x the [rows, C] math) and paid three dispatch chains.  The
    score-only solve provably SUBSUMES both others:

    * its stored-filter phase 1 (``_phase1_from_stored``) reconstructs
      feasibility exactly for every trustworthy-reasons row — for
      no-fit-flip rows the dense resources_fit recompute reproduces the
      stored plane bit-for-bit (fit did not move), for fit-flip rows it
      IS the new feasibility;
    * its full score recompute equals the gate-refreshed stored totals
      where no fit flipped (the gate's exactness argument, step 2) and
      is the only correct choice where one did — so unified rows carry
      EXACT fresh score planes, strictly better than replan's
      fresh-as-of-last-solve staleness;
    * the narrow select handles kinf rows sort-cheaply (selection = the
      feasible set; ``kinf`` arm) and finite-K rows by the certified
      candidate sort, so the replan/resolve specializations buy no
      extra exactness — only the padding and dispatches they cost.

    The engine routes ALL survivors of a chunk through this kernel in
    one greedy-grouped stream, carrying a host-side per-row mode vector
    (resolve / replan / score_only) for attribution only — the math is
    mode-blind by design.  Unlike ``drift_resolve`` it consults no
    delta-column info, so wide drifts (D > DRIFT_REFINE_MAX_COLS) ride
    it too.  Cert semantics match ``drift_scoreonly`` exactly (narrow
    select/planner certificates, fail-closed sticky arm); failures drop
    to the slab path bit-identically by construction.
    KT_SURVIVOR_UNIFIED=0 reverts to the three-stream dispatch.

    Returns (outputs [n, C], cert i8[n])."""
    n, c = inp.api_ok.shape[0], inp.cluster_valid.shape[0]
    _note_trace("drift_survivor", n, c)
    feasible, base_reasons, totals = _phase1_from_stored(inp, reasons_rows)
    out, cert = _narrow_solve(
        inp, feasible, base_reasons, totals, m, rows_only, i32_keys
    )
    sticky_active = inp.sticky & jnp.any(inp.current_mask, axis=-1)
    return out, (cert.astype(bool) & ~sticky_active).astype(jnp.int8)


# -- drift gate -----------------------------------------------------------
# A cluster-capacity drift tick must revalidate every row, but the rows
# whose DECISION can actually move are a function of which cluster
# columns changed.  These kernels classify rows exactly, from the cached
# per-object planes plus the previous tick's feasibility plane, without
# running the expensive select/planner stages:
#
#   recompute — the row's placement may change and must be re-scheduled;
#   wcheck    — the selection provably cannot change, but the row uses
#               DYNAMIC weights over a cluster whose CPU figures moved:
#               compare old-vs-new weights (drift_wcheck) and recompute
#               only on a real difference;
#   (neither) — the row's outputs are provably bit-identical.
#
# Exactness argument (each step is checked by tests/test_drift_tick.py's
# randomized differential):
#
# 1. Feasibility depends on the cluster planes ONLY through the
#    resource-fit mask (filters.resources_fit); every other filter input
#    is per-object/topology.  So feasibility can flip only on changed
#    columns — recompute any row with such a flip ("fitflip").
# 2. The normalized score plugins (taint, affinity) read per-object
#    planes and normalize by the per-row max over FEASIBLE columns; the
#    resource plugins are per-cell functions of (request, alloc, used).
#    Hence, absent a fit flip, the score totals change only on changed
#    columns — and a column that is infeasible contributes neither a
#    total nor a normalization max.
# 3. Selection: with max_clusters >= nfeas (or unlimited, or negative =
#    select nothing), the top-K cut never engages — selection IS the
#    feasible set, so score changes cannot move it.  Otherwise the cut
#    is rank-based.  Unchanged columns keep their relative order (their
#    totals are untouched, step 2), so the selected SET changes iff
#    some changed column's top-K membership flips: a non-delta column
#    can enter (leave) the set only when a delta column leaves (enters)
#    it.  For small deltas the gate tests that exactly — it derives the
#    changed columns' new totals from the stored score plane (the
#    resource plugins are per-cell, the normalized plugins untouched)
#    and counts, per the select stage's own (-total, index) comparator,
#    how many feasible columns outrank each delta column before and
#    after.  Wider deltas fall back to the conservative "any delta
#    column feasible" rule.  Either way the gate scatters the updated
#    totals back into the stored score plane, so skipped rows' stored
#    state stays exact for future drift ticks.
# 4. Replicas: the planner consumes per-object inputs plus the weights.
#    Static weights are per-object; dynamic weights read cpu_alloc/
#    cpu_avail of the SELECTED clusters.  A Divide-mode row without
#    given weights whose selection touches a cpu-changed column goes to
#    wcheck: selection is provably unchanged there (step 3), so
#    comparing dynamic_weights old-vs-new on that selection decides
#    replica equality exactly.
# 5. Sticky rows with current placements short-circuit to their current
#    clusters — independent of cluster planes entirely — and are never
#    candidates.
#
# Skipped (and weight-equal wcheck) rows keep their previous outputs;
# their score/reason introspection planes may go stale on changed
# columns, exactly like the engine's existing mask-only "skip" path —
# placement planes stay exact, which is what parity and the delta
# machinery consume.

DRIFT_RECOMPUTE = 1  # gate-mask bit: row must be re-scheduled
DRIFT_WCHECK = 2     # gate-mask bit: row needs the dynamic-weight check
DRIFT_FITFLIP = 4    # gate-mask bit: feasibility flipped at a changed
#                      column — the row's score normalization may shift,
#                      so the sort-free drift_resolve path cannot take it
#                      (the engine routes it through the slab re-solve).

# Widest delta the exact top-K membership refinement runs at: the rank
# counts cost O(Bfin x C x D) compares, so wider drifts use the
# conservative any-delta-column-feasible rule instead.
DRIFT_REFINE_MAX_COLS = 8


def _resource_scores_cols(request, score_enabled, alloc_d, used_d):
    """The cluster-plane-dependent part of a row's score total at the
    given columns: the resource plugins, enabled-masked (taint/affinity
    are per-object and normalization is untouched without a fit flip —
    see the exactness argument above)."""
    parts = (
        (S.S_BALANCED, S.balanced_allocation_score(request, alloc_d, used_d)),
        (S.S_LEAST, S.least_allocated_score(request, alloc_d, used_d)),
        (S.S_MOST, S.most_allocated_score(request, alloc_d, used_d)),
    )
    total = jnp.zeros((request.shape[0], alloc_d.shape[0]), jnp.int64)
    for idx, s in parts:
        total = total + jnp.where(score_enabled[:, idx, None], s, 0)
    return total


def _drift_classify(
    fea_new_d,      # bool[B, D] feasibility of the changed columns, new planes
    prev_feas_d,    # i8[B, D] previous feasibility at the changed columns
    prev_feas,      # i8[B, C] previous feasibility plane
    prev_scores,    # i32[B, C] previous post-normalize totals
    res_old_d,      # i64[B, D] resource-score part at the columns, old planes
    res_new_d,      # i64[B, D] resource-score part at the columns, new planes
    delta_idx,      # i32[D] changed column indices (pad: out of range)
    delta_valid,    # bool[D] slot is a real changed column (not padding)
    delta_cpu,      # bool[D] the column's cpu_alloc/cpu_avail changed
    max_clusters,   # i32[B]
    mode_divide,    # bool[B]
    weights_given,  # bool[B]
    sticky_active,  # bool[B]
    fin_idx,        # i32[Nf] rows with a finite maxClusters (host-known;
    #                 pad: out of range).  Only those rows can have an
    #                 engaged top-K cut, so the rank-count refinement
    #                 runs on this gathered subset instead of all B rows.
    nfeas,          # i32[B] CACHED per-row feasible-column counts.  The
    #                 r11 gate derived this with a full [B, C] pf.sum
    #                 pass on EVERY drift tick (~4.9s of c5 gate device
    #                 time); the engine now maintains the count alongside
    #                 prev_feas — written at every prev-plane store and
    #                 patched by every row repair — so the gate reads a
    #                 [B] vector instead of reducing a [B, C] plane.
):
    """Shared tail of the dense/compact drift gates.

    Returns (i8[B] bit mask, i32[B, C] updated score plane): the mask
    classifies rows, and the score plane is the stored totals with the
    changed columns' values refreshed — so skipped rows' cached state
    stays exact across consecutive drift ticks."""
    b, c = prev_feas.shape
    pf_d = prev_feas_d != 0
    valid = delta_valid[None, :]
    fitflip = ((fea_new_d != pf_d) & valid).any(axis=1)
    dcpu_any = (pf_d & (delta_cpu & delta_valid)[None, :]).any(axis=1)
    # Selection equals the feasible set when the top-K cut cannot engage
    # (unlimited, K >= nfeas, or negative K = empty selection).
    kinf = (
        (max_clusters == INT32_INF)
        | (max_clusters < 0)
        | (max_clusters >= nfeas)
    )

    # Updated totals at the changed columns (masked exactly like the
    # tick: zero where infeasible), and the scatter back into the
    # stored plane (padded delta slots are out of range -> dropped).
    tot_old_d = prev_scores[:, jnp.clip(delta_idx, 0, c - 1)].astype(jnp.int64)
    tot_new_d = jnp.where(pf_d, tot_old_d - res_old_d + res_new_d, 0)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    new_scores = prev_scores.at[
        rows, jnp.broadcast_to(delta_idx[None, :], (b, delta_idx.shape[0]))
    ].set(tot_new_d.astype(jnp.int32), mode="drop")

    d = delta_idx.shape[0]
    if d <= DRIFT_REFINE_MAX_COLS:
        # Exact top-K refinement: the selected set changes iff some
        # delta column's top-K membership flips (unchanged columns keep
        # their relative order, so one can only enter/leave when a
        # delta column leaves/enters).  Membership is counted with the
        # select stage's own comparator — (-total, index) ascending —
        # packed into ONE collision-free int64 key per column (the
        # narrow solve's composite-key trick): "column j outranks delta
        # column d" is a single int64 compare instead of the
        # (>, ==, index<) triple, and the counts run over the gathered
        # finite-K rows only.  kinf rows never consult sel_exposed
        # (`~kinf & sel_exposed` below), so skipping them is exact; the
        # r08 gate computed these counts on a dense [B, C, D] int64
        # broadcast over ALL rows, which was ~95% of the 60.4s c5
        # gate_wait.
        is_delta = jnp.zeros(c, bool).at[delta_idx].set(
            delta_valid, mode="drop"
        )
        ridx = jnp.clip(fin_idx, 0, b - 1)
        pf_g = prev_feas[ridx] != 0                # [Nf, C]
        pf_d_g = pf_d[ridx]                        # [Nf, D]
        iota64 = lax.broadcasted_iota(jnp.int64, pf_g.shape, 1)
        comp = (-prev_scores[ridx].astype(jnp.int64)) * c + iota64
        comp_u = jnp.where(pf_g & ~is_delta[None, :], comp, _CERT_INF)
        didx64 = delta_idx.astype(jnp.int64)[None, :]
        tot_old_g = tot_old_d[ridx]
        tot_new_g = tot_new_d[ridx]
        key_old = (-tot_old_g) * c + didx64        # [Nf, D]
        key_new = (-tot_new_g) * c + didx64

        def above_counts(key_d):
            # Unchanged-column counts: one fused [Nf, C] compare+reduce
            # per delta column (python loop over the static D).
            cnt = jnp.stack(
                [
                    jnp.sum(comp_u < key_d[:, t : t + 1], axis=1,
                            dtype=jnp.int32)
                    for t in range(d)
                ],
                axis=1,
            )
            # Delta-vs-delta comparisons use the same snapshot's totals
            # ([Nf, D, D] is tiny; keys are collision-free, so one int64
            # compare reproduces the (total desc, index asc) order).
            e_beats = key_d[:, :, None] < key_d[:, None, :]
            e_mask = (pf_d_g & valid)[:, :, None]
            return cnt + jnp.sum(e_beats & e_mask, axis=1, dtype=jnp.int32)

        k = jnp.clip(max_clusters[ridx], 0, c)[:, None]
        member_old = pf_d_g & (above_counts(key_old) < k)
        member_new = pf_d_g & (above_counts(key_new) < k)
        sel_moved_g = ((member_old != member_new) & valid).any(axis=1)
        # Finite-K rows with DYNAMIC weights whose top-K selection
        # touches a cpu-changed column: their weight set is the top-K
        # selection (not the feasible set), so the wcheck comparison
        # below cannot decide them — recompute.  (member_old|member_new
        # is exact top-K membership from the rank counts.)
        dyn_fin_g = (
            (member_old | member_new) & (delta_cpu & delta_valid)[None, :]
        ).any(axis=1)
        exposed_g = sel_moved_g | (
            mode_divide[ridx] & ~weights_given[ridx] & dyn_fin_g
        )
        # Scatter back to [B]; padded fin slots are out of range -> drop.
        sel_exposed = (
            jnp.zeros(b, bool).at[fin_idx].set(exposed_g, mode="drop")
        )
    else:
        # Conservative: any feasible delta column may cross the K cut
        # (this also covers the finite-K dynamic-weight exposure, since
        # a cpu-changed column in the selection is feasible).
        sel_exposed = ((fea_new_d | pf_d) & valid).any(axis=1)

    recompute = ~sticky_active & (fitflip | (~kinf & sel_exposed))
    # The weight check is sound ONLY where selection provably equals
    # the feasible set (kinf): dynamic weights are computed over the
    # selection, and that is what drift_wcheck reconstructs from
    # prev_feas.
    wcheck = (
        ~sticky_active
        & ~recompute
        & kinf
        & mode_divide
        & ~weights_given
        & dcpu_any
    )
    mask = (
        recompute.astype(jnp.int8) * DRIFT_RECOMPUTE
        + wcheck.astype(jnp.int8) * DRIFT_WCHECK
        + (fitflip & ~sticky_active).astype(jnp.int8) * DRIFT_FITFLIP
    )
    return mask, new_scores


def drift_gate_dense(
    per_object: dict,
    prev_feas,
    prev_scores,
    alloc_old_d,
    used_old_d,
    alloc_new_d,
    used_new_d,
    delta_idx,
    delta_valid,
    delta_cpu,
    fin_idx,
    nfeas,
):
    """Drift gate over dense cached per-object planes.

    ``per_object`` is the engine's cached device dict (every TickInputs
    field that is not cluster-axis-only); ``*_old_d``/``*_new_d`` are
    the OLD/NEW cluster tensors pre-sliced at the changed columns
    (i64[D, R]); ``delta_idx`` i32[D] names the changed columns (padded
    entries carry an out-of-range index and ``delta_valid`` False);
    ``fin_idx`` i32[Nf] the rows with a finite maxClusters (the only
    rows the rank-count refinement must visit; pad out of range);
    ``nfeas`` i32[B] the engine's cached per-row feasible counts
    (maintained alongside prev_feas — kills the gate's [B, C] pf.sum
    pass, see _drift_classify).
    Returns (i8[B] mask, i32[B, C] refreshed score plane)."""
    b = per_object["total"].shape[0]
    _note_trace("drift_gate", b, prev_feas.shape[1])
    c = prev_feas.shape[1]
    d_safe = jnp.clip(delta_idx, 0, c - 1)
    fit_new = F.resources_fit(per_object["request"], alloc_new_d, used_new_d)
    fea_new_d = F.combine_filters(
        per_object["filter_enabled"],
        per_object["api_ok"][:, d_safe],
        per_object["taint_ok_new"][:, d_safe],
        per_object["taint_ok_cur"][:, d_safe],
        per_object["current_mask"][:, d_safe],
        fit_new,
        per_object["placement_has"],
        per_object["placement_ok"][:, d_safe],
        per_object["selector_ok"][:, d_safe],
    ) & per_object["webhook_ok"][:, d_safe]
    sticky_active = per_object["sticky"] & per_object["current_mask"].any(axis=1)
    enabled = per_object["score_enabled"]
    return _drift_classify(
        fea_new_d,
        prev_feas[:, d_safe],
        prev_feas,
        prev_scores,
        _resource_scores_cols(
            per_object["request"], enabled, alloc_old_d, used_old_d
        ),
        _resource_scores_cols(
            per_object["request"], enabled, alloc_new_d, used_new_d
        ),
        delta_idx,
        delta_valid,
        delta_cpu,
        per_object["max_clusters"],
        per_object["mode_divide"],
        per_object["weights_given"],
        sticky_active,
        fin_idx,
        nfeas,
    )


def drift_gate_compact(
    per_object: dict,
    tables: dict,
    prev_feas,
    prev_scores,
    alloc_old_d,
    used_old_d,
    alloc_new_d,
    used_new_d,
    delta_idx,
    delta_valid,
    delta_cpu,
    fin_idx,
    nfeas,
    cur_absent,
):
    """Compact-format drift gate: the changed columns' filter masks are
    gathered straight from the vocabulary tables (a D-column slice of
    ops.pipeline.expand_compact), so the gate never materializes [B, C]
    planes."""
    b = per_object["total"].shape[0]
    _note_trace("drift_gate", b, prev_feas.shape[1])
    c = prev_feas.shape[1]
    d_safe = jnp.clip(delta_idx, 0, c - 1)
    api = tables["api_matrix"][:, d_safe][per_object["gvk_id"]]
    trow = tables["taint_set_id"][d_safe]
    taint_new = tables["taint_new"][per_object["tol_id"]][:, trow]
    taint_cur = tables["taint_cur"][per_object["tol_id"]][:, trow]
    selector = tables["sel_matrix"][:, d_safe][per_object["sel_id"]]
    placement = tables["place_matrix"][:, d_safe][per_object["place_id"]]
    cur_present = per_object["sparse_cur"] != cur_absent  # [B, P]
    current_d = (
        (per_object["sparse_idx"][:, :, None] == delta_idx[None, None, :])
        & cur_present[:, :, None]
    ).any(axis=1)
    fit_new = F.resources_fit(per_object["request"], alloc_new_d, used_new_d)
    fea_new_d = F.combine_filters(
        per_object["filter_enabled"],
        api,
        taint_new,
        taint_cur,
        current_d,
        fit_new,
        per_object["placement_has"],
        placement,
        selector,
    )
    sticky_active = per_object["sticky"] & cur_present.any(axis=1)
    enabled = per_object["score_enabled"]
    return _drift_classify(
        fea_new_d,
        prev_feas[:, d_safe],
        prev_feas,
        prev_scores,
        _resource_scores_cols(
            per_object["request"], enabled, alloc_old_d, used_old_d
        ),
        _resource_scores_cols(
            per_object["request"], enabled, alloc_new_d, used_new_d
        ),
        delta_idx,
        delta_valid,
        delta_cpu,
        per_object["max_clusters"],
        per_object["mode_divide"],
        per_object["weights_given"],
        sticky_active,
        fin_idx,
        nfeas,
    )


def drift_wcheck(
    prev_feas,
    rows_idx,
    cpu_alloc_old,
    cpu_avail_old,
    cpu_alloc_new,
    cpu_avail_new,
    compute_dtype=jnp.int64,
):
    """Dynamic-weight equality check for gate-classified wcheck rows.

    Those rows' selection provably equals their feasible set (see the
    gate's exactness argument, step 3/4), so comparing dynamic weights
    over prev_feas decides replica equality exactly.  Returns i8[K]:
    1 where the weights differ (row must recompute).
    ``compute_dtype=jnp.int32`` demotes the weight arithmetic behind
    the engine's host-side range guard (see ops.weights)."""
    _note_trace("drift_wcheck", rows_idx.shape[0], prev_feas.shape[1])
    sel = prev_feas[rows_idx] != 0
    w_old = dynamic_weights(
        sel, cpu_alloc_old, cpu_avail_old, compute_dtype=compute_dtype
    )
    w_new = dynamic_weights(
        sel, cpu_alloc_new, cpu_avail_new, compute_dtype=compute_dtype
    )
    return (w_old != w_new).any(axis=-1).astype(jnp.int8)


@shardguard.rows_first
def drift_resolve(
    inp: TickInputs,   # gathered survivor rows [n, C] (expanded)
    prev_feas_rows,    # i8[n, C] previous feasibility at those rows
    scores_rows,       # i32[n, C] gate-refreshed score plane rows (NEW totals)
    reasons_rows,      # i32[n, C] previous reason plane rows
    alloc_old_d,       # i64[D, R] old cluster tensors at the changed columns
    used_old_d,
    alloc_new_d,       # i64[D, R] new cluster tensors at the changed columns
    used_new_d,
    delta_idx,         # i32[D] changed column indices (pad: out of range)
    delta_valid,       # bool[D]
    m: int,            # static candidate-slot budget (engine narrow M)
) -> tuple[TickOutputs, jax.Array]:
    """Sort-free re-solve of drift-gate survivors from stored state.

    The gate proves (for rows without a feasibility flip) that phase 1
    is already known: feasibility is the stored plane untouched, and the
    refreshed score plane IS the new totals (normalization cannot move
    without a fit flip — the gate's exactness argument, step 2).  What
    remains is select + planner, and both run over a candidate set built
    WITHOUT the narrow solve's full-C sorts — the r08 drift recompute
    spent ~35s at c5 re-running generic narrow slabs whose per-slab cost
    is dominated by exactly those sorts plus a phase 1 the gate had
    already answered.

    Candidate completeness (provable, not hoped-for): the new top-K is a
    subset of
        old top-K  ∪  changed columns  ∪  best-D feasible outsiders,
    because unchanged columns keep their relative order: an outsider can
    enter the top-K only when a changed column leaves it (at most D of
    those), and entering outsiders must be the best-ranked outsiders —
    their keys did not move.  The old top-K is recovered exactly from
    the stored planes: feasible with no MAX_CLUSTERS reason bit (the
    select-stage cut is the only thing that separates a feasible column
    from the selection, and stored reasons are 0 exactly where
    selected).  The best-D outsiders come from D iterated argmins over
    the composite (-total, index) key — D fused [n, C] passes, no sort.

    The planner then runs `plan_batch_narrow` over the same candidate
    slots with a ZERO phantom tail: selection ⊆ candidates, so no member
    weight lives outside the slots and the narrow planner is exact by
    its own certificate.

    Returns (outputs [n, C], cert i8[n]).  cert == 1 guarantees the
    row's outputs are bit-identical to a full re-solve; rows with 0
    (fit moved at a changed column, kinf, sticky, candidate overflow,
    planner cert failure) must take the slab path instead.  Reason
    planes are exact, not merely fresh-as-of-last-recompute: the
    topology-derived filter bits cannot move under capacity drift, and
    the ONE capacity-derived bit (resources_fit, which the skip path is
    allowed to leave stale on infeasible columns) is recomputed dense
    for these few rows; _finalize then re-derives every select/
    replica-stage bit from the new selection."""
    n, c = prev_feas_rows.shape
    _note_trace("drift_resolve", n, c)
    d = delta_idx.shape[0]
    feas = prev_feas_rows != 0
    totals = scores_rows
    rows_n = jnp.arange(n, dtype=jnp.int32)[:, None]

    # --- cert leg 1: fit must not move at any changed column (a fit
    # flip on an already-infeasible column would stale the reason plane;
    # a feasibility flip would shift normalization — both bail).
    fit_old_d = F.resources_fit(inp.request, alloc_old_d, used_old_d)
    fit_new_d = F.resources_fit(inp.request, alloc_new_d, used_new_d)
    cert = ~jnp.any((fit_old_d != fit_new_d) & delta_valid[None, :], axis=1)

    # --- recover the old select-stage selection from stored planes.
    sel_stage = feas & (
        (reasons_rows & jnp.int32(RSN.REASON_MAX_CLUSTERS)) == 0
    )
    nfeas = jnp.sum(feas, axis=-1, dtype=jnp.int32)
    k_eff = jnp.where(
        inp.max_clusters < 0, 0, jnp.minimum(inp.max_clusters, jnp.int32(c))
    )
    kinf = (
        (inp.max_clusters == INT32_INF)
        | (inp.max_clusters < 0)
        | (k_eff >= nfeas)
    )
    sticky_active = inp.sticky & jnp.any(inp.current_mask, axis=-1)
    k_sel = jnp.sum(sel_stage, axis=-1, dtype=jnp.int32)
    cert = cert & ~kinf & ~sticky_active & (k_sel == k_eff)

    # --- candidate set: old selection ∪ feasible changed columns ∪
    # best-D feasible outsiders by the NEW composite key.
    is_delta = jnp.zeros(c, bool).at[delta_idx].set(delta_valid, mode="drop")
    iota64 = lax.broadcasted_iota(jnp.int64, (n, c), 1)
    comp = (-totals.astype(jnp.int64)) * c + iota64
    avail = feas & ~sel_stage & ~is_delta[None, :]
    compm = jnp.where(avail, comp, _CERT_INF)
    entrant = jnp.zeros((n, c), bool)
    # At most one outsider can enter per VALID delta column (an entry
    # requires a delta leaving the top-K), so the static D-iteration
    # loop masks picks past that count — smaller candidate sets, and
    # narrow M budgets that a padded delta axis would otherwise blow.
    nvd = jnp.sum(delta_valid.astype(jnp.int32))
    for t in range(d):
        mval = jnp.min(compm, axis=-1, keepdims=True)
        pick = (compm == mval) & (mval < _CERT_INF) & (t < nvd)
        entrant = entrant | pick
        compm = jnp.where(pick, _CERT_INF, compm)
    cand_mask = sel_stage | (is_delta[None, :] & feas) | entrant
    n_cand = jnp.sum(cand_mask, axis=-1, dtype=jnp.int32)
    cert = cert & (n_cand <= m)

    # Compact candidate columns into m ascending slots (sentinel c on
    # unused slots; cumsum positions keep them unique and ordered, so
    # slot rank order == column order, the narrow tie-break contract).
    pos = jnp.cumsum(cand_mask, axis=-1) - 1
    colidx = lax.broadcasted_iota(jnp.int32, (n, c), 1)
    cand = jnp.full((n, m), c, jnp.int32).at[
        rows_n, jnp.where(cand_mask, pos, m)
    ].set(colidx, mode="drop")
    valid_slot = cand < c
    cand_c = jnp.minimum(cand, c - 1)

    def take(plane):
        return jnp.take_along_axis(plane, cand_c, axis=-1)

    # --- select over the candidate slots.
    fea_s = take(feas) & valid_slot
    sel_n = select_topk(take(totals), fea_s, inp.max_clusters)
    selected = (
        jnp.zeros((n, c), bool).at[rows_n, cand].set(sel_n, mode="drop")
    )

    # --- planner over the same slots, zero phantom tail.
    weights = _planner_weights(inp, selected)
    member_p = sel_n
    zero_tail = jnp.zeros(n, jnp.int32)
    no_tail = jnp.full(n, -1, jnp.int64)
    comp_true = processing_key(
        take(weights), take(inp.tiebreak), jnp.zeros((n, m), bool)
    )
    plan_out, pcert = plan_batch_narrow(
        PlannerInputs(
            weight=jnp.where(member_p, take(weights), 0),
            min_replicas=jnp.where(member_p, take(inp.min_replicas), 0),
            max_replicas=take(inp.max_replicas),
            scale_max=take(inp.scale_max),
            capacity=take(inp.capacity),
            tiebreak=take(inp.tiebreak),
            member=member_p,
            total=inp.total,
            current=take(_current_plane(inp)),
            avoid_disruption=inp.avoid_disruption,
            keep_unschedulable=inp.keep_unschedulable,
        ),
        zero_tail,
        no_tail,
        comp_true,
    )
    divide_n = (plan_out.plan + plan_out.overflow).astype(jnp.int64)
    divide_replicas = (
        jnp.zeros((n, c), jnp.int64).at[rows_n, cand].set(divide_n, mode="drop")
    )
    cert = cert & (~inp.mode_divide | pcert)

    # Filter-stage reasons: stored bits minus the select/replica-stage
    # bits (re-derived below) with the resources_fit bit RECOMPUTED
    # against the new cluster planes — the only filter bit capacity
    # drift can move, and the one the skip path may have left stale on
    # infeasible columns of earlier drifts.  [n, C, R] over the few
    # survivor rows, a fraction of the phase 1 these rows never re-ran.
    fit_ok = F.resources_fit(inp.request, inp.alloc, inp.used)
    fit_bit = jnp.where(
        inp.filter_enabled[:, F.F_RESOURCES_FIT, None] & ~fit_ok,
        jnp.int32(RSN.REASON_RESOURCES_FIT),
        0,
    )
    base_reasons = (
        reasons_rows
        & ~jnp.int32(RSN.SELECT_REASON_MASK | RSN.REASON_RESOURCES_FIT)
    ) | fit_bit
    out = _finalize(inp, feas, base_reasons, totals, selected, divide_replicas)
    return out, cert.astype(jnp.int8)


# -- packed placement export ---------------------------------------------
# Each object lands on at most max_clusters clusters, yet the dense
# output planes ship B x C cells off the device.  The packed export
# top-k-compacts every row into K-wide tensors before the transfer, so
# fetch bytes scale as B x K instead of B x C (~C/K less traffic); the
# rare row selecting more than K clusters raises its overflow flag
# (nsel > K) and the engine re-fetches it through the dense-plane path.

PACK_FILL = -1  # idx value of unused packed slots


class PackedRows(NamedTuple):
    """The packed placement layout: one row per object, K slots."""

    idx: jax.Array   # i32[N,K] selected cluster indices, ascending; PACK_FILL pads
    rep: jax.Array   # i32[N,K] replicas of that cluster (NIL in Duplicate mode)
    cnt: jax.Array   # i32[N,K] 1 when the placement carries a replica count
    sco: jax.Array   # i32[N,K] post-normalize score total of that cluster
    nsel: jax.Array  # i32[N]   true selected count; nsel > K flags overflow
    nfeas: jax.Array # i32[N]   valid clusters with no filter-stage reason
    rsum: jax.Array  # i32[N,NUM_REASON_BITS] clusters rejected per reason
    #                  bit (ops.reasons.REASON_BITS order), valid slots only


@shardguard.rows_only
def pack_rows(selected, replicas, counted, scores, reasons, k: int) -> PackedRows:
    """Top-k-compact dense output planes (any leading row count) into the
    packed layout.  Slot order is (score desc, cluster index asc) over
    the SELECTED clusters — the select stage's own ranking — so the
    first slots ARE the row's top scorers: the flight recorder's top-k
    reads straight off the wire even for K-overflow rows.  The index is
    a comparator key (lax.sort num_keys=2, unique per row), not argsort
    stability, so the layout is bit-identical on every backend and
    matches the sequential oracle's pack_one exactly (see
    ops/select.py for why stability must not be relied on)."""
    c = selected.shape[-1]
    k = min(k, c)
    selb = selected != 0
    iota = lax.broadcasted_iota(jnp.int32, selb.shape, selb.ndim - 1)
    # Selected clusters sort to the front by (-score, index); unselected
    # sink past them (scores are bounded far below int32 max).
    key1 = jnp.where(selb, -scores.astype(jnp.int32), jnp.iinfo(jnp.int32).max)
    if jax.default_backend() == "tpu":
        # int64 is emulated on TPU; the 2-key int32 comparator is the
        # cheaper form there (the select_topk encoding rule).
        _, order = lax.sort((key1, iota), dimension=-1, num_keys=2)
    else:
        # XLA:CPU lowers the index payload of a variadic sort to a
        # row-serial comparator loop; the collision-free int64
        # composite single-key sort is ~5x faster at slab shapes (the
        # PR-5 select-sort lesson, applied to the pack — at c5 the pack
        # was 13.4s of the 51s drift device time).  Floor-mod keeps the
        # decode exact for negative keys (comp = key1*c + iota with
        # 0 <= iota < c).
        comp = key1.astype(jnp.int64) * c + iota
        order = (lax.sort(comp, dimension=-1) % c).astype(jnp.int32)
    order = order[..., :k]
    valid = jnp.take_along_axis(selb, order, axis=-1)
    gidx = jnp.where(valid, order, 0)

    def take(plane):
        return jnp.take_along_axis(plane.astype(jnp.int32), gidx, axis=-1)

    zero = jnp.int32(0)
    rsn = reasons.astype(jnp.int32)
    valid_slot = (rsn & jnp.int32(RSN.REASON_CLUSTER_INVALID)) == 0
    rsum = jnp.stack(
        [
            jnp.sum(((rsn & jnp.int32(bit)) != 0) & valid_slot, axis=-1)
            for bit in RSN.REASON_BITS
        ],
        axis=-1,
    ).astype(jnp.int32)
    nfeas = jnp.sum(
        ((rsn & jnp.int32(RSN.FILTER_REASON_MASK)) == 0) & valid_slot, axis=-1
    ).astype(jnp.int32)
    return PackedRows(
        idx=jnp.where(valid, order, jnp.int32(PACK_FILL)),
        rep=jnp.where(valid, take(replicas), zero),
        cnt=jnp.where(valid, take(counted), zero),
        sco=jnp.where(valid, take(scores), zero),
        nsel=jnp.sum(selb, axis=-1).astype(jnp.int32),
        nfeas=nfeas,
        rsum=rsum,
    )


def wire_width(k: int) -> int:
    """Column count of the packed wire row: 4 K-wide planes + nsel +
    nfeas + the reason-summary counts."""
    return 4 * k + 2 + RSN.NUM_REASON_BITS


def pack_wire(selected, replicas, counted, scores, reasons, k: int) -> jax.Array:
    """The packed layout flattened to ONE i32[N, wire_width(k)] array —
    a single device->host transfer per fetch, like the dense path's
    _gather_packed* concats."""
    p = pack_rows(selected, replicas, counted, scores, reasons, k)
    return jnp.concatenate(
        [p.idx, p.rep, p.cnt, p.sco, p.nsel[..., None], p.nfeas[..., None], p.rsum],
        axis=-1,
    )


def unpack_wire(arr, k: int) -> PackedRows:
    """Host-side inverse of pack_wire (numpy views, no copies)."""
    arr = np.asarray(arr)
    return PackedRows(
        idx=arr[:, :k],
        rep=arr[:, k : 2 * k],
        cnt=arr[:, 2 * k : 3 * k],
        sco=arr[:, 3 * k : 4 * k],
        nsel=arr[:, 4 * k],
        nfeas=arr[:, 4 * k + 1],
        rsum=arr[:, 4 * k + 2 : 4 * k + 2 + RSN.NUM_REASON_BITS],
    )
