"""The fused scheduling tick: one XLA program per reconcile batch.

Composes the stages of the reference's generic scheduler (reference:
pkg/controllers/scheduler/core/generic_scheduler.go:92-150) over the whole
pending batch at once:

    feasible = AND(enabled filter masks)            # Filter, O(B*C)
    scores   = sum(enabled normalized score plugins)# Score + Normalize
    selected = top-K(scores)                        # Select (MaxCluster)
    replicas = planner(weights, mins, maxes, caps)  # Replicas (RSP)

with the per-object special cases folded in as masks: sticky-cluster
short-circuit, Duplicate vs Divide mode, static vs dynamic RSP weights.

The featurizer (kubeadmiral_tpu.scheduler.featurize) is responsible for
producing TickInputs from API objects; this module is pure tensor math and
is jit-compiled once per (B, C, R) shape bucket.
"""

from __future__ import annotations

import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubeadmiral_tpu.ops import filters as F
from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.ops import scores as S
from kubeadmiral_tpu.ops.planner import INT32_INF, PlannerInputs, plan_batch_jit
from kubeadmiral_tpu.ops.select import select_topk
from kubeadmiral_tpu.ops.weights import dynamic_weights

NIL_REPLICAS = np.int64(-1)  # "no replica count" (Duplicate-mode placement)

# -- XLA (re)compile telemetry -------------------------------------------
# A jitted function's Python body runs exactly once per trace, i.e. per
# XLA compile of a new program shape — so a counter in the body is a
# TRUE recompile detector, not a heuristic.  The engine drains these
# events after each tick into ``engine_xla_compiles_total`` counters
# labeled by program and (B, C) shape bucket.
_trace_lock = threading.Lock()
_trace_events: list[tuple[str, int, int]] = []
_trace_seq = 0


def _note_trace(program: str, b: int, c: int) -> None:
    global _trace_seq
    with _trace_lock:
        _trace_seq += 1
        _trace_events.append((program, int(b), int(c)))


def trace_seq() -> int:
    """Monotonic count of XLA traces of this module's programs — compare
    around a dispatch to tell a compile from a cache hit."""
    with _trace_lock:
        return _trace_seq


def drain_trace_events() -> list[tuple[str, int, int]]:
    """Take (program, B, C) events recorded since the last drain."""
    global _trace_events
    with _trace_lock:
        events, _trace_events = _trace_events, []
        return events


class TickInputs(NamedTuple):
    """One scheduling problem per row. See featurize.py for construction."""

    # --- filter stage ---
    filter_enabled: jax.Array  # bool[B,5] (ops.filters.F_* order)
    api_ok: jax.Array          # bool[B,C]
    taint_ok_new: jax.Array    # bool[B,C]
    taint_ok_cur: jax.Array    # bool[B,C]
    selector_ok: jax.Array     # bool[B,C]
    placement_has: jax.Array   # bool[B]
    placement_ok: jax.Array    # bool[B,C]
    request: jax.Array         # i64[B,R]
    alloc: jax.Array           # i64[C,R]
    used: jax.Array            # i64[C,R]
    # --- score stage ---
    score_enabled: jax.Array   # bool[B,5] (ops.scores.S_* order)
    taint_counts: jax.Array    # i64[B,C]
    affinity_scores: jax.Array # i64[B,C]
    # --- out-of-process (webhook) plugins, evaluated host-side ---
    webhook_ok: jax.Array      # bool[B,C]; AND-ed into the filter result
    webhook_scores: jax.Array  # i64[B,C]; added to the score totals
    # --- select stage ---
    max_clusters: jax.Array    # i32[B]; INT32_INF = unlimited, <0 = none
    # --- replicas stage ---
    mode_divide: jax.Array     # bool[B]
    sticky: jax.Array          # bool[B]
    current_mask: jax.Array    # bool[B,C]
    current_replicas: jax.Array  # i64[B,C]; NIL_REPLICAS = nil entry
    total: jax.Array           # i32[B]
    weights_given: jax.Array   # bool[B]
    weights: jax.Array         # i32[B,C] static policy weights
    min_replicas: jax.Array    # i32[B,C]
    max_replicas: jax.Array    # i32[B,C]; INT32_INF = unbounded
    scale_max: jax.Array       # i32[B,C]; INT32_INF = unbounded
    capacity: jax.Array        # i32[B,C]; INT32_INF = no estimate
    keep_unschedulable: jax.Array  # bool[B]
    avoid_disruption: jax.Array    # bool[B]
    tiebreak: jax.Array        # i32[B,C]
    # --- dynamic weights ---
    cpu_alloc: jax.Array       # i64[C] Quantity.Value() cores
    cpu_avail: jax.Array       # i64[C]
    # --- padding ---
    cluster_valid: jax.Array   # bool[C]; False marks padded cluster slots


class TickOutputs(NamedTuple):
    """Mask outputs are int8 (0/1) and numeric outputs int32, NOT bool /
    i64: device->host transfer of bool arrays is pathologically slow on
    the tunneled TPU backend (~35x vs int8 for the same bytes), and the
    tick's outputs are the per-reconcile transfer volume."""

    selected: jax.Array   # i8[B,C] final placements (0/1)
    replicas: jax.Array   # i32[B,C]; meaningful only where counted
    counted: jax.Array    # i8[B,C]; 0 = placement carries no replica
                          # count (Duplicate mode / nil sticky entries)
    feasible: jax.Array   # i8[B,C] post-filter (introspection)
    scores: jax.Array     # i32[B,C] post-normalize totals (introspection)
    reasons: jax.Array    # i32[B,C] rejection bitmask (ops.reasons); 0
                          # exactly where selected — the decision audit
                          # plane the flight recorder serves


def expand_compact(ci) -> TickInputs:
    """Device-side expansion of CompactInputs into the dense planes the
    fused tick consumes: vocabulary-table gathers, sparse policy
    scatters, and the planner tie-break FNV-1 hash — all in HBM, where
    the [B, C] planes cost bandwidth instead of host-link transfer
    (scheduler/compact.py explains why this is the 100k x 5k enabler).

    Bit-exact with scheduler/featurize.featurize: the tables are built
    by the same host matching code, and the FNV continuation reproduces
    utils/hashing.fnv32_extend + uint32_to_sortable_int32 exactly."""
    b = ci.gvk_id.shape[0]
    c = ci.cluster_valid.shape[0]
    _note_trace("expand_compact", b, c)

    api_ok = ci.api_matrix[ci.gvk_id]
    taint_row = ci.taint_set_id  # i32[C]
    taint_ok_new = ci.taint_new[ci.tol_id][:, taint_row]
    taint_ok_cur = ci.taint_cur[ci.tol_id][:, taint_row]
    taint_counts = ci.taint_prefer[ci.tol_id][:, taint_row]
    selector_ok = ci.sel_matrix[ci.sel_id]
    affinity_scores = ci.pref_matrix[ci.pref_id]
    placement_ok = ci.place_matrix[ci.place_id]

    # Sparse per-(object, cluster) policy entries -> dense grids.  The
    # EMPTY_SLOT sentinel is out of range for any cluster padding, so
    # mode='drop' ignores unused entries.
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]

    def scatter(default, vals, dtype):
        base = jnp.full((b, c), default, dtype)
        return base.at[rows, ci.sparse_idx].set(vals.astype(dtype), mode="drop")

    min_replicas = scatter(0, ci.sparse_min, jnp.int32)
    max_replicas = scatter(INT32_INF, ci.sparse_max, jnp.int32)
    weights = scatter(0, ci.sparse_weight, jnp.int32)
    capacity = scatter(INT32_INF, ci.sparse_capacity, jnp.int32)
    cur_present = ci.sparse_cur != -2  # CUR_ABSENT
    current_mask = (
        jnp.zeros((b, c), bool)
        .at[rows, ci.sparse_idx]
        .set(cur_present, mode="drop")
    )
    current_replicas = scatter(
        NIL_REPLICAS, jnp.where(ci.sparse_cur >= 0, ci.sparse_cur, NIL_REPLICAS),
        jnp.int32,
    )

    # Planner tie-break: continue each cluster name's FNV-1 state over
    # the object key's bytes (h = h*prime ^ byte, uint32 wraparound),
    # then map to order-preserving int32 (hashing.py semantics).
    prime = jnp.uint32(16777619)
    state0 = jnp.broadcast_to(
        jnp.asarray(ci.name_hash_state), (b, c)
    ).astype(jnp.uint32)
    key_cols = jnp.asarray(ci.key_bytes).T  # [L, B] — scanned xs
    key_len = jnp.asarray(ci.key_len)
    n_bytes = key_cols.shape[0]

    def fnv_step(state, xs):
        byte, j = xs
        upd = (state * prime) ^ byte.astype(jnp.uint32)[:, None]
        keep = (j < key_len)[:, None]
        return jnp.where(keep, upd, state), None

    state, _ = jax.lax.scan(
        fnv_step, state0, (key_cols, jnp.arange(n_bytes))
    )
    tiebreak = jax.lax.bitcast_convert_type(
        state ^ jnp.uint32(0x80000000), jnp.int32
    )

    return TickInputs(
        filter_enabled=ci.filter_enabled,
        api_ok=api_ok,
        taint_ok_new=taint_ok_new,
        taint_ok_cur=taint_ok_cur,
        selector_ok=selector_ok,
        placement_has=ci.placement_has,
        placement_ok=placement_ok,
        request=ci.request,
        alloc=ci.alloc,
        used=ci.used,
        score_enabled=ci.score_enabled,
        taint_counts=taint_counts,
        affinity_scores=affinity_scores,
        webhook_ok=jnp.ones((b, c), bool),
        webhook_scores=jnp.zeros((b, c), jnp.int32),
        max_clusters=ci.max_clusters,
        mode_divide=ci.mode_divide,
        sticky=ci.sticky,
        current_mask=current_mask,
        current_replicas=current_replicas,
        total=ci.total,
        weights_given=ci.weights_given,
        weights=weights,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        scale_max=max_replicas,
        capacity=capacity,
        keep_unschedulable=ci.keep_unschedulable,
        avoid_disruption=ci.avoid_disruption,
        tiebreak=tiebreak,
        cpu_alloc=ci.cpu_alloc,
        cpu_avail=ci.cpu_avail,
        cluster_valid=ci.cluster_valid,
    )


@jax.jit
def schedule_tick(inp: TickInputs) -> TickOutputs:
    _note_trace(
        "schedule_tick", inp.total.shape[0], inp.cluster_valid.shape[0]
    )
    # --- Filter ---
    fit_ok = F.resources_fit(inp.request, inp.alloc, inp.used)
    feasible, reasons = F.combine_filters_explain(
        inp.filter_enabled,
        inp.api_ok,
        inp.taint_ok_new,
        inp.taint_ok_cur,
        inp.current_mask,
        fit_ok,
        inp.placement_has,
        inp.placement_ok,
        inp.selector_ok,
    )
    reasons = (
        reasons
        | jnp.where(~inp.webhook_ok, jnp.int32(RSN.REASON_WEBHOOK_FILTER), 0)
        | jnp.where(
            ~inp.cluster_valid[None, :], jnp.int32(RSN.REASON_CLUSTER_INVALID), 0
        )
    )
    feasible = feasible & inp.cluster_valid[None, :] & inp.webhook_ok

    # --- Score + Normalize ---
    totals = S.total_scores(
        inp.score_enabled,
        feasible,
        inp.request,
        inp.alloc,
        inp.used,
        inp.taint_counts,
        inp.affinity_scores,
    )
    # Webhook scores arrive pre-computed (one HTTP call per object x
    # cluster happens host-side); like in-tree plugin sums they only
    # matter on feasible clusters.
    totals = totals + jnp.where(feasible, inp.webhook_scores, 0)

    # --- Select ---
    selected = select_topk(totals, feasible, inp.max_clusters)
    # Feasible pairs the top-K cut: score rank >= K (including K == 0
    # for a negative maxClusters).
    reasons = reasons | jnp.where(
        feasible & ~selected, jnp.int32(RSN.REASON_MAX_CLUSTERS), 0
    )

    # --- Replicas (Divide mode) ---
    dyn_w = dynamic_weights(selected, inp.cpu_alloc, inp.cpu_avail)
    weights = jnp.where(
        inp.weights_given[:, None], inp.weights, dyn_w
    ).astype(jnp.int32)
    weights = jnp.where(selected, weights, 0)

    total64 = inp.total.astype(jnp.int64)
    current = jnp.where(
        inp.current_mask,
        jnp.where(inp.current_replicas == NIL_REPLICAS, total64[:, None], inp.current_replicas),
        0,
    ).astype(jnp.int32)

    plan_out = plan_batch_jit(
        PlannerInputs(
            weight=weights,
            min_replicas=jnp.where(selected, inp.min_replicas, 0),
            max_replicas=inp.max_replicas,
            scale_max=inp.scale_max,
            capacity=inp.capacity,
            tiebreak=inp.tiebreak,
            member=selected,
            total=inp.total,
            current=current,
            avoid_disruption=inp.avoid_disruption,
            keep_unschedulable=inp.keep_unschedulable,
        )
    )
    # The RSP merges capacity overflow back into the result as
    # "nice to schedule" replicas (rsp.go:158-177) and drops zero entries.
    divide_replicas = (plan_out.plan + plan_out.overflow).astype(jnp.int64)
    # Zero entries are dropped; negative entries (pathological min>max
    # policies) are preserved, as the reference's merge does.
    divide_selected = selected & (divide_replicas != 0)

    # Selected by top-K but dropped by the Divide-mode zero-entry merge.
    reasons = reasons | jnp.where(
        inp.mode_divide[:, None] & selected & ~divide_selected,
        jnp.int32(RSN.REASON_ZERO_REPLICAS),
        0,
    )

    mode_divide = inp.mode_divide[:, None]
    out_selected = jnp.where(mode_divide, divide_selected, selected)
    out_replicas = jnp.where(
        mode_divide, jnp.where(divide_selected, divide_replicas, 0), NIL_REPLICAS
    )
    out_counted = mode_divide & divide_selected

    # --- Sticky-cluster short-circuit (generic_scheduler.go:103-107) ---
    sticky_active = (inp.sticky & jnp.any(inp.current_mask, axis=-1))[:, None]
    out_selected = jnp.where(sticky_active, inp.current_mask, out_selected)
    out_replicas = jnp.where(
        sticky_active,
        jnp.where(inp.current_mask, inp.current_replicas, 0),
        out_replicas,
    )
    out_counted = jnp.where(
        sticky_active,
        inp.current_mask & (inp.current_replicas != NIL_REPLICAS),
        out_counted,
    )
    out_replicas = jnp.where(out_selected, out_replicas, 0)

    # Sticky short-circuit reasons: the current clusters win regardless
    # of plugin verdicts; everything else is cut by stickiness (the
    # filter bits are kept for context — they explain what WOULD reject
    # the pair if the object were rescheduled from scratch).
    reasons = jnp.where(
        sticky_active & ~inp.current_mask,
        reasons | jnp.int32(RSN.REASON_STICKY),
        reasons,
    )
    # Invariant the flight recorder (and test_explain) rely on:
    # reasons == 0 exactly where selected.
    reasons = jnp.where(out_selected, 0, reasons)

    return TickOutputs(
        selected=out_selected.astype(jnp.int8),
        replicas=out_replicas.astype(jnp.int32),
        counted=(out_counted & out_selected).astype(jnp.int8),
        feasible=feasible.astype(jnp.int8),
        scores=totals.astype(jnp.int32),
        reasons=reasons.astype(jnp.int32),
    )


# -- packed placement export ---------------------------------------------
# Each object lands on at most max_clusters clusters, yet the dense
# output planes ship B x C cells off the device.  The packed export
# top-k-compacts every row into K-wide tensors before the transfer, so
# fetch bytes scale as B x K instead of B x C (~C/K less traffic); the
# rare row selecting more than K clusters raises its overflow flag
# (nsel > K) and the engine re-fetches it through the dense-plane path.

PACK_FILL = -1  # idx value of unused packed slots


class PackedRows(NamedTuple):
    """The packed placement layout: one row per object, K slots."""

    idx: jax.Array   # i32[N,K] selected cluster indices, ascending; PACK_FILL pads
    rep: jax.Array   # i32[N,K] replicas of that cluster (NIL in Duplicate mode)
    cnt: jax.Array   # i32[N,K] 1 when the placement carries a replica count
    sco: jax.Array   # i32[N,K] post-normalize score total of that cluster
    nsel: jax.Array  # i32[N]   true selected count; nsel > K flags overflow
    nfeas: jax.Array # i32[N]   valid clusters with no filter-stage reason
    rsum: jax.Array  # i32[N,NUM_REASON_BITS] clusters rejected per reason
    #                  bit (ops.reasons.REASON_BITS order), valid slots only


def pack_rows(selected, replicas, counted, scores, reasons, k: int) -> PackedRows:
    """Top-k-compact dense output planes (any leading row count) into the
    packed layout.  Slot order is (score desc, cluster index asc) over
    the SELECTED clusters — the select stage's own ranking — so the
    first slots ARE the row's top scorers: the flight recorder's top-k
    reads straight off the wire even for K-overflow rows.  The index is
    a comparator key (lax.sort num_keys=2, unique per row), not argsort
    stability, so the layout is bit-identical on every backend and
    matches the sequential oracle's pack_one exactly (see
    ops/select.py for why stability must not be relied on)."""
    c = selected.shape[-1]
    k = min(k, c)
    selb = selected != 0
    iota = lax.broadcasted_iota(jnp.int32, selb.shape, selb.ndim - 1)
    # Selected clusters sort to the front by (-score, index); unselected
    # sink past them (scores are bounded far below int32 max).
    key1 = jnp.where(selb, -scores.astype(jnp.int32), jnp.iinfo(jnp.int32).max)
    _, order = lax.sort((key1, iota), dimension=-1, num_keys=2)
    order = order[..., :k]
    valid = jnp.take_along_axis(selb, order, axis=-1)
    gidx = jnp.where(valid, order, 0)

    def take(plane):
        return jnp.take_along_axis(plane.astype(jnp.int32), gidx, axis=-1)

    zero = jnp.int32(0)
    rsn = reasons.astype(jnp.int32)
    valid_slot = (rsn & jnp.int32(RSN.REASON_CLUSTER_INVALID)) == 0
    rsum = jnp.stack(
        [
            jnp.sum(((rsn & jnp.int32(bit)) != 0) & valid_slot, axis=-1)
            for bit in RSN.REASON_BITS
        ],
        axis=-1,
    ).astype(jnp.int32)
    nfeas = jnp.sum(
        ((rsn & jnp.int32(RSN.FILTER_REASON_MASK)) == 0) & valid_slot, axis=-1
    ).astype(jnp.int32)
    return PackedRows(
        idx=jnp.where(valid, order, jnp.int32(PACK_FILL)),
        rep=jnp.where(valid, take(replicas), zero),
        cnt=jnp.where(valid, take(counted), zero),
        sco=jnp.where(valid, take(scores), zero),
        nsel=jnp.sum(selb, axis=-1).astype(jnp.int32),
        nfeas=nfeas,
        rsum=rsum,
    )


def wire_width(k: int) -> int:
    """Column count of the packed wire row: 4 K-wide planes + nsel +
    nfeas + the reason-summary counts."""
    return 4 * k + 2 + RSN.NUM_REASON_BITS


def pack_wire(selected, replicas, counted, scores, reasons, k: int) -> jax.Array:
    """The packed layout flattened to ONE i32[N, wire_width(k)] array —
    a single device->host transfer per fetch, like the dense path's
    _gather_packed* concats."""
    p = pack_rows(selected, replicas, counted, scores, reasons, k)
    return jnp.concatenate(
        [p.idx, p.rep, p.cnt, p.sco, p.nsel[..., None], p.nfeas[..., None], p.rsum],
        axis=-1,
    )


def unpack_wire(arr, k: int) -> PackedRows:
    """Host-side inverse of pack_wire (numpy views, no copies)."""
    arr = np.asarray(arr)
    return PackedRows(
        idx=arr[:, :k],
        rep=arr[:, k : 2 * k],
        cnt=arr[:, 2 * k : 3 * k],
        sco=arr[:, 3 * k : 4 * k],
        nsel=arr[:, 4 * k],
        nfeas=arr[:, 4 * k + 1],
        rsum=arr[:, 4 * k + 2 : 4 * k + 2 + RSN.NUM_REASON_BITS],
    )
