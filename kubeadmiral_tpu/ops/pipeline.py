"""The fused scheduling tick: one XLA program per reconcile batch.

Composes the stages of the reference's generic scheduler (reference:
pkg/controllers/scheduler/core/generic_scheduler.go:92-150) over the whole
pending batch at once:

    feasible = AND(enabled filter masks)            # Filter, O(B*C)
    scores   = sum(enabled normalized score plugins)# Score + Normalize
    selected = top-K(scores)                        # Select (MaxCluster)
    replicas = planner(weights, mins, maxes, caps)  # Replicas (RSP)

with the per-object special cases folded in as masks: sticky-cluster
short-circuit, Duplicate vs Divide mode, static vs dynamic RSP weights.

The featurizer (kubeadmiral_tpu.scheduler.featurize) is responsible for
producing TickInputs from API objects; this module is pure tensor math and
is jit-compiled once per (B, C, R) shape bucket.
"""

from __future__ import annotations

import threading
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from kubeadmiral_tpu.ops import filters as F
from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.ops import scores as S
from kubeadmiral_tpu.ops.planner import INT32_INF, PlannerInputs, plan_batch_jit
from kubeadmiral_tpu.ops.select import select_topk
from kubeadmiral_tpu.ops.weights import dynamic_weights

NIL_REPLICAS = np.int64(-1)  # "no replica count" (Duplicate-mode placement)

# -- XLA (re)compile telemetry -------------------------------------------
# A jitted function's Python body runs exactly once per trace, i.e. per
# XLA compile of a new program shape — so a counter in the body is a
# TRUE recompile detector, not a heuristic.  The engine drains these
# events after each tick into ``engine_xla_compiles_total`` counters
# labeled by program and (B, C) shape bucket.
_trace_lock = threading.Lock()
_trace_events: list[tuple[str, int, int]] = []
_trace_seq = 0


def _note_trace(program: str, b: int, c: int) -> None:
    global _trace_seq
    with _trace_lock:
        _trace_seq += 1
        _trace_events.append((program, int(b), int(c)))


def trace_seq() -> int:
    """Monotonic count of XLA traces of this module's programs — compare
    around a dispatch to tell a compile from a cache hit."""
    with _trace_lock:
        return _trace_seq


def drain_trace_events() -> list[tuple[str, int, int]]:
    """Take (program, B, C) events recorded since the last drain."""
    global _trace_events
    with _trace_lock:
        events, _trace_events = _trace_events, []
        return events


class TickInputs(NamedTuple):
    """One scheduling problem per row. See featurize.py for construction."""

    # --- filter stage ---
    filter_enabled: jax.Array  # bool[B,5] (ops.filters.F_* order)
    api_ok: jax.Array          # bool[B,C]
    taint_ok_new: jax.Array    # bool[B,C]
    taint_ok_cur: jax.Array    # bool[B,C]
    selector_ok: jax.Array     # bool[B,C]
    placement_has: jax.Array   # bool[B]
    placement_ok: jax.Array    # bool[B,C]
    request: jax.Array         # i64[B,R]
    alloc: jax.Array           # i64[C,R]
    used: jax.Array            # i64[C,R]
    # --- score stage ---
    score_enabled: jax.Array   # bool[B,5] (ops.scores.S_* order)
    taint_counts: jax.Array    # i64[B,C]
    affinity_scores: jax.Array # i64[B,C]
    # --- out-of-process (webhook) plugins, evaluated host-side ---
    webhook_ok: jax.Array      # bool[B,C]; AND-ed into the filter result
    webhook_scores: jax.Array  # i64[B,C]; added to the score totals
    # --- select stage ---
    max_clusters: jax.Array    # i32[B]; INT32_INF = unlimited, <0 = none
    # --- replicas stage ---
    mode_divide: jax.Array     # bool[B]
    sticky: jax.Array          # bool[B]
    current_mask: jax.Array    # bool[B,C]
    current_replicas: jax.Array  # i64[B,C]; NIL_REPLICAS = nil entry
    total: jax.Array           # i32[B]
    weights_given: jax.Array   # bool[B]
    weights: jax.Array         # i32[B,C] static policy weights
    min_replicas: jax.Array    # i32[B,C]
    max_replicas: jax.Array    # i32[B,C]; INT32_INF = unbounded
    scale_max: jax.Array       # i32[B,C]; INT32_INF = unbounded
    capacity: jax.Array        # i32[B,C]; INT32_INF = no estimate
    keep_unschedulable: jax.Array  # bool[B]
    avoid_disruption: jax.Array    # bool[B]
    tiebreak: jax.Array        # i32[B,C]
    # --- dynamic weights ---
    cpu_alloc: jax.Array       # i64[C] Quantity.Value() cores
    cpu_avail: jax.Array       # i64[C]
    # --- padding ---
    cluster_valid: jax.Array   # bool[C]; False marks padded cluster slots


class TickOutputs(NamedTuple):
    """Mask outputs are int8 (0/1) and numeric outputs int32, NOT bool /
    i64: device->host transfer of bool arrays is pathologically slow on
    the tunneled TPU backend (~35x vs int8 for the same bytes), and the
    tick's outputs are the per-reconcile transfer volume."""

    selected: jax.Array   # i8[B,C] final placements (0/1)
    replicas: jax.Array   # i32[B,C]; meaningful only where counted
    counted: jax.Array    # i8[B,C]; 0 = placement carries no replica
                          # count (Duplicate mode / nil sticky entries)
    feasible: jax.Array   # i8[B,C] post-filter (introspection)
    scores: jax.Array     # i32[B,C] post-normalize totals (introspection)
    reasons: jax.Array    # i32[B,C] rejection bitmask (ops.reasons); 0
                          # exactly where selected — the decision audit
                          # plane the flight recorder serves


def expand_compact(ci) -> TickInputs:
    """Device-side expansion of CompactInputs into the dense planes the
    fused tick consumes: vocabulary-table gathers, sparse policy
    scatters, and the planner tie-break FNV-1 hash — all in HBM, where
    the [B, C] planes cost bandwidth instead of host-link transfer
    (scheduler/compact.py explains why this is the 100k x 5k enabler).

    Bit-exact with scheduler/featurize.featurize: the tables are built
    by the same host matching code, and the FNV continuation reproduces
    utils/hashing.fnv32_extend + uint32_to_sortable_int32 exactly."""
    b = ci.gvk_id.shape[0]
    c = ci.cluster_valid.shape[0]
    _note_trace("expand_compact", b, c)

    api_ok = ci.api_matrix[ci.gvk_id]
    taint_row = ci.taint_set_id  # i32[C]
    taint_ok_new = ci.taint_new[ci.tol_id][:, taint_row]
    taint_ok_cur = ci.taint_cur[ci.tol_id][:, taint_row]
    taint_counts = ci.taint_prefer[ci.tol_id][:, taint_row]
    selector_ok = ci.sel_matrix[ci.sel_id]
    affinity_scores = ci.pref_matrix[ci.pref_id]
    placement_ok = ci.place_matrix[ci.place_id]

    # Sparse per-(object, cluster) policy entries -> dense grids.  The
    # EMPTY_SLOT sentinel is out of range for any cluster padding, so
    # mode='drop' ignores unused entries.
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]

    def scatter(default, vals, dtype):
        base = jnp.full((b, c), default, dtype)
        return base.at[rows, ci.sparse_idx].set(vals.astype(dtype), mode="drop")

    min_replicas = scatter(0, ci.sparse_min, jnp.int32)
    max_replicas = scatter(INT32_INF, ci.sparse_max, jnp.int32)
    weights = scatter(0, ci.sparse_weight, jnp.int32)
    capacity = scatter(INT32_INF, ci.sparse_capacity, jnp.int32)
    cur_present = ci.sparse_cur != -2  # CUR_ABSENT
    current_mask = (
        jnp.zeros((b, c), bool)
        .at[rows, ci.sparse_idx]
        .set(cur_present, mode="drop")
    )
    current_replicas = scatter(
        NIL_REPLICAS, jnp.where(ci.sparse_cur >= 0, ci.sparse_cur, NIL_REPLICAS),
        jnp.int32,
    )

    # Planner tie-break: continue each cluster name's FNV-1 state over
    # the object key's bytes (h = h*prime ^ byte, uint32 wraparound),
    # then map to order-preserving int32 (hashing.py semantics).
    prime = jnp.uint32(16777619)
    state0 = jnp.broadcast_to(
        jnp.asarray(ci.name_hash_state), (b, c)
    ).astype(jnp.uint32)
    key_cols = jnp.asarray(ci.key_bytes).T  # [L, B] — scanned xs
    key_len = jnp.asarray(ci.key_len)
    n_bytes = key_cols.shape[0]

    def fnv_step(state, xs):
        byte, j = xs
        upd = (state * prime) ^ byte.astype(jnp.uint32)[:, None]
        keep = (j < key_len)[:, None]
        return jnp.where(keep, upd, state), None

    state, _ = jax.lax.scan(
        fnv_step, state0, (key_cols, jnp.arange(n_bytes))
    )
    tiebreak = jax.lax.bitcast_convert_type(
        state ^ jnp.uint32(0x80000000), jnp.int32
    )

    return TickInputs(
        filter_enabled=ci.filter_enabled,
        api_ok=api_ok,
        taint_ok_new=taint_ok_new,
        taint_ok_cur=taint_ok_cur,
        selector_ok=selector_ok,
        placement_has=ci.placement_has,
        placement_ok=placement_ok,
        request=ci.request,
        alloc=ci.alloc,
        used=ci.used,
        score_enabled=ci.score_enabled,
        taint_counts=taint_counts,
        affinity_scores=affinity_scores,
        webhook_ok=jnp.ones((b, c), bool),
        webhook_scores=jnp.zeros((b, c), jnp.int32),
        max_clusters=ci.max_clusters,
        mode_divide=ci.mode_divide,
        sticky=ci.sticky,
        current_mask=current_mask,
        current_replicas=current_replicas,
        total=ci.total,
        weights_given=ci.weights_given,
        weights=weights,
        min_replicas=min_replicas,
        max_replicas=max_replicas,
        scale_max=max_replicas,
        capacity=capacity,
        keep_unschedulable=ci.keep_unschedulable,
        avoid_disruption=ci.avoid_disruption,
        tiebreak=tiebreak,
        cpu_alloc=ci.cpu_alloc,
        cpu_avail=ci.cpu_avail,
        cluster_valid=ci.cluster_valid,
    )


@jax.jit
def schedule_tick(inp: TickInputs) -> TickOutputs:
    _note_trace(
        "schedule_tick", inp.total.shape[0], inp.cluster_valid.shape[0]
    )
    # --- Filter ---
    fit_ok = F.resources_fit(inp.request, inp.alloc, inp.used)
    feasible, reasons = F.combine_filters_explain(
        inp.filter_enabled,
        inp.api_ok,
        inp.taint_ok_new,
        inp.taint_ok_cur,
        inp.current_mask,
        fit_ok,
        inp.placement_has,
        inp.placement_ok,
        inp.selector_ok,
    )
    reasons = (
        reasons
        | jnp.where(~inp.webhook_ok, jnp.int32(RSN.REASON_WEBHOOK_FILTER), 0)
        | jnp.where(
            ~inp.cluster_valid[None, :], jnp.int32(RSN.REASON_CLUSTER_INVALID), 0
        )
    )
    feasible = feasible & inp.cluster_valid[None, :] & inp.webhook_ok

    # --- Score + Normalize ---
    totals = S.total_scores(
        inp.score_enabled,
        feasible,
        inp.request,
        inp.alloc,
        inp.used,
        inp.taint_counts,
        inp.affinity_scores,
    )
    # Webhook scores arrive pre-computed (one HTTP call per object x
    # cluster happens host-side); like in-tree plugin sums they only
    # matter on feasible clusters.
    totals = totals + jnp.where(feasible, inp.webhook_scores, 0)

    # --- Select ---
    selected = select_topk(totals, feasible, inp.max_clusters)
    # Feasible pairs the top-K cut: score rank >= K (including K == 0
    # for a negative maxClusters).
    reasons = reasons | jnp.where(
        feasible & ~selected, jnp.int32(RSN.REASON_MAX_CLUSTERS), 0
    )

    # --- Replicas (Divide mode) ---
    dyn_w = dynamic_weights(selected, inp.cpu_alloc, inp.cpu_avail)
    weights = jnp.where(
        inp.weights_given[:, None], inp.weights, dyn_w
    ).astype(jnp.int32)
    weights = jnp.where(selected, weights, 0)

    total64 = inp.total.astype(jnp.int64)
    current = jnp.where(
        inp.current_mask,
        jnp.where(inp.current_replicas == NIL_REPLICAS, total64[:, None], inp.current_replicas),
        0,
    ).astype(jnp.int32)

    plan_out = plan_batch_jit(
        PlannerInputs(
            weight=weights,
            min_replicas=jnp.where(selected, inp.min_replicas, 0),
            max_replicas=inp.max_replicas,
            scale_max=inp.scale_max,
            capacity=inp.capacity,
            tiebreak=inp.tiebreak,
            member=selected,
            total=inp.total,
            current=current,
            avoid_disruption=inp.avoid_disruption,
            keep_unschedulable=inp.keep_unschedulable,
        )
    )
    # The RSP merges capacity overflow back into the result as
    # "nice to schedule" replicas (rsp.go:158-177) and drops zero entries.
    divide_replicas = (plan_out.plan + plan_out.overflow).astype(jnp.int64)
    # Zero entries are dropped; negative entries (pathological min>max
    # policies) are preserved, as the reference's merge does.
    divide_selected = selected & (divide_replicas != 0)

    # Selected by top-K but dropped by the Divide-mode zero-entry merge.
    reasons = reasons | jnp.where(
        inp.mode_divide[:, None] & selected & ~divide_selected,
        jnp.int32(RSN.REASON_ZERO_REPLICAS),
        0,
    )

    mode_divide = inp.mode_divide[:, None]
    out_selected = jnp.where(mode_divide, divide_selected, selected)
    out_replicas = jnp.where(
        mode_divide, jnp.where(divide_selected, divide_replicas, 0), NIL_REPLICAS
    )
    out_counted = mode_divide & divide_selected

    # --- Sticky-cluster short-circuit (generic_scheduler.go:103-107) ---
    sticky_active = (inp.sticky & jnp.any(inp.current_mask, axis=-1))[:, None]
    out_selected = jnp.where(sticky_active, inp.current_mask, out_selected)
    out_replicas = jnp.where(
        sticky_active,
        jnp.where(inp.current_mask, inp.current_replicas, 0),
        out_replicas,
    )
    out_counted = jnp.where(
        sticky_active,
        inp.current_mask & (inp.current_replicas != NIL_REPLICAS),
        out_counted,
    )
    out_replicas = jnp.where(out_selected, out_replicas, 0)

    # Sticky short-circuit reasons: the current clusters win regardless
    # of plugin verdicts; everything else is cut by stickiness (the
    # filter bits are kept for context — they explain what WOULD reject
    # the pair if the object were rescheduled from scratch).
    reasons = jnp.where(
        sticky_active & ~inp.current_mask,
        reasons | jnp.int32(RSN.REASON_STICKY),
        reasons,
    )
    # Invariant the flight recorder (and test_explain) rely on:
    # reasons == 0 exactly where selected.
    reasons = jnp.where(out_selected, 0, reasons)

    return TickOutputs(
        selected=out_selected.astype(jnp.int8),
        replicas=out_replicas.astype(jnp.int32),
        counted=(out_counted & out_selected).astype(jnp.int8),
        feasible=feasible.astype(jnp.int8),
        scores=totals.astype(jnp.int32),
        reasons=reasons.astype(jnp.int32),
    )


# -- drift gate -----------------------------------------------------------
# A cluster-capacity drift tick must revalidate every row, but the rows
# whose DECISION can actually move are a function of which cluster
# columns changed.  These kernels classify rows exactly, from the cached
# per-object planes plus the previous tick's feasibility plane, without
# running the expensive select/planner stages:
#
#   recompute — the row's placement may change and must be re-scheduled;
#   wcheck    — the selection provably cannot change, but the row uses
#               DYNAMIC weights over a cluster whose CPU figures moved:
#               compare old-vs-new weights (drift_wcheck) and recompute
#               only on a real difference;
#   (neither) — the row's outputs are provably bit-identical.
#
# Exactness argument (each step is checked by tests/test_drift_tick.py's
# randomized differential):
#
# 1. Feasibility depends on the cluster planes ONLY through the
#    resource-fit mask (filters.resources_fit); every other filter input
#    is per-object/topology.  So feasibility can flip only on changed
#    columns — recompute any row with such a flip ("fitflip").
# 2. The normalized score plugins (taint, affinity) read per-object
#    planes and normalize by the per-row max over FEASIBLE columns; the
#    resource plugins are per-cell functions of (request, alloc, used).
#    Hence, absent a fit flip, the score totals change only on changed
#    columns — and a column that is infeasible contributes neither a
#    total nor a normalization max.
# 3. Selection: with max_clusters >= nfeas (or unlimited, or negative =
#    select nothing), the top-K cut never engages — selection IS the
#    feasible set, so score changes cannot move it.  Otherwise the cut
#    is rank-based.  Unchanged columns keep their relative order (their
#    totals are untouched, step 2), so the selected SET changes iff
#    some changed column's top-K membership flips: a non-delta column
#    can enter (leave) the set only when a delta column leaves (enters)
#    it.  For small deltas the gate tests that exactly — it derives the
#    changed columns' new totals from the stored score plane (the
#    resource plugins are per-cell, the normalized plugins untouched)
#    and counts, per the select stage's own (-total, index) comparator,
#    how many feasible columns outrank each delta column before and
#    after.  Wider deltas fall back to the conservative "any delta
#    column feasible" rule.  Either way the gate scatters the updated
#    totals back into the stored score plane, so skipped rows' stored
#    state stays exact for future drift ticks.
# 4. Replicas: the planner consumes per-object inputs plus the weights.
#    Static weights are per-object; dynamic weights read cpu_alloc/
#    cpu_avail of the SELECTED clusters.  A Divide-mode row without
#    given weights whose selection touches a cpu-changed column goes to
#    wcheck: selection is provably unchanged there (step 3), so
#    comparing dynamic_weights old-vs-new on that selection decides
#    replica equality exactly.
# 5. Sticky rows with current placements short-circuit to their current
#    clusters — independent of cluster planes entirely — and are never
#    candidates.
#
# Skipped (and weight-equal wcheck) rows keep their previous outputs;
# their score/reason introspection planes may go stale on changed
# columns, exactly like the engine's existing mask-only "skip" path —
# placement planes stay exact, which is what parity and the delta
# machinery consume.

DRIFT_RECOMPUTE = 1  # gate-mask bit: row must be re-scheduled
DRIFT_WCHECK = 2     # gate-mask bit: row needs the dynamic-weight check

# Widest delta the exact top-K membership refinement runs at: the rank
# counts cost O(B x C x D) compares, so wider drifts use the
# conservative any-delta-column-feasible rule instead.
DRIFT_REFINE_MAX_COLS = 8


def _resource_scores_cols(request, score_enabled, alloc_d, used_d):
    """The cluster-plane-dependent part of a row's score total at the
    given columns: the resource plugins, enabled-masked (taint/affinity
    are per-object and normalization is untouched without a fit flip —
    see the exactness argument above)."""
    parts = (
        (S.S_BALANCED, S.balanced_allocation_score(request, alloc_d, used_d)),
        (S.S_LEAST, S.least_allocated_score(request, alloc_d, used_d)),
        (S.S_MOST, S.most_allocated_score(request, alloc_d, used_d)),
    )
    total = jnp.zeros((request.shape[0], alloc_d.shape[0]), jnp.int64)
    for idx, s in parts:
        total = total + jnp.where(score_enabled[:, idx, None], s, 0)
    return total


def _drift_classify(
    fea_new_d,      # bool[B, D] feasibility of the changed columns, new planes
    prev_feas_d,    # i8[B, D] previous feasibility at the changed columns
    prev_feas,      # i8[B, C] previous feasibility plane
    prev_scores,    # i32[B, C] previous post-normalize totals
    res_old_d,      # i64[B, D] resource-score part at the columns, old planes
    res_new_d,      # i64[B, D] resource-score part at the columns, new planes
    delta_idx,      # i32[D] changed column indices (pad: out of range)
    delta_valid,    # bool[D] slot is a real changed column (not padding)
    delta_cpu,      # bool[D] the column's cpu_alloc/cpu_avail changed
    max_clusters,   # i32[B]
    mode_divide,    # bool[B]
    weights_given,  # bool[B]
    sticky_active,  # bool[B]
):
    """Shared tail of the dense/compact drift gates.

    Returns (i8[B] bit mask, i32[B, C] updated score plane): the mask
    classifies rows, and the score plane is the stored totals with the
    changed columns' values refreshed — so skipped rows' cached state
    stays exact across consecutive drift ticks."""
    b, c = prev_feas.shape
    pf = prev_feas != 0
    pf_d = prev_feas_d != 0
    valid = delta_valid[None, :]
    fitflip = ((fea_new_d != pf_d) & valid).any(axis=1)
    dcpu_any = (pf_d & (delta_cpu & delta_valid)[None, :]).any(axis=1)
    nfeas = pf.sum(axis=1, dtype=jnp.int32)
    # Selection equals the feasible set when the top-K cut cannot engage
    # (unlimited, K >= nfeas, or negative K = empty selection).
    kinf = (
        (max_clusters == INT32_INF)
        | (max_clusters < 0)
        | (max_clusters >= nfeas)
    )

    # Updated totals at the changed columns (masked exactly like the
    # tick: zero where infeasible), and the scatter back into the
    # stored plane (padded delta slots are out of range -> dropped).
    tot_old_d = prev_scores[:, jnp.clip(delta_idx, 0, c - 1)].astype(jnp.int64)
    tot_new_d = jnp.where(pf_d, tot_old_d - res_old_d + res_new_d, 0)
    rows = jnp.arange(b, dtype=jnp.int32)[:, None]
    new_scores = prev_scores.at[
        rows, jnp.broadcast_to(delta_idx[None, :], (b, delta_idx.shape[0]))
    ].set(tot_new_d.astype(jnp.int32), mode="drop")

    d = delta_idx.shape[0]
    if d <= DRIFT_REFINE_MAX_COLS:
        # Exact top-K refinement: the selected set changes iff some
        # delta column's top-K membership flips (unchanged columns keep
        # their relative order, so one can only enter/leave when a
        # delta column leaves/enters).  Membership is counted with the
        # select stage's own comparator: (-total, index) ascending.
        is_delta = jnp.zeros(c, bool).at[delta_idx].set(
            delta_valid, mode="drop"
        )
        s_plane = prev_scores.astype(jnp.int64)[:, :, None]   # [B, C, 1]
        j_idx = jnp.arange(c, dtype=jnp.int32)[None, :, None]

        def above_counts(tot_d):
            t = tot_d[:, None, :]                              # [B, 1, D]
            beats = (s_plane > t) | (
                (s_plane == t) & (j_idx < delta_idx[None, None, :])
            )
            unchanged = (pf & ~is_delta[None, :])[:, :, None]
            cnt = jnp.sum(beats & unchanged, axis=1, dtype=jnp.int32)
            # Delta-vs-delta comparisons use the same snapshot's totals.
            te = tot_d[:, :, None]                             # [B, D(e), 1]
            td = tot_d[:, None, :]                             # [B, 1, D(d)]
            e_beats = (te > td) | (
                (te == td)
                & (delta_idx[:, None] < delta_idx[None, :])[None, :, :]
            )
            e_mask = (pf_d & valid)[:, :, None]
            return cnt + jnp.sum(e_beats & e_mask, axis=1, dtype=jnp.int32)

        k = jnp.clip(max_clusters, 0, c)[:, None]
        member_old = pf_d & (above_counts(tot_old_d) < k)
        member_new = pf_d & (above_counts(tot_new_d) < k)
        sel_exposed = ((member_old != member_new) & valid).any(axis=1)
        # Finite-K rows with DYNAMIC weights whose top-K selection
        # touches a cpu-changed column: their weight set is the top-K
        # selection (not the feasible set), so the wcheck comparison
        # below cannot decide them — recompute.  (member_old|member_new
        # is exact top-K membership from the rank counts.)
        dyn_fin = (
            (member_old | member_new) & (delta_cpu & delta_valid)[None, :]
        ).any(axis=1)
        sel_exposed = sel_exposed | (
            mode_divide & ~weights_given & dyn_fin
        )
    else:
        # Conservative: any feasible delta column may cross the K cut
        # (this also covers the finite-K dynamic-weight exposure, since
        # a cpu-changed column in the selection is feasible).
        sel_exposed = ((fea_new_d | pf_d) & valid).any(axis=1)

    recompute = ~sticky_active & (fitflip | (~kinf & sel_exposed))
    # The weight check is sound ONLY where selection provably equals
    # the feasible set (kinf): dynamic weights are computed over the
    # selection, and that is what drift_wcheck reconstructs from
    # prev_feas.
    wcheck = (
        ~sticky_active
        & ~recompute
        & kinf
        & mode_divide
        & ~weights_given
        & dcpu_any
    )
    mask = (
        recompute.astype(jnp.int8) * DRIFT_RECOMPUTE
        + wcheck.astype(jnp.int8) * DRIFT_WCHECK
    )
    return mask, new_scores


def drift_gate_dense(
    per_object: dict,
    prev_feas,
    prev_scores,
    alloc_old_d,
    used_old_d,
    alloc_new_d,
    used_new_d,
    delta_idx,
    delta_valid,
    delta_cpu,
):
    """Drift gate over dense cached per-object planes.

    ``per_object`` is the engine's cached device dict (every TickInputs
    field that is not cluster-axis-only); ``*_old_d``/``*_new_d`` are
    the OLD/NEW cluster tensors pre-sliced at the changed columns
    (i64[D, R]); ``delta_idx`` i32[D] names the changed columns (padded
    entries carry an out-of-range index and ``delta_valid`` False).
    Returns (i8[B] mask, i32[B, C] refreshed score plane)."""
    b = per_object["total"].shape[0]
    _note_trace("drift_gate", b, prev_feas.shape[1])
    c = prev_feas.shape[1]
    d_safe = jnp.clip(delta_idx, 0, c - 1)
    fit_new = F.resources_fit(per_object["request"], alloc_new_d, used_new_d)
    fea_new_d = F.combine_filters(
        per_object["filter_enabled"],
        per_object["api_ok"][:, d_safe],
        per_object["taint_ok_new"][:, d_safe],
        per_object["taint_ok_cur"][:, d_safe],
        per_object["current_mask"][:, d_safe],
        fit_new,
        per_object["placement_has"],
        per_object["placement_ok"][:, d_safe],
        per_object["selector_ok"][:, d_safe],
    ) & per_object["webhook_ok"][:, d_safe]
    sticky_active = per_object["sticky"] & per_object["current_mask"].any(axis=1)
    enabled = per_object["score_enabled"]
    return _drift_classify(
        fea_new_d,
        prev_feas[:, d_safe],
        prev_feas,
        prev_scores,
        _resource_scores_cols(
            per_object["request"], enabled, alloc_old_d, used_old_d
        ),
        _resource_scores_cols(
            per_object["request"], enabled, alloc_new_d, used_new_d
        ),
        delta_idx,
        delta_valid,
        delta_cpu,
        per_object["max_clusters"],
        per_object["mode_divide"],
        per_object["weights_given"],
        sticky_active,
    )


def drift_gate_compact(
    per_object: dict,
    tables: dict,
    prev_feas,
    prev_scores,
    alloc_old_d,
    used_old_d,
    alloc_new_d,
    used_new_d,
    delta_idx,
    delta_valid,
    delta_cpu,
    cur_absent,
):
    """Compact-format drift gate: the changed columns' filter masks are
    gathered straight from the vocabulary tables (a D-column slice of
    ops.pipeline.expand_compact), so the gate never materializes [B, C]
    planes."""
    b = per_object["total"].shape[0]
    _note_trace("drift_gate", b, prev_feas.shape[1])
    c = prev_feas.shape[1]
    d_safe = jnp.clip(delta_idx, 0, c - 1)
    api = tables["api_matrix"][:, d_safe][per_object["gvk_id"]]
    trow = tables["taint_set_id"][d_safe]
    taint_new = tables["taint_new"][per_object["tol_id"]][:, trow]
    taint_cur = tables["taint_cur"][per_object["tol_id"]][:, trow]
    selector = tables["sel_matrix"][:, d_safe][per_object["sel_id"]]
    placement = tables["place_matrix"][:, d_safe][per_object["place_id"]]
    cur_present = per_object["sparse_cur"] != cur_absent  # [B, P]
    current_d = (
        (per_object["sparse_idx"][:, :, None] == delta_idx[None, None, :])
        & cur_present[:, :, None]
    ).any(axis=1)
    fit_new = F.resources_fit(per_object["request"], alloc_new_d, used_new_d)
    fea_new_d = F.combine_filters(
        per_object["filter_enabled"],
        api,
        taint_new,
        taint_cur,
        current_d,
        fit_new,
        per_object["placement_has"],
        placement,
        selector,
    )
    sticky_active = per_object["sticky"] & cur_present.any(axis=1)
    enabled = per_object["score_enabled"]
    return _drift_classify(
        fea_new_d,
        prev_feas[:, d_safe],
        prev_feas,
        prev_scores,
        _resource_scores_cols(
            per_object["request"], enabled, alloc_old_d, used_old_d
        ),
        _resource_scores_cols(
            per_object["request"], enabled, alloc_new_d, used_new_d
        ),
        delta_idx,
        delta_valid,
        delta_cpu,
        per_object["max_clusters"],
        per_object["mode_divide"],
        per_object["weights_given"],
        sticky_active,
    )


def drift_wcheck(
    prev_feas,
    rows_idx,
    cpu_alloc_old,
    cpu_avail_old,
    cpu_alloc_new,
    cpu_avail_new,
):
    """Dynamic-weight equality check for gate-classified wcheck rows.

    Those rows' selection provably equals their feasible set (see the
    gate's exactness argument, step 3/4), so comparing dynamic weights
    over prev_feas decides replica equality exactly.  Returns i8[K]:
    1 where the weights differ (row must recompute)."""
    _note_trace("drift_wcheck", rows_idx.shape[0], prev_feas.shape[1])
    sel = prev_feas[rows_idx] != 0
    w_old = dynamic_weights(sel, cpu_alloc_old, cpu_avail_old)
    w_new = dynamic_weights(sel, cpu_alloc_new, cpu_avail_new)
    return (w_old != w_new).any(axis=-1).astype(jnp.int8)


# -- packed placement export ---------------------------------------------
# Each object lands on at most max_clusters clusters, yet the dense
# output planes ship B x C cells off the device.  The packed export
# top-k-compacts every row into K-wide tensors before the transfer, so
# fetch bytes scale as B x K instead of B x C (~C/K less traffic); the
# rare row selecting more than K clusters raises its overflow flag
# (nsel > K) and the engine re-fetches it through the dense-plane path.

PACK_FILL = -1  # idx value of unused packed slots


class PackedRows(NamedTuple):
    """The packed placement layout: one row per object, K slots."""

    idx: jax.Array   # i32[N,K] selected cluster indices, ascending; PACK_FILL pads
    rep: jax.Array   # i32[N,K] replicas of that cluster (NIL in Duplicate mode)
    cnt: jax.Array   # i32[N,K] 1 when the placement carries a replica count
    sco: jax.Array   # i32[N,K] post-normalize score total of that cluster
    nsel: jax.Array  # i32[N]   true selected count; nsel > K flags overflow
    nfeas: jax.Array # i32[N]   valid clusters with no filter-stage reason
    rsum: jax.Array  # i32[N,NUM_REASON_BITS] clusters rejected per reason
    #                  bit (ops.reasons.REASON_BITS order), valid slots only


def pack_rows(selected, replicas, counted, scores, reasons, k: int) -> PackedRows:
    """Top-k-compact dense output planes (any leading row count) into the
    packed layout.  Slot order is (score desc, cluster index asc) over
    the SELECTED clusters — the select stage's own ranking — so the
    first slots ARE the row's top scorers: the flight recorder's top-k
    reads straight off the wire even for K-overflow rows.  The index is
    a comparator key (lax.sort num_keys=2, unique per row), not argsort
    stability, so the layout is bit-identical on every backend and
    matches the sequential oracle's pack_one exactly (see
    ops/select.py for why stability must not be relied on)."""
    c = selected.shape[-1]
    k = min(k, c)
    selb = selected != 0
    iota = lax.broadcasted_iota(jnp.int32, selb.shape, selb.ndim - 1)
    # Selected clusters sort to the front by (-score, index); unselected
    # sink past them (scores are bounded far below int32 max).
    key1 = jnp.where(selb, -scores.astype(jnp.int32), jnp.iinfo(jnp.int32).max)
    _, order = lax.sort((key1, iota), dimension=-1, num_keys=2)
    order = order[..., :k]
    valid = jnp.take_along_axis(selb, order, axis=-1)
    gidx = jnp.where(valid, order, 0)

    def take(plane):
        return jnp.take_along_axis(plane.astype(jnp.int32), gidx, axis=-1)

    zero = jnp.int32(0)
    rsn = reasons.astype(jnp.int32)
    valid_slot = (rsn & jnp.int32(RSN.REASON_CLUSTER_INVALID)) == 0
    rsum = jnp.stack(
        [
            jnp.sum(((rsn & jnp.int32(bit)) != 0) & valid_slot, axis=-1)
            for bit in RSN.REASON_BITS
        ],
        axis=-1,
    ).astype(jnp.int32)
    nfeas = jnp.sum(
        ((rsn & jnp.int32(RSN.FILTER_REASON_MASK)) == 0) & valid_slot, axis=-1
    ).astype(jnp.int32)
    return PackedRows(
        idx=jnp.where(valid, order, jnp.int32(PACK_FILL)),
        rep=jnp.where(valid, take(replicas), zero),
        cnt=jnp.where(valid, take(counted), zero),
        sco=jnp.where(valid, take(scores), zero),
        nsel=jnp.sum(selb, axis=-1).astype(jnp.int32),
        nfeas=nfeas,
        rsum=rsum,
    )


def wire_width(k: int) -> int:
    """Column count of the packed wire row: 4 K-wide planes + nsel +
    nfeas + the reason-summary counts."""
    return 4 * k + 2 + RSN.NUM_REASON_BITS


def pack_wire(selected, replicas, counted, scores, reasons, k: int) -> jax.Array:
    """The packed layout flattened to ONE i32[N, wire_width(k)] array —
    a single device->host transfer per fetch, like the dense path's
    _gather_packed* concats."""
    p = pack_rows(selected, replicas, counted, scores, reasons, k)
    return jnp.concatenate(
        [p.idx, p.rep, p.cnt, p.sco, p.nsel[..., None], p.nfeas[..., None], p.rsum],
        axis=-1,
    )


def unpack_wire(arr, k: int) -> PackedRows:
    """Host-side inverse of pack_wire (numpy views, no copies)."""
    arr = np.asarray(arr)
    return PackedRows(
        idx=arr[:, :k],
        rep=arr[:, k : 2 * k],
        cnt=arr[:, 2 * k : 3 * k],
        sco=arr[:, 3 * k : 4 * k],
        nsel=arr[:, 4 * k],
        nfeas=arr[:, 4 * k + 1],
        rsum=arr[:, 4 * k + 2 : 4 * k + 2 + RSN.NUM_REASON_BITS],
    )
