"""Batched follower-scheduling union.

The reference's follower controller makes a follower resource's
placement the union of its leader workloads' placements (reference:
pkg/controllers/follower/controller.go:95-521 — leaders' placements are
unioned into the follower fed object via ``spec.follows``).  The
control-plane path here is :mod:`kubeadmiral_tpu.federation.follower`;
this module is the ENGINE-side capability for batch ticks: given engine
row indices, overwrite each follower row's result with the union of its
leader rows' placements.

Incremental by design: the union for a follower is recomputed only when
one of its leaders' placements changed this tick (the engine's
``last_changed`` row set), so a 1%-churn steady tick pays O(affected
followers), not O(all followers) — the per-tick all-followers Python
loop was ~1.1 s of the config-5 host floor (VERDICT r4 #1b).
"""

from __future__ import annotations

from typing import Iterable, Mapping, Optional, Sequence

from kubeadmiral_tpu.scheduler.engine import ScheduleResult, _FrozenDict


class FollowerIndex:
    """Leader→follower union over engine rows.

    ``follows`` maps a follower row index to the row indices of its
    leaders.  The graph is bipartite, mirroring the reference (leaders
    are workloads, followers are config/secret-style resources): a
    follower must not itself appear as another follower's leader.
    """

    def __init__(self, follows: Mapping[int, Sequence[int]]):
        self.follows: dict[int, tuple[int, ...]] = {
            int(f): tuple(int(x) for x in leaders)
            for f, leaders in follows.items()
        }
        for f, leaders in self.follows.items():
            for leader in leaders:
                if leader in self.follows:
                    raise ValueError(
                        f"row {leader} is both a leader (of {f}) and a "
                        "follower; the follows graph must be bipartite"
                    )
        # Reverse index: leader row -> follower rows it affects.
        self._followers_of: dict[int, list[int]] = {}
        for f, leaders in self.follows.items():
            for leader in leaders:
                self._followers_of.setdefault(leader, []).append(f)
        self._cache: dict[int, ScheduleResult] = {}

    def affected(self, changed: Optional[Iterable[int]]) -> Iterable[int]:
        """Follower rows whose union is stale given changed leader rows
        (None = everything)."""
        if changed is None or not self._cache:
            return self.follows.keys()
        out: set[int] = set()
        for row in changed:
            out.update(self._followers_of.get(row, ()))
        return out

    def apply(
        self,
        results: list[ScheduleResult],
        changed: Optional[Iterable[int]] = None,
    ) -> list[ScheduleResult]:
        """Overwrite follower rows of ``results`` in place with their
        leaders' placement union (clusters only, no replica counts —
        follower placement mirrors spec.follows semantics).  ``changed``
        is the engine's ``last_changed`` from the same tick."""
        for f in self.affected(changed):
            union: dict = {}
            for leader in self.follows[f]:
                union.update(results[leader].clusters)
            self._cache[f] = ScheduleResult(
                clusters=_FrozenDict(dict.fromkeys(union))
            )
        cache = self._cache
        for f in self.follows:
            results[f] = cache[f]
        return results
