"""Select stage: MaxCluster top-K (reference: plugins/maxcluster/max_cluster.go).

The reference sorts feasible clusters by score (unstable Go sort) and keeps
the first K = min(maxClusters, len).  Here ties break deterministically by
cluster index (the reference's tie order is unspecified), a negative
maxClusters selects nothing (reference returns Unschedulable), and the
sentinel INT32_INF means "no limit".
"""

from __future__ import annotations

import jax
from jax import lax
import jax.numpy as jnp

from kubeadmiral_tpu.parallel import shardguard

from kubeadmiral_tpu.ops.planner import INT32_INF


@shardguard.rows_first
def select_topk(scores, feasible, max_clusters):
    """scores i64[B,C], feasible bool[B,C], max_clusters i32[B] -> bool[B,C].

    Shape-polymorphic over the cluster axis: the narrow solve
    (ops.pipeline.schedule_tick_narrow) calls this on [B, M] candidate
    planes gathered in ascending column order, so the (score desc,
    index asc) comparator ranks narrow slots exactly as it ranks the
    dense columns they came from.

    The keys are int32-bounded: plugin totals are bounded by 5 x 100
    (normalized in-tree scores) plus webhook scores clamped to
    int32max/2 by the featurizer, so every total fits int32 with room.

    The index tie-break is part of the sort KEY, not argsort stability:
    jnp.argsort(stable=True) carries the iota as a value operand and
    trusts the backend's is_stable flag, which the axon TPU sort
    ignores at wide rows — caught by the r5 on-chip parity check as ~3%
    placement mismatches at 100k x 5120 (ties at the top-K boundary
    selected backend-dependent clusters) while narrow shapes agreed
    exactly.  Two key encodings give the same bit-exact rank:

    * CPU: the (key, index) pair packs into one collision-free int64
      (key * C + iota) and a SINGLE-key sort ranks it — XLA:CPU lowers
      variadic sorts to a slow row-serial comparator loop, so the
      packed form is ~3x faster (70.5 -> 21.6ms at [256, 512]).
    * TPU: the comparator form (lax.sort num_keys=2 on int32 keys) —
      int64 is emulated on TPU, where the variadic int32 sort is the
      cheaper one.
    """
    c = scores.shape[-1]
    # Rank feasible clusters by score desc, index asc; infeasible last.
    sort_key = jnp.where(
        feasible, -scores.astype(jnp.int32), jnp.iinfo(jnp.int32).max
    )
    iota = lax.broadcasted_iota(jnp.int32, sort_key.shape, sort_key.ndim - 1)
    if jax.default_backend() == "tpu":
        _, order = lax.sort((sort_key, iota), dimension=-1, num_keys=2)
    else:
        comp = sort_key.astype(jnp.int64) * c + iota
        order = (lax.sort(comp, dimension=-1) % c).astype(jnp.int32)
    # Inverting a permutation: values are unique, so any correct sort
    # yields the same rank regardless of backend stability.  Scatter
    # inversion (rank[order[i]] = i) beats a second argsort.
    rows = jnp.arange(sort_key.shape[0], dtype=jnp.int32)[:, None]
    rank = jnp.zeros_like(order).at[rows, order].set(iota)
    k = jnp.where(
        max_clusters < 0,
        0,
        jnp.minimum(max_clusters, jnp.int32(c)),
    )
    return feasible & (rank < k[:, None])
