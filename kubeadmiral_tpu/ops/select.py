"""Select stage: MaxCluster top-K (reference: plugins/maxcluster/max_cluster.go).

The reference sorts feasible clusters by score (unstable Go sort) and keeps
the first K = min(maxClusters, len).  Here ties break deterministically by
cluster index (the reference's tie order is unspecified), a negative
maxClusters selects nothing (reference returns Unschedulable), and the
sentinel INT32_INF means "no limit".
"""

from __future__ import annotations

from jax import lax
import jax.numpy as jnp

from kubeadmiral_tpu.ops.planner import INT32_INF


def select_topk(scores, feasible, max_clusters):
    """scores i64[B,C], feasible bool[B,C], max_clusters i32[B] -> bool[B,C].

    The sort runs on int32 keys: plugin totals are bounded by 5 x 100
    (normalized in-tree scores) plus webhook scores clamped to
    int32max/2 by the featurizer, so every total fits int32 with room —
    and 64-bit sorts are disproportionately expensive to compile (and,
    on TPU, to run: int64 is emulated).

    The index tie-break is a comparator KEY (lax.sort num_keys=2), not
    argsort stability: jnp.argsort(stable=True) carries the iota as a
    value operand and trusts the backend's is_stable flag, which the
    axon TPU sort ignores at wide rows — caught by the r5 on-chip
    parity check as ~3% placement mismatches at 100k x 5120 (ties at
    the top-K boundary selected backend-dependent clusters) while
    narrow shapes agreed exactly."""
    c = scores.shape[-1]
    # Rank feasible clusters by score desc, index asc; infeasible last.
    sort_key = jnp.where(
        feasible, -scores.astype(jnp.int32), jnp.iinfo(jnp.int32).max
    )
    iota = lax.broadcasted_iota(jnp.int32, sort_key.shape, sort_key.ndim - 1)
    _, order = lax.sort((sort_key, iota), dimension=-1, num_keys=2)
    # Inverting a permutation: values are unique, so any correct sort
    # yields the same rank regardless of backend stability.
    rank = jnp.argsort(order, axis=-1, stable=False)  # rank[b,c] = position of c
    k = jnp.where(
        max_clusters < 0,
        0,
        jnp.minimum(max_clusters, jnp.int32(c)),
    )
    return feasible & (rank < k[:, None])
