"""Reason-code vocabulary for scheduling decisions.

Every (object, cluster) pair a tick rejects carries a bitmask saying
WHY — one bit per filter plugin (matching the ``ops.filters`` plugin
indices: bit i is filter plugin i), plus the host-side webhook filter,
the padded-cluster sentinel, and the select/replica-stage cuts.  A
selected pair carries mask 0.  The mask is computed on device inside
``ops.pipeline.schedule_tick`` (TickOutputs.reasons), verified
bit-exactly against the sequential oracle
(``ops.pipeline_oracle.explain_one``), and rendered for operators by the
flight recorder (``runtime/flightrec.py`` → ``GET /debug/explain``).

The slugs below are the operator-facing decision vocabulary:
``tools/metrics_lint.py`` cross-checks them against
``runtime.metric_catalog.DECISION_REASONS`` so the strings served by
``/debug/explain`` (and recorded in events) never drift from the
documented set in docs/observability.md.
"""

from __future__ import annotations

from kubeadmiral_tpu.ops import filters as F

# -- filter-stage bits (bit i == ops.filters plugin index i) -------------
REASON_API_RESOURCES = 1 << F.F_API_RESOURCES      # 1
REASON_TAINT_TOLERATION = 1 << F.F_TAINT_TOLERATION  # 2
REASON_RESOURCES_FIT = 1 << F.F_RESOURCES_FIT      # 4
REASON_PLACEMENT = 1 << F.F_PLACEMENT              # 8
REASON_CLUSTER_AFFINITY = 1 << F.F_CLUSTER_AFFINITY  # 16
# Host-side (out-of-process) webhook filter plugins, AND-ed into the
# feasibility mask by the tick.
REASON_WEBHOOK_FILTER = 1 << 5
# Padded / invalid cluster slot (cluster_valid == False).  Engine
# consumers never see it (they slice to the real cluster count); it
# keeps the invariant "not selected => nonzero mask" on padded slots.
REASON_CLUSTER_INVALID = 1 << 6

# -- select / replica-stage bits -----------------------------------------
# Feasible but cut by the MaxCluster top-K (score rank >= K, including
# K == 0 for a negative maxClusters).
REASON_MAX_CLUSTERS = 1 << 7
# Selected by top-K but the replica planner assigned 0 replicas, so the
# Divide-mode merge dropped the placement (rsp.go drops zero entries).
REASON_ZERO_REPLICAS = 1 << 8
# Dropped by the sticky-cluster short-circuit: the object is stickily
# placed, so plugins never ran for real and only the current clusters
# survive (generic_scheduler.go:103-107).
REASON_STICKY = 1 << 9

# Bits that make a pair infeasible (filter stage, before select).
FILTER_REASON_MASK = (
    REASON_API_RESOURCES
    | REASON_TAINT_TOLERATION
    | REASON_RESOURCES_FIT
    | REASON_PLACEMENT
    | REASON_CLUSTER_AFFINITY
    | REASON_WEBHOOK_FILTER
    | REASON_CLUSTER_INVALID
)
SELECT_REASON_MASK = REASON_MAX_CLUSTERS | REASON_ZERO_REPLICAS | REASON_STICKY
ALL_REASON_MASK = FILTER_REASON_MASK | SELECT_REASON_MASK

# Canonical bit order (ascending bit value) — the column order of the
# packed export's per-row reason-summary counts (ops/pipeline.pack_rows)
# and of DecisionRecord.reason_counts in the flight recorder.
REASON_BITS: tuple[int, ...] = (
    REASON_API_RESOURCES,
    REASON_TAINT_TOLERATION,
    REASON_RESOURCES_FIT,
    REASON_PLACEMENT,
    REASON_CLUSTER_AFFINITY,
    REASON_WEBHOOK_FILTER,
    REASON_CLUSTER_INVALID,
    REASON_MAX_CLUSTERS,
    REASON_ZERO_REPLICAS,
    REASON_STICKY,
)
NUM_REASON_BITS = len(REASON_BITS)

# bit value -> operator-facing slug (the decision vocabulary).
REASON_NAMES: dict[int, str] = {
    REASON_API_RESOURCES: "api_resources",
    REASON_TAINT_TOLERATION: "taint_toleration",
    REASON_RESOURCES_FIT: "resources_fit",
    REASON_PLACEMENT: "placement",
    REASON_CLUSTER_AFFINITY: "cluster_affinity",
    REASON_WEBHOOK_FILTER: "webhook_filter",
    REASON_CLUSTER_INVALID: "cluster_invalid",
    REASON_MAX_CLUSTERS: "max_clusters",
    REASON_ZERO_REPLICAS: "zero_replicas",
    REASON_STICKY: "sticky_cluster",
}

# The packed column order must cover exactly the named bits, ascending.
assert REASON_BITS == tuple(sorted(REASON_NAMES))


def describe(mask: int) -> list[str]:
    """Bitmask -> list of reason slugs, lowest bit first."""
    return [name for bit, name in REASON_NAMES.items() if mask & bit]


def is_feasible(mask: int) -> bool:
    """A pair is feasible iff no filter-stage bit is set (it may still
    be unselected via a select-stage cut)."""
    return not (mask & FILTER_REASON_MASK)
