"""Pallas TPU kernel for the narrow-slab hot loop's per-cell front.

The narrow slab program's cost at [rows, C] is dominated by its
per-cell work: the five filter masks, the five score plugins with their
per-row normalizations, and the reason-bit assembly are O(B*C) integer
math that XLA materializes as a dozen-plus separate [B, C] (mostly
int64) passes — the ~0.45us/cell floor ROADMAP item 2 names, which
every sub-batch path rides (churn slabs, drift survivors, certificate
fallbacks).  This module hand-fuses that front into ONE VMEM-resident
pass per row block (SNIPPETS [1]'s shard_map + Pallas pattern, minus
the remote copies): each grid step holds a [bm, C] tile of every
per-object plane plus the shared [C, R] cluster tensors in VMEM and
emits feasibility, reason bits and normalized score totals without
spilling an intermediate plane to HBM between plugin passes.

Exactness: the kernel body calls the very same ops.filters / ops.scores
jnp math the XLA ``_phase1`` runs — integer arithmetic end to end (the
balanced-allocation score's rational form and ``_floordiv_smallq``'s
estimate+correct division are backend-stable by design; ops/scores.py
derives the error bounds).  Bit-identity is enforced three ways:

* interpret-mode parity tests (tests/test_pallas_slab.py) assert the
  triple equals ``_phase1(inp)`` bit-for-bit on randomized worlds,
  including webhook planes and padded cluster columns;
* the graft dryrun harness runs a pallas-vs-dense parity block
  (``__graft_entry__.dryrun_multichip``);
* downstream, nothing changes: the narrow solve's per-row certificates
  and the dense fallback still guard the select/planner stages, so a
  row the narrow solve cannot certify re-solves through the dense
  (non-Pallas) program — placements stay bit-identical by construction
  even if a backend ever disagreed on the fused front.

Knob: ``KT_PALLAS=1`` opts in; the default is OFF everywhere — on
non-TPU platforms the kernel only exists in interpreter mode (a parity
harness, not a fast path), and the compiled Mosaic kernel awaits its
first on-chip validation round (ROADMAP item 1) before it can default
on for TPU.  Non-TPU backends always run the interpreter regardless of
the knob, so tier-1 parity tests exercise the real kernel body.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from kubeadmiral_tpu.ops import filters as F
from kubeadmiral_tpu.ops import reasons as RSN
from kubeadmiral_tpu.ops import scores as S

# Row-block height: 8 sublanes is the f32 VPU tile height and divides
# every engine row bucket (pow2 >= 16); tiny test batches fall back to
# the largest pow2 that divides B.
_BLOCK_ROWS = 8


def pallas_enabled() -> bool:
    """The KT_PALLAS opt-in (default off — see the module docstring)."""
    return os.environ.get("KT_PALLAS", "0") in ("1", "true", "yes")


def _phase1_kernel(
    # per-row blocks [bm, *]
    filter_enabled_ref,  # i8[bm, 5]
    score_enabled_ref,   # i8[bm, 5]
    request_ref,         # i64[bm, R]
    placement_has_ref,   # i8[bm, 1]
    api_ref,             # i8[bm, C]
    taint_new_ref,       # i8[bm, C]
    taint_cur_ref,       # i8[bm, C]
    selector_ref,        # i8[bm, C]
    placement_ref,       # i8[bm, C]
    current_ref,         # i8[bm, C]
    webhook_ok_ref,      # i8[bm, C]
    webhook_sco_ref,     # i64[bm, C]
    taint_counts_ref,    # i64[bm, C]
    affinity_ref,        # i64[bm, C]
    # shared cluster planes (whole axis in every block)
    alloc_ref,           # i64[C, R]
    used_ref,            # i64[C, R]
    cluster_valid_ref,   # i8[1, C]
    # outputs [bm, C]
    feas_ref,            # i8
    rsn_ref,             # i32
    tot_ref,             # i64
):
    """One fused pass over a [bm, C] tile: filters -> reason bits ->
    score plugins -> normalization -> totals, all VMEM-resident.  The
    body is ops.filters/ops.scores verbatim — the fusion is the kernel,
    the math is the library's."""
    fe = filter_enabled_ref[:] != 0
    se = score_enabled_ref[:] != 0
    request = request_ref[:]
    placement_has = placement_has_ref[:][:, 0] != 0
    api_ok = api_ref[:] != 0
    taint_ok_new = taint_new_ref[:] != 0
    taint_ok_cur = taint_cur_ref[:] != 0
    selector_ok = selector_ref[:] != 0
    placement_ok = placement_ref[:] != 0
    current_mask = current_ref[:] != 0
    webhook_ok = webhook_ok_ref[:] != 0
    webhook_scores = webhook_sco_ref[:]
    taint_counts = taint_counts_ref[:]
    affinity_scores = affinity_ref[:]
    alloc = alloc_ref[:]
    used = used_ref[:]
    cluster_valid = cluster_valid_ref[:][0] != 0

    fit_ok = F.resources_fit(request, alloc, used)
    feasible, reasons = F.combine_filters_explain(
        fe, api_ok, taint_ok_new, taint_ok_cur, current_mask, fit_ok,
        placement_has, placement_ok, selector_ok,
    )
    reasons = (
        reasons
        | jnp.where(~webhook_ok, jnp.int32(RSN.REASON_WEBHOOK_FILTER), 0)
        | jnp.where(
            ~cluster_valid[None, :], jnp.int32(RSN.REASON_CLUSTER_INVALID), 0
        )
    )
    feasible = feasible & cluster_valid[None, :] & webhook_ok
    totals = S.total_scores(
        se, feasible, request, alloc, used, taint_counts, affinity_scores,
    )
    totals = totals + jnp.where(feasible, webhook_scores, 0)
    feas_ref[:] = feasible.astype(jnp.int8)
    rsn_ref[:] = reasons.astype(jnp.int32)
    tot_ref[:] = totals.astype(jnp.int64)


def _block_rows(b: int) -> int:
    bm = _BLOCK_ROWS
    while bm > 1 and b % bm:
        bm //= 2
    return bm


def phase1_slab(inp, interpret: bool | None = None):
    """The fused Pallas phase 1 over expanded TickInputs planes.

    Returns (feasible bool[B, C], reasons i32[B, C], totals i64[B, C])
    — the exact triple ``ops.pipeline._phase1`` computes, consumable by
    ``schedule_tick_narrow(..., phase1=...)``.  Traceable under jit
    (the engine's narrow program wraps it); ``interpret`` defaults to
    True off-TPU so the kernel body runs everywhere tier-1 runs."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, c = inp.api_ok.shape
    r = inp.request.shape[1]
    bm = _block_rows(b)

    def row(x):
        return pl.BlockSpec((bm, x), lambda i: (i, 0))

    def shared(shape):
        return pl.BlockSpec(shape, lambda i: (0,) * len(shape))

    i8 = jnp.int8
    args = (
        inp.filter_enabled.astype(i8),
        inp.score_enabled.astype(i8),
        inp.request.astype(jnp.int64),
        inp.placement_has.astype(i8).reshape(b, 1),
        inp.api_ok.astype(i8),
        inp.taint_ok_new.astype(i8),
        inp.taint_ok_cur.astype(i8),
        inp.selector_ok.astype(i8),
        inp.placement_ok.astype(i8),
        inp.current_mask.astype(i8),
        inp.webhook_ok.astype(i8),
        inp.webhook_scores.astype(jnp.int64),
        inp.taint_counts.astype(jnp.int64),
        inp.affinity_scores.astype(jnp.int64),
        inp.alloc.astype(jnp.int64),
        inp.used.astype(jnp.int64),
        inp.cluster_valid.astype(i8).reshape(1, c),
    )
    in_specs = [
        row(5), row(5), row(r), row(1),
        row(c), row(c), row(c), row(c), row(c), row(c),
        row(c), row(c), row(c), row(c),
        shared((c, r)), shared((c, r)), shared((1, c)),
    ]
    feas8, reasons, totals = pl.pallas_call(
        _phase1_kernel,
        grid=(b // bm,),
        in_specs=in_specs,
        out_specs=(row(c), row(c), row(c)),
        out_shape=(
            jax.ShapeDtypeStruct((b, c), jnp.int8),
            jax.ShapeDtypeStruct((b, c), jnp.int32),
            jax.ShapeDtypeStruct((b, c), jnp.int64),
        ),
        interpret=interpret,
    )(*args)
    return feas8 != 0, reasons, totals
