"""Score stage: per-(object, cluster) int scores + normalization.

Tensor re-statements of the reference score plugins (reference:
pkg/controllers/scheduler/framework/plugins/...), masked to feasible
clusters, summed per the generic scheduler (core/generic_scheduler.go:171-192).

Score plugin indices (column order of ``score_enabled``):
  0 TaintToleration, 1 ClusterResourcesBalancedAllocation,
  2 ClusterResourcesLeastAllocated, 3 ClusterAffinity,
  4 ClusterResourcesMostAllocated.

Integer-division truncation matches Go exactly (all operands are
non-negative); the balanced-allocation plugin is float math in the
reference too and is computed in f64.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubeadmiral_tpu.ops.filters import R_CPU, R_MEM

S_TAINT = 0
S_BALANCED = 1
S_LEAST = 2
S_AFFINITY = 3
S_MOST = 4
NUM_SCORE_PLUGINS = 5

MAX_CLUSTER_SCORE = 100


def _requested_totals(request, alloc, used):
    """Per-pair (allocatable, requested-including-this-object) for cpu+mem.

    Mirrors calculateResourceAllocatableRequest (fit.go:160-183): the
    cluster's in-use request is (alloc - available) plus this object's own
    request.
    """
    req_cpu = used[None, :, R_CPU] + request[:, None, R_CPU]
    req_mem = used[None, :, R_MEM] + request[:, None, R_MEM]
    alloc_cpu = jnp.broadcast_to(alloc[None, :, R_CPU], req_cpu.shape)
    alloc_mem = jnp.broadcast_to(alloc[None, :, R_MEM], req_mem.shape)
    return alloc_cpu, alloc_mem, req_cpu, req_mem


# Range-reduction thresholds for the exact balanced-allocation score:
# the smallest shift s (multiple of 8) with (x >> s) < 2^26 keeps the
# cross products below 2^52 so 100*(T-D) fits int64 exactly.
_BALANCED_SHIFT_THRESHOLDS = tuple(1 << (26 + 8 * k) for k in range(5))


def _balanced_range_shift(cap):
    s = jnp.zeros_like(cap)
    for t in _BALANCED_SHIFT_THRESHOLDS:
        s = s + 8 * (cap >= t).astype(cap.dtype)
    return s


def balanced_allocation_score(request, alloc, used):
    """(1 - |cpuFraction - memFraction|) * 100, 0 if either fraction >= 1
    (balanced_allocation.go:45-78); fraction of zero capacity counts as 1.

    Computed in EXACT integer arithmetic:
    |rc/ac - rm/am| = |rc*am - rm*ac| / (ac*am), with both resource
    pairs range-shifted so the products fit int64, then one small-
    quotient floor division.  Float forms diverge across backends —
    axon TPUs demote f64 to f32, and the truncation of (1-diff)*100
    flips scores near integer boundaries (~1e-5 of pairs at bench
    shapes), which the r5 on-chip parity check caught as a batched-vs-
    native placement mismatch.  At exact integer boundaries the
    reference's value is itself f64-rounding dependent; this rational
    semantics is applied identically in the device kernel, the Python
    oracle (ops/pipeline_oracle.py), and the C++ baseline
    (native/seqsched.cpp), so parity is bit-exact on every backend."""
    alloc_cpu, alloc_mem, req_cpu, req_mem = _requested_totals(request, alloc, used)
    infeasible = (
        (alloc_cpu == 0)
        | (alloc_mem == 0)
        | (req_cpu >= alloc_cpu)
        | (req_mem >= alloc_mem)
    )
    s_cpu = _balanced_range_shift(alloc_cpu)
    s_mem = _balanced_range_shift(alloc_mem)
    ac = jnp.right_shift(alloc_cpu, s_cpu)
    rc = jnp.right_shift(req_cpu, s_cpu)
    am = jnp.right_shift(alloc_mem, s_mem)
    rm = jnp.right_shift(req_mem, s_mem)
    total = jnp.maximum(ac * am, 1)
    diff_num = jnp.abs(rc * am - rm * ac)
    score = _floordiv_smallq(MAX_CLUSTER_SCORE * (total - diff_num), total)
    return jnp.where(infeasible, 0, score)


def _floordiv_smallq(num, den):
    """Exact int64 floor division for non-negative operands whose
    QUOTIENT is small (callers: scores <= 100, weights._round_half_div
    <= ~1401): an f64 estimate plus one integer correction step.  XLA
    expands a 64-bit integer divide into a large software sequence
    (~2s of compile PER SITE on CPU; int64 is emulated on TPU), while
    the estimate+correct form is a handful of cheap ops.  Exactness:
    the float estimate of a quotient q carries absolute error ~q*eps
    (eps = 2^-52 in f64; 2^-23 if the backend demotes f64 to f32, as
    axon TPUs do), so the error stays << 1 for q up to ~2^20 and one
    +/-1 correction against the true integer remainder lands exactly
    on floor(num/den).  Do NOT narrow the correction without
    re-deriving that bound for every caller's quotient range."""
    den = jnp.maximum(den, 1)
    q = jnp.floor(num.astype(jnp.float64) / den.astype(jnp.float64)).astype(
        num.dtype
    )
    r = num - q * den
    return q + (r >= den).astype(num.dtype) - (r < 0).astype(num.dtype)


def _ratio_score(req, alloc, least: bool):
    zero = alloc == 0
    over = req > alloc
    free = jnp.where(least, alloc - req, req)
    score = _floordiv_smallq(free * MAX_CLUSTER_SCORE, alloc)
    return jnp.where(zero | over, 0, score)


def least_allocated_score(request, alloc, used):
    """((cap-req)*100//cap per resource, cpu+mem averaged) — least_allocated.go:42-93."""
    alloc_cpu, alloc_mem, req_cpu, req_mem = _requested_totals(request, alloc, used)
    s = _ratio_score(req_cpu, alloc_cpu, True) + _ratio_score(req_mem, alloc_mem, True)
    return s // 2


def most_allocated_score(request, alloc, used):
    """(req*100//cap per resource, cpu+mem averaged) — most_allocated.go:42-93."""
    alloc_cpu, alloc_mem, req_cpu, req_mem = _requested_totals(request, alloc, used)
    s = _ratio_score(req_cpu, alloc_cpu, False) + _ratio_score(req_mem, alloc_mem, False)
    return s // 2


def normalize(scores, feasible, reverse: bool):
    """DefaultNormalizeScore (framework/util.go:455-482) over feasible
    clusters of each object: scale to [0,100] by the per-object max; if the
    max is 0 -> all 100 when reversed, else left as-is."""
    masked = jnp.where(feasible, scores, 0)
    max_count = jnp.max(masked, axis=-1, keepdims=True)
    scaled = _floordiv_smallq(MAX_CLUSTER_SCORE * masked, max_count)
    scaled = jnp.where(reverse, MAX_CLUSTER_SCORE - scaled, scaled)
    untouched = jnp.where(reverse, jnp.full_like(masked, MAX_CLUSTER_SCORE), masked)
    return jnp.where(max_count == 0, untouched, scaled)


def total_scores(
    score_enabled,   # bool[B, 5]
    feasible,        # bool[B, C]
    request, alloc, used,
    taint_counts,    # i64[B, C] intolerable PreferNoSchedule taints
    affinity_scores, # i64[B, C] preferred-term weight sums
):
    """Sum of enabled, normalized plugin scores; 0 on infeasible clusters.

    All five plugins compute unconditionally and the enablement mask
    selects — a lax.cond per plugin was tried (ISSUE 10) and REGRESSED
    the big shapes ~2x: the conditional regions block XLA's fusion of
    the plugin math into one [B, C] pass and materialize full int64
    planes per branch, costing more than the skipped arithmetic saved."""
    taint = normalize(taint_counts, feasible, reverse=True)
    affinity = normalize(affinity_scores, feasible, reverse=False)
    plugin_scores = (
        (S_TAINT, taint),
        (S_BALANCED, balanced_allocation_score(request, alloc, used)),
        (S_LEAST, least_allocated_score(request, alloc, used)),
        (S_AFFINITY, affinity),
        (S_MOST, most_allocated_score(request, alloc, used)),
    )
    total = jnp.zeros_like(feasible, dtype=jnp.int64)
    for idx, s in plugin_scores:
        total = total + jnp.where(score_enabled[:, idx, None], s, 0)
    return jnp.where(feasible, total, 0)
