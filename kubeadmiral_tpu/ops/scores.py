"""Score stage: per-(object, cluster) int scores + normalization.

Tensor re-statements of the reference score plugins (reference:
pkg/controllers/scheduler/framework/plugins/...), masked to feasible
clusters, summed per the generic scheduler (core/generic_scheduler.go:171-192).

Score plugin indices (column order of ``score_enabled``):
  0 TaintToleration, 1 ClusterResourcesBalancedAllocation,
  2 ClusterResourcesLeastAllocated, 3 ClusterAffinity,
  4 ClusterResourcesMostAllocated.

Integer-division truncation matches Go exactly (all operands are
non-negative); the balanced-allocation plugin is float math in the
reference too and is computed in f64.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubeadmiral_tpu.ops.filters import R_CPU, R_MEM

S_TAINT = 0
S_BALANCED = 1
S_LEAST = 2
S_AFFINITY = 3
S_MOST = 4
NUM_SCORE_PLUGINS = 5

MAX_CLUSTER_SCORE = 100


def _requested_totals(request, alloc, used):
    """Per-pair (allocatable, requested-including-this-object) for cpu+mem.

    Mirrors calculateResourceAllocatableRequest (fit.go:160-183): the
    cluster's in-use request is (alloc - available) plus this object's own
    request.
    """
    req_cpu = used[None, :, R_CPU] + request[:, None, R_CPU]
    req_mem = used[None, :, R_MEM] + request[:, None, R_MEM]
    alloc_cpu = jnp.broadcast_to(alloc[None, :, R_CPU], req_cpu.shape)
    alloc_mem = jnp.broadcast_to(alloc[None, :, R_MEM], req_mem.shape)
    return alloc_cpu, alloc_mem, req_cpu, req_mem


def balanced_allocation_score(request, alloc, used):
    """(1 - |cpuFraction - memFraction|) * 100, 0 if either fraction >= 1
    (balanced_allocation.go:45-78); fraction of zero capacity counts as 1."""
    alloc_cpu, alloc_mem, req_cpu, req_mem = _requested_totals(request, alloc, used)
    f_cpu = jnp.where(alloc_cpu == 0, 1.0, req_cpu / jnp.maximum(alloc_cpu, 1))
    f_mem = jnp.where(alloc_mem == 0, 1.0, req_mem / jnp.maximum(alloc_mem, 1))
    diff = jnp.abs(f_cpu - f_mem)
    score = ((1.0 - diff) * MAX_CLUSTER_SCORE).astype(jnp.int64)
    return jnp.where((f_cpu >= 1.0) | (f_mem >= 1.0), 0, score)


def _floordiv_smallq(num, den):
    """Exact int64 floor division for non-negative operands whose
    QUOTIENT is small (here <= 100): an f64 estimate plus one integer
    correction step.  XLA expands a 64-bit integer divide into a large
    software sequence (~2s of compile PER SITE on CPU; int64 is
    emulated on TPU), while the estimate+correct form is a handful of
    cheap ops.  Exactness: the f64 estimate of a quotient q carries
    absolute error ~q*2^-52 << 1, so one +/-1 correction against the
    true integer remainder lands exactly on floor(num/den)."""
    den = jnp.maximum(den, 1)
    q = jnp.floor(num.astype(jnp.float64) / den.astype(jnp.float64)).astype(
        num.dtype
    )
    r = num - q * den
    return q + (r >= den).astype(num.dtype) - (r < 0).astype(num.dtype)


def _ratio_score(req, alloc, least: bool):
    zero = alloc == 0
    over = req > alloc
    free = jnp.where(least, alloc - req, req)
    score = _floordiv_smallq(free * MAX_CLUSTER_SCORE, alloc)
    return jnp.where(zero | over, 0, score)


def least_allocated_score(request, alloc, used):
    """((cap-req)*100//cap per resource, cpu+mem averaged) — least_allocated.go:42-93."""
    alloc_cpu, alloc_mem, req_cpu, req_mem = _requested_totals(request, alloc, used)
    s = _ratio_score(req_cpu, alloc_cpu, True) + _ratio_score(req_mem, alloc_mem, True)
    return s // 2


def most_allocated_score(request, alloc, used):
    """(req*100//cap per resource, cpu+mem averaged) — most_allocated.go:42-93."""
    alloc_cpu, alloc_mem, req_cpu, req_mem = _requested_totals(request, alloc, used)
    s = _ratio_score(req_cpu, alloc_cpu, False) + _ratio_score(req_mem, alloc_mem, False)
    return s // 2


def normalize(scores, feasible, reverse: bool):
    """DefaultNormalizeScore (framework/util.go:455-482) over feasible
    clusters of each object: scale to [0,100] by the per-object max; if the
    max is 0 -> all 100 when reversed, else left as-is."""
    masked = jnp.where(feasible, scores, 0)
    max_count = jnp.max(masked, axis=-1, keepdims=True)
    scaled = _floordiv_smallq(MAX_CLUSTER_SCORE * masked, max_count)
    scaled = jnp.where(reverse, MAX_CLUSTER_SCORE - scaled, scaled)
    untouched = jnp.where(reverse, jnp.full_like(masked, MAX_CLUSTER_SCORE), masked)
    return jnp.where(max_count == 0, untouched, scaled)


def total_scores(
    score_enabled,   # bool[B, 5]
    feasible,        # bool[B, C]
    request, alloc, used,
    taint_counts,    # i64[B, C] intolerable PreferNoSchedule taints
    affinity_scores, # i64[B, C] preferred-term weight sums
):
    """Sum of enabled, normalized plugin scores; 0 on infeasible clusters."""
    taint = normalize(taint_counts, feasible, reverse=True)
    affinity = normalize(affinity_scores, feasible, reverse=False)
    plugin_scores = (
        (S_TAINT, taint),
        (S_BALANCED, balanced_allocation_score(request, alloc, used)),
        (S_LEAST, least_allocated_score(request, alloc, used)),
        (S_AFFINITY, affinity),
        (S_MOST, most_allocated_score(request, alloc, used)),
    )
    total = jnp.zeros_like(feasible, dtype=jnp.int64)
    for idx, s in plugin_scores:
        total = total + jnp.where(score_enabled[:, idx, None], s, 0)
    return jnp.where(feasible, total, 0)
