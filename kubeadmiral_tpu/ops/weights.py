"""Dynamic cluster weights for replica scheduling.

Tensor form of the reference RSP plugin's weight derivation (reference:
pkg/controllers/scheduler/framework/plugins/rsp/rsp.go:183-272): when the
policy provides no static weights, each object's selected clusters are
weighted by their share of available CPU, clamped by an allocatable-share
limit (x1.4), then re-normalized to sum to 1000 with the rounding residual
handed to the heaviest cluster.

All rounding is "half away from zero" (Go math.Round), computed in EXACT
integer arithmetic: round_half(num/den) = (2*num + den) // (2*den) for
non-negative operands, with the x1.4 supply limit as the rational
1400/1000.  The reference computes these in f64; axon TPUs demote f64 to
f32, and a float formulation flips weights by one at half-boundaries,
which cascades into different replica plans (caught by the r5 on-chip
batched-vs-native parity check).  The same exact rule is implemented in
the Python oracle (ops/pipeline_oracle.py) and the C++ baseline
(native/seqsched.cpp).  CPU values here are Quantity.Value() cores
(ceiling), as in the reference.
"""

from __future__ import annotations

import jax.numpy as jnp

from kubeadmiral_tpu.parallel import shardguard

from kubeadmiral_tpu.ops.scores import _floordiv_smallq

SUM_WEIGHT = 1000
# SUM_WEIGHT * 1.4 as an exact rational (rsp.go:183-213 supplyLimitRatio).
SUPPLY_LIMIT_NUM = 1400


def _round_half_div(num, den):
    """Round-half-away-from-zero of num/den for non-negative integers:
    floor((2*num + den) / (2*den)), exact on every backend."""
    return _floordiv_smallq(2 * num + den, 2 * den)


@shardguard.rows_first
def dynamic_weights(selected, cpu_alloc, cpu_avail, compute_dtype=jnp.int64):
    """selected bool[B,C]; cpu_alloc/cpu_avail i64[C] -> i32[B,C] weights.

    Weights are zero outside the selection mask.

    Sums/maxima here range over the SELECTION, so the result for a row
    depends only on its selected columns — the narrow solve relies on
    that: it computes weights dense (this is elementwise + reductions,
    no sorts) and gathers them into the [B, M] planner slots, and the
    residual's first-max tie-break (index order) survives the gather
    because candidate slots preserve ascending column order.

    ``compute_dtype=jnp.int32`` demotes the arithmetic (identical
    values when no intermediate overflows — all rounding is the exact
    integer form below).  Callers must have proven the range
    host-side: the worst intermediate is ``2*max_cpu*(1400 + C)``
    (the x1.4 supply-limit round over the allocatable sum), so the
    demotion is safe iff that stays under 2**31.  The engine's drift
    weight-check applies it behind exactly that guard — on CPU the
    [rows, C] i64 passes were ~half the wcheck kernel's time."""
    sel = selected
    cpu_alloc = cpu_alloc.astype(compute_dtype)
    cpu_avail = cpu_avail.astype(compute_dtype)
    n = jnp.maximum(jnp.sum(sel, axis=-1, keepdims=True), 1).astype(
        compute_dtype
    )

    # CalcWeightLimit: allocatable-CPU share * 1000 * 1.4 (rsp.go:183-213).
    alloc = jnp.where(sel, cpu_alloc[None, :], 0)
    alloc_sum = jnp.sum(alloc, axis=-1, keepdims=True)
    equal = _round_half_div(jnp.full_like(n, SUM_WEIGHT), n)
    limit = jnp.where(
        alloc_sum == 0,
        equal,
        _round_half_div(alloc * SUPPLY_LIMIT_NUM, jnp.maximum(alloc_sum, 1)),
    )

    # AvailableToPercentage (rsp.go:215-272): available-CPU share, clamped.
    avail = jnp.where(sel, cpu_avail[None, :], 0)
    avail_pos = jnp.maximum(avail, 0)
    avail_sum = jnp.sum(avail_pos, axis=-1, keepdims=True)
    tmp = jnp.where(
        avail_sum == 0,
        equal,
        jnp.minimum(
            _round_half_div(avail_pos * SUM_WEIGHT, jnp.maximum(avail_sum, 1)),
            limit,
        ),
    )
    tmp = jnp.where(sel, tmp, 0)
    tmp_sum = jnp.sum(tmp, axis=-1, keepdims=True)
    weight = jnp.where(
        tmp_sum > 0,
        _round_half_div(tmp * SUM_WEIGHT, jnp.maximum(tmp_sum, 1)),
        0,
    )
    weight = jnp.where(sel, weight, 0)

    # Residual of the second rounding pass goes to the heaviest cluster
    # (first index on ties; the reference's pick is map-order dependent),
    # clamped at zero: at thousands of selected clusters the round-up
    # bias can exceed the max weight, and a negative weight has no
    # defined share (the planner treats non-positive weights as zero —
    # the clamp keeps all three implementations' weight vectors, and
    # hence the planner's processing ORDER, identical).
    residual = SUM_WEIGHT - jnp.sum(weight, axis=-1, keepdims=True)
    max_w = jnp.max(weight, axis=-1, keepdims=True)
    is_first_max = (
        jnp.cumsum((weight == max_w) & sel, axis=-1) == 1
    ) & (weight == max_w) & sel
    weight = jnp.where(
        is_first_max & (max_w > 0), jnp.maximum(weight + residual, 0), weight
    )
    return weight.astype(jnp.int32)
