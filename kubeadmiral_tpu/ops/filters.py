"""Filter stage: feasibility masks over [B objects x C clusters].

Each reference filter plugin (reference: pkg/controllers/scheduler/framework/
plugins/*) becomes a boolean mask; a disabled plugin contributes all-True.
String-world plugins (API resources, taints, selectors/affinity) are
pre-matched host-side by the featurizer into per-(object,cluster) booleans
via set-dedup + gather, so this module only combines masks and does the
numeric resource-fit math.

Filter plugin indices (column order of ``filter_enabled``):
  0 APIResources, 1 TaintToleration, 2 ClusterResourcesFit,
  3 PlacementFilter, 4 ClusterAffinity.
"""

from __future__ import annotations

import jax.numpy as jnp

F_API_RESOURCES = 0
F_TAINT_TOLERATION = 1
F_RESOURCES_FIT = 2
F_PLACEMENT = 3
F_CLUSTER_AFFINITY = 4
NUM_FILTER_PLUGINS = 5

# Resource tensor column layout (shared with scores): fixed columns then
# dynamically discovered scalar/extended resources.
R_CPU = 0  # millicores
R_MEM = 1  # bytes
NUM_FIXED_RESOURCES = 2


def resources_fit(request, alloc, used):
    """ClusterResourcesFit (reference: plugins/clusterresources/fit.go:47-131).

    request: i64[B, R]; alloc/used: i64[C, R].  CPU and memory are always
    checked once any resource is requested; scalar columns only where the
    request is positive.  An all-zero request fits everywhere.
    """
    free_ok = alloc[None, :, :] >= request[:, None, :] + used[None, :, :]
    scalar_req = request[:, None, NUM_FIXED_RESOURCES:] > 0
    scalar_ok = jnp.where(scalar_req, free_ok[:, :, NUM_FIXED_RESOURCES:], True)
    fixed_ok = free_ok[:, :, R_CPU] & free_ok[:, :, R_MEM]
    ok = fixed_ok & jnp.all(scalar_ok, axis=-1)
    no_request = jnp.all(request <= 0, axis=-1)
    return no_request[:, None] | ok


def combine_filters(
    filter_enabled,  # bool[B, 5]
    api_ok,          # bool[B, C]
    taint_ok_new,    # bool[B, C] tolerated for a not-yet-placed object
    taint_ok_cur,    # bool[B, C] tolerated when already placed (NoExecute only)
    current_mask,    # bool[B, C]
    fit_ok,          # bool[B, C]
    placement_has,   # bool[B] explicit placement list is non-empty
    placement_ok,    # bool[B, C]
    selector_ok,     # bool[B, C] labels selector AND required affinity
):
    """Conjunction of enabled filter plugins -> feasible[B, C]."""
    feasible, _ = combine_filters_explain(
        filter_enabled, api_ok, taint_ok_new, taint_ok_cur, current_mask,
        fit_ok, placement_has, placement_ok, selector_ok,
    )
    return feasible


def combine_filters_explain(
    filter_enabled,  # bool[B, 5]
    api_ok,          # bool[B, C]
    taint_ok_new,    # bool[B, C]
    taint_ok_cur,    # bool[B, C]
    current_mask,    # bool[B, C]
    fit_ok,          # bool[B, C]
    placement_has,   # bool[B]
    placement_ok,    # bool[B, C]
    selector_ok,     # bool[B, C]
):
    """Conjunction of enabled filter plugins, plus a per-(object,
    cluster) reason bitmask: bit i is set iff enabled plugin i rejected
    the pair (ops.reasons vocabulary).  ``feasible == (reasons == 0)``
    by construction — the conjunction and its explanation cannot drift.
    Returns (feasible bool[B, C], reasons i32[B, C])."""
    taint_ok = jnp.where(current_mask, taint_ok_cur, taint_ok_new)
    placement = ~placement_has[:, None] | placement_ok
    reasons = jnp.zeros(api_ok.shape, jnp.int32)
    for idx, ok in (
        (F_API_RESOURCES, api_ok),
        (F_TAINT_TOLERATION, taint_ok),
        (F_RESOURCES_FIT, fit_ok),
        (F_PLACEMENT, placement),
        (F_CLUSTER_AFFINITY, selector_ok),
    ):
        rejected = filter_enabled[:, idx, None] & ~ok
        reasons = reasons | jnp.where(rejected, jnp.int32(1 << idx), 0)
    return reasons == 0, reasons
