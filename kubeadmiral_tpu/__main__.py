"""CLI entry: ``python -m kubeadmiral_tpu``.

Mirrors the reference controller-manager's flag surface (reference:
cmd/controller-manager/main.go:32-46,
cmd/controller-manager/app/options/options.go:34-130) over the in-memory
control plane: build a fleet, install the default FederatedTypeConfigs,
start the controller manager behind leader election, and serve
/livez + /readyz.  This is the ``make dev-up`` analogue — a
self-contained control plane for local exploration; a real-apiserver
transport drops in behind the same ClusterFleet interface.
"""

from __future__ import annotations

import argparse
import os
import time


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="kubeadmiral-tpu-controller-manager",
        description="TPU-native KubeAdmiral controller manager",
    )
    parser.add_argument(
        "--port", type=int, default=11257,
        help="health probe port (0 = ephemeral)",
    )
    parser.add_argument(
        "--controllers", default="*",
        help="comma list of always-on controllers; '-name' disables, '*' = defaults",
    )
    parser.add_argument(
        "--worker-count", type=int, default=1,
        help="reconcile worker threads per controller",
    )
    parser.add_argument("--leader-elect", action="store_true", default=True)
    parser.add_argument("--no-leader-elect", dest="leader_elect", action="store_false")
    parser.add_argument(
        "--cluster-join-timeout", type=float, default=600.0,
        help="seconds before an unjoinable cluster is marked timed out",
    )
    parser.add_argument(
        "--nsautoprop-exclude-regexp", default="",
        help="namespaces matching this regexp are not auto-propagated",
    )
    parser.add_argument(
        "--create-crds-for-ftcs", action="store_true",
        help="install the default FederatedTypeConfig set at startup",
    )
    parser.add_argument(
        "--members", type=int, default=3,
        help="number of member clusters to create",
    )
    parser.add_argument(
        "--host-port", type=int, default=0,
        help="host apiserver port for --transport http (0 = ephemeral)",
    )
    parser.add_argument(
        "--transport", choices=("memory", "http"), default="memory",
        help="memory = in-process stores (demo); http = a kwok-lite farm "
        "of real apiserver sockets (REST + watch + bearer auth), with the "
        "cluster-join handshake run for each member",
    )
    parser.add_argument("--run-seconds", type=float, default=0.0,
        help="exit after this many seconds (0 = run forever)")
    parser.add_argument(
        "--max-pod-listers", type=int, default=4,
        help="bound on concurrent member pod LISTs (pod informer)",
    )
    parser.add_argument(
        "--enable-pod-pruning", action=argparse.BooleanOptionalAction,
        default=True,
        help="strip cached pods to scheduling-relevant fields (default on)",
    )
    parser.add_argument(
        "--enable-profiling", action="store_true",
        help="serve pprof-style endpoints on --profiling-port "
        "(/debug/profile, /debug/stacks, /debug/threads); the health "
        "port always serves them too",
    )
    parser.add_argument(
        "--profiling-port", type=int, default=6060,
        help="standalone profiling port (reference's :6060)",
    )
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from kubeadmiral_tpu.runtime.logconf import setup_logging

    setup_logging()  # KT_LOG_LEVEL / KT_LOG_JSON (docs/operations.md)

    from kubeadmiral_tpu.models.ftc import FEDERATED_TYPE_CONFIGS, default_ftcs, ftc_to_object
    from kubeadmiral_tpu.runtime.healthcheck import HealthCheckRegistry, HealthServer
    from kubeadmiral_tpu.runtime.leaderelection import LeaderElector
    from kubeadmiral_tpu.runtime.manager import ControllerManager
    from kubeadmiral_tpu.runtime.metrics import Metrics
    from kubeadmiral_tpu.testing.fakekube import AlreadyExists, ClusterFleet

    farm = None
    if args.transport == "http":
        # Real sockets: a kwok-lite farm (host + member apiservers with
        # REST/watch/auth), FederatedCluster CRs registered so the
        # cluster controller performs the real join handshake.
        from kubeadmiral_tpu.federation.common import FEDERATED_CLUSTERS
        from kubeadmiral_tpu.testing.kwoklite import KwokLiteFarm

        farm = KwokLiteFarm(host_port=args.host_port)
        fleet = farm.fleet
        for i in range(args.members):
            name = f"member-{i + 1}"
            member = farm.add_member(name)
            member.create(
                "v1/nodes",
                {
                    "apiVersion": "v1",
                    "kind": "Node",
                    "metadata": {"name": f"{name}-node"},
                    "spec": {},
                    "status": {
                        "allocatable": {"cpu": "32", "memory": "128Gi"},
                        "conditions": [{"type": "Ready", "status": "True"}],
                    },
                },
            )
            fleet.host.create(
                FEDERATED_CLUSTERS,
                {
                    "apiVersion": "core.kubeadmiral.io/v1alpha1",
                    "kind": "FederatedCluster",
                    "metadata": {"name": name},
                    "spec": farm.cluster_spec(name),
                },
            )
        print(f"host apiserver on {farm.host_server.url}")
        for name, server in farm.member_servers.items():
            # Demo farm: print the member admin token so quickstart curls
            # can read the propagated objects (member apiservers require
            # bearer auth, exactly like real clusters).
            print(
                f"member {name} apiserver on {server.url} "
                f"(admin token: {server.admin_token})"
            )
    else:
        fleet = ClusterFleet()
        for i in range(args.members):
            fleet.add_member(f"member-{i + 1}")

    health = HealthCheckRegistry()
    # ONE registry shared by the manager's controllers, the XLA engine
    # and the HTTP exposition (docs/observability.md).
    metrics = Metrics()
    server = HealthServer(health, port=args.port, metrics=metrics)
    port = server.start()
    print(
        f"health endpoints on :{port} (/livez, /readyz, /metrics, /debug/*)"
    )

    if args.enable_profiling:
        from kubeadmiral_tpu.runtime.profiling import ProfilingServer

        prof_server = ProfilingServer(port=args.profiling_port, metrics=metrics)
        print(
            f"profiling endpoints on :{prof_server.start()} (/metrics, /debug/*)"
        )

    elector = LeaderElector(fleet.host, identity=f"manager-{os.getpid()}")
    if args.leader_elect:
        while not elector.try_acquire_or_renew():
            time.sleep(1.0)
        print(f"leader election won as {elector.identity}")

    manager = ControllerManager(
        fleet,
        enabled=[c for c in args.controllers.split(",") if c],
        metrics=metrics,
        health=health,
        cluster_controller_kwargs={"join_timeout": args.cluster_join_timeout},
        max_pod_listers=args.max_pod_listers,
        enable_pod_pruning=args.enable_pod_pruning,
    )
    if args.create_crds_for_ftcs:
        for ftc in default_ftcs():
            try:
                fleet.host.create(FEDERATED_TYPE_CONFIGS, ftc_to_object(ftc))
            except AlreadyExists:
                pass
        print(f"installed {len(default_ftcs())} FederatedTypeConfigs")

    manager.run(args.worker_count)
    print("controller manager running; Ctrl-C to stop")

    # SIGTERM = graceful failover (docs/operations.md § Restart &
    # failover runbook): drain in-flight dispatch flushes under the
    # bounded KT_SHUTDOWN_DEADLINE_S budget, write a final engine
    # snapshot, release leadership so a standby acquires immediately.
    # SIGKILL gets none of this — which is exactly what the snapshot
    # store's atomic-write + quarantine design (and make restart-smoke)
    # exists for.
    import signal
    import threading

    stop_event = threading.Event()

    def _on_sigterm(signum, frame):
        print("SIGTERM: draining for graceful failover")
        stop_event.set()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded use)

    deadline = time.monotonic() + args.run_seconds if args.run_seconds else None
    try:
        while not stop_event.is_set() and (
            deadline is None or time.monotonic() < deadline
        ):
            if args.leader_elect and not elector.try_acquire_or_renew():
                print("lost leader election; exiting")  # fatal, as in the reference
                return 1
            stop_event.wait(min(elector.lease_seconds / 3, 5.0))
    except KeyboardInterrupt:
        pass
    finally:
        summary = manager.shutdown()
        if args.leader_elect and elector.release():
            print("leadership released")
        print(
            f"shutdown: shed_writes={summary['shed_writes']} "
            f"snapshot={summary['snapshot']}"
        )
        server.stop()
        if farm is not None:
            farm.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
