"""Sharding-contract declarations for order-sensitive device ops.

GSPMD mis-combines sorts, scans and reshapes along a SHARDED dimension:
a ``lax.sort``/``cumsum`` whose axis is partitioned across the mesh
lowers to per-shard partials that get shard-summed (observed twice by
``dryrun_multichip``, once as 11/11 wrong fallback rows in PR 5).  The
repo's standing rule — the "pack-sort rule" (see
``parallel/mesh.rows_only_sharding``) — is that any such op must run
with the axis it orders over WHOLE on every shard: rows-only /
rows-first layouts for per-row ops, full replication otherwise.

This module turns that convention into a DECLARATION the static
analyzer can check (``tools/ktlint`` rule ``sharding-discipline``):
every function containing a sort-family call (``sort``/``argsort``/
``top_k``/``cumsum``/``argmin``/``argmax`` …) must be decorated with
the contract describing the layout its callers are required to
constrain it under.  The decorators are zero-overhead — they tag the
function with ``__sharding_contract__`` and return it unchanged, so
jit tracing, vmap and donation are unaffected.

Contracts (mirroring ``parallel/mesh.py``'s constraint helpers):

* ``rows_first``  — per-row op inside a rank-N tensor sharded on the
  FIRST (objects) axis only; every ordered-over axis is whole per
  shard (``mesh.rows_first_sharding``).
* ``rows_only``   — the [B, C] special case (``mesh.rows_only_sharding``).
* ``replicated``  — the op's operands must be fully replicated before
  it runs (``mesh.replicated``); used for cross-row ops.

Adding a sort to an undecorated function fails ``make lint`` — the
author must either pick the contract (and its callers the matching
constraint) or suppress with a written justification.  See
docs/static_analysis.md.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)

ROWS_ONLY = "rows-only"
ROWS_FIRST = "rows-first"
REPLICATED = "replicated"

CONTRACTS = (ROWS_ONLY, ROWS_FIRST, REPLICATED)


def shard_contract(spec: str) -> Callable[[F], F]:
    """Declare the sharding layout a sort-carrying function requires.

    ``spec`` must be one of :data:`CONTRACTS`.  The returned decorator
    only tags the function — enforcement is the caller constraining its
    operands (``mesh.rows_only_sharding``/``rows_first_sharding``/
    ``replicated``) plus the multichip dryrun's parity blocks; ktlint
    enforces that the declaration exists at all.
    """
    if spec not in CONTRACTS:
        raise ValueError(f"unknown sharding contract {spec!r}; use one of {CONTRACTS}")

    def deco(fn: F) -> F:
        fn.__sharding_contract__ = spec
        return fn

    return deco


def rows_only(fn: F) -> F:
    """Contract: [B, C] operands sharded over objects only."""
    return shard_contract(ROWS_ONLY)(fn)


def rows_first(fn: F) -> F:
    """Contract: rank-N operands sharded on the first (row) axis only."""
    return shard_contract(ROWS_FIRST)(fn)


def replicated(fn: F) -> F:
    """Contract: operands fully replicated before the op runs."""
    return shard_contract(REPLICATED)(fn)
