"""Device mesh + sharding layout for the scheduling tick.

The tick is data-parallel over objects and model-parallel over clusters:
every [B, C] tensor is laid out on a 2-D ``(objects, clusters)`` mesh so
the filter/score stages run fully local, per-object reductions (score
normalization max, top-K select, the planner's cluster-axis sorts and
scans) turn into XLA collectives along the ``clusters`` axis, and the
batch scales out along ``objects`` with zero communication.  This is the
TPU equivalent of the reference's concurrency story (N reconcile worker
goroutines; reference: pkg/controllers/util/worker/worker.go:132-134),
except the "workers" are mesh slices and the reduction is ICI, not a
mutex.

On a single chip the same program runs with a 1x1 mesh (fully
replicated); multi-chip needs no code changes, only a bigger mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubeadmiral_tpu.ops.pipeline import TickInputs, TickOutputs

OBJECTS = "objects"
CLUSTERS = "clusters"

# Axis layout per TickInputs/TickOutputs field: which mesh axis each
# tensor dimension maps to (None = replicated dimension).
_FIELD_SPECS: dict[str, tuple[Optional[str], ...]] = {
    "filter_enabled": (OBJECTS, None),
    "api_ok": (OBJECTS, CLUSTERS),
    "taint_ok_new": (OBJECTS, CLUSTERS),
    "taint_ok_cur": (OBJECTS, CLUSTERS),
    "selector_ok": (OBJECTS, CLUSTERS),
    "placement_has": (OBJECTS,),
    "placement_ok": (OBJECTS, CLUSTERS),
    "request": (OBJECTS, None),
    "alloc": (CLUSTERS, None),
    "used": (CLUSTERS, None),
    "score_enabled": (OBJECTS, None),
    "taint_counts": (OBJECTS, CLUSTERS),
    "affinity_scores": (OBJECTS, CLUSTERS),
    "webhook_ok": (OBJECTS, CLUSTERS),
    "webhook_scores": (OBJECTS, CLUSTERS),
    "max_clusters": (OBJECTS,),
    "mode_divide": (OBJECTS,),
    "sticky": (OBJECTS,),
    "current_mask": (OBJECTS, CLUSTERS),
    "current_replicas": (OBJECTS, CLUSTERS),
    "total": (OBJECTS,),
    "weights_given": (OBJECTS,),
    "weights": (OBJECTS, CLUSTERS),
    "min_replicas": (OBJECTS, CLUSTERS),
    "max_replicas": (OBJECTS, CLUSTERS),
    "scale_max": (OBJECTS, CLUSTERS),
    "capacity": (OBJECTS, CLUSTERS),
    "keep_unschedulable": (OBJECTS,),
    "avoid_disruption": (OBJECTS,),
    "tiebreak": (OBJECTS, CLUSTERS),
    "cpu_alloc": (CLUSTERS,),
    "cpu_avail": (CLUSTERS,),
    "cluster_valid": (CLUSTERS,),
}

_OUTPUT_SPEC = (OBJECTS, CLUSTERS)

# Axis layout for the compact input format (scheduler/compact.py):
# per-object vectors shard over objects, vocabulary tables replicate
# their vocab axis and shard the cluster axis, taint tables replicate.
_COMPACT_FIELD_SPECS: dict[str, tuple[Optional[str], ...]] = {
    "gvk_id": (OBJECTS,),
    "tol_id": (OBJECTS,),
    "sel_id": (OBJECTS,),
    "pref_id": (OBJECTS,),
    "place_id": (OBJECTS,),
    "placement_has": (OBJECTS,),
    "filter_enabled": (OBJECTS, None),
    "score_enabled": (OBJECTS, None),
    "request": (OBJECTS, None),
    "max_clusters": (OBJECTS,),
    "mode_divide": (OBJECTS,),
    "sticky": (OBJECTS,),
    "total": (OBJECTS,),
    "weights_given": (OBJECTS,),
    "keep_unschedulable": (OBJECTS,),
    "avoid_disruption": (OBJECTS,),
    "sparse_idx": (OBJECTS, None),
    "sparse_min": (OBJECTS, None),
    "sparse_max": (OBJECTS, None),
    "sparse_weight": (OBJECTS, None),
    "sparse_capacity": (OBJECTS, None),
    "sparse_cur": (OBJECTS, None),
    "key_bytes": (OBJECTS, None),
    "key_len": (OBJECTS,),
    "api_matrix": (None, CLUSTERS),
    "taint_new": (None, None),
    "taint_cur": (None, None),
    "taint_prefer": (None, None),
    "sel_matrix": (None, CLUSTERS),
    "pref_matrix": (None, CLUSTERS),
    "place_matrix": (None, CLUSTERS),
    "taint_set_id": (CLUSTERS,),
    "name_hash_state": (CLUSTERS,),
    "alloc": (CLUSTERS, None),
    "used": (CLUSTERS, None),
    "cpu_alloc": (CLUSTERS,),
    "cpu_avail": (CLUSTERS,),
    "cluster_valid": (CLUSTERS,),
}


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    objects_axis: Optional[int] = None,
) -> Mesh:
    """Build an (objects, clusters) mesh over the given devices.

    Default: ALL devices on the objects axis, clusters replicated.
    Sharding the cluster axis turns every per-object cluster reduction
    (score-normalize maxima, top-K select, the planner's cluster-axis
    sorts) into collectives — measured on the 8-device virtual mesh at
    1024x5120 (config-5 shape), a (4,2) split runs 428 all-to-alls +
    98MB of all-gathers per tick and is ~11x slower than the (8,1)
    objects-only layout, whose census is 3 all-reduces moving ~nothing
    (the r5 multichip dryrun collective census).  Per-cluster tables
    are tiny (C x R ints), so replicating them costs ~nothing; pass
    ``objects_axis`` explicitly to trade that for a cluster axis.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if objects_axis is None:
        objects_axis = n
    clusters_axis = n // objects_axis
    grid = np.array(devices[: objects_axis * clusters_axis]).reshape(
        objects_axis, clusters_axis
    )
    return Mesh(grid, (OBJECTS, CLUSTERS))


def input_shardings(mesh: Mesh) -> TickInputs:
    """NamedSharding pytree matching TickInputs."""
    return TickInputs(
        **{
            name: NamedSharding(mesh, P(*spec))
            for name, spec in _FIELD_SPECS.items()
        }
    )


def output_shardings(mesh: Mesh) -> TickOutputs:
    sharding = NamedSharding(mesh, P(*_OUTPUT_SPEC))
    return TickOutputs(
        selected=sharding,
        replicas=sharding,
        counted=sharding,
        feasible=sharding,
        scores=sharding,
        reasons=sharding,
    )


def shard_inputs(inputs: TickInputs, mesh: Mesh) -> TickInputs:
    """Device-put each field with its mesh layout."""
    shardings = input_shardings(mesh)
    return TickInputs(
        *(
            jax.device_put(np.asarray(arr), sh)
            for arr, sh in zip(inputs, shardings)
        )
    )


def field_shardings(mesh: Mesh, names) -> dict[str, NamedSharding]:
    """NamedShardings for a subset of TickInputs fields by name (the
    engine shards its cached per-object tensors with exactly the same
    layout the full tick expects)."""
    return {
        name: NamedSharding(mesh, P(*_FIELD_SPECS[name])) for name in names
    }


def compact_field_shardings(mesh: Mesh, names) -> dict[str, NamedSharding]:
    """NamedShardings for CompactInputs fields by name."""
    return {
        name: NamedSharding(mesh, P(*_COMPACT_FIELD_SPECS[name]))
        for name in names
    }


def compact_input_shardings(mesh: Mesh):
    """The full CompactInputs sharding pytree (imported lazily to avoid
    a mesh -> scheduler import cycle)."""
    from kubeadmiral_tpu.scheduler.compact import CompactInputs

    return CompactInputs(
        **compact_field_shardings(mesh, CompactInputs._fields)
    )


def grid_sharding(mesh: Mesh) -> NamedSharding:
    """The [B, C] (objects, clusters) layout shared by all tick outputs."""
    return NamedSharding(mesh, P(*_OUTPUT_SPEC))


def rows_sharding(mesh: Mesh) -> NamedSharding:
    """[B] per-object vectors (e.g. the delta mask)."""
    return NamedSharding(mesh, P(OBJECTS))


def rows_only_sharding(mesh: Mesh) -> NamedSharding:
    """[B, C] sharded over objects ONLY — for row-wise device programs
    (the packed export's per-row sort, the overflow bit-pack reshape)
    whose cluster axis must be whole on every shard: GSPMD mis-combines
    sorts/reshapes along a sharded dimension (observed as shard-summed
    outputs in the multichip dryrun)."""
    return NamedSharding(mesh, P(OBJECTS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def rows_first_sharding(mesh: Mesh, ndim: int) -> NamedSharding:
    """Rank-``ndim`` tensors sharded on their FIRST axis only (objects),
    every later axis whole per shard — the survivor-stream layout: a
    gathered [G, ...] sub-problem partitions its row axis across the
    ``objects`` mesh axis so each device solves G/N rows concurrently
    (the tick is row-independent), while per-row sorts/scans along the
    cluster/candidate axes stay safely un-sharded (the pack-sort rule:
    GSPMD mis-combines sorts along a sharded dimension)."""
    return NamedSharding(mesh, P(OBJECTS, *([None] * (ndim - 1))))


def objects_axis_size(mesh: Optional[Mesh]) -> int:
    """Device count along the ``objects`` axis (1 for no mesh) — the
    scale-out factor the engine's geometry / pipeline-depth policies key
    on (per-device budgets multiply by this)."""
    if mesh is None:
        return 1
    return int(mesh.devices.shape[0])
