"""Metrics interface (reference: pkg/stats/stats.go:33-103).

The reference defines {Store, Counter, Rate, Timer, Duration} with a
log-backed default; this keeps the same surface with an in-memory
implementation that tests and the monitor controller can read back.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.stores: dict[str, float] = {}
        self.durations: dict[str, list[float]] = defaultdict(list)

    def counter(self, name: str, value: float = 1, **tags) -> None:
        with self._lock:
            self.counters[name] += value

    def rate(self, name: str, value: float = 1, **tags) -> None:
        self.counter(name, value, **tags)

    def store(self, name: str, value: float, **tags) -> None:
        with self._lock:
            self.stores[name] = value

    def duration(self, name: str, seconds: float, **tags) -> None:
        with self._lock:
            self.durations[name].append(seconds)

    @contextmanager
    def timer(self, name: str, **tags):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.duration(name, time.perf_counter() - start, **tags)


def null_metrics() -> Metrics:
    return Metrics()
