"""Label-aware metrics registry (reference: pkg/stats/stats.go:33-103).

The reference defines {Store, Counter, Rate, Timer, Duration} with a
log-backed default; this keeps the same call surface but upgrades the
in-memory implementation to a real time-series registry:

* every emission may carry ``**tags`` — ``counter("worker_retries",
  cluster="c1")`` and ``cluster="c2"`` are distinct series, keyed by the
  name plus the *sorted* label pairs (untagged call sites keep their
  plain-name keys, so existing readers of ``metrics.counters[...]`` /
  ``.stores[...]`` / ``.durations[...]`` are unaffected);
* ``duration()`` additionally feeds a fixed-bucket histogram of the same
  name, and ``histogram()`` observes one directly;
* :meth:`render_prometheus` serializes the whole registry in Prometheus
  text exposition format (name sanitization, label escaping, cumulative
  histogram buckets, deterministic ordering) — served at ``GET /metrics``
  by the health/profiling servers (runtime/healthcheck.py,
  runtime/profiling.py).

The catalog of metric names lives in runtime/metric_catalog.py;
``make metrics-lint`` fails the build on emissions outside it.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Optional, Sequence

# Prometheus' default latency buckets (seconds) — control-plane
# reconciles and device ticks both land comfortably inside them.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)

# Keep the raw per-series duration lists bounded: they exist for
# test/monitor readback, not long-horizon storage (the histogram is the
# durable aggregate).
_MAX_RAW_DURATIONS = 4096

LabelPairs = tuple[tuple[str, str], ...]


def series_key(name: str, tags: dict) -> str:
    """The string key a (name, labels) series lives under in the legacy
    dict views: the bare name when untagged, else the name plus sorted
    ``{k=v,...}`` pairs — so differently-labeled emissions never collide
    and untagged call sites keep their historical keys."""
    if not tags:
        return name
    pairs = ",".join(f"{k}={tags[k]}" for k in sorted(tags))
    return f"{name}{{{pairs}}}"


def _label_pairs(tags: dict) -> LabelPairs:
    return tuple((k, str(tags[k])) for k in sorted(tags))


class Histogram:
    """Fixed-bucket histogram; bucket counts are per-bucket (cumulation
    happens at exposition, as Prometheus expects)."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        for i, bound in enumerate(self.buckets):
            if value <= bound:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.sum += value
        self.count += 1

    def cumulative(self) -> list[tuple[float, int]]:
        """[(le, cumulative count), ...] ending with (inf, total)."""
        out = []
        running = 0
        for bound, n in zip(self.buckets, self.counts):
            running += n
            out.append((bound, running))
        out.append((float("inf"), self.count))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Interpolated quantile from the fixed buckets — the same
        linear-within-bucket estimate Prometheus' histogram_quantile()
        computes, so in-process percentiles (the SLO evaluator,
        /debug/slo, bench detail) agree with dashboard math.  Returns
        None on an empty histogram; a quantile landing in the +Inf
        bucket clamps to the highest finite bound (the estimate is a
        floor there, exactly as in PromQL)."""
        if self.count == 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        target = q * self.count
        running = 0
        lower = 0.0
        for bound, n in zip(self.buckets, self.counts):
            if n > 0 and running + n >= target:
                return lower + (bound - lower) * ((target - running) / n)
            running += n
            lower = bound
        return self.buckets[-1] if self.buckets else None


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        # Legacy views, keyed by series_key(): untagged series keep their
        # plain-name keys so existing readers keep working.
        self.counters: dict[str, float] = {}
        self.stores: dict[str, float] = {}
        self.durations: dict[str, list[float]] = {}
        self.histograms: dict[str, Histogram] = {}
        # series key -> (family name, sorted label pairs), for exposition.
        self._series: dict[str, tuple[str, LabelPairs]] = {}
        # family name -> prometheus type ("counter"|"gauge"|"histogram");
        # first emission wins.
        self._types: dict[str, str] = {}

    def _register(self, name: str, tags: dict, mtype: str) -> str:
        key = series_key(name, tags)
        if key not in self._series:
            self._series[key] = (name, _label_pairs(tags))
            self._types.setdefault(name, mtype)
        return key

    # -- emission (the stats.go surface + histogram/gauge) ---------------
    def counter(self, name: str, value: float = 1, **tags) -> None:
        with self._lock:
            key = self._register(name, tags, "counter")
            self.counters[key] = self.counters.get(key, 0.0) + value

    def rate(self, name: str, value: float = 1, **tags) -> None:
        self.counter(name, value, **tags)

    def store(self, name: str, value: float, **tags) -> None:
        with self._lock:
            key = self._register(name, tags, "gauge")
            self.stores[key] = value

    gauge = store

    def duration(self, name: str, seconds: float, **tags) -> None:
        with self._lock:
            key = self._register(name, tags, "histogram")
            raw = self.durations.setdefault(key, [])
            raw.append(seconds)
            if len(raw) > _MAX_RAW_DURATIONS:
                del raw[: len(raw) - _MAX_RAW_DURATIONS]
            hist = self.histograms.get(key)
            if hist is None:
                hist = self.histograms[key] = Histogram()
            hist.observe(seconds)

    def histogram(
        self,
        name: str,
        value: float,
        buckets: Sequence[float] = DEFAULT_BUCKETS,
        **tags,
    ) -> None:
        with self._lock:
            key = self._register(name, tags, "histogram")
            hist = self.histograms.get(key)
            if hist is None:
                hist = self.histograms[key] = Histogram(buckets)
            hist.observe(value)

    @contextmanager
    def timer(self, name: str, **tags):
        start = time.perf_counter()
        try:
            yield
        finally:
            self.duration(name, time.perf_counter() - start, **tags)

    # -- readback ---------------------------------------------------------
    def get_counter(self, name: str, **tags) -> float:
        with self._lock:
            return self.counters.get(series_key(name, tags), 0.0)

    def counter_family(self, name: str) -> dict[LabelPairs, float]:
        """Every series of one counter family, keyed by label pairs —
        what the monitor controller aggregates error rates from."""
        with self._lock:
            return {
                labels: self.counters[key]
                for key, (fam, labels) in self._series.items()
                if fam == name and key in self.counters
            }

    def sum_counter(self, name: str) -> float:
        return sum(self.counter_family(name).values())

    def histogram_quantiles(
        self, name: str, qs: Sequence[float] = (0.5, 0.99), **tags
    ) -> dict[float, Optional[float]]:
        """Interpolated quantile snapshot of one histogram series —
        the shared percentile extraction the SLO evaluator, /debug/slo
        and bench detail all read instead of re-implementing bucket
        math.  Missing series yield all-None values."""
        with self._lock:
            hist = self.histograms.get(series_key(name, tags))
        if hist is None:
            return {q: None for q in qs}
        return {q: hist.quantile(q) for q in qs}

    def histogram_count(self, name: str, **tags) -> int:
        with self._lock:
            hist = self.histograms.get(series_key(name, tags))
        return 0 if hist is None else hist.count

    def snapshot(self) -> dict:
        """JSON-friendly dump sharing the exposition vocabulary — what
        bench.py embeds in its BENCH artifact so the perf trajectory and
        live metrics speak one language."""
        with self._lock:
            out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
            for key, value in self.counters.items():
                out["counters"][key] = value
            for key, value in self.stores.items():
                out["gauges"][key] = value
            for key, hist in self.histograms.items():
                out["histograms"][key] = {
                    "sum": hist.sum,
                    "count": hist.count,
                    "buckets": {
                        ("+Inf" if le == float("inf") else repr(le)): n
                        for le, n in hist.cumulative()
                    },
                }
            return out

    # -- Prometheus text exposition ---------------------------------------
    def render_prometheus(self) -> str:
        with self._lock:
            families: dict[str, list[tuple[LabelPairs, str, object]]] = {}
            for key, (name, labels) in self._series.items():
                if key in self.counters:
                    families.setdefault(name, []).append(
                        (labels, "counter", self.counters[key])
                    )
                if key in self.stores:
                    families.setdefault(name, []).append(
                        (labels, "gauge", self.stores[key])
                    )
                if key in self.histograms:
                    families.setdefault(name, []).append(
                        (labels, "histogram", self.histograms[key])
                    )
            types = dict(self._types)
        lines: list[str] = []
        for name in sorted(families):
            prom = _sanitize_name(name)
            lines.append(f"# TYPE {prom} {types.get(name, 'untyped')}")
            for labels, kind, value in sorted(
                families[name], key=lambda item: item[0]
            ):
                if kind == "histogram":
                    for le, n in value.cumulative():
                        le_s = "+Inf" if le == float("inf") else _fmt_value(le)
                        lines.append(
                            f"{prom}_bucket{_fmt_labels(labels + (('le', le_s),))}"
                            f" {n}"
                        )
                    lines.append(
                        f"{prom}_sum{_fmt_labels(labels)} {_fmt_value(value.sum)}"
                    )
                    lines.append(f"{prom}_count{_fmt_labels(labels)} {value.count}")
                else:
                    lines.append(
                        f"{prom}{_fmt_labels(labels)} {_fmt_value(value)}"
                    )
        return "\n".join(lines) + "\n"


def _sanitize_name(name: str) -> str:
    """Metric names must match [a-zA-Z_:][a-zA-Z0-9_:]* — legacy dotted
    names (``monitor.clusters.ready``) map deterministically onto it."""
    out = [
        ch if (ch.isascii() and (ch.isalnum() or ch in "_:")) else "_"
        for ch in name
    ]
    if out and out[0].isdigit():
        out.insert(0, "_")
    return "".join(out) or "_"


def _escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _fmt_labels(labels: LabelPairs) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_sanitize_name(k)}="{_escape_label_value(v)}"' for k, v in labels
    )
    return "{" + inner + "}"


def _fmt_value(value) -> str:
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def null_metrics() -> Metrics:
    return Metrics()
