"""Profiling endpoints — the pprof analogue.

The reference starts ``net/http/pprof`` on :6060 behind
``--enable-profiling`` (reference:
cmd/controller-manager/app/controllermanager.go:61-71, blank import
main.go:21).  The Python control plane's equivalent serves:

* ``GET /debug/profile?seconds=N`` — a SAMPLING profile of every thread
  in the process (pprof's CPU-profile role): stacks are sampled from
  ``sys._current_frames()`` at ~100Hz for N seconds (default 5, max
  120) and aggregated into per-function self/cumulative sample counts.
  Sampling, not tracing, because a tracer (cProfile) only sees the
  installing thread — useless for worker-thread controllers — and adds
  overhead to the very loops being measured.
* ``GET /debug/profile?seconds=N&mode=jax`` — an on-demand
  ``jax.profiler`` capture around whatever the process is doing
  (live ticks included): the trace artifact is written to a fresh
  timestamped subdirectory of ``KT_PROFILE_DIR`` and the response
  carries its path (load in TensorBoard's profile plugin / xprof).
  Works on CPU and TPU; one capture at a time
  (runtime/devprof.capture_jax_profile).
* ``GET /debug/waterfall`` — the dispatch ledger's per-tick waterfall
  (runtime/devprof.py): ordered device-dispatch records with the
  chain-model device/queue attribution and the host-side stage split,
  for the most recent ticks (``?tick=``/``?ticks=``/``?records=``
  narrow it).  See docs/observability.md § Device-time attribution.
* ``GET /debug/stacks`` — current stack of every thread (pprof's
  ``goroutine?debug=2`` role) — the first thing to pull from a wedged
  control plane.
* ``GET /debug/threads`` — thread names/ids/daemon flags.
* ``GET /metrics`` — the metrics registry in Prometheus text format
  (runtime/metrics.py), the pkg/stats exposition analogue.
* ``GET /debug/trace`` — completed reconcile-path spans as Chrome
  trace-event JSON (runtime/trace.py) MERGED with the dispatch ledger's
  device records on per-device lanes (one timeline, correlated by tick
  id; ``?device=0`` for host spans only); load in chrome://tracing.
* ``GET /debug/decisions`` — the scheduling flight recorder's ring
  summary (runtime/flightrec.py): recent ticks, record volumes.
* ``GET /debug/explain?key=<ns/name>`` — per-cluster verdicts for one
  object's latest recorded scheduling decision (which filter rejected
  each infeasible cluster, score/rank for select-stage cuts, the chosen
  clusters + replica split).
* ``GET /debug/drift`` — desired-vs-observed placement drift, from the
  providers registered with the flight recorder module (the monitor
  controller's drift detector).
* ``GET /debug/members`` — per-member circuit-breaker health
  (transport/breaker.py): state, consecutive failures, latency EWMA,
  shed-write and dispatch-retry tallies, and the per-member write
  latency reservoir (p50/p99) the SLO layer joins in — the
  degraded-member runbook's first stop (docs/operations.md).
* ``GET /debug/slo`` — the end-to-end SLO surface (runtime/slo.py):
  per-stage event→placement-written percentiles, the slowest-N
  exemplars fully decomposed, freshness gauges, and the burn-rate
  evaluator's red/green objective status.
* ``GET /debug/timeline`` — the continuous telemetry timeline
  (runtime/timeline.py): multi-tier downsampled series of every
  registry counter/gauge plus the sampler's synthesized SLO-burn,
  breaker, queue-depth, and process gauges; ``?series=`` (comma list
  of substrings) and ``?tier=`` narrow the payload.
* ``GET /debug/tenants`` — the per-tenant attribution ledger
  (runtime/tenancy.py): per-tenant SLO burn, stage latencies, member
  writes, shed writes, admission deferrals, flushed rows.
* ``GET /debug`` — the index: every debug provider this process
  serves, with one-line descriptions.

``respond_debug`` is the shared route handler: the health server mounts
it so one port serves livez/readyz/metrics/debug, and
``ProfilingServer`` runs the same routes standalone on a dedicated port
(the :6060 layout).
"""

from __future__ import annotations

import json
import sys
import threading
import time
import traceback
from collections import Counter
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlsplit

_profile_lock = threading.Lock()


def collect_profile(
    seconds: float = 5.0, top: int = 40, hz: float = 100.0
) -> dict:
    """Sample every thread's stack for ``seconds``; one profile at a
    time (overlapping samplers would double-count each other)."""
    seconds = max(0.1, min(float(seconds), 120.0))
    if not _profile_lock.acquire(blocking=False):
        return {"error": "a profile is already running"}
    try:
        interval = 1.0 / hz
        me = threading.get_ident()
        self_counts: Counter = Counter()
        cum_counts: Counter = Counter()
        samples = 0
        deadline = time.perf_counter() + seconds
        while time.perf_counter() < deadline:
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue  # the sampler itself is noise
                samples += 1
                leaf = True
                seen = set()
                while frame is not None:
                    code = frame.f_code
                    key = f"{code.co_filename}:{code.co_firstlineno}({code.co_name})"
                    if leaf:
                        self_counts[key] += 1
                        leaf = False
                    if key not in seen:  # count recursion once
                        seen.add(key)
                        cum_counts[key] += 1
                    frame = frame.f_back
            time.sleep(interval)
        rows = [
            {
                "function": key,
                "self": self_counts.get(key, 0),
                "cumulative": cum,
            }
            for key, cum in cum_counts.most_common(top)
        ]
        return {"seconds": seconds, "samples": samples, "top": rows}
    finally:
        _profile_lock.release()


def collect_stacks() -> dict:
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in frames.items():
        stacks[f"{names.get(ident, '?')}-{ident}"] = traceback.format_stack(frame)
    return {"threads": stacks}


def collect_threads() -> dict:
    return {
        "threads": [
            {"name": t.name, "ident": t.ident, "daemon": t.daemon,
             "alive": t.is_alive()}
            for t in threading.enumerate()
        ]
    }


# Process-default /debug/shards provider (last manager wins, like the
# SLO recorder and dispatch-ledger attach): a callable returning the
# shard-ownership report — ShardMap identity/epoch, per-shard lease
# holder + freshness, owned-key counts.
_shards_provider: Optional[Callable[[], dict]] = None


def set_shards_provider(fn: Optional[Callable[[], dict]]) -> None:
    global _shards_provider
    _shards_provider = fn


def shards_report() -> Optional[dict]:
    return _shards_provider() if _shards_provider is not None else None


def handle_debug_path(path: str, query: dict) -> Optional[dict]:
    """Route a /debug/* request; None = not a debug path."""
    if path == "/debug/profile":
        try:
            seconds = float(query.get("seconds", 5))
        except (TypeError, ValueError):
            return {"error": f"bad seconds value: {query.get('seconds')!r}"}
        mode = query.get("mode", "stack")
        if mode in ("jax", "device"):
            from kubeadmiral_tpu.runtime import devprof

            return devprof.capture_jax_profile(
                seconds, out_dir=query.get("dir") or None
            )
        return collect_profile(seconds)
    if path == "/debug/waterfall":
        from kubeadmiral_tpu.runtime import devprof

        try:
            tick = int(query["tick"]) if "tick" in query else None
            max_ticks = int(query.get("ticks", 4))
            max_records = int(query.get("records", 512))
        except (TypeError, ValueError):
            return {"error": "bad tick/ticks/records value"}
        return devprof.get_default().waterfall(
            tick=tick, max_ticks=max_ticks, max_records=max_records
        )
    if path == "/debug/stacks":
        return collect_stacks()
    if path == "/debug/threads":
        return collect_threads()
    return None


# The /debug index: route -> one-line description.  Kept static (not
# reflected from the router) so the index documents intent, including
# query parameters the route dispatch alone can't express.
DEBUG_INDEX = {
    "/metrics": "metrics registry, Prometheus text format",
    "/debug/trace": "reconcile spans + device lanes, Chrome trace JSON"
    " (?device=0 host only)",
    "/debug/slo": "end-to-end SLO: stage percentiles, exemplars,"
    " freshness, burn-rate status",
    "/debug/timeline": "continuous telemetry timeline, multi-tier"
    " downsampled series (?series=,&tier=)",
    "/debug/tenants": "per-tenant attribution: SLO burn, writes, sheds,"
    " admission deferrals",
    "/debug/fleet": "fleet-merged telemetry: per-instance metrics +"
    " scrape health + manager snapshots",
    "/debug/members": "per-member circuit-breaker health and write"
    " latency reservoirs",
    "/debug/waterfall": "per-tick device-dispatch waterfall"
    " (?tick=&ticks=&records=)",
    "/debug/decisions": "scheduling flight recorder ring summary",
    "/debug/explain": "per-cluster verdicts for one object"
    " (?key=<ns/name>)",
    "/debug/drift": "desired-vs-observed placement drift",
    "/debug/shards": "sharded control plane: shard ownership, lease"
    " holders/freshness, epoch, owned-key counts",
    "/debug/profile": "sampling profile of every thread"
    " (?seconds=&mode=jax for device capture)",
    "/debug/stacks": "current stack of every thread",
    "/debug/threads": "thread names/ids/daemon flags",
}


def _send(http_handler, body: bytes, content_type: str) -> None:
    http_handler.send_response(200)
    http_handler.send_header("Content-Type", content_type)
    http_handler.send_header("Content-Length", str(len(body)))
    http_handler.end_headers()
    http_handler.wfile.write(body)


def respond_debug(
    http_handler, path: str, raw_query: str, metrics=None, tracer=None,
    flightrec=None, drift=None, members=None, slo=None, timeline=None,
    tenants=None, fleet=None,
) -> bool:
    """Serve a /metrics or /debug/* route on any BaseHTTPRequestHandler;
    returns False when the path isn't one of ours (caller handles it).
    The single implementation shared by the health server and the
    standalone profiling server.

    ``metrics`` is the registry to expose (no default: the caller owns
    its registry); ``tracer`` defaults to the process-wide span tracer
    the reconcile path records into; ``flightrec`` defaults to the
    process-wide decision flight recorder the engine feeds; ``drift``
    (a callable returning the drift listing) defaults to the registered
    drift providers (flightrec.drift_report); ``members`` (a callable
    returning the member-health listing) defaults to the aggregated
    circuit-breaker registries (transport/breaker.members_report);
    ``timeline``/``tenants``/``fleet`` default to the process-wide
    timeline ring, tenant ledger and fleet scraper (each opt-in: 404
    when none is installed)."""
    if path in ("/debug", "/debug/"):
        _send(
            http_handler,
            json.dumps({"endpoints": DEBUG_INDEX}, indent=2).encode(),
            "application/json",
        )
        return True
    if path == "/metrics":
        if metrics is None:
            return False
        _send(
            http_handler,
            metrics.render_prometheus().encode(),
            "text/plain; version=0.0.4; charset=utf-8",
        )
        return True
    if path == "/debug/trace":
        from kubeadmiral_tpu.runtime import trace as trace_mod

        active = tracer or trace_mod.get_default()
        doc = active.chrome_trace()
        # Merge the dispatch ledger's device records as their own
        # per-device lanes (timestamps share the span tracer's epoch, so
        # one trace load shows host + device timelines correlated by
        # tick id).  ?device=0 yields the host-only trace.
        query = {k: v[-1] for k, v in parse_qs(raw_query).items()}
        if query.get("device", "1") not in ("0", "false", "no"):
            try:
                from kubeadmiral_tpu.runtime import devprof

                doc["traceEvents"].extend(
                    devprof.get_default().chrome_events(trace_mod.epoch())
                )
            except Exception:
                pass  # a wedged ledger must not take the trace down
        _send(http_handler, json.dumps(doc).encode(), "application/json")
        return True
    if path == "/debug/slo":
        from kubeadmiral_tpu.runtime import slo as slo_mod

        recorder = slo if slo is not None else slo_mod.get_default()
        _send(
            http_handler,
            json.dumps(recorder.summary()).encode(),
            "application/json",
        )
        return True
    if path == "/debug/timeline":
        from kubeadmiral_tpu.runtime import timeline as timeline_mod

        ring = timeline if timeline is not None else timeline_mod.get_default()
        if ring is None:
            http_handler.send_error(
                404, explain="no timeline installed (KT_TIMELINE=0?)"
            )
            return True
        query = {k: v[-1] for k, v in parse_qs(raw_query).items()}
        doc = ring.to_doc(
            series=query.get("series") or None,
            tier=query.get("tier") or None,
        )
        _send(http_handler, json.dumps(doc).encode(), "application/json")
        return True
    if path == "/debug/tenants":
        from kubeadmiral_tpu.runtime import tenancy as tenancy_mod

        ledger = tenants if tenants is not None else tenancy_mod.get_default()
        if ledger is None:
            http_handler.send_error(
                404, explain="no tenant ledger installed"
            )
            return True
        _send(
            http_handler,
            json.dumps(ledger.summary()).encode(),
            "application/json",
        )
        return True
    if path == "/debug/fleet":
        from kubeadmiral_tpu.runtime import fleetscrape

        scraper = fleet if fleet is not None else fleetscrape.get_default()
        if scraper is None:
            http_handler.send_error(
                404, explain="no fleet scraper installed"
            )
            return True
        _send(
            http_handler,
            json.dumps(scraper.summary()).encode(),
            "application/json",
        )
        return True
    if path == "/debug/members":
        from kubeadmiral_tpu.transport import breaker as breaker_mod

        report = members() if members is not None else breaker_mod.members_report()
        _send(http_handler, json.dumps(report).encode(), "application/json")
        return True
    if path == "/debug/shards":
        report = shards_report()
        if report is None:
            http_handler.send_error(
                404, explain="no shard report provider installed"
            )
            return True
        _send(http_handler, json.dumps(report).encode(), "application/json")
        return True
    if path in ("/debug/decisions", "/debug/explain", "/debug/drift"):
        from kubeadmiral_tpu.runtime import flightrec as flightrec_mod

        recorder = flightrec or flightrec_mod.get_default()
        if path == "/debug/decisions":
            body = json.dumps(recorder.decisions()).encode()
        elif path == "/debug/explain":
            query = {k: v[-1] for k, v in parse_qs(raw_query).items()}
            key = query.get("key", "")
            if not key:
                http_handler.send_error(
                    400, explain="missing ?key=<namespace/name>"
                )
                return True
            result = recorder.explain(key)
            if result is None:
                http_handler.send_error(
                    404, explain=f"no recorded decision for {key!r}"
                )
                return True
            body = json.dumps(result).encode()
        else:
            report = drift() if drift is not None else flightrec_mod.drift_report()
            body = json.dumps(report).encode()
        _send(http_handler, body, "application/json")
        return True
    query = {k: v[-1] for k, v in parse_qs(raw_query).items()}
    result = handle_debug_path(path, query)
    if result is None:
        return False
    _send(http_handler, json.dumps(result).encode(), "application/json")
    return True


class ProfilingServer:
    """Standalone profiling HTTP server (the reference's :6060)."""

    def __init__(
        self, host: str = "127.0.0.1", port: int = 0, metrics=None,
        tracer=None, flightrec=None, drift=None, members=None, slo=None,
        timeline=None, tenants=None, fleet=None,
    ):
        self._host = host
        self._port = port
        self.metrics = metrics
        self.tracer = tracer
        self.flightrec = flightrec
        self.drift = drift
        self.members = members
        self.slo = slo
        self.timeline = timeline
        self.tenants = tenants
        self.fleet = fleet
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        assert self._server is not None
        return self._server.server_address[1]

    def start(self) -> int:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 (http.server API)
                split = urlsplit(self.path)
                if not respond_debug(
                    self, split.path, split.query,
                    metrics=outer.metrics, tracer=outer.tracer,
                    flightrec=outer.flightrec, drift=outer.drift,
                    members=outer.members, slo=outer.slo,
                    timeline=outer.timeline, tenants=outer.tenants,
                    fleet=outer.fleet,
                ):
                    self.send_error(404)

            def log_message(self, *args) -> None:
                pass

        self._server = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="profiling-server", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
