"""End-to-end SLO subsystem: event provenance, freshness, burn rates.

Every latency number this control plane reported before this module
stopped at a subsystem boundary: ``engine_stream_stage_seconds`` ends at
the engine tick, the dispatch ledger attributes device time, and
``worker_*_seconds`` time one controller's queue.  None of them measure
what a member cluster experiences — the time from a watch event entering
the control plane to the resulting placement being durably WRITTEN (and
acked) in the member apiserver.  This module closes that gap:

* **Provenance tokens.**  A birth timestamp is minted where a watch
  event enters the control plane — ``FakeKube._notify`` (in-process
  fleets), ``transport/client._ResourceWatch._dispatch`` (HTTP watch
  streams), with ``runtime/informer.Informer`` as the fallback ingress
  for stores that do not self-ingest — for the *tracked* source
  resources (the federate controller registers its FTC's source).
  Pipeline stages close marks on the token as the object moves:
  ``queued`` (ingress → scheduler tick pickup), ``slab`` (scheduling-
  unit assembly / streaming slab coalesce), ``engine`` (the XLA solve),
  ``fetch`` (placement persisted to the host), ``dispatch`` (sync staged
  the member writes), ``write`` (member apiserver acked).  The
  decomposition *sums to the measured end-to-end latency by
  construction* — stages are consecutive intervals of one clock.
  Emitted as ``slo_event_to_written_seconds{stage=...}`` (plus
  ``stage="total"``).

* **Exemplar ring.**  The slowest-N closed events are retained fully
  decomposed (flightrec-style bounded ring) and served at
  ``GET /debug/slo`` — "which event was slow, and in which stage".

* **Freshness.**  ``slo_oldest_pending_event_seconds`` /
  ``slo_unwritten_placements`` measure how stale the written world is
  versus the observed world: an event whose expected member writes have
  not all acked stays pending, so a silently-wedged dispatch path is
  visible even when no new events flow.  Sampled by the monitor
  controller's tick (federation/monitor.py).

* **Burn-rate evaluator.**  Declared objectives (the catalog lives in
  runtime/metric_catalog.py ``SLO_OBJECTIVES`` and is lint-enforced like
  metric names) are evaluated continuously in-process over multiple
  windows, exposed as ``slo_burn_rate{objective,window}`` gauges and a
  red/green summary on ``/debug/slo``, and embedded in bench detail
  (bench_e2e.py) where ``tools/bench_gate.py`` gates the e2e p99.

Knobs: ``KT_SLO`` (default on; ``0`` disables the token path entirely —
every hook early-outs on one attribute read), ``KT_SLO_E2E_P99_S`` /
``KT_SLO_WRITE_P99_S`` / ``KT_SLO_FRESHNESS_S`` (objective thresholds),
``KT_SLO_WINDOWS_S`` (burn windows, default "60,300"),
``KT_SLO_EXEMPLARS`` (slowest-N ring), ``KT_SLO_PENDING_CAP`` (pending-
token bound), ``KT_SLO_MAX_AGE_S`` (0 = never expire pending tokens).
See docs/observability.md § End-to-end SLOs.
"""

from __future__ import annotations

import heapq
import itertools
import os
import threading
import time
import weakref
from collections import deque
from typing import Iterable, Optional, Sequence

from kubeadmiral_tpu.runtime import metric_catalog as MC
from kubeadmiral_tpu.runtime import tenancy as _tenancy
from kubeadmiral_tpu.runtime.metrics import Metrics

# Provenance stage vocabulary, in pipeline order (metrics-lint checks it
# against metric_catalog.SLO_STAGES; docs/observability.md documents the
# boundary each stage closes at).
STAGES = ("queued", "slab", "engine", "fetch", "dispatch", "write")

# Event→written latencies legitimately span µs (in-proc no-op rounds) to
# minutes (a hard-down member holding a placement hostage): the bucket
# ladder extends DEFAULT_BUCKETS past 10s so outage-scale latencies stay
# in finite buckets and percentile interpolation keeps resolution.
SLO_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def slo_enabled() -> bool:
    """KT_SLO: the master switch for the provenance-token path."""
    return os.environ.get("KT_SLO", "1") not in ("0", "false", "no")


def slo_windows() -> tuple[float, ...]:
    """Burn-rate windows in seconds (KT_SLO_WINDOWS_S, "fast,slow")."""
    raw = os.environ.get("KT_SLO_WINDOWS_S", "60,300")
    out = []
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            v = float(part)
        except ValueError:
            continue
        if v > 0:
            out.append(v)
    return tuple(out) or (60.0, 300.0)


class _Pending:
    """One in-flight provenance token."""

    __slots__ = (
        "key", "birth", "wall", "gen", "marks", "expected", "acked",
        "last_ack", "tenant",
    )

    def __init__(self, key: str, birth: float, gen: Optional[int],
                 tenant: str = ""):
        self.key = key
        self.birth = birth
        self.wall = time.time()
        self.gen = gen
        self.marks: list[tuple[str, float]] = []
        self.expected: Optional[set] = None  # placements sync declared
        self.acked: set = set()
        self.last_ack: Optional[float] = None
        self.tenant = tenant  # namespace-derived (runtime/tenancy.py)


class SLOEvaluator:
    """Multi-window burn-rate evaluation of the declared objectives.

    ``ratio`` objectives track the fraction of observed events over
    their latency threshold against the error budget ``1 - target``
    (burn 1.0 = spending budget exactly as fast as allowed); ``gauge``
    objectives burn as ``value / threshold`` (the freshness lag).  An
    objective is RED when EVERY window is burning ≥ 1 — the classic
    multi-window alert shape: the slow window proves it is not a blip,
    the fast window proves it is still happening.
    """

    def __init__(self, clock=time.monotonic, windows: Optional[Sequence[float]] = None):
        self.clock = clock
        self.windows = tuple(windows) if windows else slo_windows()
        self._lock = threading.Lock()
        self.objectives: dict[str, MC.SLOObjectiveSpec] = {}
        self.thresholds: dict[str, float] = {}
        for name, spec in MC.SLO_OBJECTIVES.items():
            self.objectives[name] = spec
            self.thresholds[name] = _env_float(spec.env, spec.threshold_s)
        # ratio: cumulative (total, bad); gauge: last sampled value.
        self._totals = {n: 0 for n in self.objectives}
        self._bad = {n: 0 for n in self.objectives}
        self._value = {n: 0.0 for n in self.objectives}
        # Snapshot history per objective for window math, trimmed past
        # the slowest window: ratio → (t, total, bad); gauge → (t, ratio).
        # Seeded with a zero snapshot at birth so the FIRST evaluation
        # already has a window baseline (without it, evaluate() would
        # report burn 0 until its second pass regardless of traffic).
        horizon = max(self.windows) * 1.5 + 10.0
        self._horizon = horizon
        born = self.clock()
        # maxlen bounds a tight /debug/slo poll loop; at the default
        # windows it still holds minutes of 10 Hz samples.
        self._snaps: dict[str, deque] = {
            n: deque(
                [(born, 0.0)] if spec.kind == "gauge" else [(born, 0, 0)],
                maxlen=4096,
            )
            for n, spec in self.objectives.items()
        }
        self._status: dict[str, dict] = {}

    def observe(self, name: str, seconds: float) -> None:
        spec = self.objectives.get(name)
        if spec is None or spec.kind != "ratio":
            return
        with self._lock:
            self._totals[name] += 1
            if seconds > self.thresholds[name]:
                self._bad[name] += 1

    def sample_gauge(self, name: str, value: float) -> None:
        spec = self.objectives.get(name)
        if spec is None or spec.kind != "gauge":
            return
        with self._lock:
            self._value[name] = float(value)

    def _window_burn_locked(self, name: str, now: float, window: float) -> float:
        spec = self.objectives[name]
        snaps = self._snaps[name]
        cutoff = now - window
        if spec.kind == "gauge":
            burns = [r for (t, r) in snaps if t >= cutoff]
            burns.append(self._value[name] / max(1e-9, self.thresholds[name]))
            return max(burns)
        # ratio: the newest snapshot at or before the window start is the
        # baseline; shorter history evaluates over what exists.
        base_t, base_total, base_bad = snaps[0] if snaps else (now, 0, 0)
        for (t, total, bad) in snaps:
            if t <= cutoff:
                base_t, base_total, base_bad = t, total, bad
            else:
                break
        d_total = self._totals[name] - base_total
        d_bad = self._bad[name] - base_bad
        if d_total <= 0:
            return 0.0
        budget = max(1e-9, 1.0 - spec.target)
        return (d_bad / d_total) / budget

    def evaluate(self, now: Optional[float] = None, metrics=None) -> dict:
        """One evaluation pass: snapshot, window burns, red/green.
        Returns {objective: {"burn": {window: x}, "red": bool, ...}}."""
        if now is None:
            now = self.clock()
        status: dict[str, dict] = {}
        with self._lock:
            for name, spec in self.objectives.items():
                snaps = self._snaps[name]
                if spec.kind == "gauge":
                    snaps.append(
                        (now, self._value[name] / max(1e-9, self.thresholds[name]))
                    )
                else:
                    snaps.append((now, self._totals[name], self._bad[name]))
                while snaps and snaps[0][0] < now - self._horizon:
                    snaps.popleft()
                burns = {
                    w: self._window_burn_locked(name, now, w)
                    for w in self.windows
                }
                entry = {
                    "kind": spec.kind,
                    "target": spec.target,
                    "threshold_s": self.thresholds[name],
                    "burn": {f"{int(w)}s": round(b, 4) for w, b in burns.items()},
                    "red": all(b >= 1.0 for b in burns.values()),
                }
                if spec.kind == "ratio":
                    entry["events"] = self._totals[name]
                    entry["breaches"] = self._bad[name]
                else:
                    entry["value_s"] = round(self._value[name], 4)
                status[name] = entry
            self._status = status
        if metrics is not None:
            for name, entry in status.items():
                for window, burn in entry["burn"].items():
                    metrics.store(
                        "slo_burn_rate", burn, objective=name, window=window
                    )
        return status

    def status(self) -> dict:
        """The most recent evaluation (empty before the first pass)."""
        with self._lock:
            return dict(self._status)


class SLORecorder:
    """Provenance tokens + stage histograms + freshness + evaluator.

    One instance per control plane (the process default mirrors
    trace/flightrec); its own :class:`Metrics` registry holds the
    ``slo_*`` / ``member_write_seconds`` families unless ``attach()``
    points emission at a shared one.
    """

    def __init__(
        self,
        enabled: Optional[bool] = None,
        metrics: Optional[Metrics] = None,
        clock=time.monotonic,
        exemplars: Optional[int] = None,
        pending_cap: Optional[int] = None,
        windows: Optional[Sequence[float]] = None,
    ):
        self.enabled = slo_enabled() if enabled is None else bool(enabled)
        self.metrics = metrics if metrics is not None else Metrics()
        self.clock = clock
        self.exemplars = (
            int(os.environ.get("KT_SLO_EXEMPLARS", "32"))
            if exemplars is None
            else int(exemplars)
        )
        self.pending_cap = (
            int(os.environ.get("KT_SLO_PENDING_CAP", "200000"))
            if pending_cap is None
            else int(pending_cap)
        )
        # 0 disables expiry: a wedged dispatch path must stay visible in
        # the freshness gauges indefinitely, not quietly age out.
        self.max_age_s = _env_float("KT_SLO_MAX_AGE_S", 0.0)
        self.evaluator = SLOEvaluator(clock=clock, windows=windows)
        self._lock = threading.RLock()
        self._pending: dict[str, _Pending] = {}
        # Ingress stores whose events mint tokens: store → {resources}.
        # Weak keys so a torn-down fleet's host cannot alias a recycled
        # id, and the recorder never pins test fleets alive.
        self._tracked: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        # Last seen metadata.generation per key: MODIFIED events that do
        # not bump it (finalizer/annotation/status echoes of our own
        # writes) must not re-mint — they are not new intent.
        self._gen: dict[str, int] = {}
        self._seq = itertools.count(1)
        # Slowest-N min-heap of (total_s, seq, exemplar-dict).
        self._slow: list = []

    # -- wiring -----------------------------------------------------------
    def attach(self, metrics: Metrics) -> None:
        """Point emission at a shared registry (manager wiring)."""
        self.metrics = metrics

    def track(self, store, resource: str) -> None:
        """Register (store, resource) as a token-minting ingress."""
        with self._lock:
            try:
                resources = self._tracked.get(store)
                if resources is None:
                    resources = set()
                    self._tracked[store] = resources
                resources.add(resource)
            except TypeError:
                pass  # un-weakref-able store: nothing to track

    def tracked(self, store, resource: str) -> bool:
        try:
            resources = self._tracked.get(store)
        except TypeError:
            return False
        return resources is not None and resource in resources

    # -- ingress ----------------------------------------------------------
    def ingest(self, store, resource: str, event: str, obj: dict) -> None:
        """Called by the transport/store dispatch point ONCE per event.
        Mints a token for tracked resources; DELETED forgets; MODIFIED
        without a generation bump is an echo and mints nothing."""
        if not self.enabled or not self.tracked(store, resource):
            return
        meta = obj.get("metadata", {}) or {}
        ns = meta.get("namespace", "")
        name = meta.get("name", "")
        key = f"{ns}/{name}" if ns else name
        if not name:
            return
        if event == "DELETED":
            self.forget(key)
            return
        gen = meta.get("generation")
        tenant = _tenancy.tenant_of(ns, meta.get("labels"))
        t = self.clock()
        with self._lock:
            if gen is not None:
                last = self._gen.get(key)
                if last is not None and int(gen) <= last:
                    self.metrics.counter("slo_events_total", result="echo")
                    return
                self._gen[key] = int(gen)
            self._mint_locked(key, t, gen, tenant)

    def mint(self, key: str, t: Optional[float] = None, gen: Optional[int] = None) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._mint_locked(
                key, self.clock() if t is None else t, gen,
                _tenancy.tenant_of_key(key),
            )

    def _mint_locked(
        self, key: str, t: float, gen: Optional[int], tenant: str = ""
    ) -> None:
        if key in self._pending:
            # Newer intent supersedes the in-flight token: latency is
            # measured from the LAST event that changed the object.
            self.metrics.counter("slo_events_total", result="superseded")
        elif len(self._pending) >= self.pending_cap:
            self.metrics.counter("slo_events_total", result="dropped")
            return
        else:
            self.metrics.counter("slo_events_total", result="minted")
        self._pending[key] = _Pending(key, t, gen, tenant)

    def forget(self, key: str) -> None:
        """Object deleted: its pending token (if any) is void."""
        if not self.enabled:
            return
        with self._lock:
            self._gen.pop(key, None)
            if self._pending.pop(key, None) is not None:
                self.metrics.counter("slo_events_total", result="forgotten")

    # -- stage marks -------------------------------------------------------
    def mark(self, key: str, stage: str, t: Optional[float] = None) -> None:
        if not self.enabled:
            return
        self.mark_many((key,), stage, t)

    def mark_many(
        self, keys: Iterable[str], stage: str, t: Optional[float] = None
    ) -> None:
        """Close ``stage`` for every pending key in one lock hold (the
        batch controllers' path).  First mark wins per stage — a re-run
        of the same pipeline pass keeps the original boundary."""
        if not self.enabled:
            return
        if t is None:
            t = self.clock()
        with self._lock:
            for key in keys:
                entry = self._pending.get(key)
                if entry is None:
                    continue
                if any(s == stage for s, _ in entry.marks):
                    continue
                entry.marks.append((stage, t))

    def expect(self, key: str, clusters: Iterable[str], t: Optional[float] = None) -> None:
        """Sync declared the placements this event must reach: the token
        closes (and freshness clears) only when every one has acked."""
        if not self.enabled:
            return
        with self._lock:
            entry = self._pending.get(key)
            if entry is not None:
                entry.expected = set(clusters)

    # -- completion --------------------------------------------------------
    def written(self, key: str, cluster: str, t: Optional[float] = None) -> None:
        """One member write acked.  The token finalizes when all expected
        placements have acked (or on the first ack when no expectation
        was declared)."""
        if not self.enabled:
            return
        if t is None:
            t = self.clock()
        with self._lock:
            entry = self._pending.get(key)
            if entry is None:
                return
            entry.acked.add(cluster)
            entry.last_ack = t
            if entry.expected is not None and (entry.expected - entry.acked):
                return
            del self._pending[key]
        self._finalize(entry, t)

    def written_many(
        self, pairs: Iterable[tuple[str, str]], t: Optional[float] = None
    ) -> None:
        """Batch of member-write acks — :meth:`written` for a whole sync
        flush under ONE lock hold (finalizations collected inside,
        histogram work done outside the lock).  Acks land with one
        shared timestamp: within a flush the per-op ack spread is
        bookkeeping skew, not member latency."""
        if not self.enabled:
            return
        if t is None:
            t = self.clock()
        done: list[_Pending] = []
        with self._lock:
            for key, cluster in pairs:
                entry = self._pending.get(key)
                if entry is None:
                    continue
                entry.acked.add(cluster)
                entry.last_ack = t
                if entry.expected is not None and (entry.expected - entry.acked):
                    continue
                del self._pending[key]
                done.append(entry)
        for entry in done:
            self._finalize(entry, t)

    def settle(self, key: str) -> None:
        """The sync round for this object ended fully OK.  A token with
        acked writes finalizes at its last ack (partial version-skips
        must not lose the sample); one with none — a pure no-op round —
        is dropped quietly."""
        if not self.enabled:
            return
        with self._lock:
            entry = self._pending.pop(key, None)
            if entry is None:
                return
            if not entry.acked:
                self.metrics.counter("slo_events_total", result="settled")
                return
        self._finalize(entry, entry.last_ack)

    def _finalize(self, entry: _Pending, t_end: float) -> None:
        m = self.metrics
        stages: dict[str, float] = {}
        prev = entry.birth
        for stage, tm in sorted(entry.marks, key=lambda p: p[1]):
            stages[stage] = max(0.0, tm - prev)
            prev = max(prev, tm)
        stages["write"] = max(0.0, t_end - prev)
        total = max(0.0, t_end - entry.birth)
        for stage, dur in stages.items():
            m.histogram(
                "slo_event_to_written_seconds", dur,
                buckets=SLO_BUCKETS, stage=stage,
            )
        m.histogram(
            "slo_event_to_written_seconds", total,
            buckets=SLO_BUCKETS, stage="total",
        )
        m.counter("slo_events_total", result="written")
        self.evaluator.observe("event_to_written_p99", total)
        # Per-tenant attribution (runtime/tenancy.py; no-op unless a
        # ledger is installed): the token's namespace-derived tenant
        # carries the whole stage decomposition.
        _tenancy.note_event(
            entry.tenant or _tenancy.tenant_of_key(entry.key),
            total, stages,
        )
        exemplar = {
            "key": entry.key,
            "total_s": round(total, 6),
            "stages_s": {s: round(v, 6) for s, v in stages.items()},
            "acked": sorted(entry.acked),
            "wall": entry.wall,
        }
        with self._lock:
            item = (total, next(self._seq), exemplar)
            if len(self._slow) < max(1, self.exemplars):
                heapq.heappush(self._slow, item)
            elif total > self._slow[0][0]:
                heapq.heapreplace(self._slow, item)

    # -- per-member attribution -------------------------------------------
    def member_write(self, cluster: str, seconds: float) -> None:
        """One member batch round trip (retries included) completed —
        dispatch feeds this so a slow MEMBER is distinguishable from a
        slow engine (the member-vs-engine triage in docs/operations.md)."""
        if not self.enabled:
            return
        self.metrics.histogram(
            "member_write_seconds", seconds, buckets=SLO_BUCKETS,
            cluster=cluster,
        )
        self.evaluator.observe("member_write_p99", seconds)

    # -- freshness ---------------------------------------------------------
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def oldest_pending_seconds(self, now: Optional[float] = None) -> float:
        if now is None:
            now = self.clock()
        with self._lock:
            if not self._pending:
                return 0.0
            return max(0.0, now - min(e.birth for e in self._pending.values()))

    def unwritten_placements(self) -> int:
        """Expected member writes not yet acked (tokens without a
        declared expectation count 1: the object itself is unwritten)."""
        with self._lock:
            total = 0
            for e in self._pending.values():
                if e.expected is None:
                    total += 1
                else:
                    total += len(e.expected - e.acked)
            return total

    def _expire_locked(self, now: float) -> None:
        if self.max_age_s <= 0:
            return
        stale = [
            k for k, e in self._pending.items()
            if now - e.birth > self.max_age_s
        ]
        for k in stale:
            del self._pending[k]
            self.metrics.counter("slo_events_total", result="expired")

    def publish(self, extra: Optional[Metrics] = None, now: Optional[float] = None) -> None:
        """Emit the freshness gauge pair (monitor tick / bench sampling).
        ``extra`` mirrors into a second registry (the monitor's shared
        one) when it differs from the recorder's own."""
        if not self.enabled:
            return
        if now is None:
            now = self.clock()
        with self._lock:
            self._expire_locked(now)
        oldest = self.oldest_pending_seconds(now)
        unwritten = self.unwritten_placements()
        for m in {id(self.metrics): self.metrics,
                  **({id(extra): extra} if extra is not None else {})}.values():
            m.store("slo_oldest_pending_event_seconds", oldest)
            m.store("slo_unwritten_placements", unwritten)
        self.evaluator.sample_gauge("freshness", oldest)

    def evaluate(
        self, extra: Optional[Metrics] = None, now: Optional[float] = None
    ) -> dict:
        """Freshness sample + one evaluator pass; returns the red/green
        status map and emits slo_burn_rate gauges."""
        if not self.enabled:
            return {}
        self.publish(extra=extra, now=now)
        status = self.evaluator.evaluate(now=now, metrics=self.metrics)
        if extra is not None and extra is not self.metrics:
            for name, entry in status.items():
                for window, burn in entry["burn"].items():
                    extra.store(
                        "slo_burn_rate", burn, objective=name, window=window
                    )
        return status

    # -- /debug/slo --------------------------------------------------------
    def summary(self, slowest: Optional[int] = None) -> dict:
        """The GET /debug/slo payload (schema in docs/observability.md)."""
        if not self.enabled:
            return {"enabled": False}
        now = self.clock()
        status = self.evaluate(now=now)
        stages = {}
        for stage in STAGES + ("total",):
            qs = self.metrics.histogram_quantiles(
                "slo_event_to_written_seconds", (0.5, 0.99), stage=stage
            )
            count = self.metrics.histogram_count(
                "slo_event_to_written_seconds", stage=stage
            )
            if count:
                stages[stage] = {
                    "count": count,
                    "p50_s": round(qs[0.5], 6) if qs[0.5] is not None else None,
                    "p99_s": round(qs[0.99], 6) if qs[0.99] is not None else None,
                }
        with self._lock:
            slow = sorted(self._slow, key=lambda it: -it[0])
            pending = len(self._pending)
        if slowest is not None:
            slow = slow[:slowest]
        return {
            "enabled": True,
            "generated_at": time.time(),
            "pending_events": pending,
            "oldest_pending_s": round(self.oldest_pending_seconds(now), 4),
            "unwritten_placements": self.unwritten_placements(),
            "stages": stages,
            "objectives": status,
            "red": sorted(n for n, e in status.items() if e.get("red")),
            "slowest": [ex for (_, _, ex) in slow],
        }


# -- process default -------------------------------------------------------
_default: Optional[SLORecorder] = None
_default_lock = threading.Lock()


def get_default() -> SLORecorder:
    global _default
    rec = _default
    if rec is None:
        with _default_lock:
            rec = _default
            if rec is None:
                rec = _default = SLORecorder()
    return rec


def set_default(recorder: SLORecorder) -> SLORecorder:
    """Install a recorder as the process default (tests, embedders);
    returns the previous one."""
    global _default
    with _default_lock:
        prev = _default
        _default = recorder
    return prev


def reset_default() -> SLORecorder:
    """Fresh default recorder (re-reads the KT_SLO_* environment)."""
    return set_default(SLORecorder()) or get_default()


# -- module-level hooks (all early-out when the token path is off) ---------
def _rec() -> Optional[SLORecorder]:
    rec = _default
    if rec is None:
        rec = get_default()
    return rec if rec.enabled else None


def active() -> bool:
    """Cheap hot-path guard: is the default recorder's token path on?
    Callers use it to skip building key lists for mark_many()."""
    rec = _default
    if rec is None:
        rec = get_default()
    return rec.enabled


def track(store, resource: str) -> None:
    rec = _default or get_default()
    rec.track(store, resource)


def ingest(store, resource: str, event: str, obj: dict) -> None:
    rec = _rec()
    if rec is not None:
        rec.ingest(store, resource, event, obj)


def mark(key: str, stage: str, t: Optional[float] = None) -> None:
    rec = _rec()
    if rec is not None:
        rec.mark(key, stage, t)


def mark_many(keys: Iterable[str], stage: str, t: Optional[float] = None) -> None:
    rec = _rec()
    if rec is not None:
        rec.mark_many(keys, stage, t)


def expect(key: str, clusters: Iterable[str]) -> None:
    rec = _rec()
    if rec is not None:
        rec.expect(key, clusters)


def written(key: str, cluster: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.written(key, cluster)


def written_many(pairs: Iterable[tuple[str, str]]) -> None:
    rec = _rec()
    if rec is not None:
        rec.written_many(pairs)


def settle(key: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.settle(key)


def forget(key: str) -> None:
    rec = _rec()
    if rec is not None:
        rec.forget(key)


def member_write(cluster: str, seconds: float) -> None:
    rec = _rec()
    if rec is not None:
        rec.member_write(cluster, seconds)
