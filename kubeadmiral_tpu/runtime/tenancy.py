"""Per-tenant attribution: the bounded-cardinality tenant dimension.

ROADMAP item 4's weighted fair admission needs per-tenant data — which
tenant is burning the latency budget, shedding writes, triggering
admission backpressure — and before this module nothing in the pipeline
carried a tenant identity.  Tenancy here is namespace-derived:

* default: tenant == the object's namespace ("~cluster" for
  cluster-scoped objects);
* KT_TENANT_LABEL names a metadata label whose value overrides the
  namespace when present (call sites that only know a "ns/name" key
  fall back to the namespace — labels aren't carried that deep);
* cardinality is bounded by KT_TENANT_MAX (default 64): the first
  KT_TENANT_MAX distinct tenants keep their names, later arrivals
  collapse into the "~other" bucket — so the tenant label can never
  blow up the metric registry, whatever the workload does.

:class:`TenantLedger` accumulates per-tenant: finalized SLO events and
their per-stage latencies, threshold breaches (and the derived
error-budget burn for the event_to_written_p99 objective), member-write
latency and op counts, shed writes, admission deferrals, and flushed
stream rows.  Emissions go to the shared Metrics registry under the
``tenant_*`` families (runtime/metric_catalog.py); the full report is
served at GET /debug/tenants (runtime/profiling.py).

Module-level hooks mirror runtime/slo.py: every call early-outs on one
attribute read when no ledger is installed, so the hot paths
(dispatch success tail, worker enqueue, stream flush) pay nothing by
default.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from kubeadmiral_tpu.runtime import lockcheck
from kubeadmiral_tpu.runtime.metric_catalog import SLO_OBJECTIVES
from kubeadmiral_tpu.runtime.metrics import Metrics

__all__ = [
    "tenant_of",
    "tenant_of_key",
    "TenantLedger",
    "get_default",
    "set_default",
    "reset_default",
    "active",
    "note_event",
    "note_write",
    "note_shed",
    "note_admission",
    "note_flush",
    "note_scheduled",
]

OTHER = "~other"
CLUSTER_SCOPED = "~cluster"


def tenant_of(namespace: str, labels: Optional[dict] = None) -> str:
    """Tenant identity for an object: the KT_TENANT_LABEL label value
    when configured and present, else the namespace (cluster-scoped
    objects share the "~cluster" tenant)."""
    label = os.environ.get("KT_TENANT_LABEL", "")
    if label and labels:
        value = labels.get(label)
        if value:
            return str(value)
    return namespace if namespace else CLUSTER_SCOPED


def tenant_of_key(key: str) -> str:
    """Tenant for a "ns/name" worker/stream key (no labels that deep).
    Skips tenant_of()'s KT_TENANT_LABEL env read — a key never carries
    labels, and this runs per key on the enqueue hot path (the PR 18
    10000x500 profile surfaced the per-key getenv)."""
    ns, _, rest = key.partition("/")
    return ns if (rest and ns) else CLUSTER_SCOPED


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class _TenantStats:
    __slots__ = (
        "events", "breaches", "total_s", "stage_s", "write_ops",
        "write_s", "sheds", "admissions", "rows_flushed", "scheduled",
    )

    def __init__(self):
        self.events = 0
        self.breaches = 0
        self.total_s = 0.0
        self.stage_s: dict[str, float] = {}
        self.write_ops = 0
        self.write_s = 0.0
        self.sheds = 0
        self.admissions = 0
        self.rows_flushed = 0
        self.scheduled = 0


@lockcheck.shared_field_guard
class TenantLedger:
    """Bounded per-tenant accounting (see module docstring)."""

    _shared_fields_ = {"_tenants": "_lock"}

    def __init__(self, metrics: Optional[Metrics] = None,
                 max_tenants: Optional[int] = None):
        self.metrics = metrics if metrics is not None else Metrics()
        self.max_tenants = (
            _env_int("KT_TENANT_MAX", 64)
            if max_tenants is None else int(max_tenants)
        )
        spec = SLO_OBJECTIVES["event_to_written_p99"]
        self.e2e_threshold_s = _env_float(spec.env, spec.threshold_s)
        self.e2e_target = spec.target
        self._lock = lockcheck.make_lock("tenancy")
        self._tenants: dict[str, _TenantStats] = {}

    def attach(self, metrics: Metrics) -> None:
        self.metrics = metrics

    @lockcheck.assumes_held("_lock")
    def _slot_locked(self, tenant: str) -> tuple[str, _TenantStats]:
        """The canonical (possibly "~other"-collapsed) tenant and its
        stats — the single cardinality gate every note_* goes through."""
        stats = self._tenants.get(tenant)
        if stats is None:
            if len(self._tenants) >= self.max_tenants and tenant != OTHER:
                tenant = OTHER
                stats = self._tenants.get(OTHER)
            if stats is None:
                stats = _TenantStats()
                self._tenants[tenant] = stats
        return tenant, stats

    # -- accounting --------------------------------------------------------
    def note_event(self, tenant: str, total_s: float,
                   stages: Optional[dict] = None) -> None:
        """One finalized provenance token (slo.SLORecorder._finalize)."""
        with self._lock:
            tenant, stats = self._slot_locked(tenant)
            stats.events += 1
            stats.total_s += total_s
            breached = total_s > self.e2e_threshold_s
            if breached:
                stats.breaches += 1
            if stages:
                for stage, dur in stages.items():
                    stats.stage_s[stage] = stats.stage_s.get(stage, 0.0) + dur
            burn = self._burn_locked(stats)
        m = self.metrics
        m.counter("tenant_events_total",
                  tenant=tenant, result="bad" if breached else "good")
        m.store("tenant_slo_burn", burn, tenant=tenant)
        if stages:
            for stage, dur in stages.items():
                m.histogram("tenant_stage_seconds", dur,
                            tenant=tenant, stage=stage)

    def note_write(self, tenant: str, seconds: float, ops: int = 1) -> None:
        """Member-write latency attributed to the ops' tenant (the
        dispatch success tail; retries included in ``seconds``)."""
        with self._lock:
            tenant, stats = self._slot_locked(tenant)
            stats.write_ops += ops
            stats.write_s += seconds
        self.metrics.histogram("tenant_write_seconds", seconds, tenant=tenant)

    def note_shed(self, tenant: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            tenant, stats = self._slot_locked(tenant)
            stats.sheds += n
        self.metrics.counter("tenant_shed_writes_total", n, tenant=tenant)

    def note_admission(self, tenant: str, n: int = 1) -> None:
        with self._lock:
            tenant, stats = self._slot_locked(tenant)
            stats.admissions += n
        self.metrics.counter(
            "tenant_admission_deferrals_total", n, tenant=tenant)

    def note_flush(self, tenant: str, rows: int = 1) -> None:
        with self._lock:
            tenant, stats = self._slot_locked(tenant)
            stats.rows_flushed += rows
        self.metrics.counter("tenant_rows_flushed_total", rows, tenant=tenant)

    def note_scheduled(self, tenant: str, n: int = 1) -> None:
        """Objects pushed through the scheduler for this tenant — the
        demand side of the fair-admission picture."""
        with self._lock:
            tenant, stats = self._slot_locked(tenant)
            stats.scheduled += n
        self.metrics.counter("tenant_scheduled_total", n, tenant=tenant)

    # -- read side ---------------------------------------------------------
    @lockcheck.assumes_held("_lock")
    def _burn_locked(self, stats: _TenantStats) -> float:
        """Whole-run error-budget burn of event_to_written_p99 for one
        tenant: (bad fraction) / (allowed bad fraction); 1.0 = spending
        the budget exactly as fast as allowed."""
        if stats.events == 0:
            return 0.0
        budget = max(1e-9, 1.0 - self.e2e_target)
        return (stats.breaches / stats.events) / budget

    def summary(self) -> dict:
        """The GET /debug/tenants payload."""
        with self._lock:
            tenants = {}
            for name, s in sorted(self._tenants.items()):
                tenants[name] = {
                    "events": s.events,
                    "breaches": s.breaches,
                    "slo_burn": round(self._burn_locked(s), 4),
                    "event_total_s": round(s.total_s, 6),
                    "event_mean_s": round(s.total_s / s.events, 6)
                    if s.events else None,
                    "stage_s": {k: round(v, 6)
                                for k, v in sorted(s.stage_s.items())},
                    "write_ops": s.write_ops,
                    "write_s": round(s.write_s, 6),
                    "shed_writes": s.sheds,
                    "admission_deferrals": s.admissions,
                    "rows_flushed": s.rows_flushed,
                    "scheduled": s.scheduled,
                }
            return {
                "generated_at": time.time(),
                "tenant_label": os.environ.get("KT_TENANT_LABEL", ""),
                "max_tenants": self.max_tenants,
                "e2e_threshold_s": self.e2e_threshold_s,
                "tenants": tenants,
                "overflowed": OTHER in self._tenants,
            }


# -- process default --------------------------------------------------------
_default: Optional[TenantLedger] = None
_default_lock = threading.Lock()


def get_default() -> Optional[TenantLedger]:
    """The installed ledger or None — attribution is opt-in (the soak
    harness, benches, and tests install one; production embedders may),
    so the default hot-path cost is one module-global read."""
    return _default


def set_default(ledger: Optional[TenantLedger]) -> Optional[TenantLedger]:
    global _default
    with _default_lock:
        prev = _default
        _default = ledger
    return prev


def reset_default() -> None:
    set_default(None)


def active() -> bool:
    return _default is not None


# -- module-level hooks (early-out when no ledger is installed) -------------

def note_event(tenant: str, total_s: float,
               stages: Optional[dict] = None) -> None:
    ledger = _default
    if ledger is not None:
        ledger.note_event(tenant, total_s, stages)


def note_write(tenant: str, seconds: float, ops: int = 1) -> None:
    ledger = _default
    if ledger is not None:
        ledger.note_write(tenant, seconds, ops)


def note_shed(tenant: str, n: int = 1) -> None:
    ledger = _default
    if ledger is not None:
        ledger.note_shed(tenant, n)


def note_admission(tenant: str, n: int = 1) -> None:
    ledger = _default
    if ledger is not None:
        ledger.note_admission(tenant, n)


def note_flush(tenant: str, rows: int = 1) -> None:
    ledger = _default
    if ledger is not None:
        ledger.note_flush(tenant, rows)


def note_scheduled(tenant: str, n: int = 1) -> None:
    ledger = _default
    if ledger is not None:
        ledger.note_scheduled(tenant, n)
