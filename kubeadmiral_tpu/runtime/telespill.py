"""Crash-durable telemetry spill — the observatory's black box.

Every telemetry surface in this process (the span ring, the timeline,
the flight recorder) lives in memory and dies with the process: a
SIGKILL'd soak victim takes its last seconds to the grave.  This module
periodically persists those surfaces to an append-only segment log in
``KT_TELEMETRY_DIR``, so a successor (or the soak gate, or
``tools/trace_assemble.py``) can recover everything the victim had
fully framed at the instant of death.

Segment format (mirroring ``runtime/snapshot.py`` durability
semantics — CRC-guarded, quarantine on damage, never trust blindly):

* each segment file starts with MAGIC ``KTSPILL1``;
* each record is ``<u32 length><u32 crc32>`` + a JSON payload; records
  are appended and flushed (a SIGKILL loses at most the torn tail of
  the final record — page cache survives process death);
* a reader salvages the longest fully-framed prefix of a damaged
  segment, then renames the file ``*.quarantined`` (kept for
  forensics, never re-read);
* rotation: a segment exceeding its share closes and a new one opens;
  oldest segments are deleted while the directory exceeds
  ``KT_SPILL_BYTES`` (per instance).

Every record envelope carries ``wall`` + ``mono`` clock readings and
the process's trace ``wall_epoch``, so monotonic timeline timestamps
and perf_counter span timestamps can both be mapped onto the shared
wall clock when processes merge.

``KT_SPILL=0`` disables the module entirely: no files, no thread.
Spilling is opt-in by directory (``KT_TELEMETRY_DIR``), like
``KT_SNAPSHOT_DIR``.  See docs/observability.md § Fleet observatory.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from typing import Optional

MAGIC = b"KTSPILL1"
_FRAME = struct.Struct("<II")  # payload length, payload crc32

__all__ = [
    "MAGIC",
    "SpillWriter",
    "TelemetrySpiller",
    "spill_enabled",
    "telemetry_dir",
    "read_segment",
    "load_dir",
]


def spill_enabled() -> bool:
    """KT_SPILL: master switch (default on; spilling still requires a
    directory).  Off means zero files and no spiller thread."""
    return os.environ.get("KT_SPILL", "1") not in ("0", "false", "no")


def telemetry_dir() -> Optional[str]:
    return os.environ.get("KT_TELEMETRY_DIR") or None


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _sanitize(instance: str) -> str:
    return "".join(
        c if c.isalnum() or c in "-_." else "-" for c in instance
    ) or "proc"


class SpillWriter:
    """Bounded append-only CRC-framed segment log for one instance."""

    def __init__(
        self,
        directory: str,
        instance: str = "",
        max_bytes: Optional[int] = None,
        segment_bytes: Optional[int] = None,
        metrics=None,
    ):
        self.enabled = spill_enabled()
        self.dir = directory
        self.instance = _sanitize(instance or f"pid{os.getpid()}")
        self.max_bytes = (
            _env_int("KT_SPILL_BYTES", 8 << 20)
            if max_bytes is None else int(max_bytes)
        )
        # Rotation grain: small enough that deleting the oldest segment
        # under byte pressure sheds history in slices, not halves.
        self.segment_bytes = (
            max(4096, self.max_bytes // 8)
            if segment_bytes is None else max(4096, int(segment_bytes))
        )
        self.metrics = metrics
        self._lock = threading.Lock()
        self._fh = None
        self._seq = 0
        self._written = 0  # bytes in the open segment

    def _segment_name(self) -> str:
        return f"spill-{self.instance}-{os.getpid()}-{self._seq:06d}.ktspill"

    def _open_locked(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        # Never append to a pre-existing file (a previous incarnation's
        # segment, possibly torn): claim the next free sequence number.
        while True:
            path = os.path.join(self.dir, self._segment_name())
            if not os.path.exists(path):
                break
            self._seq += 1
        self._fh = open(path, "wb")
        self._fh.write(MAGIC)
        self._written = len(MAGIC)
        if self.metrics is not None:
            self.metrics.counter("telespill_segment_rotations_total")

    def append(self, kind: str, payload: dict) -> bool:
        """Frame and append one record; returns False when spilling is
        disabled.  The write is flushed to the OS (SIGKILL-durable) but
        not fsynced — the spill protects against process death, not
        power loss, and an fsync per interval would dominate the ≤2%
        overhead budget."""
        if not self.enabled:
            return False
        blob = json.dumps(payload).encode()
        with self._lock:
            if self._fh is None or self._written >= self.segment_bytes:
                self._rotate_locked()
            self._fh.write(_FRAME.pack(len(blob), zlib.crc32(blob)))
            self._fh.write(blob)
            self._fh.flush()
            self._written += _FRAME.size + len(blob)
        if self.metrics is not None:
            self.metrics.counter("telespill_records_total", kind=kind)
            self.metrics.counter(
                "telespill_bytes_written_total",
                value=_FRAME.size + len(blob),
            )
        return True

    def _rotate_locked(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
            self._seq += 1
        self._open_locked()
        self._prune_locked()

    def _prune_locked(self) -> None:
        """Delete oldest segments of THIS instance while the instance's
        total exceeds the byte bound (the open segment never deletes
        itself: at least the newest history always survives)."""
        segs = []
        try:
            for de in os.scandir(self.dir):
                if (
                    de.name.startswith(f"spill-{self.instance}-")
                    and de.name.endswith(".ktspill")
                ):
                    try:
                        segs.append((de.name, de.stat().st_size, de.path))
                    except OSError:
                        continue
        except OSError:
            return
        segs.sort()
        total = sum(size for _, size, _ in segs)
        for name, size, path in segs[:-1]:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(path)
                total -= size
                if self.metrics is not None:
                    self.metrics.counter("telespill_segments_deleted_total")
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# -- read side ---------------------------------------------------------------

def read_segment(
    path: str, quarantine: bool = True, metrics=None
) -> tuple[list[dict], bool]:
    """(records, damaged) — the longest fully-framed prefix of one
    segment.  A bad MAGIC, torn frame, short payload or CRC mismatch
    stops the scan; the damaged file is renamed ``*.quarantined``
    (mirroring snapshot-load semantics) so it is never re-read, but the
    salvaged prefix IS returned — a SIGKILL mid-append must not cost
    the records before the tear."""
    records: list[dict] = []
    damaged = False
    try:
        with open(path, "rb") as fh:
            if fh.read(len(MAGIC)) != MAGIC:
                damaged = True
            else:
                while True:
                    head = fh.read(_FRAME.size)
                    if not head:
                        break  # clean EOF
                    if len(head) < _FRAME.size:
                        damaged = True
                        break
                    length, crc = _FRAME.unpack(head)
                    if length > 64 << 20:
                        damaged = True  # implausible frame: corruption
                        break
                    blob = fh.read(length)
                    if len(blob) != length or zlib.crc32(blob) != crc:
                        damaged = True
                        break
                    try:
                        records.append(json.loads(blob))
                    except ValueError:
                        damaged = True
                        break
    except OSError:
        return [], True
    if damaged and quarantine:
        try:
            os.replace(path, path + ".quarantined")
        except OSError:
            pass
        if metrics is not None:
            metrics.counter("telespill_quarantined_total")
    return records, damaged


def load_dir(
    directory: str, quarantine: bool = True, metrics=None
) -> list[dict]:
    """Every salvageable record in a spill directory, in (instance,
    segment, append) order.  Quarantined files are skipped; damaged
    segments are quarantined on the way (unless ``quarantine=False``,
    for purely read-only consumers)."""
    names = []
    try:
        for de in os.scandir(directory):
            if de.name.endswith(".ktspill"):
                names.append((de.name, de.path))
    except OSError:
        return []
    out: list[dict] = []
    for _, path in sorted(names):
        records, _ = read_segment(path, quarantine=quarantine, metrics=metrics)
        out.extend(records)
    return out


# -- the periodic spiller -----------------------------------------------------

class TelemetrySpiller:
    """Periodically persists the process's telemetry surfaces:

    * ``spans`` records — the span-ring delta since the last spill
      (span ids are monotonic per tracer, so the delta is a cheap id
      cut), with the perf_counter wall anchor;
    * ``timeline`` records — the raw-tier bucket delta (by bucket end
      time), with the mono→wall anchor;
    * ``flightrec`` records — the decision ring summary (small;
      last-writer-wins on read).

    ``spill_now()`` is also the explicit hook the soak victim calls at
    the end of each round — the crash-durability contract is "whatever
    the last spill_now saw survives SIGKILL".
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        instance: str = "",
        metrics=None,
        tracer=None,
        timeline=None,
        flightrec=None,
        interval_s: Optional[float] = None,
        max_bytes: Optional[int] = None,
    ):
        directory = directory or telemetry_dir()
        self.enabled = spill_enabled() and directory is not None
        self.instance = _sanitize(instance or f"pid{os.getpid()}")
        self.interval_s = (
            _env_float("KT_SPILL_INTERVAL_S", 1.0)
            if interval_s is None else float(interval_s)
        )
        self.metrics = metrics
        self._tracer = tracer
        self._timeline = timeline
        self._flightrec = flightrec
        self._writer = (
            SpillWriter(
                directory, instance=self.instance, metrics=metrics,
                max_bytes=max_bytes,
            )
            if self.enabled else None
        )
        self._last_span_id = 0
        self._last_tl_t = float("-inf")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _envelope(self, kind: str) -> dict:
        from kubeadmiral_tpu.runtime import trace as trace_mod

        return {
            "kind": kind,
            "instance": self.instance,
            "pid": os.getpid(),
            "wall": time.time(),
            "mono": time.monotonic(),
            "wall_epoch": trace_mod.wall_epoch(),
        }

    # -- one pass ---------------------------------------------------------
    def spill_now(self) -> int:
        """Persist the deltas; returns the number of records written."""
        if not self.enabled or self._writer is None:
            return 0
        wrote = 0
        wrote += self._spill_spans()
        wrote += self._spill_timeline()
        wrote += self._spill_flightrec()
        return wrote

    def _spill_spans(self) -> int:
        from kubeadmiral_tpu.runtime import trace as trace_mod

        tracer = self._tracer or trace_mod.get_default()
        fresh = []
        newest = self._last_span_id
        for sp in tracer.spans():
            if sp.span_id <= self._last_span_id:
                continue
            newest = max(newest, sp.span_id)
            fresh.append(
                {
                    "name": sp.name,
                    "span_id": sp.span_id,
                    "parent_id": sp.parent_id,
                    "trace_id": sp.trace_id,
                    "start": sp.start,
                    "end": sp.end,
                    "tid": sp.tid,
                    "thread_name": sp.thread_name,
                    "args": sp.args,
                }
            )
        if not fresh:
            return 0
        env = self._envelope("spans")
        env["spans"] = fresh
        if self._writer.append("spans", env):
            self._last_span_id = newest
            return 1
        return 0

    def _spill_timeline(self) -> int:
        from kubeadmiral_tpu.runtime import timeline as timeline_mod

        tl = self._timeline or timeline_mod.get_default()
        if tl is None or not getattr(tl, "enabled", False):
            return 0
        doc = tl.to_doc(tier="raw")
        raw = (doc.get("tiers") or {}).get("raw") or {}
        series_out: dict[str, dict] = {}
        newest = self._last_tl_t
        for key, series in (raw.get("series") or {}).items():
            points = [
                p for p in series.get("points") or []
                if p[0] > self._last_tl_t
            ]
            if points:
                newest = max(newest, max(p[0] for p in points))
                series_out[key] = {"kind": series.get("kind"), "points": points}
        if not series_out:
            return 0
        env = self._envelope("timeline")
        env["interval_s"] = doc.get("interval_s")
        env["series"] = series_out
        if self._writer.append("timeline", env):
            self._last_tl_t = newest
            return 1
        return 0

    def _spill_flightrec(self) -> int:
        rec = self._flightrec
        if rec is None:
            from kubeadmiral_tpu.runtime import flightrec as flightrec_mod

            rec = flightrec_mod.get_default()
        if rec is None or not getattr(rec, "enabled", True):
            return 0
        try:
            summary = rec.decisions()
        except Exception:
            return 0
        if not (summary.get("ticks") or summary.get("recent")):
            # An empty ring spills nothing (keeps KT_SPILL-off parity
            # tests honest: no decisions -> no flightrec records).
            if not any(v for v in summary.values() if isinstance(v, list)):
                return 0
        env = self._envelope("flightrec")
        env["summary"] = summary
        return 1 if self._writer.append("flightrec", env) else 0

    # -- background thread ------------------------------------------------
    def start(self) -> bool:
        if not self.enabled or self.interval_s <= 0 or self._thread is not None:
            return False
        self._stop.clear()
        t = threading.Thread(
            target=self._run, name="kt-telespill", daemon=True
        )
        self._thread = t
        t.start()
        return True

    def stop(self, final_spill: bool = True) -> None:
        t = self._thread
        if t is not None:
            self._stop.set()
            t.join(timeout=5.0)
            self._thread = None
        if final_spill:
            try:
                self.spill_now()
            except Exception:
                pass
        if self._writer is not None:
            self._writer.close()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.spill_now()
            except Exception:
                pass  # a failing spill must never take the process down
