"""Continuous telemetry timeline — the soak observatory's recorder.

Every surface this control plane exposes today (/metrics, /debug/slo,
/debug/members, bench detail) is a point-in-time snapshot; a whole-run
claim like "no SLO window went red during the soak" is unverifiable
from snapshots.  This module adds the missing time axis: a sampler
(thread or explicit :meth:`Timeline.sample_now` calls) scrapes the
Metrics registry — in ONE lock-held copy per scrape, so counters can
never go backwards mid-tick — plus a set of provider callables (the SLO
evaluator's burn rates and red/green verdicts, breaker states, stream
depth/age, RSS and live device-buffer bytes) into a bounded RRD-style
downsampling ring:

* **raw** tier: one bucket per scrape (KT_TIMELINE_INTERVAL_S apart);
* **10s** and **60s** tiers: coarser buckets the raw samples merge into
  as they age (or under byte pressure), counters by SUM of per-scrape
  deltas, gauges by MAX — so a red burn-rate sample survives
  downsampling as a red bucket, and counter rates integrate exactly;
* the whole ring stays under KT_TIMELINE_BYTES (oldest coarse buckets
  drop last, with a drop counter so truncation is never silent).

Counters are stored as per-scrape DELTAS clamped at >= 0 (a registry
reset reads as a zero-delta sample, not a negative spike); gauges as
last-read values; histograms contribute ``<series>:count`` and
``<series>:sum`` delta series (quantiles don't downsample — counts and
sums do).

Served as JSON at GET /debug/timeline (health + profiling servers,
runtime/profiling.py) and dumped into SOAK_r<n>.json by the soak
scenario (bench.py --scenario soak).  KT_TIMELINE=0 disables the module
entirely: no thread is ever created and sample_now() is a no-op.

Schema and tier semantics: docs/observability.md ("Soak observatory").
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Callable, Optional

from kubeadmiral_tpu.runtime import lockcheck

__all__ = [
    "Timeline",
    "timeline_enabled",
    "get_default",
    "set_default",
    "reset_default",
]


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def timeline_enabled() -> bool:
    """KT_TIMELINE: master switch (default on).  Off means no sampler
    thread exists and every sample call early-outs."""
    return os.environ.get("KT_TIMELINE", "1") not in ("0", "false", "no")


# Age horizons: raw samples older than RAW_HORIZON_S merge into the 10s
# tier, 10s buckets older than MID_HORIZON_S into the 60s tier.  Byte
# pressure (KT_TIMELINE_BYTES) promotes earlier when the budget demands.
RAW_HORIZON_S = 900.0
MID_HORIZON_S = 7200.0

TIER_WIDTHS_S = (0.0, 10.0, 60.0)  # 0.0 = raw (one bucket per scrape)


class _Bucket:
    """One time bucket: counter deltas (merge: sum) + gauges (merge:
    max) observed over [t0, t1], covering ``n`` raw scrapes."""

    __slots__ = ("t0", "t1", "n", "counters", "gauges", "cost")

    def __init__(self, t0: float, t1: float, n: int,
                 counters: dict, gauges: dict):
        self.t0 = t0
        self.t1 = t1
        self.n = n
        self.counters = counters
        self.gauges = gauges
        self.cost = _bucket_cost(counters, gauges)

    def merge(self, other: "_Bucket") -> None:
        """Fold ``other`` (adjacent in time) into this bucket: counter
        deltas SUM (rates integrate), gauges MAX (a spike survives)."""
        self.t0 = min(self.t0, other.t0)
        self.t1 = max(self.t1, other.t1)
        self.n += other.n
        for key, val in other.counters.items():
            self.counters[key] = self.counters.get(key, 0.0) + val
        for key, val in other.gauges.items():
            prev = self.gauges.get(key)
            self.gauges[key] = val if prev is None else max(prev, val)
        self.cost = _bucket_cost(self.counters, self.gauges)


def _bucket_cost(counters: dict, gauges: dict) -> int:
    """Approximate resident bytes of one bucket: per-series key string +
    float box + dict slot, plus the bucket object itself.  An estimate
    (CPython internals vary) but a stable one, so KT_TIMELINE_BYTES is a
    real, testable bound on ring growth."""
    n = len(counters) + len(gauges)
    chars = sum(len(k) for k in counters) + sum(len(k) for k in gauges)
    return 120 + 110 * n + chars


class _Tier:
    __slots__ = ("name", "width", "horizon", "buckets")

    def __init__(self, name: str, width: float, horizon: Optional[float]):
        self.name = name
        self.width = width
        self.horizon = horizon  # None = terminal tier (drops, no promote)
        self.buckets: list[_Bucket] = []


@lockcheck.shared_field_guard
class Timeline:
    """The bounded, downsampling telemetry ring (see module docstring).

    Thread-shape: the sampler thread appends; HTTP handler threads read
    via :meth:`to_doc`; the soak harness calls :meth:`sample_now` from
    its round loop.  All ring state is guarded by ``_lock`` (declared
    below per the lockcheck discipline); provider callables and the
    registry scrape run OUTSIDE the ring lock — the registry snapshot is
    one atomic copy under the registry's own lock, which is what keeps
    counters monotonic within a series.
    """

    _shared_fields_ = {
        "_tiers": "_lock",
        "_prev": "_lock",
        "_external": "_lock",
        "_samples": "_lock",
        "_dropped": "_lock",
        "_provider_errors": "_lock",
        "_sample_seconds": "_lock",
    }

    def __init__(
        self,
        metrics=None,
        interval_s: Optional[float] = None,
        max_bytes: Optional[int] = None,
        clock=time.monotonic,
    ):
        self.metrics = metrics
        self.clock = clock
        self.enabled = timeline_enabled()
        self.interval_s = (
            _env_float("KT_TIMELINE_INTERVAL_S", 1.0)
            if interval_s is None else float(interval_s)
        )
        self.max_bytes = (
            _env_int("KT_TIMELINE_BYTES", 2 << 20)
            if max_bytes is None else int(max_bytes)
        )
        self._lock = lockcheck.make_lock("timeline")
        self._tiers = [
            _Tier("raw", TIER_WIDTHS_S[0], RAW_HORIZON_S),
            _Tier("10s", TIER_WIDTHS_S[1], MID_HORIZON_S),
            _Tier("60s", TIER_WIDTHS_S[2], None),
        ]
        self._prev: dict[str, float] = {}   # last absolute counter reads
        self._external: dict[str, float] = {}  # harness-set gauges (obj/s)
        self._samples = 0
        self._dropped = 0
        self._provider_errors = 0
        self._sample_seconds = 0.0
        self._providers: list[Callable[[], Optional[dict]]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- providers ---------------------------------------------------------
    def add_provider(self, fn: Callable[[], Optional[dict]]) -> None:
        """Register a callable returning a gauge dict merged into every
        scrape.  Providers run outside the ring lock and are exception-
        guarded (a failing provider degrades to a missing series, never
        a dead sampler)."""
        self._providers.append(fn)

    def set_gauge(self, name: str, value: float) -> None:
        """Pin an externally-computed gauge (e.g. the harness's obj/s)
        into every subsequent scrape."""
        with self._lock:
            self._external[name] = float(value)

    def attach_runtime(self, slo=None, breakers=None, stream=None) -> None:
        """Wire the standard runtime providers: SLO burn/red verdicts,
        breaker states, stream depth/age, RSS + live device bytes."""
        self.add_provider(lambda: _slo_gauges(slo))
        if breakers is not None:
            self.add_provider(lambda: _breaker_gauges(breakers))
        if stream is not None:
            self.add_provider(lambda: _stream_gauges(stream))
        self.add_provider(_process_gauges)

    # -- sampling ----------------------------------------------------------
    def start(self) -> bool:
        """Spawn the sampler thread.  Returns False (and creates NO
        thread) when KT_TIMELINE=0 or the interval is non-positive."""
        if not self.enabled or self.interval_s <= 0 or self._thread is not None:
            return False
        self._stop.clear()
        t = threading.Thread(
            target=self._run, name="kt-timeline", daemon=True
        )
        self._thread = t
        t.start()
        return True

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_now()
            except Exception:
                with self._lock:
                    self._provider_errors += 1

    def sample_now(self, now: Optional[float] = None) -> bool:
        """Take one sample synchronously (the soak harness's per-round
        call; also what the sampler thread runs).  Returns False when
        the timeline is disabled."""
        if not self.enabled:
            return False
        t_work = time.perf_counter()
        t = self.clock() if now is None else now
        gauges: dict[str, float] = {}
        errors = 0
        for fn in self._providers:
            try:
                extra = fn()
            except Exception:
                errors += 1
                continue
            if extra:
                for key, val in extra.items():
                    try:
                        gauges[str(key)] = float(val)
                    except (TypeError, ValueError):
                        continue
        # ONE lock-held registry copy: every counter in this scrape is
        # from the same instant, so per-series deltas are >= 0 by
        # construction (clamped anyway against registry resets).
        if self.metrics is not None:
            snap = self.metrics.snapshot()
        else:
            snap = {"counters": {}, "gauges": {}, "histograms": {}}
        registry_gauges = dict(snap["gauges"])
        registry_gauges.update(gauges)
        with self._lock:
            counters: dict[str, float] = {}
            for key, val in snap["counters"].items():
                delta = val - self._prev.get(key, 0.0)
                counters[key] = delta if delta > 0.0 else 0.0
                self._prev[key] = val
            for key, hist in snap["histograms"].items():
                for suffix, val in (
                    (":count", float(hist["count"])),
                    (":sum", float(hist["sum"])),
                ):
                    hkey = key + suffix
                    delta = val - self._prev.get(hkey, 0.0)
                    counters[hkey] = delta if delta > 0.0 else 0.0
                    self._prev[hkey] = val
            registry_gauges.update(self._external)
            self._tiers[0].buckets.append(
                _Bucket(t, t, 1, counters, registry_gauges)
            )
            self._samples += 1
            self._provider_errors += errors
            self._rebalance_locked(t)
            # Sampler self-cost, for the "timeline overhead <= 2% of
            # steady obj/s" acceptance: cumulative wall seconds spent
            # inside sample_now (providers + scrape + ring work).
            self._sample_seconds += time.perf_counter() - t_work
        return True

    # -- ring maintenance --------------------------------------------------
    @lockcheck.assumes_held("_lock")
    def _rebalance_locked(self, now: float) -> None:
        raw, mid, coarse = self._tiers
        # Age-based promotion keeps the tiers meaningful even far below
        # the byte budget.
        while raw.buckets and raw.buckets[0].t1 < now - raw.horizon:
            self._promote_locked(raw, mid)
        while mid.buckets and mid.buckets[0].t1 < now - mid.horizon:
            self._promote_locked(mid, coarse)
        # Byte pressure: promote oldest-first, drop terminal-tier
        # buckets only as the last resort (and count the drops).
        guard = 0
        while self._approx_bytes_locked() > self.max_bytes:
            guard += 1
            if guard > 100000:  # defensive: never wedge the sampler
                break
            if len(raw.buckets) > 1:
                self._promote_locked(raw, mid)
            elif len(mid.buckets) > 1:
                self._promote_locked(mid, coarse)
            elif coarse.buckets:
                coarse.buckets.pop(0)
                self._dropped += 1
                if not raw.buckets and not mid.buckets and not coarse.buckets:
                    break
            else:
                break

    @lockcheck.assumes_held("_lock")
    def _promote_locked(self, src: _Tier, dst: _Tier) -> None:
        """Move the oldest src bucket into dst's slot grid (floor-
        aligned to dst.width), merging when the slot already exists.
        Buckets are appended in time order, so the landing slot is
        always dst's LAST bucket or a new one."""
        bucket = src.buckets.pop(0)
        slot = (bucket.t0 // dst.width) * dst.width if dst.width > 0 else bucket.t0
        if dst.buckets and dst.buckets[-1].t0 >= slot - 1e-9:
            dst.buckets[-1].merge(bucket)
        else:
            bucket.t0 = slot
            dst.buckets.append(bucket)

    @lockcheck.assumes_held("_lock")
    def _approx_bytes_locked(self) -> int:
        return sum(b.cost for tier in self._tiers for b in tier.buckets)

    def approx_bytes(self) -> int:
        with self._lock:
            return self._approx_bytes_locked()

    # -- read side ---------------------------------------------------------
    def to_doc(
        self,
        series: Optional[str] = None,
        tier: Optional[str] = None,
    ) -> dict:
        """The GET /debug/timeline payload: per tier, series-major
        ``[t_end, value]`` point lists.  ``series`` substring-filters
        series names; ``tier`` selects one tier."""
        with self._lock:
            tiers_out = {}
            for t in self._tiers:
                if tier is not None and t.name != tier:
                    continue
                out: dict[str, dict] = {}
                for b in t.buckets:
                    point_t = round(b.t1, 3)
                    for key, val in b.counters.items():
                        if series is not None and series not in key:
                            continue
                        entry = out.get(key)
                        if entry is None:
                            entry = out[key] = {
                                "kind": "counter", "points": []
                            }
                        entry["points"].append([point_t, val])
                    for key, val in b.gauges.items():
                        if series is not None and series not in key:
                            continue
                        entry = out.get(key)
                        if entry is None:
                            entry = out[key] = {"kind": "gauge", "points": []}
                        entry["points"].append([point_t, val])
                tiers_out[t.name] = {
                    "width_s": t.width,
                    "buckets": len(t.buckets),
                    "series": out,
                }
            return {
                "enabled": self.enabled,
                "interval_s": self.interval_s,
                "max_bytes": self.max_bytes,
                "approx_bytes": self._approx_bytes_locked(),
                "samples_total": self._samples,
                "dropped_buckets_total": self._dropped,
                "provider_errors_total": self._provider_errors,
                "sample_seconds_total": round(self._sample_seconds, 6),
                "tiers": tiers_out,
            }

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_doc(**kw))

    def dump(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.to_doc(), f)


# -- standard providers -----------------------------------------------------

def _slo_gauges(rec=None) -> dict:
    """One evaluator pass: burn rates per (objective, window) plus a
    synthesized 0/1 ``slo_red{objective=...}`` verdict gauge — MAX-merge
    makes a downsampled bucket red iff ANY sample inside it was red,
    exactly the semantics the soak's red-outside-injection-window gate
    needs."""
    from kubeadmiral_tpu.runtime import slo as slo_mod

    recorder = rec if rec is not None else slo_mod.get_default()
    if recorder is None or not getattr(recorder, "enabled", False):
        return {}
    status = recorder.evaluate()
    out: dict[str, float] = {}
    for name, entry in status.items():
        for window, burn in entry.get("burn", {}).items():
            out[f"slo_burn_rate{{objective={name},window={window}}}"] = burn
        out[f"slo_red{{objective={name}}}"] = 1.0 if entry.get("red") else 0.0
    return out


def _breaker_gauges(breakers) -> dict:
    from kubeadmiral_tpu.transport import breaker as breaker_mod

    out: dict[str, float] = {}
    snap = breakers.snapshot()
    for name, entry in snap.items():
        state = entry.get("state") if isinstance(entry, dict) else entry
        code = breaker_mod.STATE_CODE.get(state, -1) if isinstance(
            state, str
        ) else float(state)
        out[f"member_breaker_state{{cluster={name}}}"] = float(code)
    return out


def _stream_gauges(stream) -> dict:
    return {
        "engine_stream_slab_depth": float(stream.pending()),
        "engine_stream_oldest_age_seconds": float(stream.oldest_age()),
    }


def _process_gauges() -> dict:
    """Resident set + live device-buffer bytes.  jax is consulted only
    when it is ALREADY imported — the timeline never pulls the device
    stack into a process that didn't need it."""
    out: dict[str, float] = {}
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        out["process_resident_bytes"] = float(pages * os.sysconf("SC_PAGE_SIZE"))
    except (OSError, ValueError, IndexError):
        try:
            import resource

            out["process_resident_bytes"] = float(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
            )
        except Exception:
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            out["device_buffer_bytes"] = float(
                sum(int(b.nbytes) for b in jax.live_arrays())
            )
        except Exception:
            pass
    return out


# -- process default --------------------------------------------------------
_default: Optional[Timeline] = None
_default_lock = threading.Lock()


def get_default() -> Optional[Timeline]:
    """The installed process timeline, or None — unlike the SLO
    recorder there is no lazy auto-construction: a timeline needs a
    registry to scrape, so embedders install one explicitly."""
    return _default


def set_default(timeline: Optional[Timeline]) -> Optional[Timeline]:
    global _default
    with _default_lock:
        prev = _default
        _default = timeline
    return prev


def reset_default() -> None:
    prev = set_default(None)
    if prev is not None:
        prev.stop()
