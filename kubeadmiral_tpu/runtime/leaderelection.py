"""Leader election over a lease object in the host store.

The reference elects one controller-manager replica through a
resourcelock lease in the federation system namespace; losing the lease
is fatal to the process (reference:
pkg/controllermanager/leaderelection/leaderelection.go).  Here the lock
is a plain object in the host store updated under optimistic
concurrency: acquire when absent or expired, renew while held, and
report loss when another identity overwrites an expired lease.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from kubeadmiral_tpu.testing.fakekube import AlreadyExists, Conflict, FakeKube, NotFound

LEASES = "coordination.k8s.io/v1/leases"

DEFAULT_LEASE_SECONDS = 15.0


class LeaderElector:
    def __init__(
        self,
        host: FakeKube,
        identity: str,
        name: str = "kubeadmiral-controller-manager",
        namespace: str = "kube-admiral-system",
        lease_seconds: float = DEFAULT_LEASE_SECONDS,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.host = host
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_seconds = lease_seconds
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        self.is_leader = False

    @property
    def _key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def _lease_expired(self, lease: dict) -> bool:
        renewed = float(lease.get("spec", {}).get("renewTime", 0.0))
        duration = float(
            lease.get("spec", {}).get("leaseDurationSeconds", self.lease_seconds)
        )
        return self.clock() - renewed > duration

    def try_acquire_or_renew(self) -> bool:
        """One election round; call periodically (≲ lease_seconds/3).
        Returns True while this identity holds the lease."""
        now = self.clock()
        desired_spec = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": self.lease_seconds,
            "renewTime": now,
        }
        lease = self.host.try_get(LEASES, self._key)
        try:
            if lease is None:
                self.host.create(
                    LEASES,
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {"name": self.name, "namespace": self.namespace},
                        "spec": desired_spec,
                    },
                )
                self._became(True)
                return True
            holder = lease.get("spec", {}).get("holderIdentity")
            if holder != self.identity and not self._lease_expired(lease):
                self._became(False)
                return False
            lease["spec"] = desired_spec
            self.host.update(LEASES, lease)
            self._became(True)
            return True
        except (Conflict, AlreadyExists, NotFound):
            # Someone else won the race this round.
            self._became(False)
            return False

    def release(self) -> bool:
        """Graceful handoff (the SIGTERM path): zero the lease's
        renewTime so a standby's next election round acquires
        immediately instead of waiting out the full lease duration —
        the k8s resourcelock ReleaseOnCancel behavior.  Best-effort:
        returns False when the lease is not ours (or already gone),
        which is fine — the successor then waits out the expiry."""
        if not self.is_leader:
            return False
        try:
            lease = self.host.try_get(LEASES, self._key)
            if (
                lease is None
                or lease.get("spec", {}).get("holderIdentity") != self.identity
            ):
                self._became(False)
                return False
            lease["spec"] = {
                "holderIdentity": "",
                "leaseDurationSeconds": self.lease_seconds,
                "renewTime": 0.0,
            }
            self.host.update(LEASES, lease)
        except (Conflict, AlreadyExists, NotFound):
            self._became(False)
            return False
        self._became(False)
        return True

    def _became(self, leading: bool) -> None:
        if self.is_leader and not leading and self.on_stopped_leading is not None:
            self.on_stopped_leading()
        self.is_leader = leading
