"""Leader election over a lease object in the host store.

The reference elects one controller-manager replica through a
resourcelock lease in the federation system namespace; losing the lease
is fatal to the process (reference:
pkg/controllermanager/leaderelection/leaderelection.go).  Here the lock
is a plain object in the host store updated under optimistic
concurrency: acquire when absent or expired, renew while held, and
report loss when another identity overwrites an expired lease.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from kubeadmiral_tpu.testing.fakekube import AlreadyExists, Conflict, FakeKube, NotFound

LEASES = "coordination.k8s.io/v1/leases"

DEFAULT_LEASE_SECONDS = 15.0


def shard_lease_name(shard_index: int) -> str:
    """Lease object name for one shard of the sharded control plane.
    N replicas each run an elector against their own ``kt-shard-<i>``
    lease, so shard ownership is disjoint by construction: the jump-hash
    router decides WHICH keys a shard owns, the per-shard lease decides
    WHICH replica owns the shard."""
    return f"kt-shard-{shard_index}"


def shard_elector(
    host: FakeKube,
    identity: str,
    shard_index: int,
    **kw,
) -> LeaderElector:
    """A LeaderElector over the shard's ``kt-shard-<i>`` lease."""
    return LeaderElector(
        host, identity, name=shard_lease_name(shard_index), **kw
    )


def shard_lease_status(
    host: FakeKube,
    shard_count: int,
    namespace: str = "kube-admiral-system",
    clock: Callable[[], float] = time.monotonic,
) -> list:
    """Ownership/freshness of every shard lease, for /debug/shards.

    One row per shard: ``{shard, lease, holder, age_s, fresh}`` where
    ``holder`` is None when the lease is absent or released and
    ``fresh`` means the holder renewed within its lease duration (a
    stale row is a shard whose replica died and whose standby has not
    taken over yet — exactly the failover gap the soak gate bounds)."""
    rows = []
    now = clock()
    for i in range(shard_count):
        name = shard_lease_name(i)
        lease = host.try_get(LEASES, f"{namespace}/{name}") or {}
        spec = lease.get("spec", {})
        holder = spec.get("holderIdentity") or None
        renewed = float(spec.get("renewTime", 0.0))
        duration = float(spec.get("leaseDurationSeconds", DEFAULT_LEASE_SECONDS))
        age = now - renewed if holder is not None else None
        rows.append(
            {
                "shard": i,
                "lease": name,
                "holder": holder,
                "age_s": round(age, 3) if age is not None else None,
                "fresh": holder is not None and age is not None and age <= duration,
            }
        )
    return rows


class LeaderElector:
    def __init__(
        self,
        host: FakeKube,
        identity: str,
        name: str = "kubeadmiral-controller-manager",
        namespace: str = "kube-admiral-system",
        lease_seconds: float = DEFAULT_LEASE_SECONDS,
        on_stopped_leading: Optional[Callable[[], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.host = host
        self.identity = identity
        self.name = name
        self.namespace = namespace
        self.lease_seconds = lease_seconds
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        self.is_leader = False

    @property
    def _key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def _lease_expired(self, lease: dict) -> bool:
        renewed = float(lease.get("spec", {}).get("renewTime", 0.0))
        duration = float(
            lease.get("spec", {}).get("leaseDurationSeconds", self.lease_seconds)
        )
        return self.clock() - renewed > duration

    def try_acquire_or_renew(self) -> bool:
        """One election round; call periodically (≲ lease_seconds/3).
        Returns True while this identity holds the lease."""
        now = self.clock()
        desired_spec = {
            "holderIdentity": self.identity,
            "leaseDurationSeconds": self.lease_seconds,
            "renewTime": now,
        }
        lease = self.host.try_get(LEASES, self._key)
        try:
            if lease is None:
                self.host.create(
                    LEASES,
                    {
                        "apiVersion": "coordination.k8s.io/v1",
                        "kind": "Lease",
                        "metadata": {"name": self.name, "namespace": self.namespace},
                        "spec": desired_spec,
                    },
                )
                self._became(True)
                return True
            holder = lease.get("spec", {}).get("holderIdentity")
            if holder != self.identity and not self._lease_expired(lease):
                self._became(False)
                return False
            lease["spec"] = desired_spec
            self.host.update(LEASES, lease)
            self._became(True)
            return True
        except (Conflict, AlreadyExists, NotFound):
            # Someone else won the race this round.
            self._became(False)
            return False

    def release(self) -> bool:
        """Graceful handoff (the SIGTERM path): zero the lease's
        renewTime so a standby's next election round acquires
        immediately instead of waiting out the full lease duration —
        the k8s resourcelock ReleaseOnCancel behavior.  Best-effort:
        returns False when the lease is not ours (or already gone),
        which is fine — the successor then waits out the expiry."""
        if not self.is_leader:
            return False
        try:
            lease = self.host.try_get(LEASES, self._key)
            if (
                lease is None
                or lease.get("spec", {}).get("holderIdentity") != self.identity
            ):
                self._became(False)
                return False
            lease["spec"] = {
                "holderIdentity": "",
                "leaseDurationSeconds": self.lease_seconds,
                "renewTime": 0.0,
            }
            self.host.update(LEASES, lease)
        except (Conflict, AlreadyExists, NotFound):
            self._became(False)
            return False
        self._became(False)
        return True

    def _became(self, leading: bool) -> None:
        if self.is_leader and not leading and self.on_stopped_leading is not None:
            self.on_stopped_leading()
        self.is_leader = leading
