"""Fleet aggregation: one merged telemetry pane over many processes.

A subprocess kwok farm is N member apiservers, each serving its own
/metrics and /debug surface on its own port — N uncorrelated pages.
:class:`FleetScraper` walks a roster of (instance, url, token) targets,
scrapes each member's Prometheus exposition, and merges the results —
per-instance sample counts, scrape health, and the raw series
re-labeled by instance — together with the MANAGER's own local
snapshots (breaker health, SLO status, tenant ledger) into the payload
``GET /debug/fleet`` serves (runtime/profiling.py).

The scraper is deliberately read-only and failure-tolerant: a member
that refuses its scrape becomes ``up: false`` with an error string,
never an exception on the debug route.  ``KT_FLEET_SCRAPE_S > 0``
additionally runs a background refresh thread; at 0 (the default) each
/debug/fleet GET scrapes on demand (stale results older than the
interval are refreshed either way).

See docs/observability.md § Fleet observatory.
"""

from __future__ import annotations

import http.client
import os
import threading
import time
from typing import Callable, Optional

__all__ = [
    "FleetScraper",
    "parse_prometheus",
    "get_default",
    "set_default",
    "reset_default",
]

# Per-instance series cap in the merged payload: a 500-member farm's
# full series dump would be a multi-MB pane; the counts stay exact.
MAX_SERIES_PER_INSTANCE = 2000


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def parse_prometheus(text: str) -> dict[str, float]:
    """A minimal Prometheus text-exposition parser: ``name{labels} value``
    lines into a flat dict (comments/blank lines skipped, unparsable
    values dropped).  Enough for aggregation — no TYPE/HELP semantics."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        # The value is the last whitespace-separated field; the series
        # name (labels may contain spaces inside quotes) is the rest.
        head, _, tail = line.rpartition(" ")
        if not head:
            continue
        try:
            out[head.strip()] = float(tail)
        except ValueError:
            continue
    return out


def _fetch(url: str, path: str, token: Optional[str], timeout: float) -> str:
    """GET one member route, bearer-authed; raises OSError-family on
    any transport failure (the caller folds it into scrape health)."""
    from urllib.parse import urlsplit

    split = urlsplit(url)
    conn = http.client.HTTPConnection(split.netloc, timeout=timeout)
    try:
        headers = {}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        conn.request("GET", path, headers=headers)
        resp = conn.getresponse()
        body = resp.read()
        if resp.status != 200:
            raise OSError(f"HTTP {resp.status} for {path}")
        return body.decode("utf-8", errors="replace")
    finally:
        conn.close()


class FleetScraper:
    """Scrapes a roster of member /metrics pages and merges them with
    the manager's local telemetry snapshots.

    ``roster`` is a zero-arg callable returning ``[(instance, url,
    token), ...]`` — a callable, not a list, because farm membership
    changes (members join, die, get replaced) and the scrape must see
    the CURRENT roster."""

    def __init__(
        self,
        roster: Callable[[], list[tuple[str, str, Optional[str]]]],
        metrics=None,
        interval_s: Optional[float] = None,
        timeout: float = 2.0,
        manager_instance: str = "manager",
    ):
        self.roster = roster
        self.metrics = metrics
        self.interval_s = (
            _env_float("KT_FLEET_SCRAPE_S", 0.0)
            if interval_s is None else float(interval_s)
        )
        self.timeout = timeout
        self.manager_instance = manager_instance
        self._lock = threading.Lock()
        self._last: Optional[dict] = None
        self._last_at = float("-inf")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- one scrape pass ---------------------------------------------------
    def scrape(self) -> dict:
        """Walk the roster once; returns (and caches) the merged doc."""
        t0 = time.perf_counter()
        instances: dict[str, dict] = {}
        errors = 0
        for instance, url, token in self.roster():
            entry: dict = {"url": url}
            try:
                text = _fetch(url, "/metrics", token, self.timeout)
                series = parse_prometheus(text)
                entry["up"] = True
                entry["samples"] = len(series)
                entry["series"] = dict(
                    list(series.items())[:MAX_SERIES_PER_INSTANCE]
                )
                if len(series) > MAX_SERIES_PER_INSTANCE:
                    entry["series_truncated"] = (
                        len(series) - MAX_SERIES_PER_INSTANCE
                    )
            except Exception as e:
                errors += 1
                entry["up"] = False
                entry["samples"] = 0
                entry["error"] = str(e)
            instances[instance] = entry
        # The manager's own registry joins the pane as one more
        # instance (same shape as a scraped member).
        if self.metrics is not None:
            series = parse_prometheus(self.metrics.render_prometheus())
            instances[self.manager_instance] = {
                "url": None,
                "up": True,
                "samples": len(series),
                "series": dict(
                    list(series.items())[:MAX_SERIES_PER_INSTANCE]
                ),
            }
        doc = {
            "scraped_at": time.time(),
            "scrape_seconds": round(time.perf_counter() - t0, 4),
            "instances": instances,
            "scrape_errors": errors,
            "manager": self._manager_snapshots(),
        }
        if self.metrics is not None:
            self.metrics.counter("fleet_scrapes_total")
            if errors:
                self.metrics.counter("fleet_scrape_errors_total", value=errors)
            self.metrics.store("fleet_instances", float(len(instances)))
        with self._lock:
            self._last = doc
            self._last_at = time.monotonic()
        return doc

    def _manager_snapshots(self) -> dict:
        """The manager-local surfaces the fleet pane merges in: breaker
        health, SLO status, tenant ledger — each best-effort (an
        uninstalled surface is absent, never an error)."""
        out: dict = {}
        try:
            from kubeadmiral_tpu.transport import breaker as breaker_mod

            out["members"] = breaker_mod.members_report()
        except Exception:
            pass
        try:
            from kubeadmiral_tpu.runtime import slo as slo_mod

            rec = slo_mod.get_default()
            if rec is not None and getattr(rec, "enabled", False):
                out["slo"] = rec.summary(slowest=0)
        except Exception:
            pass
        try:
            from kubeadmiral_tpu.runtime import tenancy as tenancy_mod

            ledger = tenancy_mod.get_default()
            if ledger is not None:
                out["tenants"] = ledger.summary()
        except Exception:
            pass
        return out

    def summary(self, refresh: bool = True) -> dict:
        """The cached merged doc, refreshed when stale (older than the
        scrape interval, or never scraped).  ``refresh=False`` returns
        whatever is cached (possibly a placeholder)."""
        with self._lock:
            last, last_at = self._last, self._last_at
        age = time.monotonic() - last_at
        stale = last is None or age > max(self.interval_s, 0.0)
        if refresh and stale:
            try:
                return self.scrape()
            except Exception as e:
                return {"error": str(e), "instances": {}}
        return last if last is not None else {"instances": {}}

    # -- background refresh ------------------------------------------------
    def start(self) -> bool:
        """Spawn the periodic refresher (KT_FLEET_SCRAPE_S > 0 only)."""
        if self.interval_s <= 0 or self._thread is not None:
            return False
        self._stop.clear()
        t = threading.Thread(
            target=self._run, name="kt-fleetscrape", daemon=True
        )
        self._thread = t
        t.start()
        return True

    def stop(self) -> None:
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape()
            except Exception:
                pass  # a failed pass keeps the previous pane


# -- process default ----------------------------------------------------------
_default: Optional[FleetScraper] = None
_default_lock = threading.Lock()


def get_default() -> Optional[FleetScraper]:
    """The installed fleet scraper, or None (no auto-construction: a
    scraper needs a roster, so embedders install one explicitly)."""
    return _default


def set_default(scraper: Optional[FleetScraper]) -> Optional[FleetScraper]:
    global _default
    with _default_lock:
        prev = _default
        _default = scraper
    return prev


def reset_default() -> None:
    prev = set_default(None)
    if prev is not None:
        prev.stop()
