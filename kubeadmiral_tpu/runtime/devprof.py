"""Device-time attribution: the dispatch ledger + on-demand profiler.

Every stage number the engine reports is a host-side ``perf_counter``
interval, which conflates XLA device compute with dispatch backpressure
— a pipelined tick can spend 12s of its "decode" stage blocked behind
queued device work and the stage histograms cannot say so.  This module
turns "the tick took X ms" into "program P at shape S occupied the
device for Y ms and waited Z ms in queue":

* **Dispatch ledger** (:class:`DispatchLedger`).  Every engine/pipeline
  program launch calls :meth:`DispatchLedger.observe` with the program
  kind and the dispatched output; the hot path records only a
  ``perf_counter`` timestamp and a deque append (~1µs — the ledger
  stays on in production, ``KT_DEVPROF=0`` disables).  A daemon watcher
  thread observes readiness asynchronously: it blocks on a small
  representative output leaf of each record IN DISPATCH ORDER and
  applies the single-stream chain model —

      start_i   = max(dispatch_ts_i, ready_ts_{i-1})
      device_s  = ready_ts_i - start_i
      queue_s   = start_i - dispatch_ts_i

  which is exact for an in-order device queue (both CPU and TPU
  streams execute enqueued programs FIFO): ``device_s`` is the time
  the program actually occupied the device, ``queue_s`` the time it
  sat enqueued behind earlier work (the backpressure the host-side
  stage timers misattribute).  Records dispatched while no tick is
  open (the prewarm thread) land in a bounded "untracked" ring.

* **Per-tick waterfalls.**  The engine brackets each ``schedule()``
  call with :meth:`begin_tick`/:meth:`end_tick`; the resulting
  waterfall (ordered dispatch records with the host-side stage split
  attached) is served at ``GET /debug/waterfall`` and embedded in
  bench ``detail.device_attr``, so BENCH_DETAIL stage numbers decompose
  into device-attributed per-program costs.

* **On-demand ``jax.profiler`` capture** (:func:`capture_jax_profile`).
  ``GET /debug/profile?seconds=N&mode=jax`` starts/stops a profiler
  trace around live ticks and writes the artifact under
  ``KT_PROFILE_DIR`` (works on CPU and TPU; load the directory in
  TensorBoard's profile plugin / xprof).  ``make profile`` /
  ``make profile-smoke`` drive the same capture from the CLI.

Holding an output reference could collide with buffer donation (the
engine donates prev planes into the next tick): a donated-away array
raises on ``block_until_ready``, which the watcher treats as "ready at
observation time" and tags ``note="donated"`` — attribution degrades
gracefully instead of crashing the hot path.

See docs/observability.md § Device-time attribution.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

# Known program kinds (the engine's central wrappers): documented in
# docs/observability.md so waterfall readers have one vocabulary.
PROGRAM_KINDS = (
    "tick",            # fused dense/compact tick (full-width solve + diff)
    "tick_narrow",     # narrow tick (phase 1 + top-M candidate solve + cert)
    "narrow_fallback", # dense re-solve of certificate-failed narrow rows
    "gate",            # drift gate (row classification from cached planes)
    "wcheck",          # drift dynamic-weight comparison
    "resolve",         # sort-free drift survivor resolve
    "replan",          # selection-known replan of kinf fit-flip survivors
    "scoreonly",       # score-only narrow solve of finite-K fit-flip rows
    "survivor",        # UNIFIED drift-survivor kernel (subsumes the three
    #                    above; KT_SURVIVOR_UNIFIED)
    "nfeas",           # cached per-row feasible-count reduce (store-site
    #                    companion of prev_feas; kills the gate's pf.sum)
    "tiebreak",        # precomputed planner tie-break plane (full/patch)
    "gather",          # delta-row plane gathers (dense wire)
    "pack",            # packed-export wire compaction (gather/full)
    "overflow",        # K-overflow bit-packed row re-fetch gather
    "repair",          # in-place prev-plane / narrow-output scatter repair
    "patch",           # stale-row device input scatter repair
    "stack",           # window-drain same-shape transfer stacking
    "zeros",           # device-resident zero prev-plane builders
    "score_pack",      # f16 score-plane compress / upcast (KT_SCORE_F16)
)

_UNTRACKED_RING = 4096


class DispatchRecord:
    __slots__ = (
        "seq", "tick", "kind", "shape", "t_dispatch", "t_ready",
        "queue_s", "device_s", "note", "device",
    )

    def __init__(self, seq: int, tick: Optional[int], kind: str):
        self.seq = seq
        self.tick = tick
        self.kind = kind
        self.shape = ""
        self.t_dispatch = time.perf_counter()
        self.t_ready: Optional[float] = None
        self.queue_s = 0.0
        self.device_s = 0.0
        self.note = "ok"
        # Which device(s) the dispatched output resides on: "d<id>" for
        # a single committed device, "mesh<N>" for a GSPMD output
        # spanning N devices, "?" when the sharding is unreadable.  The
        # label rides engine_device_seconds / engine_queue_wait_seconds
        # and the waterfall rows, so multi-device rounds attribute
        # device time per lane instead of flattening the mesh.
        self.device = "?"


class _TickEntry:
    __slots__ = (
        "tick", "t0", "t1", "meta", "stage_s", "records", "closed",
        "owner",
    )

    def __init__(self, tick: int, meta: dict):
        self.tick = tick
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.meta = meta
        self.stage_s: dict = {}
        self.records: list[DispatchRecord] = []
        self.closed = False
        # Dispatches are attributed to this tick only from the thread
        # that opened the bracket: a concurrent prewarm thread's
        # programs must not pollute a live tick's waterfall.
        self.owner = threading.get_ident()


def _pick_leaf(out):
    """A small representative jax.Array leaf of a dispatched output
    pytree: readiness of one output of a fused program implies the
    program ran to completion, and holding the smallest leaf pins the
    least memory until the watcher retires the record."""
    try:
        import jax

        leaves = jax.tree_util.tree_leaves(out)
    except Exception:
        leaves = [out]
    best = None
    best_bytes = None
    for leaf in leaves:
        if not hasattr(leaf, "block_until_ready"):
            continue
        nbytes = getattr(leaf, "nbytes", 0)
        if best is None or nbytes < best_bytes:
            best, best_bytes = leaf, nbytes
    return best


class DispatchLedger:
    """Central dispatch-site wrapper state: observe() on the hot path,
    a single watcher thread retiring records in dispatch order."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        ring_ticks: Optional[int] = None,
        metrics=None,
    ):
        if enabled is None:
            enabled = os.environ.get("KT_DEVPROF", "1") not in (
                "0", "false", "no",
            )
        self.enabled = bool(enabled)
        if ring_ticks is None:
            ring_ticks = int(os.environ.get("KT_DEVPROF_TICKS", "8"))
        self.metrics = metrics
        self._cv = threading.Condition()
        self._pending: deque = deque()  # (record, leaf)
        self._ticks: deque[_TickEntry] = deque(maxlen=max(1, ring_ticks))
        self._open: Optional[_TickEntry] = None
        self._untracked: deque[DispatchRecord] = deque(maxlen=_UNTRACKED_RING)
        self._seq = 0
        self._retired_seq = 0
        self._tick_seq = 0
        self._chain_ready: Optional[float] = None
        self.inflight = 0
        self._thread: Optional[threading.Thread] = None

    # -- wiring -----------------------------------------------------------
    def attach(self, metrics) -> None:
        """Point histogram emission at a registry (the engine attaches
        its own; last writer wins for the process-default ledger)."""
        self.metrics = metrics

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._watch, name="devprof-watcher", daemon=True
            )
            self._thread.start()

    # -- hot path ---------------------------------------------------------
    def observe(self, kind: str, out) -> None:
        """Record one device dispatch (call immediately after the
        program launch returns).  Cost: one perf_counter read, a leaf
        pick, and a lock-guarded deque append."""
        if not self.enabled:
            return
        leaf = _pick_leaf(out)
        if leaf is None:
            return  # host-only output: nothing dispatched
        with self._cv:
            self._seq += 1
            open_entry = self._open
            tick = (
                open_entry.tick
                if open_entry is not None
                and open_entry.owner == threading.get_ident()
                else None
            )
            rec = DispatchRecord(self._seq, tick, kind)
            self._pending.append((rec, leaf))
            self.inflight += 1
            self._ensure_thread()
            self._cv.notify_all()

    # -- tick bracketing --------------------------------------------------
    def begin_tick(self, **meta) -> int:
        """Open a tick bracket; returns the ledger-wide tick id.  The
        engine serializes schedule(), so one bracket is open at a time
        (a nested/overlapping begin closes the previous bracket)."""
        if not self.enabled:
            return 0
        with self._cv:
            self._tick_seq += 1
            if self._open is not None and not self._open.closed:
                self._finish_open_locked()
            self._open = _TickEntry(self._tick_seq, dict(meta))
            return self._tick_seq

    def _finish_open_locked(self) -> None:
        entry = self._open
        entry.closed = True
        if entry.t1 is None:
            entry.t1 = time.perf_counter()
        self._ticks.append(entry)

    def end_tick(self, stage_s: Optional[dict] = None) -> None:
        """Close the open bracket, attaching the host-side stage split
        (seconds).  Non-blocking: readiness observation may still be in
        flight — waterfall() drains before reading."""
        if not self.enabled:
            return
        with self._cv:
            if self._open is None:
                return
            self._open.t1 = time.perf_counter()
            if stage_s:
                self._open.stage_s = {
                    k: float(v) for k, v in stage_s.items()
                }
            self._finish_open_locked()
            self._open = None

    # -- watcher ----------------------------------------------------------
    def _watch(self) -> None:
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                rec, leaf = self._pending.popleft()
            try:
                leaf.block_until_ready()
            except Exception:
                # Donated/deleted buffer: the program certainly finished
                # before its output could be donated into a later
                # dispatch, so "ready by now" is the best lower bound.
                rec.note = "donated"
            t_ready = time.perf_counter()
            try:
                rec.shape = "x".join(str(d) for d in leaf.shape)
            except Exception:
                rec.shape = "?"
            try:
                # Off the hot path (watcher thread): derive the device
                # lane from the output's sharding.
                devs = getattr(leaf, "sharding", None)
                devs = sorted(
                    d.id for d in devs.device_set
                ) if devs is not None else []
                if len(devs) == 1:
                    rec.device = f"d{devs[0]}"
                elif devs:
                    rec.device = f"mesh{len(devs)}"
            except Exception:
                pass
            del leaf
            start = rec.t_dispatch
            if self._chain_ready is not None and self._chain_ready > start:
                start = self._chain_ready
            if t_ready < start:
                t_ready = start
            rec.queue_s = start - rec.t_dispatch
            rec.device_s = t_ready - start
            rec.t_ready = t_ready
            self._chain_ready = t_ready
            m = self.metrics
            if m is not None:
                try:
                    m.histogram(
                        "engine_device_seconds", rec.device_s,
                        program=rec.kind, device=rec.device,
                    )
                    m.histogram(
                        "engine_queue_wait_seconds", rec.queue_s,
                        program=rec.kind, device=rec.device,
                    )
                except Exception:
                    pass
            with self._cv:
                self.inflight -= 1
                self._retired_seq = rec.seq
                entry = None
                if rec.tick is not None:
                    if self._open is not None and self._open.tick == rec.tick:
                        entry = self._open
                    else:
                        for e in reversed(self._ticks):
                            if e.tick == rec.tick:
                                entry = e
                                break
                if entry is not None:
                    entry.records.append(rec)
                else:
                    self._untracked.append(rec)
                if m is not None:
                    try:
                        m.store("engine_dispatch_inflight", self.inflight)
                    except Exception:
                        pass
                self._cv.notify_all()

    def drain(self, timeout: float = 5.0) -> bool:
        """Wait until every observed record has been retired (the
        programs themselves have long finished by the time callers ask
        — this waits out the watcher, not the device)."""
        if not self.enabled:
            return True
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._pending or self.inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(timeout=remaining)
        return True

    # -- readback ---------------------------------------------------------
    @staticmethod
    def _summarize(records) -> dict:
        by: dict[str, dict] = {}
        by_dev: dict[str, dict] = {}
        dev = queue = 0.0
        for r in records:
            slot = by.setdefault(
                r.kind, {"n": 0, "device_ms": 0.0, "queue_ms": 0.0}
            )
            slot["n"] += 1
            slot["device_ms"] += r.device_s * 1e3
            slot["queue_ms"] += r.queue_s * 1e3
            lane = by_dev.setdefault(
                getattr(r, "device", "?"),
                {"n": 0, "device_ms": 0.0, "queue_ms": 0.0},
            )
            lane["n"] += 1
            lane["device_ms"] += r.device_s * 1e3
            lane["queue_ms"] += r.queue_s * 1e3
            dev += r.device_s
            queue += r.queue_s
        for slot in by.values():
            slot["device_ms"] = round(slot["device_ms"], 3)
            slot["queue_ms"] = round(slot["queue_ms"], 3)
        for lane in by_dev.values():
            lane["device_ms"] = round(lane["device_ms"], 3)
            lane["queue_ms"] = round(lane["queue_ms"], 3)
        return {
            "records": len(records),
            "device_ms": round(dev * 1e3, 3),
            "queue_ms": round(queue * 1e3, 3),
            "by_program": by,
            "by_device": by_dev,
        }

    def tick_summary(self, tick: Optional[int] = None, timeout: float = 5.0) -> dict:
        """Per-program device/queue totals for one tick (default: the
        most recently closed one)."""
        if not self.enabled:
            return {"enabled": False}
        self.drain(timeout)
        with self._cv:
            entry = self._find_locked(tick)
            if entry is None:
                return {"enabled": True, "tick": None, "records": 0}
            summary = self._summarize(entry.records)
            summary.update(
                tick=entry.tick,
                wall_ms=round(((entry.t1 or entry.t0) - entry.t0) * 1e3, 3),
                stage_ms={
                    k: round(v * 1e3, 3) for k, v in entry.stage_s.items()
                },
                meta=dict(entry.meta),
            )
            return summary

    def _find_locked(self, tick: Optional[int]) -> Optional[_TickEntry]:
        if tick is None:
            return self._ticks[-1] if self._ticks else None
        for e in reversed(self._ticks):
            if e.tick == tick:
                return e
        return None

    def chrome_events(
        self,
        epoch: float,
        max_ticks: int = 8,
        max_records: int = 2048,
        timeout: float = 2.0,
    ) -> list[dict]:
        """The ledger's recent dispatch records as Chrome trace events on
        per-device lanes, timestamped against the span tracer's epoch
        (trace.epoch()) so GET /debug/trace shows host spans and device
        timelines on ONE timeline, correlated by tick id in args.

        Each record renders as a device-occupancy slice (name = program
        kind) on a synthetic ``device <lane>`` thread, preceded by a
        ``queue:<kind>`` slice when the program waited behind earlier
        device work — the same chain-model split the waterfall reports.
        """
        if not self.enabled:
            return []
        self.drain(timeout)
        with self._cv:
            records = [
                r
                for e in list(self._ticks)[-max_ticks:]
                for r in e.records
            ]
        records.sort(key=lambda r: r.seq)
        if len(records) > max_records:
            records = records[-max_records:]
        pid = os.getpid()
        # Stable synthetic tids per device lane, far above real thread
        # ids' typical range so tools sort them into their own block.
        lanes: dict[str, int] = {}
        events: list[dict] = []
        for r in records:
            lane = getattr(r, "device", "?")
            if lane not in lanes:
                lanes[lane] = 0x64657600 + len(lanes)
            tid = lanes[lane]
            t_ready = r.t_ready if r.t_ready is not None else r.t_dispatch
            start = t_ready - r.device_s
            args = {
                "tick": r.tick,
                "seq": r.seq,
                "shape": r.shape,
                "queue_ms": round(r.queue_s * 1e3, 3),
                "device_ms": round(r.device_s * 1e3, 3),
            }
            if r.note != "ok":
                args["note"] = r.note
            if r.queue_s > 0:
                events.append(
                    {
                        "name": f"queue:{r.kind}",
                        "ph": "X",
                        "ts": round((r.t_dispatch - epoch) * 1e6, 3),
                        "dur": round(r.queue_s * 1e6, 3),
                        "pid": pid,
                        "tid": tid,
                        "args": args,
                    }
                )
            events.append(
                {
                    "name": r.kind,
                    "ph": "X",
                    "ts": round((start - epoch) * 1e6, 3),
                    "dur": round(r.device_s * 1e6, 3),
                    "pid": pid,
                    "tid": tid,
                    "args": args,
                }
            )
        for lane, tid in lanes.items():
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": f"device {lane} (dispatch ledger)"},
                }
            )
        return events

    def waterfall(
        self,
        tick: Optional[int] = None,
        max_ticks: int = 4,
        max_records: int = 512,
        timeout: float = 5.0,
    ) -> dict:
        """The waterfall artifact: the most recent ticks' ordered
        dispatch records with the host/device split attached (schema in
        docs/observability.md)."""
        if not self.enabled:
            return {"enabled": False, "ticks": []}
        self.drain(timeout)
        out_ticks = []
        with self._cv:
            entries = (
                [e for e in self._ticks if e.tick == tick]
                if tick is not None
                else list(self._ticks)[-max_ticks:]
            )
            for e in entries:
                records = sorted(e.records, key=lambda r: r.seq)
                trimmed = len(records) > max_records
                rows = [
                    {
                        "seq": r.seq,
                        "kind": r.kind,
                        "shape": r.shape,
                        "device": getattr(r, "device", "?"),
                        "t_ms": round((r.t_dispatch - e.t0) * 1e3, 3),
                        "queue_ms": round(r.queue_s * 1e3, 3),
                        "device_ms": round(r.device_s * 1e3, 3),
                        "ready_ms": round(
                            ((r.t_ready or r.t_dispatch) - e.t0) * 1e3, 3
                        ),
                        **({"note": r.note} if r.note != "ok" else {}),
                    }
                    for r in records[:max_records]
                ]
                summary = self._summarize(records)
                out_ticks.append(
                    {
                        "tick": e.tick,
                        "meta": dict(e.meta),
                        "wall_ms": round(
                            ((e.t1 or e.t0) - e.t0) * 1e3, 3
                        ),
                        "stage_ms": {
                            k: round(v * 1e3, 3)
                            for k, v in e.stage_s.items()
                        },
                        "device_ms": summary["device_ms"],
                        "queue_ms": summary["queue_ms"],
                        "by_program": summary["by_program"],
                        "records": rows,
                        **({"records_trimmed": True} if trimmed else {}),
                    }
                )
            untracked = self._summarize(self._untracked)
        return {
            "enabled": True,
            "inflight": self.inflight,
            "ticks": out_ticks,
            "untracked": untracked,
        }


_default = DispatchLedger()


def get_default() -> DispatchLedger:
    return _default


# -- on-demand jax.profiler capture ---------------------------------------
_capture_lock = threading.Lock()


def profile_dir() -> str:
    """Root directory for on-demand profiler artifacts
    (``KT_PROFILE_DIR``, default ``/tmp/kt-jax-profile``)."""
    return os.environ.get("KT_PROFILE_DIR", "/tmp/kt-jax-profile")


def capture_jax_profile(seconds: float = 2.0, out_dir: Optional[str] = None) -> dict:
    """Capture a ``jax.profiler`` trace of whatever the process is
    doing for ``seconds`` (live ticks included) into a fresh
    timestamped subdirectory of ``out_dir`` (default
    :func:`profile_dir`).  One capture at a time — overlapping traces
    would corrupt each other.  Works on CPU and TPU; load the directory
    with TensorBoard's profile plugin (``tensorboard --logdir <dir>``)
    or xprof."""
    seconds = max(0.05, min(float(seconds), 120.0))
    root = out_dir or profile_dir()
    target = os.path.join(
        root, time.strftime("%Y%m%d-%H%M%S") + f"-{os.getpid()}"
    )
    if not _capture_lock.acquire(blocking=False):
        return {"error": "a profiler capture is already running"}
    t0 = time.perf_counter()
    try:
        import jax

        os.makedirs(target, exist_ok=True)
        jax.profiler.start_trace(target)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
    except Exception as e:
        return {"error": f"profiler capture failed: {e}", "dir": target}
    finally:
        _capture_lock.release()
    n_files = sum(len(files) for _, _, files in os.walk(target))
    # wall_s >> seconds is expected on a BUSY process: start/stop_trace
    # serialize against in-flight XLA activity (measured ~8s activation
    # under continuous dispatch on CPU) — the capture itself still
    # covers ~`seconds` of live ticks.  HTTP callers must budget the
    # wall, not `seconds` (docs/observability.md profiler runbook).
    return {
        "dir": target,
        "seconds": seconds,
        "wall_s": round(time.perf_counter() - t0, 2),
        "files": n_files,
    }
